"""High-dimensional regime evaluation — the d=28-90 coverage gap (VERDICT r2).

The paper's real datasets reach d=28 (HEPMASS/HIGGS) and d=90
(YearPrediction) — BASELINE.md Table 1 — while every round-1/2 measurement ran
d <= 10. Two risks scale with d: the MXU dot-form distance expansion loses
relative precision (the round-2 bf16 bug was caught at d >= 5 and fixed with
``Precision.HIGHEST``; this harness cross-checks the fix holds at d=90), and
``top_k`` working sets grow.

Per (n, d) leg:
  1. f64 ORACLE CROSS-CHECK: exact core distances from the tiled f32 device
     scan vs a float64 numpy oracle on a row sample — max abs/rel error.
  2. exact tiled-Borůvka fit (wall + ARI vs truth).
  3. boundary-hybrid fit (wall + ARI vs truth + vs exact).

Emits one JSON line per leg. Usage:
  python benchmarks/highdim_eval.py [n] [dims_csv] [modes_csv]
Defaults: n=500_000, dims=28,90, modes=oracle,exact,bound05.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import exact, mr_hdbscan
from hdbscan_tpu.utils.datasets import make_gauss
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from hdbscan_tpu.utils.flops import counter as flops_counter
from hdbscan_tpu.utils.flops import phase_stats
from hdbscan_tpu.utils.tracing import Tracer


def oracle_core_check(data, min_pts, sample=512, seed=0):
    """Max abs/rel error of the device core distances vs a float64 oracle."""
    from hdbscan_tpu.ops.tiled import knn_core_distances

    core, _ = knn_core_distances(data, min_pts, fetch_knn=False)
    rng = np.random.default_rng(seed)
    rows = rng.choice(len(data), min(sample, len(data)), replace=False)
    d2 = (
        np.sum(data[rows] ** 2, axis=1)[:, None]
        + np.sum(data**2, axis=1)[None, :]
        - 2.0 * data[rows] @ data.T
    )
    want = np.sqrt(np.maximum(np.sort(d2, axis=1)[:, min_pts - 2], 0.0))
    got = core[rows]
    abs_err = np.abs(got - want)
    rel_err = abs_err / np.maximum(want, 1e-30)
    return float(abs_err.max()), float(rel_err.max())


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
    dims_list = [int(x) for x in (sys.argv[2] if len(sys.argv) > 2 else "28,90").split(",")]
    modes = (sys.argv[3] if len(sys.argv) > 3 else "oracle,exact,bound05").split(",")
    min_pts = 8
    cap = 16384
    for dims in dims_list:
        # HEPMASS-class difficulty: few clusters. Separation scales with
        # sqrt(d): within-cluster nearest-neighbor distances concentrate at
        # ~sigma*sqrt(2d), so a FIXED center separation that is decisive at
        # d=10 blends clusters at d=90 — 3*sqrt(d) keeps the difficulty in
        # the same class as the sep-9 rows at d=10.
        n_cl = 8
        mcs = max(64, n // 200)
        sep = 3.0 * float(np.sqrt(dims))
        data, y = make_gauss(n, dims=dims, n_clusters=n_cl, separation=sep, seed=4)
        base = dict(
            min_points=min_pts, min_cluster_size=mcs, processing_units=cap,
            seed=0, k=0.01,
        )
        exact_labels = None
        for mode in modes:
            tracer = Tracer(stream=sys.stderr)
            fsnap = flops_counter.snapshot()
            t0 = time.time()
            if mode == "oracle":
                abs_e, rel_e = oracle_core_check(data, min_pts)
                rec = {
                    "config": "oracle_core_check",
                    "n": n,
                    "dims": dims,
                    "core_abs_err_max": round(abs_e, 8),
                    "core_rel_err_max": round(rel_e, 8),
                    "wall_s": round(time.time() - t0, 2),
                }
                print(json.dumps(rec), flush=True)
                continue
            if mode == "exact":
                r = exact.fit(data, HDBSCANParams(**base), trace=tracer)
                exact_labels = r.labels
            elif mode == "bound05":
                r = mr_hdbscan.fit(
                    data, HDBSCANParams(**base, boundary_quality=0.05),
                    trace=tracer,
                )
            elif mode == "db":
                # The plain recursive-sampling + bubbles pipeline, no
                # boundary phase (per-block cores, bubble-weight pooling,
                # no refinement — the reference-faithful cost shape). At
                # d >= 28 this is the RIGHT tool: within-cluster block
                # radii (~sigma*sqrt(2d)) exceed k-NN cores, so the
                # boundary rescan's block pruning cannot exclude any
                # same-cluster window and its work degenerates toward
                # O(m * n) (measured: the 10.5M x 28 bound05 rescan
                # projected ~1e18 FLOPs); meanwhile seams at this
                # separation class are empty, so per-block core inflation
                # does not move the flat cut.
                r = mr_hdbscan.fit(
                    data,
                    HDBSCANParams(
                        **base,
                        global_core_distances=False,
                        exact_inter_edges=False,
                        refine_iterations=0,
                    ),
                    trace=tracer,
                )
            else:
                raise ValueError(mode)
            wall = time.time() - t0
            rec = {
                "config": mode,
                "n": n,
                "dims": dims,
                "min_cluster_size": mcs,
                "wall_s": round(wall, 2),
                "ari_truth": round(float(adjusted_rand_index(r.labels, y)), 4),
                **phase_stats(fsnap, wall),
            }
            if exact_labels is not None and mode != "exact":
                rec["ari_exact"] = round(
                    float(adjusted_rand_index(r.labels, exact_labels)), 4
                )
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
