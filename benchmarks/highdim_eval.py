"""High-dimensional regime evaluation — the d=28-90 coverage gap (VERDICT r2).

The paper's real datasets reach d=28 (HEPMASS/HIGGS) and d=90
(YearPrediction) — BASELINE.md Table 1 — while every round-1/2 measurement ran
d <= 10. Two risks scale with d: the MXU dot-form distance expansion loses
relative precision (the round-2 bf16 bug was caught at d >= 5 and fixed with
``Precision.HIGHEST``; this harness cross-checks the fix holds at d=90), and
``top_k`` working sets grow.

Per (n, d) leg:
  1. f64 ORACLE CROSS-CHECK: exact core distances from the tiled f32 device
     scan vs a float64 numpy oracle on a row sample — max abs/rel error.
  2. exact tiled-Borůvka fit (wall + ARI vs truth).
  3. boundary-hybrid fit (wall + ARI vs truth + vs exact).

Emits one JSON line per leg. Usage:
  python benchmarks/highdim_eval.py [n] [dims_csv] [modes_csv]
Defaults: n=500_000, dims=28,90, modes=oracle,exact,bound05.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import exact, mr_hdbscan
from hdbscan_tpu.utils.datasets import make_gauss
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from hdbscan_tpu.utils.flops import counter as flops_counter
from hdbscan_tpu.utils.flops import phase_stats
from hdbscan_tpu.utils.tracing import Tracer


def oracle_core_check(data, min_pts, sample=512, seed=0):
    """Max abs/rel error of the device core distances vs a float64 oracle."""
    from hdbscan_tpu.ops.tiled import knn_core_distances

    core, _ = knn_core_distances(data, min_pts, fetch_knn=False)
    rng = np.random.default_rng(seed)
    rows = rng.choice(len(data), min(sample, len(data)), replace=False)
    d2 = (
        np.sum(data[rows] ** 2, axis=1)[:, None]
        + np.sum(data**2, axis=1)[None, :]
        - 2.0 * data[rows] @ data.T
    )
    want = np.sqrt(np.maximum(np.sort(d2, axis=1)[:, min_pts - 2], 0.0))
    got = core[rows]
    abs_err = np.abs(got - want)
    rel_err = abs_err / np.maximum(want, 1e-30)
    return float(abs_err.max()), float(rel_err.max())


def bounds_probe(data, y, min_pts, cap, seed=0, n_rows=2048, n_pivots=8,
                 proj_dims=8):
    """Exclusion-rate analytics for three rescan pruning bounds at high d
    (VERDICT r5 item 5's prototype, measured WITHOUT paying the rescan).

    Geometry = the forced-split regime: each true cluster's rows split into
    cap-sized blocks (what the pipeline's forced splits produce at this
    separation class). For a row sample with EXACT k-NN cores as ball radii
    (the tightest possible ub), measures the fraction of (row, block) pairs
    excluded by:

    - ``ball``: the production centroid/radius bound d(i,c_B) - r_B > ub;
    - ``pivot``: sample-pivot triangle bounds — max over P pivots of
      max(d(i,p) - hi_p(B), lo_p(B) - d(i,p)) > ub, with [lo,hi] the
      block's distance interval to each pivot (strictly tighter family);
    - ``proj``: orthogonal-projection contraction — the same centroid/radius
      test in an r-dim projection (projected distances lower-bound true
      ones; projected radii shrink ~sqrt(r/d)).

    Split by same-cluster vs other-cluster blocks: the high-d question is
    whether ANY bound can exclude same-cluster blocks (theory says no —
    covering a d=28 gaussian with balls of radius < core needs exp(d)
    balls; this measures how far from 'no' the practical bounds land).
    """
    rng = np.random.default_rng(seed)
    n, d = data.shape
    # Forced-split blocks: cluster-sorted rows cut into cap-sized chunks.
    order = np.argsort(y, kind="stable")
    block_of = np.empty(n, np.int64)
    block_of[order] = np.arange(n) // cap
    blocks = np.unique(block_of)
    g = len(blocks)
    centroid = np.stack([data[block_of == b].mean(axis=0) for b in blocks])
    radius = np.array([
        np.sqrt(((data[block_of == b] - centroid[i]) ** 2).sum(axis=1)).max()
        for i, b in enumerate(blocks)
    ])
    block_cluster = np.array([y[block_of == b][0] for b in blocks])

    rows = rng.choice(n, n_rows, replace=False)
    from hdbscan_tpu.ops.tiled import knn_core_distances_rows

    ub = knn_core_distances_rows(data, rows, min_pts)

    x = data[rows]
    dc = np.sqrt(
        np.maximum(
            (x**2).sum(1)[:, None] + (centroid**2).sum(1)[None, :]
            - 2 * x @ centroid.T,
            0,
        )
    )
    ball_lb = dc - radius[None, :]

    piv = data[rng.choice(n, n_pivots, replace=False)]
    dp_rows = np.sqrt(
        np.maximum(
            (x**2).sum(1)[:, None] + (piv**2).sum(1)[None, :]
            - 2 * x @ piv.T,
            0,
        )
    )  # (rows, P)
    lo = np.empty((g, n_pivots))
    hi = np.empty((g, n_pivots))
    for i, b in enumerate(blocks):
        seg = data[block_of == b]
        dpb = np.sqrt(
            np.maximum(
                (seg**2).sum(1)[:, None] + (piv**2).sum(1)[None, :]
                - 2 * seg @ piv.T,
                0,
            )
        )
        lo[i] = dpb.min(axis=0)
        hi[i] = dpb.max(axis=0)
    pivot_lb = np.maximum(
        dp_rows[:, None, :] - hi[None, :, :], lo[None, :, :] - dp_rows[:, None, :]
    ).max(axis=2)  # (rows, G)
    pivot_lb = np.maximum(pivot_lb, ball_lb)  # family includes the ball test

    q, _ = np.linalg.qr(rng.normal(size=(d, proj_dims)))
    xp = data @ q  # (n, r) orthogonal projection: contraction of distances
    cp = np.stack([xp[block_of == b].mean(axis=0) for b in blocks])
    rp = np.array([
        np.sqrt(((xp[block_of == b] - cp[i]) ** 2).sum(axis=1)).max()
        for i, b in enumerate(blocks)
    ])
    dcp = np.sqrt(
        np.maximum(
            (xp[rows] ** 2).sum(1)[:, None] + (cp**2).sum(1)[None, :]
            - 2 * xp[rows] @ cp.T,
            0,
        )
    )
    proj_lb = dcp - rp[None, :]

    same = block_cluster[None, :] == y[rows][:, None]
    out = {}
    for name, lb in (("ball", ball_lb), ("pivot", pivot_lb), ("proj", proj_lb)):
        excl = lb > ub[:, None]
        out[f"{name}_excl_same"] = round(float(excl[same].mean()), 4)
        out[f"{name}_excl_other"] = round(float(excl[~same].mean()), 4)
    out.update(
        n_rows=n_rows, n_blocks=int(g), n_pivots=n_pivots,
        proj_dims=proj_dims,
        mean_radius=round(float(radius.mean()), 3),
        mean_core=round(float(ub.mean()), 3),
    )
    return out


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
    dims_list = [int(x) for x in (sys.argv[2] if len(sys.argv) > 2 else "28,90").split(",")]
    modes = (sys.argv[3] if len(sys.argv) > 3 else "oracle,exact,bound05").split(",")
    sep_class = float(sys.argv[4]) if len(sys.argv) > 4 else 9.0
    min_pts = 8
    cap = 16384
    for dims in dims_list:
        # HEPMASS-class difficulty: few clusters. Separation scales with
        # sqrt(d): within-cluster nearest-neighbor distances concentrate at
        # ~sigma*sqrt(2d), so a FIXED center separation that is decisive at
        # d=10 blends clusters at d=90 — 3*sqrt(d) keeps the difficulty in
        # the same class as the sep-9 rows at d=10 (sep_class argv scales
        # it: 7 -> the overlapping stress class).
        n_cl = 8
        mcs = max(64, n // 200)
        sep = (sep_class / 3.0) * float(np.sqrt(dims))
        data, y = make_gauss(n, dims=dims, n_clusters=n_cl, separation=sep, seed=4)
        base = dict(
            min_points=min_pts, min_cluster_size=mcs, processing_units=cap,
            seed=0, k=0.01,
        )
        exact_labels = None
        for mode in modes:
            tracer = Tracer(stream=sys.stderr)
            fsnap = flops_counter.snapshot()
            t0 = time.time()
            if mode == "oracle":
                abs_e, rel_e = oracle_core_check(data, min_pts)
                rec = {
                    "config": "oracle_core_check",
                    "n": n,
                    "dims": dims,
                    "core_abs_err_max": round(abs_e, 8),
                    "core_rel_err_max": round(rel_e, 8),
                    "wall_s": round(time.time() - t0, 2),
                }
                print(json.dumps(rec), flush=True)
                continue
            if mode == "bounds":
                rec = {
                    "config": "bounds_probe",
                    "n": n,
                    "dims": dims,
                    "sep_class": sep_class,
                    **bounds_probe(data, y, min_pts, cap),
                }
                rec["wall_s"] = round(time.time() - t0, 2)
                print(json.dumps(rec), flush=True)
                continue
            if mode == "exact":
                r = exact.fit(data, HDBSCANParams(**base), trace=tracer)
                exact_labels = r.labels
            elif mode == "bound05":
                r = mr_hdbscan.fit(
                    data, HDBSCANParams(**base, boundary_quality=0.05),
                    trace=tracer,
                )
            elif mode == "db":
                # The plain recursive-sampling + bubbles pipeline, no
                # boundary phase (per-block cores, bubble-weight pooling,
                # no refinement — the reference-faithful cost shape). At
                # d >= 28 this is the RIGHT tool: within-cluster block
                # radii (~sigma*sqrt(2d)) exceed k-NN cores, so the
                # boundary rescan's block pruning cannot exclude any
                # same-cluster window and its work degenerates toward
                # O(m * n) (measured: the 10.5M x 28 bound05 rescan
                # projected ~1e18 FLOPs); meanwhile seams at this
                # separation class are empty, so per-block core inflation
                # does not move the flat cut.
                r = mr_hdbscan.fit(
                    data,
                    HDBSCANParams(
                        **base,
                        global_core_distances=False,
                        exact_inter_edges=False,
                        refine_iterations=0,
                    ),
                    trace=tracer,
                )
            else:
                raise ValueError(mode)
            wall = time.time() - t0
            rec = {
                "config": mode,
                "n": n,
                "dims": dims,
                "sep_class": sep_class,
                "min_cluster_size": mcs,
                "wall_s": round(wall, 2),
                "ari_truth": round(float(adjusted_rand_index(r.labels, y)), 4),
                **phase_stats(fsnap, wall),
            }
            if exact_labels is not None and mode != "exact":
                rec["ari_exact"] = round(
                    float(adjusted_rand_index(r.labels, exact_labels)), 4
                )
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
