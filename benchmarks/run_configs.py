"""The five BASELINE.json benchmark configs, one JSON line each.

Run on the real TPU chip (do not force CPU):

    python benchmarks/run_configs.py [--quick]

Configs (BASELINE.json "configs"):
  1. HDBSCAN* single-partition Euclidean (dataset.txt, minPts=4)
  2. HDBSCAN* (exact, blocked Borůvka) Euclidean on Skin_NonSkin —
     TWO rows: literal (minPts=16) and calibrated (minPts=8 + dedup)
  3. MR-HDBSCAN* with data bubbles + recursive-sampling partitioner —
     TWO rows: literal (8 partitions, minPts=16) and calibrated
  4. Alternate distance plug-ins: Manhattan (Skin 8k) + cosine on a
     directional set (Skin cosine is degenerate — see the config 4 comment)
  5. 64-partition random split with inter-partition MST merge

Reference wall-clock baselines (BASELINE.md, seconds): Skin DB = 60.19,
Skin RB (exact) = 1743.93. ``vs_baseline`` compares like with like: config 2
and 5 against RB, config 3 against DB; configs 1 and 4 have no bundled
baseline (reference ran Iris interactively and never timed the plug-ins) and
report ``vs_baseline: null``.

Quality is reported as ARI against the bundled class labels with
noise-as-singletons (the reference's protocol, ResearchReport.pdf §5.2).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

IRIS = "/root/reference/数据集/dataset.txt"
SKIN = "/root/reference/数据集/Skin_NonSkin.txt"
SKIN_DB_BASELINE = 60.19
SKIN_RB_BASELINE = 1743.93

# Calibrated Skin macro-structure parameters (see BASELINE.md north star):
# the exact condensed tree at minPts=8, minClSize=3000 resolves the 2-class
# ground truth at ARI ~0.69 (vs the paper's exact 0.441).
SKIN_MP, SKIN_MCS = 8, 3000


def emit(name: str, wall: float, baseline: float | None, **extra) -> None:
    print(
        json.dumps(
            {
                "metric": name,
                "value": round(wall, 3),
                "unit": "s",
                "vs_baseline": round(baseline / wall, 3) if baseline else None,
                **extra,
            }
        ),
        flush=True,
    )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="subsample Skin 10x")
    ap.add_argument("--configs", default="1,2,3,4,5")
    args = ap.parse_args()
    which = {int(c) for c in args.configs.split(",")}

    from hdbscan_tpu.config import HDBSCANParams
    from hdbscan_tpu.core import tree as tree_mod
    from hdbscan_tpu.models import exact, hdbscan, mr_hdbscan
    from hdbscan_tpu.utils.evaluation import adjusted_rand_index

    raw = np.loadtxt(SKIN)
    if args.quick:
        raw = raw[::10]
    skin, truth = raw[:, :3], raw[:, 3].astype(np.int64)
    if args.quick:
        # Subsampled runs must not claim baseline multiples.
        global SKIN_DB_BASELINE, SKIN_RB_BASELINE
        SKIN_DB_BASELINE = SKIN_RB_BASELINE = None

    def ari(labels):
        return round(adjusted_rand_index(labels, truth, noise_as_singletons=True), 4)

    if 1 in which:
        iris = np.loadtxt(IRIS)
        params = HDBSCANParams(min_points=4, min_cluster_size=4)
        hdbscan.fit(iris, params)  # warm
        t0 = time.monotonic()
        r = hdbscan.fit(iris, params)
        emit(
            "iris_single_partition",
            time.monotonic() - t0,
            None,
            clusters=len(set(r.labels[r.labels > 0].tolist())),
        )

    # Configs 2 and 3 emit TWO rows each (the unified benchmark story,
    # VERDICT r1 item 4): "literal" = the BASELINE.json parameterization
    # verbatim (minPts=16 / 8-partition capacity, rows as-is), "calibrated" =
    # the macro-structure setting the headline bench uses (minPts=8,
    # dedup_points — chosen against ground truth and labeled as such).
    if 2 in which:
        for tag, params in (
            (
                "literal",
                HDBSCANParams(min_points=16, min_cluster_size=SKIN_MCS),
            ),
            (
                "calibrated",
                HDBSCANParams(
                    min_points=SKIN_MP, min_cluster_size=SKIN_MCS, dedup_points=True
                ),
            ),
        ):
            exact.fit(skin, params)  # warm (all configs time warm-compile runs)
            t0 = time.monotonic()
            r = exact.fit(skin, params)
            emit(
                f"skin_exact_rb_{tag}",
                time.monotonic() - t0,
                SKIN_RB_BASELINE,
                ari=ari(r.labels),
                min_points=params.min_points,
                dedup=params.dedup_points,
            )

    if 3 in which:
        for tag, params in (
            (
                "literal",  # 8 partitions of the 245k rows, as BASELINE.json
                HDBSCANParams(
                    min_points=16,
                    min_cluster_size=SKIN_MCS,
                    processing_units=32768,
                    k=0.01,
                    seed=0,
                ),
            ),
            (
                "calibrated",  # the headline bench's DB setting
                HDBSCANParams(
                    min_points=SKIN_MP,
                    min_cluster_size=SKIN_MCS,
                    processing_units=8192,
                    k=0.03,
                    seed=0,
                    dedup_points=True,
                ),
            ),
        ):
            mr_hdbscan.fit(skin, params)  # warm (full shapes)
            t0 = time.monotonic()
            r = mr_hdbscan.fit(skin, params)
            emit(
                f"skin_mr_db_{tag}",
                time.monotonic() - t0,
                SKIN_DB_BASELINE,
                ari=ari(r.labels),
                levels=r.n_levels,
                min_points=params.min_points,
                processing_units=params.processing_units,
                dedup=params.dedup_points,
            )

    if 4 in which:
        sub = skin[:: max(1, len(skin) // 8192)]
        sub_truth = truth[:: max(1, len(skin) // 8192)]
        params = HDBSCANParams(
            min_points=8, min_cluster_size=100, dist_function="manhattan"
        )
        hdbscan.fit(sub, params)  # warm
        t0 = time.monotonic()
        r = hdbscan.fit(sub, params)
        emit(
            "skin8k_manhattan",
            time.monotonic() - t0,
            None,
            ari=round(
                adjusted_rand_index(r.labels, sub_truth, noise_as_singletons=True), 4
            ),
        )
        # Cosine on Skin is DEGENERATE (resolved r1 finding): RGB rows are
        # near-collinear rays — 13.8% of pairs sit at cosine distance < 1e-3,
        # minPts=16 cosine core distances are ~1e-5, and 256 all-zero rows
        # have no direction at all — so every cosine clustering of Skin
        # collapses to one cluster (ARI 0 regardless of implementation; see
        # utils/datasets.make_directional docstring for the numbers). The
        # cosine plug-in leg therefore runs on a dataset whose structure IS
        # angular: direction clusters with random magnitudes, where cosine
        # separates cleanly and Euclidean cannot.
        from hdbscan_tpu.utils.datasets import make_directional

        dpts, dtruth = make_directional(8192, dims=8, n_clusters=6, seed=0)
        for metric in ("cosine", "euclidean"):
            params = HDBSCANParams(
                min_points=8, min_cluster_size=100, dist_function=metric
            )
            hdbscan.fit(dpts, params)  # warm
            t0 = time.monotonic()
            r = hdbscan.fit(dpts, params)
            emit(
                f"directional8k_{metric}",
                time.monotonic() - t0,
                None,
                ari=round(
                    adjusted_rand_index(r.labels, dtruth, noise_as_singletons=True), 4
                ),
                note="cosine plug-in leg; Skin cosine is degenerate (see comment)",
            )

    if 5 in which:
        exact.mst_edges_random_blocks(skin, SKIN_MP, n_parts=64, seed=0)  # warm
        t0 = time.monotonic()
        u, v, w, core = exact.mst_edges_random_blocks(
            skin, SKIN_MP, n_parts=64, seed=0
        )
        tree, labels = tree_mod.extract_clusters(
            len(skin), u, v, w, SKIN_MCS, self_levels=core
        )
        emit(
            "skin_random_blocks_64_merge",
            time.monotonic() - t0,
            SKIN_RB_BASELINE,
            ari=ari(labels),
            edges=len(u),
        )


if __name__ == "__main__":
    main()
