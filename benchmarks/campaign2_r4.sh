#!/bin/bash
# Round-4 follow-up campaign — sequential (one TPU job at a time).
# H/I: the two-phase probe rescan A/B at 4M/8M (vs legs E/F which ran the
#      pre-probe code) — the VERDICT item-1 scaling evidence.
# J:   glue_rows=-1 quality probe at the 4M stress shape (the r3-054ef0f
#      composition behind the 0.9754 high-water mark).
# K:   45-seed Skin consensus at 9 draws (cons5 reached std 0.012; target
#      <= 0.01).
# L:   pallas high-d legs re-run under the scale-aware tolerance.
# M:   bench.py (median-of-3 protocol smoke on the real chip).
set -u
cd /root/repo
mkdir -p logs_r4
B=benchmarks
log() { echo "[campaign2 $(date +%H:%M:%S)] $*" >> logs_r4/campaign.log; }

log "H: 4M sep9 bound05 (two-phase probe)"
python $B/boundary_eval.py 4000000 9.0 bound05 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/4M9_probe.log
log "H done rc=$?"

log "I: 8M sep9 bound05 (two-phase probe)"
python $B/boundary_eval.py 8000000 9.0 bound05 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/8M9_probe.log
log "I done rc=$?"

log "G2: HEPMASS-class 10.5M x 28d plain-DB pipeline"
python $B/highdim_eval.py 10500000 28 db \
  >> $B/highdim_r4.jsonl 2> logs_r4/hepmass_10M5_db.log
log "G2 done rc=$?"

log "J: 4M sep7 bound05 glue_rows=-1"
python $B/boundary_eval.py 4000000 7.0 bound05 glue_rows=-1 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/4M7_deepglue.log
log "J done rc=$?"

log "K: skin 45-seed consensus sweep (cons9)"
python $B/seed_sweep.py 45 skin cons9 \
  >> $B/seed_sweep45_skin_r4.jsonl 2> logs_r4/sweep_cons9.log
log "K done rc=$?"

log "L: pallas high-d legs rerun"
python $B/pallas_knn_bench.py --datasets gauss500k_d28,gauss500k_d90 \
  >> $B/pallas_r4.jsonl 2> logs_r4/pallas_highd2.log
log "L done rc=$?"

log "M: bench.py median-of-3"
python bench.py > logs_r4/bench_smoke.json 2> logs_r4/bench_smoke.log
log "M done rc=$?"

log "campaign2 complete"
