"""Quality/wall evaluation of the boundary-aware hybrid mode (config.boundary_quality).

Compares, on a Gauss-family synthetic (the paper's evaluation shape):
  exact      — tiled global Borůvka (ground truth tree)
  compat     — per-block cores, no glue, no refine (reference-faithful, weak)
  boundary   — the hybrid: seam-margin boundary set, exact cores + glue on it
  fullq      — global cores + full glue + refine (round-1 default, O(n²) heavy)

Emits one JSON line per run: {config, n, dims, sep, wall_s, ari_truth, ari_exact}.
Usage: python benchmarks/boundary_eval.py [n] [separation] [modes_csv] [key=value ...]

Trailing key=value pairs are HDBSCANParams overrides applied to every
non-exact mode (e.g. ``glue_factor=6 boundary_alpha=1.0``), parsed by the
CLI flag vocabulary (config.HDBSCANParams.from_args) and echoed in the JSON
record's ``overrides`` field.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import exact, mr_hdbscan
from hdbscan_tpu.utils.datasets import make_gauss
from hdbscan_tpu.utils.evaluation import adjusted_rand_index


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
    # Separation 7.0 is the DISCRIMINATING regime (measured, round 2):
    # at 12.0 every mode lands ARI 1.0 (nothing to compare); at 5.0 even the
    # exact tree only reaches ARI 0.33 vs truth and flat cuts are unstable,
    # so approx-vs-exact ARI measures cut noise, not tree quality. At 7.0
    # exact scores ~0.94 — the paper's Gauss difficulty class — and mode
    # quality differences are real tree differences.
    sep = float(sys.argv[2]) if len(sys.argv) > 2 else 7.0
    modes = (sys.argv[3] if len(sys.argv) > 3 else "exact,compat,bound05,fullq").split(",")
    overrides = {}
    if len(sys.argv) > 4:
        # Keys come from argv, not a value-vs-default diff: an explicit
        # override that happens to EQUAL a dataclass default must still
        # override the script's base/config values.
        from hdbscan_tpu.config import FLAG_FIELDS

        parsed = HDBSCANParams.from_args(sys.argv[4:])
        overrides = {
            FLAG_FIELDS[a.partition("=")[0]][0]: getattr(
                parsed, FLAG_FIELDS[a.partition("=")[0]][0]
            )
            for a in sys.argv[4:]
        }
    dims, n_clusters = 10, 30
    # Dense per-block MST needs cap^2 x ~8 f32 temps in HBM: 16384 (~8.6 GB)
    # is the single-chip ceiling; 32768+ OOMs a v5e (15.75 GB).
    cap = 16384
    mcs = max(64, n // 200)
    data, y = make_gauss(n, dims=dims, n_clusters=n_clusters, separation=sep, seed=2)
    base = dict(
        min_points=8, min_cluster_size=mcs, processing_units=cap, seed=0, k=0.01
    )

    configs = {
        "compat": dict(
            global_core_distances=False, exact_inter_edges=False, refine_iterations=0
        ),
        "bound02": dict(boundary_quality=0.02),
        "bound05": dict(boundary_quality=0.05),
        "bound10": dict(boundary_quality=0.10),
        "fullq": dict(),
    }

    # Exact labels persist across invocations so each mode can run in its own
    # process (fresh device state) and still report ARI vs the exact tree.
    import os

    cache = f"/tmp/beval_exact_{n}_{sep}_{mcs}.npy"
    exact_labels = np.load(cache) if os.path.exists(cache) else None
    from hdbscan_tpu.utils.tracing import Tracer

    from hdbscan_tpu.utils.flops import counter as flops_counter
    from hdbscan_tpu.utils.flops import phase_stats

    for mode in modes:
        tracer = Tracer(stream=sys.stderr)  # per-stage walls for the record
        fsnap = flops_counter.snapshot()
        t0 = time.time()
        if mode == "exact":
            r = exact.fit(data, HDBSCANParams(**base), trace=tracer)
            exact_labels = r.labels
            np.save(cache, exact_labels)
        else:
            p = HDBSCANParams(**{**base, **configs[mode], **overrides})
            r = mr_hdbscan.fit(data, p, trace=tracer)  # consensus inside
        wall = time.time() - t0
        rec = {
            "config": mode,
            # Overrides only apply to non-exact modes; echoing them on the
            # exact row would attribute the baseline to a config it never ran.
            **({"overrides": overrides} if overrides and mode != "exact" else {}),
            "n": n,
            "dims": dims,
            "sep": sep,
            "min_cluster_size": mcs,
            "processing_units": cap,
            "wall_s": round(wall, 2),
            "ari_truth": round(float(adjusted_rand_index(r.labels, y)), 4),
            **phase_stats(fsnap, wall),
        }
        if exact_labels is not None and mode != "exact":
            rec["ari_exact"] = round(
                float(adjusted_rand_index(r.labels, exact_labels)), 4
            )
        # Persist labels so any run can be re-scored post-hoc (e.g. against
        # an exact tree computed LATER — the r4 glue-dial question needed
        # exactly this and leg J's labels were gone).
        otag = "_".join(
            f"{k}={v}" for k, v in sorted(overrides.items())
        ) if mode != "exact" else ""
        np.save(f"/tmp/beval_labels_{mode}_{otag}_{n}_{sep}_{mcs}.npy", r.labels)
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
