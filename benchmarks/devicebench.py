"""Device-timed kernel microbench — on-chip rate vs dispatch overhead.

VERDICT r4 "what's missing" #1: every phase MFU figure (0.03-0.45%) divides
analytic FLOPs by WALL that includes host candidate building, chunked
dispatch round trips, and tunnel fetches — so "the pipeline is
dispatch/transfer-bound, kernels are not worth optimizing" (ROADMAP r4 item
6) was asserted, never isolated. This bench isolates it:

- ``dispatch_latency``: median round trip of a trivial program — the
  per-dispatch floor the tunnel imposes.
- ``matmul_floor``: the tiled euclidean distance expansion with a ONE-PASS
  min reduction instead of top_k, one big program, block_until_ready-timed.
  The arithmetic ceiling of any scan schedule on this chip.
- ``scan_body``: the production ``_knn_core_scan`` body (distance + per-tile
  ``lax.top_k`` merge) as ONE program on the same shape. matmul_floor vs
  scan_body = the price of exact selection; scan_body vs scan_e2e = the
  price of chunked dispatch + transfers.
- ``scan_e2e``: the public ``knn_core_distances`` wall on the same data
  (chunked dispatch, host round trips) — what the pipeline actually pays.
- ``rescan_chunk_T{n}``: the boundary rescan's ``_knn_window_merge_chunk``
  at production geometry (256-row tiles x 4-tile windows), chained
  donated-buffer calls at two chunk sizes — the dispatch-amortization curve
  of the phase that dominates multi-M walls.
- ``fused_body`` / ``scan_e2e_fused`` / ``rescan_chunk_fused_T{n}``: the r6
  fused distance+selection kernel (``ops/pallas_knn``) on the SAME shapes —
  selection stays in VMEM registers instead of round-tripping tiles through
  ``lax.top_k``, which r5 measured at ~90% of on-chip scan time
  (scan_body_guarded vs matmul_floor). Off-TPU these legs run the Pallas
  INTERPRETER (orders of magnitude slower than compiled XLA), so they are
  gated to small ``--n`` smoke rows there; interpreter rates validate the
  wiring, not TPU throughput.
- ``finalize_reference`` / ``finalize_vectorized``: the host condensed-tree
  engines (``core/tree.py`` vs ``core/tree_vec.py``, README "Finalize
  pipeline") on the same Skin-shaped merge forest — condense + EOM
  propagate + flat labels, bitwise-checked. Host-only leg (no device);
  the ``vs_reference`` ratio is the tree_backend acceptance figure.
- ``ring_scan`` / ``ring_e2e``: the ring-sharded scan engine
  (``parallel/ring.py``, README "Scaling out") vs the host path on the same
  rows — raw scan and ``exact.fit`` end-to-end. TPU targets: >= 0.8x linear
  scaling efficiency on 8 chips, no 1-chip regression vs host; CPU rows
  are wiring smoke checks marked ``cpu_smoke`` (see ``bench_ring_scan``).
- ``rpforest_build`` / ``rpforest_e2e``: the approximate-neighbor engine
  (``ops/rpforest.py``, README "Approximate neighbors") — forest build
  wall, then ``rpforest_core_distances`` end-to-end against the exact
  O(n^2 d) scan on the same rows, with recomputed recall@k and a paired
  full-fit ARI-vs-exact. Acceptance: ``vs_exact >= 3`` at n=200k,
  leaf_size=1024.
- ``fused_forest_*``: the r16 fused forest-query program
  (``ops/pallas_forest``, README "Kernel depth") — leaf-scan and rescan
  candidate-panel phase pairs, unfused production chain vs the fused
  kernel body vs the actual Pallas program (full batch on TPU,
  ``interpret:true`` wiring rows off it), with modeled roofline
  ``ai_flops_per_byte`` per row. Acceptance: body >= 1.5x unfused
  ``gflops_s`` at the 200k proxy; arithmetic intensity up on both scan
  phases (the unfused chain round-trips the candidate matrix through
  HBM).

FLOP convention matches ``utils/flops`` (2*rows*cols*d logical; the
f32-HIGHEST cross matmul runs ~6 bf16 passes, so a perfectly MXU-bound
euclidean scan tops out near PEAK/6 — compare legs RELATIVE to that
ceiling). Counterpart being replaced: the reference's runtime tables
(ResearchReport.pdf §5.4) — here the table is per-kernel, on-device.

Rows append to ``benchmarks/devicebench_r6.jsonl`` with full config echo
(r5 baseline rows: ``devicebench_r5.jsonl``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

def _early_flag(name: str, default: str) -> str:
    """Read ``--name VALUE``/``--name=VALUE`` from sys.argv before argparse
    runs — the compile-cache config must win before the first jit."""
    argv = sys.argv
    for i, a in enumerate(argv):
        if a == name and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith(name + "="):
            return a.split("=", 1)[1]
    return default


enable_persistent_compilation_cache(_early_flag("--compile-cache", "auto"))

from hdbscan_tpu.core.distances import pairwise_distance
from hdbscan_tpu.utils.flops import PEAK_FLOPS


def _time_call(fn, iters: int, warmup: int = 1):
    """Median wall of ``iters`` calls, after ``warmup``.

    Each call's (small) result is fetched with ``jax.device_get``: on the
    tunneled axon platform ``block_until_ready`` returns without waiting for
    the remote device (measured: a 1.8 TFLOP program "completed" in 0.1 ms),
    so a host fetch is the only reliable completion barrier. Timed programs
    must return a REDUCED result (scalar/vector) so the fetch itself stays
    off the critical path (~10-25 MB/s tunnel)."""
    for _ in range(warmup):
        jax.device_get(fn())
    walls = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.device_get(fn())
        walls.append(time.perf_counter() - t0)
    return float(np.median(walls)), [round(min(walls), 4), round(max(walls), 4)]


def _emit(out_path, row):
    row = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"), **row}
    print(json.dumps(row), flush=True)
    with open(out_path, "a") as f:
        f.write(json.dumps(row) + "\n")


@partial(jax.jit, static_argnames=("metric", "row_tile", "col_tile"))
def _dist_min_scan(rows, data, valid, metric: str, row_tile: int, col_tile: int):
    """The scan loop structure of ``_knn_core_scan`` with the cheapest
    possible reduction (rowwise running min) in place of top_k: the
    arithmetic floor of the schedule."""
    n_pad = data.shape[0]
    n_col_tiles = n_pad // col_tile
    inf = jnp.array(jnp.inf, data.dtype)

    def row_step(r):
        xr = jax.lax.dynamic_slice_in_dim(rows, r * row_tile, row_tile)

        def col_step(c, best):
            xc = jax.lax.dynamic_slice_in_dim(data, c * col_tile, col_tile)
            vc = jax.lax.dynamic_slice_in_dim(valid, c * col_tile, col_tile)
            d = pairwise_distance(xr, xc, metric)
            d = jnp.where(vc[None, :], d, inf)
            return jnp.minimum(best, jnp.min(d, axis=1))

        return jax.lax.fori_loop(
            0, n_col_tiles, col_step, jnp.full((row_tile,), jnp.inf, data.dtype)
        )

    n_row_tiles = rows.shape[0] // row_tile
    return jax.lax.map(row_step, jnp.arange(n_row_tiles)).reshape(-1)


def bench_exact_scan(out_path, n=500_000, d=28, k=15, iters=3, seed=0):
    """matmul_floor / scan_body / scan_e2e triplet at the 500k x 28 shape
    (the r4 pallas-campaign shape: XLA 41.9 s, pallas dot 30.3 s)."""
    from hdbscan_tpu.ops.tiled import _knn_core_scan, _tile_sizes, _pad_rows

    rng = np.random.default_rng(seed)
    data = rng.normal(size=(n, d)).astype(np.float32)
    row_tile, col_tile, n_pad = _tile_sizes(n, 1024, 8192)
    data_p = jnp.asarray(_pad_rows(data, n_pad))
    valid_p = jnp.asarray(np.arange(n_pad) < n)
    # One big program: ~1.8 TFLOP logical at the default 500k x 28 (clamped
    # so small --n smoke runs don't credit rows the slice can't deliver).
    chunk = min(1 << 16, n_pad)
    rows = data_p[:chunk]
    flops = 2.0 * chunk * n_pad * d

    base = dict(
        n=n, d=d, k=k, n_pad=n_pad, chunk_rows=chunk, row_tile=row_tile,
        col_tile=col_tile, iters=iters, seed=seed, device=str(jax.devices()[0]),
        peak_flops=PEAK_FLOPS,
    )

    wall, spread = _time_call(
        lambda: jnp.sum(
            _dist_min_scan(rows, data_p, valid_p, "euclidean", row_tile, col_tile)
        ),
        iters,
    )
    _emit(out_path, dict(
        leg="matmul_floor", wall_s=round(wall, 4), spread_s=spread,
        gflops=round(flops / 1e9, 1), gflops_s=round(flops / wall / 1e9, 1),
        mfu=round(flops / wall / PEAK_FLOPS, 5), **base,
    ))

    for guarded in (False, True):
        wall, spread = _time_call(
            lambda: jnp.sum(
                _knn_core_scan(
                    rows, data_p, valid_p, k, "euclidean", row_tile, col_tile,
                    guarded=guarded,
                )[0]
            ),
            iters,
        )
        _emit(out_path, dict(
            leg="scan_body" + ("_guarded" if guarded else ""),
            wall_s=round(wall, 4), spread_s=spread,
            gflops=round(flops / 1e9, 1), gflops_s=round(flops / wall / 1e9, 1),
            mfu=round(flops / wall / PEAK_FLOPS, 5), **base,
        ))

    from hdbscan_tpu.ops.tiled import knn_core_distances

    flops_full = 2.0 * n_pad * n_pad * d
    for guarded in (False, True):
        walls = []
        # One untimed warmup so the recorded median excludes one-time XLA
        # compiles (the pre-fix rows mixed up to ~50% compile into the leg
        # this bench exists to adjudicate — r5 review finding).
        knn_core_distances(
            data, k + 1, "euclidean", backend="xla",
            fetch_knn=False, guarded=guarded,
        )
        for _ in range(max(1, iters - 1)):
            t0 = time.perf_counter()
            knn_core_distances(
                data, k + 1, "euclidean", backend="xla",
                fetch_knn=False, guarded=guarded,
            )
            walls.append(time.perf_counter() - t0)
        wall = float(np.median(walls))
        _emit(out_path, dict(
            leg="scan_e2e" + ("_guarded" if guarded else ""),
            wall_s=round(wall, 4),
            spread_s=[round(min(walls), 4), round(max(walls), 4)],
            gflops=round(flops_full / 1e9, 1),
            gflops_s=round(flops_full / wall / 1e9, 1),
            mfu=round(flops_full / wall / PEAK_FLOPS, 5), **base,
        ))

    # Fused distance+selection legs (r6 tentpole). fused_body is the
    # kernel-resident analog of scan_body_guarded — one program, chunk rows
    # vs every column, k-best (distance, id) registers merged in VMEM.
    # scan_e2e_fused is the public dispatcher under backend="fused" (host
    # pad + transpose + kth-column fetch included). The gap these legs close
    # is scan_body_guarded vs matmul_floor (~5x at r5).
    from hdbscan_tpu.ops import pallas_knn as pk

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and n > (1 << 14):
        print(
            f"# fused legs skipped: platform={jax.devices()[0].platform!r}, "
            f"n={n} > 16384 — the off-TPU path is the Pallas interpreter "
            "(impractically slow at bench shapes); rerun with --n 4096 for "
            "a wiring smoke row",
            flush=True,
        )
        return
    n_pad_f = max(pk.COL_TILE, pk.ROW_TILE)
    while n_pad_f < n:
        n_pad_f *= 2
    x = np.zeros((n_pad_f, pk.LANES), np.float32)
    x[:n, :d] = data
    colmask = np.full((1, n_pad_f), np.inf, np.float32)
    colmask[0, :n] = 0.0
    xj, xtj, mj = jax.device_put((x, np.ascontiguousarray(x.T), colmask))
    chunk_f = min(chunk, n_pad_f)
    rows_f = xj[:chunk_f]
    flops_f = 2.0 * chunk_f * n_pad_f * d
    fbase = dict(base, n_pad_fused=n_pad_f, chunk_rows_fused=chunk_f,
                 interpret=not on_tpu)

    def run_fused_body():
        dd, _ = pk.knn_fused_pallas(rows_f, xtj, mj, k, interpret=not on_tpu)
        return jnp.sum(jnp.where(jnp.isfinite(dd), dd, 0.0))

    wall, spread = _time_call(run_fused_body, iters)
    _emit(out_path, dict(
        leg="fused_body", wall_s=round(wall, 4), spread_s=spread,
        gflops=round(flops_f / 1e9, 1), gflops_s=round(flops_f / wall / 1e9, 1),
        mfu=round(flops_f / wall / PEAK_FLOPS, 5), **fbase,
    ))

    flops_ff = 2.0 * n_pad_f * n_pad_f * d
    knn_core_distances(
        data, k + 1, "euclidean", backend="fused", fetch_knn=False
    )
    walls = []
    for _ in range(max(1, iters - 1)):
        t0 = time.perf_counter()
        knn_core_distances(
            data, k + 1, "euclidean", backend="fused", fetch_knn=False
        )
        walls.append(time.perf_counter() - t0)
    wall = float(np.median(walls))
    _emit(out_path, dict(
        leg="scan_e2e_fused", wall_s=round(wall, 4),
        spread_s=[round(min(walls), 4), round(max(walls), 4)],
        gflops=round(flops_ff / 1e9, 1),
        gflops_s=round(flops_ff / wall / 1e9, 1),
        mfu=round(flops_ff / wall / PEAK_FLOPS, 5), **fbase,
    ))


def bench_dispatch_latency(out_path, iters=50):
    x = jnp.zeros(8, jnp.float32)
    f = jax.jit(lambda a: a + 1.0)
    wall, spread = _time_call(lambda: f(x), iters, warmup=3)
    _emit(out_path, dict(
        leg="dispatch_latency", wall_s=round(wall, 6), spread_s=spread,
        iters=iters, device=str(jax.devices()[0]),
    ))


def bench_rescan_chunk(out_path, n=1_000_000, d=10, k=15, win_tiles=4,
                       row_tile=256, col_tile=8192, chunk_tiles=(64, 1024),
                       iters=3, seed=0):
    """``_knn_window_merge_chunk`` at production rescan geometry, chained
    donated-buffer calls: the on-chip rate of the phase that dominates
    multi-M boundary walls (r4: 51.9-94.9 GFLOP/s incl. host time)."""
    from hdbscan_tpu.ops.blockscan import (
        _knn_window_merge_chunk,
        _knn_window_merge_chunk_fused,
    )
    from hdbscan_tpu.ops.pallas_knn import LANES

    rng = np.random.default_rng(seed)
    n_pad = -(-n // col_tile) * col_tile
    data = rng.normal(size=(n_pad, d)).astype(np.float32)
    data_dev = jax.device_put(data)
    valid_dev = jax.device_put(np.arange(n_pad) < n)
    n_tiles = n_pad // col_tile
    on_tpu = jax.devices()[0].platform == "tpu"
    fused_ok = on_tpu or n_pad <= (1 << 14)
    if fused_ok:
        # Fused-twin operands (BlockGeometry.fused_operands layout): the
        # lane-padded transpose + 0/inf column mask.
        data_t = np.zeros((LANES, n_pad), np.float32)
        data_t[:d] = data.T
        colmask = np.full((1, n_pad), np.inf, np.float32)
        colmask[0, :n] = 0.0
        data_t_dev, colmask_dev = jax.device_put((data_t, colmask))
    else:
        print(
            f"# rescan fused legs skipped: platform="
            f"{jax.devices()[0].platform!r}, n_pad={n_pad} > 16384 "
            "(interpreter-only off TPU); rerun with --rescan-n 16384 "
            "--rescan-col-tile 2048 --rescan-tiles 16 for a smoke row",
            flush=True,
        )
    base = dict(
        n=n, d=d, k=k, win_tiles=win_tiles, row_tile=row_tile,
        col_tile=col_tile, iters=iters, seed=seed,
        device=str(jax.devices()[0]), peak_flops=PEAK_FLOPS,
    )
    for t_chunk in chunk_tiles:
        m = t_chunk * row_tile
        # Production jobs address CONTIGUOUS runs of the block-sorted copy
        # (each job is one block's rows); random ids would benchmark HBM
        # gather pathology the real path never pays. Each tile's rows sit
        # inside its own window.
        starts = (
            rng.integers(0, max(1, n_tiles - win_tiles), size=t_chunk) * col_tile
        ).astype(np.int32)
        ids = (
            starts[:, None] + np.arange(row_tile, dtype=np.int32)[None, :]
        ).astype(np.int32)
        locs = np.arange(m, dtype=np.int32).reshape(t_chunk, row_tile)
        ids_d, locs_d, starts_d = jax.device_put((ids, locs, starts))
        flops = 2.0 * m * win_tiles * col_tile * d

        def run():
            bd = jnp.full((m + 1, k), jnp.inf, jnp.float32)
            bi = jnp.full((m + 1, k), -1, jnp.int32)
            out = _knn_window_merge_chunk(
                bd, bi, ids_d, locs_d, data_dev, valid_dev, starts_d,
                k, "euclidean", col_tile, win_tiles,
            )[0]
            return jnp.sum(jnp.where(jnp.isfinite(out), out, 0.0))

        # (A "primed second pass over the same windows" leg was tried and
        # removed: identical windows re-merge every sub-k element, so it
        # models neither the production probe/main split — which EXCLUDES
        # probed pairs — nor the guard's real skip behavior, and its
        # derived wall made spread_s incoherent. Production skip evidence
        # comes from the pipeline phase traces instead.)
        wall, spread = _time_call(run, iters)
        _emit(out_path, dict(
            leg=f"rescan_chunk_T{t_chunk}", wall_s=round(wall, 4),
            spread_s=spread, tiles=t_chunk, rows=m,
            gflops=round(flops / 1e9, 1),
            gflops_s=round(flops / wall / 1e9, 1),
            mfu=round(flops / wall / PEAK_FLOPS, 5), **base,
        ))

        if not fused_ok:
            continue
        # Same windows through the fused twin: window tiles reduce to
        # (distance, id) registers on-chip, one kernel per chunk.
        starts_tiles_d = jax.device_put((starts // col_tile).astype(np.int32))

        def run_fused():
            bd = jnp.full((m + 1, k), jnp.inf, jnp.float32)
            bi = jnp.full((m + 1, k), -1, jnp.int32)
            out = _knn_window_merge_chunk_fused(
                bd, bi, ids_d, locs_d, data_dev, data_t_dev, colmask_dev,
                starts_tiles_d, k, col_tile, win_tiles, not on_tpu,
            )[0]
            return jnp.sum(jnp.where(jnp.isfinite(out), out, 0.0))

        wall, spread = _time_call(run_fused, iters)
        _emit(out_path, dict(
            leg=f"rescan_chunk_fused_T{t_chunk}", wall_s=round(wall, 4),
            spread_s=spread, tiles=t_chunk, rows=m, interpret=not on_tpu,
            gflops=round(flops / 1e9, 1),
            gflops_s=round(flops / wall / 1e9, 1),
            mfu=round(flops / wall / PEAK_FLOPS, 5), **base,
        ))


def bench_ring_scan(out_path, n=100_000, d=8, min_pts=16, iters=3, seed=0):
    """Ring-sharded scan engine legs (README "Scaling out").

    - ``ring_scan``: ``parallel.ring.ring_knn_core_distances`` — row shards
      compute against column panels circulating over ``lax.ppermute`` —
      against the host ``knn_core_distances`` on the same rows. The raw
      scan-engine comparison.
    - ``ring_e2e``: ``models.exact.fit`` under ``scan_backend=ring`` vs
      ``scan_backend=host`` — the end-to-end path the CLI ships (core scan
      + every Borůvka round on the ring).

    TPU targets (the numbers this bench exists to adjudicate):

    - 8-chip slice: scaling efficiency ``host_wall / (ring_wall * n_dev)``
      >= 0.8x linear on both legs (panels are in flight during compute, so
      the ring should hide nearly all ICI time at production shapes).
    - 1-chip: no regression vs host (ratio ~1.0 — a 1-device ring is the
      host schedule plus an identity permute).

    CPU meshes exist only via ``--xla_force_host_platform_device_count``
    and share one socket, so CPU ratios say nothing about scaling — those
    rows are wiring smoke checks and are marked ``cpu_smoke=true``.
    """
    from hdbscan_tpu.config import HDBSCANParams
    from hdbscan_tpu.models import exact
    from hdbscan_tpu.ops.tiled import knn_core_distances
    from hdbscan_tpu.parallel.mesh import get_mesh
    from hdbscan_tpu.parallel.ring import ring_knn_core_distances

    if len(jax.devices()) < 2:
        print(
            "# ring legs skipped: single device — the ring scan needs a "
            "multi-device mesh (TPU slice, or "
            "--xla_force_host_platform_device_count for a CPU smoke row)",
            flush=True,
        )
        return
    mesh = get_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    platform = jax.devices()[0].platform
    rng = np.random.default_rng(seed)
    data = rng.normal(size=(n, d)).astype(np.float32)
    base = dict(
        n=n, d=d, min_pts=min_pts, iters=iters, seed=seed, devices=n_dev,
        platform=platform, cpu_smoke=platform != "tpu",
        device=str(jax.devices()[0]), peak_flops=PEAK_FLOPS,
    )
    flops = 2.0 * n * n * d  # logical; host/ring pad differently

    def timed(fn):
        fn()  # untimed warmup — exclude one-time XLA compiles
        walls = []
        for _ in range(max(1, iters - 1)):
            t0 = time.perf_counter()
            fn()
            walls.append(time.perf_counter() - t0)
        return float(np.median(walls)), [
            round(min(walls), 4), round(max(walls), 4),
        ]

    host_wall, host_spread = timed(
        lambda: knn_core_distances(
            data, min_pts, "euclidean", backend="xla", fetch_knn=False
        )
    )
    ring_wall, ring_spread = timed(
        lambda: ring_knn_core_distances(
            data, min_pts, "euclidean", fetch_knn=False, mesh=mesh
        )
    )
    _emit(out_path, dict(
        leg="ring_scan", wall_s=round(ring_wall, 4), spread_s=ring_spread,
        host_wall_s=round(host_wall, 4), host_spread_s=host_spread,
        vs_host=round(host_wall / ring_wall, 3),
        scaling_efficiency=round(host_wall / (ring_wall * n_dev), 3),
        gflops=round(flops / 1e9, 1),
        gflops_s=round(flops / ring_wall / 1e9, 1),
        mfu=round(flops / ring_wall / PEAK_FLOPS, 5), **base,
    ))

    params_host = HDBSCANParams(
        min_points=min_pts, min_cluster_size=64, scan_backend="host"
    )
    params_ring = params_host.replace(scan_backend="ring")
    e2e_host, e2e_host_spread = timed(
        lambda: exact.fit(data, params_host, mesh=mesh)
    )
    e2e_ring, e2e_ring_spread = timed(
        lambda: exact.fit(data, params_ring, mesh=mesh)
    )
    _emit(out_path, dict(
        leg="ring_e2e", wall_s=round(e2e_ring, 4), spread_s=e2e_ring_spread,
        host_wall_s=round(e2e_host, 4), host_spread_s=e2e_host_spread,
        vs_host=round(e2e_host / e2e_ring, 3),
        scaling_efficiency=round(e2e_host / (e2e_ring * n_dev), 3),
        **base,
    ))


def bench_finalize(out_path, n=245_057, iters=3, seed=0, min_cluster_size=3000):
    """Host finalize engines head-to-head (README "Finalize pipeline").

    ``core/tree.py`` (reference) vs ``core/tree_vec.py`` (vectorized) on the
    SAME merge forest: condense + extract (EOM propagate + flat labels), the
    host tail every pipeline pays after the device scans. The synthetic pool
    is Skin-shaped — n ~ Skin_NonSkin rows, lattice-valued edge weights with
    heavy duplicate chains (zero-weight ties), one spanning pool — the
    regime where the reference's per-subtree Python walks are costliest.
    Both engines must agree bitwise (asserted, not sampled); the acceptance
    figure is ``vs_reference`` on the vectorized row (target >= 5x at 245k).
    """
    from hdbscan_tpu.core import tree as T
    from hdbscan_tpu.core import tree_vec as V

    rng = np.random.default_rng(seed)
    # Skin-shaped spanning pool: a handful of clusters that each ERODE one
    # point at a time over distinct increasing weights — the condensed-tree
    # shape clustered data produces, and the regime where the reference's
    # per-node Python walk is costliest — plus a zero-weight duplicate mass
    # (Skin's integer lattice collapses ~80% of rows into tie groups) and
    # cluster joins at large distinct weights.
    n_clusters = 8
    csizes = np.full(n_clusters, n // n_clusters)
    csizes[: n % n_clusters] += 1
    us, vs, ws = [], [], []
    start = 0
    for c in range(n_clusters):
        m = int(csizes[c])
        idx = np.arange(start, start + m)
        us.append(idx[:-1])
        vs.append(idx[1:])
        wc = 1.0 + np.arange(m - 1) * 1e-5 + c * 1e-9
        # Duplicate mass: a fraction of attachments happen at weight 0 and
        # tie-contract into multi-way nodes at the chain bottoms.
        wc[rng.random(m - 1) < 0.3] = 0.0
        ws.append(wc)
        start += m
    heads = np.cumsum(np.concatenate([[0], csizes[:-1]]))
    us.append(heads[:-1])
    vs.append(heads[1:])
    ws.append(100.0 + np.arange(n_clusters - 1, dtype=np.float64))
    u = np.concatenate(us).astype(np.int64)
    v = np.concatenate(vs).astype(np.int64)
    w = np.concatenate(ws)
    forest = T.build_merge_forest(n, u, v, w)
    self_levels = rng.random(n) + 0.5

    def run(eng):
        tree = eng.condense_forest(
            forest, min_cluster_size, self_levels=self_levels
        )
        with np.errstate(invalid="ignore"):
            eng.propagate_tree(tree)
        return tree, eng.flat_labels(tree)

    walls = {}
    out = {}
    base = dict(
        n=n, min_cluster_size=min_cluster_size, iters=iters, seed=seed,
        edges=len(u),
    )
    for name, eng in (("reference", T), ("vectorized", V)):
        run(eng)  # warmup (first-touch allocator noise)
        ws = []
        for _ in range(iters):
            t0 = time.perf_counter()
            out[name] = run(eng)
            ws.append(time.perf_counter() - t0)
        walls[name] = float(np.median(ws))
        row = dict(
            leg=f"finalize_{name}", wall_s=round(walls[name], 4),
            spread_s=[round(min(ws), 4), round(max(ws), 4)],
            clusters=out[name][0].n_clusters, **base,
        )
        if name == "vectorized":
            ref_tree, ref_labels = out["reference"]
            vec_tree, vec_labels = out["vectorized"]
            bitwise = ref_labels.tobytes() == vec_labels.tobytes() and all(
                np.asarray(getattr(ref_tree, f)).tobytes()
                == np.asarray(getattr(vec_tree, f)).tobytes()
                for f in ("parent", "birth", "death", "stability",
                          "num_members", "point_exit_level",
                          "point_last_cluster")
            )
            assert bitwise, "finalize engines diverged — parity bug"
            row["bitwise_match"] = bitwise
            row["vs_reference"] = round(walls["reference"] / walls["vectorized"], 2)
        _emit(out_path, row)


def _dup_proxy_pool(n, seed=0):
    """Skin-shaped duplicate-heavy spanning pool (eligible for the device
    engine: exact-tie lattice weights, no near-tied-unequal pairs).

    Skin's integer lattice collapses ~80% of rows into duplicate groups
    with a heavy head (the biggest tie groups hold thousands of rows); the
    proxy reproduces that with a top-50 geometric head over zero-weight
    duplicate stars plus a near-uniform tail of small groups, joined by a
    chain of distinct lattice weights.
    """
    rng = np.random.default_rng(seed)
    head = np.maximum(2, ((n // 20) * 0.8 ** np.arange(50)).astype(np.int64))
    tail_total = n - int(head.sum())
    tail_n = max(1, int(tail_total / 3.6))
    k_unique = 50 + tail_n
    base = tail_total // tail_n
    sizes = np.full(k_unique, base, np.int64)
    sizes[:50] = head
    sizes[50 : 50 + (tail_total - base * tail_n)] += 1
    starts = np.zeros(k_unique + 1, np.int64)
    np.cumsum(sizes, out=starts[1:])
    us, vs = [], []
    for g in range(k_unique):
        s0, s1 = starts[g], starts[g + 1]
        if s1 - s0 > 1:
            us.append(np.full(s1 - s0 - 1, s0))
            vs.append(np.arange(s0 + 1, s1))
    uz, vz = np.concatenate(us), np.concatenate(vs)
    gi = rng.permutation(k_unique)
    u = np.concatenate([uz, starts[gi[:-1]]])
    v = np.concatenate([vz, starts[gi[1:]]])
    # Dyadic lattice weights (k/1024): exactly float32-representable, so
    # the device engine stays eligible with jax_enable_x64 off.
    w = np.concatenate(
        [np.zeros(len(uz)), 1.0 + np.arange(k_unique - 1) / 1024.0]
    )
    return u.astype(np.int64), v.astype(np.int64), w


def _erosion_proxy_pool(n, seed=0):
    """Random-attachment spanning pool with distinct lattice weights + 30%
    zero-weight duplicate mass — the one-point-at-a-time erosion regime."""
    rng = np.random.default_rng(seed)
    v = np.arange(1, n)
    u = rng.integers(0, v)
    w = 1.0 + np.arange(n - 1) / 16384.0  # dyadic: f32-exact up to ~16
    w[np.random.default_rng(seed + 1).random(n - 1) < 0.3] = 0.0
    return u.astype(np.int64), v, w


def bench_mst_device(out_path, n=245_057, iters=3, seed=0,
                     round_n=50_000, round_d=3, min_pts=8):
    """Device-resident MST -> merge-forest legs (README "Device-resident
    finalize").

    ``mst_round``: the jitted Borůvka ``while_loop`` (``core/mst_device.
    boruvka_mst_device`` — in-jit contraction, one fetch at the end) vs the
    host round loop (``models/exact.mst_edges_from_core`` — per-round label
    round-trips), same data/cores, edge lists asserted identical.

    ``finalize_device``: ``build_merge_forest_device`` (device lexsort +
    union-find event scan, ONE device_get, vectorized host reconstruction)
    vs the host builder — both the native-C and pure-Python engines — on the
    Skin-shaped duplicate-heavy 245k proxy, MergeForest fields asserted
    bitwise equal. Acceptance: ``vs_host_python >= 3x`` at 245k (the
    cuSLINK-style split: GPU/TPU edge program + array dendrogram assembly).
    An erosion-shaped secondary row tracks the chain-heavy regime.
    """
    from hdbscan_tpu.core import mst_device as MD
    from hdbscan_tpu.core import tree as T
    from hdbscan_tpu import native as native_mod
    from hdbscan_tpu.models.exact import mst_edges_from_core
    from hdbscan_tpu.ops.tiled import knn_core_distances

    platform = jax.devices()[0].platform

    # --- mst_round: device round loop vs host round loop -------------------
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-4, 4, size=(8, round_d))
    data = (
        centers[rng.integers(0, 8, round_n)]
        + rng.normal(0, 0.3, (round_n, round_d))
    ).astype(np.float64)
    core, _ = knn_core_distances(
        data, min_pts, fetch_knn=False, dtype=np.float64
    )

    def dev_edges():
        return jax.device_get(
            MD.boruvka_mst_device(data, core, dtype=np.float64)
        )

    res = dev_edges()  # warmup + parity edges
    count = int(res["count"])
    rounds = int(res["rounds"])
    walls_d = []
    for _ in range(iters):
        t0 = time.perf_counter()
        res = dev_edges()
        walls_d.append(time.perf_counter() - t0)
    u_h, v_h, w_h = mst_edges_from_core(data, core, dtype=np.float64)
    walls_h = []
    for _ in range(iters):
        t0 = time.perf_counter()
        u_h, v_h, w_h = mst_edges_from_core(data, core, dtype=np.float64)
        walls_h.append(time.perf_counter() - t0)
    assert count == len(u_h)
    assert (
        np.array_equal(res["u"][:count], u_h)
        and np.array_equal(res["v"][:count], v_h)
        and np.array_equal(res["w"][:count], w_h)
    ), "device Borůvka diverged from the host round loop"
    wd, wh = float(np.median(walls_d)), float(np.median(walls_h))
    _emit(out_path, dict(
        leg="mst_round", n=round_n, d=round_d, min_pts=min_pts,
        platform=platform, rounds=rounds, edges=count, iters=iters,
        device_wall_s=round(wd, 4), host_wall_s=round(wh, 4),
        device_per_round_s=round(wd / max(rounds, 1), 4),
        vs_host=round(wh / wd, 2), edges_bitwise=True,
    ))

    # --- finalize_device: forest build device vs host (native + python) ----
    for tag, (u, v, w) in (
        ("", _dup_proxy_pool(n, seed)),
        ("_erosion", _erosion_proxy_pool(n, seed)),
    ):
        assert MD.supports_inputs(w)
        MD.build_merge_forest_device(n, u, v, w, build_children=False)
        walls_dev = []
        for _ in range(iters):
            t0 = time.perf_counter()
            f_dev = MD.build_merge_forest_device(
                n, u, v, w, build_children=False
            )
            walls_dev.append(time.perf_counter() - t0)
        host = {}
        saved = native_mod._lib, native_mod._lib_tried
        for eng in ("native", "python"):
            native_mod._lib_tried = eng == "python" or saved[1]
            native_mod._lib = None if eng == "python" else saved[0]
            T.build_merge_forest(n, u, v, w)
            ws = []
            for _ in range(iters):
                t0 = time.perf_counter()
                ref = T.build_merge_forest(n, u, v, w)
                ws.append(time.perf_counter() - t0)
            host[eng] = (float(np.median(ws)), ref)
        native_mod._lib, native_mod._lib_tried = saved
        ref = host["native"][1]
        assert f_dev is not None
        assert (
            np.array_equal(f_dev.dist, ref.dist)
            and np.array_equal(f_dev.sizes, ref.sizes)
            and list(f_dev.roots) == [int(r) for r in ref.roots]
            and (
                ref.kids_csr is None
                or (
                    np.array_equal(f_dev.kids_csr[0], ref.kids_csr[0])
                    and np.array_equal(f_dev.kids_csr[1], ref.kids_csr[1])
                )
            )
        ), "device merge forest diverged from the host builder"
        wdev = float(np.median(walls_dev))
        _emit(out_path, dict(
            leg=f"finalize_device{tag}", n=n, edges=len(u),
            platform=platform, iters=iters,
            device_wall_s=round(wdev, 4),
            host_native_wall_s=round(host["native"][0], 4),
            host_python_wall_s=round(host["python"][0], 4),
            vs_host_native=round(host["native"][0] / wdev, 2),
            vs_host_python=round(host["python"][0] / wdev, 2),
            bitwise_match=True,
        ))


def bench_rpforest(out_path, n=200_000, d=8, min_pts=16, k=16, trees=4,
                   leaf_size=1024, rescan_rounds=1, iters=1, seed=0,
                   ari_n=5000, recall_sample=256):
    """Approximate-neighbor engine legs (README "Approximate neighbors").

    - ``rpforest_build``: ``ops/rpforest.build_forest`` wall alone — T
      trees of batched hyperplane rank-splits down to ``leaf_size`` leaves.
    - ``rpforest_e2e``: ``rpforest_core_distances`` (build + per-leaf scan
      + multi-tree merge + ``rescan_rounds`` neighbor-of-neighbor rounds)
      against the exact ``knn_core_distances`` scan on the SAME rows. The
      acceptance figure is ``vs_exact`` (target >= 3x at n=200k,
      leaf_size=1024 — the exact scan is O(n^2 d), the forest
      O(n * trees * leaf_size * d)), alongside query ``rows_per_s``,
      ``recall_at_k`` measured here against a brute-force subsample, and
      ``ari_vs_exact`` from a paired ``exact.fit`` at ``ari_n`` rows
      (full-pipeline agreement, not just neighbor overlap).

    The pool is a 32-center Gaussian mixture — clustered like real fits,
    not a single isotropic blob that would flatter hyperplane splits.
    """
    from hdbscan_tpu.config import HDBSCANParams
    from hdbscan_tpu.models import exact
    from hdbscan_tpu.ops.rpforest import build_forest, rpforest_core_distances
    from hdbscan_tpu.ops.tiled import knn_core_distances
    from hdbscan_tpu.utils.evaluation import adjusted_rand_index

    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, (32, d))
    data = (centers[rng.integers(0, 32, n)]
            + rng.normal(0, 0.6, (n, d))).astype(np.float32)
    platform = jax.devices()[0].platform
    base = dict(
        n=n, d=d, min_pts=min_pts, k=k, trees=trees, leaf_size=leaf_size,
        rescan_rounds=rescan_rounds, seed=seed, platform=platform,
        cpu_smoke=platform != "tpu", device=str(jax.devices()[0]),
    )

    def timed(fn):
        walls = []
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            out = fn()
            walls.append(time.perf_counter() - t0)
        return out, float(np.median(walls)), [
            round(min(walls), 4), round(max(walls), 4),
        ]

    forest, build_wall, build_spread = timed(
        lambda: build_forest(data, trees=trees, leaf_size=leaf_size,
                             seed=seed)
    )
    _emit(out_path, dict(
        leg="rpforest_build", wall_s=round(build_wall, 4),
        spread_s=build_spread, depth=forest.depth,
        leaves=forest.num_leaves, max_leaf=forest.max_leaf, **base,
    ))

    _, exact_wall, exact_spread = timed(
        lambda: knn_core_distances(
            data, min_pts, "euclidean", backend="xla", fetch_knn=False
        )
    )
    (_, knn, idx), rpf_wall, rpf_spread = timed(
        lambda: rpforest_core_distances(
            data, min_pts, "euclidean", k=k, trees=trees,
            leaf_size=leaf_size, rescan_rounds=rescan_rounds, seed=seed,
            return_indices=True, recall_sample=0,
        )
    )

    # Recall vs a brute-force subsample (recomputed here, not trusted from
    # the engine's own counters).
    sample = np.linspace(0, n - 1, min(recall_sample, n)).astype(np.int64)
    kk = idx.shape[1]
    data64 = data.astype(np.float64)
    ids = np.arange(n)
    hits = []
    for s in sample:
        row = ((data64 - data64[s]) ** 2).sum(-1)
        exact_ids = np.lexsort((ids, row))[:kk]  # (dist, id) tie-break
        hits.append(len(np.intersect1d(exact_ids, idx[s])) / kk)
    hits = float(np.mean(hits))

    ari_rng = np.random.default_rng(seed + 1)
    ari_data = (centers[ari_rng.integers(0, 32, ari_n)]
                + ari_rng.normal(0, 0.6, (ari_n, d))).astype(np.float32)
    params = HDBSCANParams(
        min_points=min_pts, min_cluster_size=max(ari_n // 100, 16)
    )
    labels_exact = exact.fit(ari_data, params).labels
    labels_rpf = exact.fit(ari_data, params.replace(
        knn_index="rpforest", rpf_trees=trees,
        rpf_leaf_size=min(leaf_size, max(ari_n // 8, 4 * k)),
        rpf_rescan_rounds=rescan_rounds,
    )).labels
    query_wall = max(rpf_wall - build_wall, 1e-9)
    _emit(out_path, dict(
        leg="rpforest_e2e", wall_s=round(rpf_wall, 4), spread_s=rpf_spread,
        build_wall_s=round(build_wall, 4),
        exact_wall_s=round(exact_wall, 4), exact_spread_s=exact_spread,
        vs_exact=round(exact_wall / rpf_wall, 3),
        query_rows_per_s=round(n / query_wall, 1),
        recall_at_k=round(float(hits), 4),
        recall_rows=int(len(sample)),
        ari_vs_exact=round(float(
            adjusted_rand_index(labels_rpf, labels_exact)
        ), 4),
        ari_n=ari_n, **base,
    ))


def bench_fused_forest(out_path, n=200_000, d=8, k=16, trees=4,
                       leaf_size=1024, iters=3, seed=0):
    """Fused forest-query program legs (README "Kernel depth").

    Two phase pairs on the same forest geometry, unfused production chain
    vs the fused kernel BODY (the r6 ``fused_body`` convention: the
    kernel-resident math jitted as plain jnp, so off-TPU rows measure the
    algorithm, not the Pallas interpreter), plus the actual Pallas
    programs — full-batch on TPU, small-batch ``interpret:true`` wiring
    rows off it:

    - ``fused_forest_leafscan_unfused`` / ``_body`` / ``_pallas``: the
      per-leaf candidate scan — ``rpforest._leaf_scan`` ((Lmax, Lmax)
      distance matrix in HBM + ``lax.top_k`` + lexsort) vs
      ``pallas_forest.leaf_topk_values`` (distance tile + k-pass lex
      registers, matrix never leaves VMEM on TPU).
    - ``fused_forest_rescan_unfused`` / ``_body`` / ``_pallas``: the
      rescan candidate-panel reduction — vmapped ``pairwise_distance`` +
      ``dedup_lex_merge`` of the (m, k²) matrix vs
      ``pallas_forest.rescan_topk_values``.

    Acceptance (ISSUE 19): body rows >= 1.5x ``gflops_s`` over their
    unfused twin at the 200k proxy. ``ai_flops_per_byte`` is the MODELED
    TPU roofline arithmetic intensity (same analytic convention both
    rows: the unfused chain round-trips the candidate distance matrix
    through HBM, the fused body does not) — the companion
    ``bench_compare`` headline tracks it higher-better.
    """
    from hdbscan_tpu.ops import pallas_forest as pf
    from hdbscan_tpu.ops.rpforest import (
        _dedup_lex_merge,
        _leaf_scan,
        build_forest,
    )

    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, (32, d))
    data = (centers[rng.integers(0, 32, n)]
            + rng.normal(0, 0.6, (n, d))).astype(np.float32)
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    forest = build_forest(data, trees=trees, leaf_size=leaf_size, seed=seed)
    lmax = forest.max_leaf
    bsz = min(forest.num_leaves, max(1, (1 << 25) // (lmax * lmax)))
    members = jnp.asarray(forest.members[0, :bsz])
    mask = jnp.asarray(forest.leaf_mask[:bsz])
    data_dev = jnp.asarray(data)
    sentinel = n
    kk = min(k, lmax)
    form = pf.euclid_form(lmax, lmax, d)
    f32 = 4
    base = dict(
        n=n, d=d, k=kk, trees=trees, leaf_size=leaf_size,
        leaves=forest.num_leaves, max_leaf=lmax, leaf_batch=bsz,
        iters=iters, seed=seed, platform=platform,
        cpu_smoke=platform != "tpu", device=str(jax.devices()[0]),
        peak_flops=PEAK_FLOPS,
    )

    # --- leaf scan pair ----------------------------------------------------
    flops_l = 2.0 * bsz * lmax * lmax * d
    # HBM traffic model: operand gather + outputs both ways; the unfused
    # chain additionally writes the (B, Lmax, Lmax) matrix and reads it
    # back for top_k.
    bytes_l_unf = f32 * (
        bsz * lmax * d + 2 * bsz * lmax * lmax + 2 * bsz * lmax * kk
    )
    bytes_l_fus = f32 * (bsz * lmax * d + 2 * bsz * lmax * kk)

    def run_leaf_unfused():
        nd, _ = _leaf_scan(data_dev, members, mask, kk, "euclidean", sentinel)
        return jnp.sum(jnp.where(jnp.isfinite(nd), nd, 0.0))

    wall_u, spread = _time_call(run_leaf_unfused, iters)
    _emit(out_path, dict(
        leg="fused_forest_leafscan_unfused", wall_s=round(wall_u, 4),
        spread_s=spread, gflops=round(flops_l / 1e9, 1),
        gflops_s=round(flops_l / wall_u / 1e9, 2),
        mfu=round(flops_l / wall_u / PEAK_FLOPS, 5),
        ai_flops_per_byte=round(flops_l / bytes_l_unf, 2), **base,
    ))

    lp = pf._ceil_to(max(lmax, pf.SUBLANES), pf.LANES)
    dp = pf.LANES

    @jax.jit
    def leaf_body():
        pts = jnp.pad(
            data_dev[members], ((0, 0), (0, lp - lmax), (0, dp - d))
        )
        ids = jnp.pad(
            members.astype(jnp.int32), ((0, 0), (0, lp - lmax)),
            constant_values=sentinel,
        )
        cm = jnp.pad(mask.astype(jnp.int32), ((0, 0), (0, lp - lmax)))
        nd, ni = jax.vmap(
            lambda p, i, c: pf.leaf_topk_values(
                p, i, c, kk, d_real=d, metric="euclidean", form=form,
                precision="f32", sentinel=sentinel,
            )
        )(pts, ids, cm)
        nd, ni = nd[:, :lmax], ni[:, :lmax]
        order = jnp.lexsort((ni, nd), axis=-1)
        nd = jnp.take_along_axis(nd, order, axis=-1)
        return jnp.sum(jnp.where(jnp.isfinite(nd), nd, 0.0))

    wall_b, spread = _time_call(lambda: leaf_body(), iters)
    _emit(out_path, dict(
        leg="fused_forest_leafscan_body", wall_s=round(wall_b, 4),
        spread_s=spread, gflops=round(flops_l / 1e9, 1),
        gflops_s=round(flops_l / wall_b / 1e9, 2),
        mfu=round(flops_l / wall_b / PEAK_FLOPS, 5),
        ai_flops_per_byte=round(flops_l / bytes_l_fus, 2),
        vs_unfused=round(wall_u / wall_b, 3),
        note=(
            "CPU proxy inverts this pair: lax.top_k is a tuned native "
            "kernel on CPU while the k-pass registers are TPU-VPU-shaped "
            "(r5 measured top_k at ~90% of on-chip scan wall); the "
            "compiled TPU leg is the real test" if not on_tpu else None
        ), **base,
    ))

    # Actual Pallas program: full batch on TPU (the staged real leg);
    # off-TPU a small-batch interpreter wiring row, honestly marked.
    bsz_p = bsz if on_tpu else min(bsz, 8)
    flops_p = 2.0 * bsz_p * lmax * lmax * d

    def run_leaf_pallas():
        nd, _ = pf.forest_leaf_topk(
            data_dev, members[:bsz_p], mask[:bsz_p], kk, "euclidean", form,
            "f32", sentinel, interpret=not on_tpu,
        )
        return jnp.sum(jnp.where(jnp.isfinite(nd), nd, 0.0))

    wall, spread = _time_call(run_leaf_pallas, iters)
    _emit(out_path, dict(
        leg="fused_forest_leafscan_pallas", wall_s=round(wall, 4),
        spread_s=spread, interpret=not on_tpu, leaf_batch_pallas=bsz_p,
        gflops=round(flops_p / 1e9, 1),
        gflops_s=round(flops_p / wall / 1e9, 2),
        mfu=round(flops_p / wall / PEAK_FLOPS, 5),
        ai_flops_per_byte=round(flops_l / bytes_l_fus, 2), **base,
    ))

    # --- rescan candidate-panel pair --------------------------------------
    m = min(n, 1 << 14)
    cc = kk * kk
    cand = jnp.asarray(rng.integers(0, n, (m, cc)).astype(np.int32))
    q = data_dev[:m]
    flops_r = 2.0 * m * cc * d
    bytes_r_unf = f32 * (m * d + m * cc * d + 2 * m * cc + 2 * m * kk)
    bytes_r_fus = f32 * (m * d + m * cc * d + 2 * m * kk)

    @jax.jit
    def rescan_unfused():
        cpts = data_dev[cand]
        cd = jax.vmap(
            lambda qq, pts: pairwise_distance(qq[None, :], pts, "euclidean")[0]
        )(q, cpts)
        nd, _ = _dedup_lex_merge(cd, cand, kk, sentinel)
        return jnp.sum(jnp.where(jnp.isfinite(nd), nd, 0.0))

    wall_u, spread = _time_call(lambda: rescan_unfused(), iters)
    _emit(out_path, dict(
        leg="fused_forest_rescan_unfused", wall_s=round(wall_u, 4),
        spread_s=spread, rows=m, cand_cols=cc,
        gflops=round(flops_r / 1e9, 1),
        gflops_s=round(flops_r / wall_u / 1e9, 2),
        mfu=round(flops_r / wall_u / PEAK_FLOPS, 5),
        ai_flops_per_byte=round(flops_r / bytes_r_unf, 2), **base,
    ))

    for precision in ("f32",) + (("bf16",) if on_tpu else ()):

        @partial(jax.jit, static_argnames=("prec",))
        def rescan_body(prec=precision):
            cpts = data_dev[cand]
            nd, _ = pf.rescan_topk_values(
                q, cpts, cand, kk, d_real=d, metric="euclidean",
                precision=prec, sentinel=sentinel,
            )
            return jnp.sum(jnp.where(jnp.isfinite(nd), nd, 0.0))

        wall_b, spread = _time_call(lambda: rescan_body(), iters)
        tag = "" if precision == "f32" else "_bf16"
        _emit(out_path, dict(
            leg=f"fused_forest_rescan_body{tag}", wall_s=round(wall_b, 4),
            spread_s=spread, rows=m, cand_cols=cc, precision=precision,
            gflops=round(flops_r / 1e9, 1),
            gflops_s=round(flops_r / wall_b / 1e9, 2),
            mfu=round(flops_r / wall_b / PEAK_FLOPS, 5),
            ai_flops_per_byte=round(flops_r / bytes_r_fus, 2),
            vs_unfused=round(wall_u / wall_b, 3), **base,
        ))

    m_p = m if on_tpu else min(m, 256)

    def run_rescan_pallas():
        nd, _ = pf.forest_rescan_topk(
            q[:m_p], data_dev[cand[:m_p]], cand[:m_p], kk, "euclidean",
            "f32", sentinel, interpret=not on_tpu,
        )
        return jnp.sum(jnp.where(jnp.isfinite(nd), nd, 0.0))

    flops_rp = 2.0 * m_p * cc * d
    wall, spread = _time_call(run_rescan_pallas, iters)
    _emit(out_path, dict(
        leg="fused_forest_rescan_pallas", wall_s=round(wall, 4),
        spread_s=spread, interpret=not on_tpu, rows=m_p, cand_cols=cc,
        gflops=round(flops_rp / 1e9, 1),
        gflops_s=round(flops_rp / wall / 1e9, 2),
        mfu=round(flops_rp / wall / PEAK_FLOPS, 5),
        ai_flops_per_byte=round(flops_r / bytes_r_fus, 2), **base,
    ))


def bench_predict(out_path, n=100_000, d=8, iters=50, seed=0, max_batch=256):
    """Serving predict-throughput leg (README "Serving").

    Fits an n-row synthetic model once (exact path), then drives batched
    ``serve/predict.Predictor`` dispatches at request sizes 1/16/``max_batch``
    against the device-resident model. Per size: nearest-rank p50/p99
    latency and rows/s over ``iters`` batches of jittered training queries
    (near-manifold, so the attachment climb runs — not the duplicate
    shortcut). Also emits the warmup row (bucket count, compile count) and
    asserts the zero-steady-state-recompile contract: jit compiles across
    every timed batch after warmup must be 0 (reported, not silently
    assumed). TPU target: b=256 throughput >= 1M rows/s at n=100k, d=8;
    CPU rows are marked cpu_smoke."""
    from hdbscan_tpu.config import HDBSCANParams
    from hdbscan_tpu.models import exact
    from hdbscan_tpu.serve.predict import Predictor
    from hdbscan_tpu.utils.telemetry import compile_counter, latency_percentiles

    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, (8, d))
    data = centers[rng.integers(0, 8, n)] + rng.normal(0, 0.5, (n, d))
    params = HDBSCANParams(
        min_points=8, min_cluster_size=max(n // 100, 16)
    )
    t0 = time.perf_counter()
    result = exact.fit(data, params)
    fit_wall = time.perf_counter() - t0
    model = result.to_cluster_model(data, params)
    predictor = Predictor(model, max_batch=max_batch)
    winfo = predictor.warmup()
    platform = jax.devices()[0].platform
    _emit(out_path, dict(
        leg="predict_warmup", n=n, d=d, backend=predictor.backend,
        platform=platform, cpu_smoke=platform != "tpu",
        fit_wall_s=round(fit_wall, 3), buckets=winfo["buckets"],
        warmup_wall_s=winfo["wall_s"], jit_compiles=winfo["jit_compiles"],
    ))
    counter = compile_counter()
    before = counter()
    for bs in (1, 16, max_batch):
        walls = []
        for _ in range(iters):
            q = data[rng.integers(0, n, bs)] + rng.normal(0, 0.05, (bs, d))
            t0 = time.perf_counter()
            predictor.predict(q)
            walls.append(time.perf_counter() - t0)
        pct = latency_percentiles(walls)
        _emit(out_path, dict(
            leg=f"predict_b{bs}", n=n, d=d, batch=bs, iters=iters,
            backend=predictor.backend, platform=platform,
            cpu_smoke=platform != "tpu",
            p50_ms=round(pct["p50_s"] * 1e3, 3),
            p99_ms=round(pct["p99_s"] * 1e3, 3),
            rows_per_s=round(bs * iters / max(sum(walls), 1e-9), 1),
        ))
    _emit(out_path, dict(
        leg="predict_steady_state", n=n, d=d,
        jit_compiles=counter() - before,  # the zero-recompile contract
        platform=platform, cpu_smoke=platform != "tpu",
    ))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "devicebench_r6.jsonl"))
    ap.add_argument(
        "--legs",
        default="dispatch,exact,rescan,ring,finalize,mst_device,rpforest,"
                "fused_forest,predict",
    )
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--compile-cache", default="auto",
                    help="persistent XLA cache: auto, off, or a directory "
                         "(consumed before argparse — listed for --help)")
    ap.add_argument("--ring-n", type=int, default=100_000,
                    help="ring-leg rows (needs a multi-device mesh; CPU "
                         "smoke rows are marked cpu_smoke)")
    ap.add_argument("--ring-d", type=int, default=8)
    ap.add_argument("--n", type=int, default=500_000,
                    help="exact-scan rows (use ~4096 for off-TPU fused "
                         "smoke rows — interpreter-mode gate at 16384)")
    ap.add_argument("--d", type=int, default=28)
    ap.add_argument("--finalize-n", type=int, default=245_057,
                    help="finalize-leg vertices (defaults to the "
                         "Skin_NonSkin row count)")
    ap.add_argument("--mst-n", type=int, default=245_057,
                    help="finalize_device-leg vertices (Skin row count)")
    ap.add_argument("--mst-round-n", type=int, default=50_000,
                    help="mst_round-leg rows (the host loop's O(n^2) scans "
                         "dominate off-TPU; use ~5000 for CPU smoke rows)")
    ap.add_argument("--rescan-n", type=int, default=1_000_000)
    ap.add_argument("--rescan-col-tile", type=int, default=8192)
    ap.add_argument("--rescan-tiles", default="64,1024",
                    help="comma-separated chunk sizes in 256-row tiles")
    ap.add_argument("--rpf-n", type=int, default=200_000,
                    help="rpforest-leg rows (the >=3x acceptance shape; "
                         "use ~20000 for quick CPU smoke rows)")
    ap.add_argument("--rpf-d", type=int, default=8)
    ap.add_argument("--rpf-trees", type=int, default=4)
    ap.add_argument("--rpf-leaf-size", type=int, default=1024)
    ap.add_argument("--rpf-ari-n", type=int, default=5000,
                    help="rows for the paired full-fit ARI-vs-exact check")
    ap.add_argument("--predict-n", type=int, default=100_000,
                    help="predict-leg training rows (use ~5000 for CPU "
                         "smoke rows — the leg fits an exact model first)")
    ap.add_argument("--predict-d", type=int, default=8)
    args = ap.parse_args()
    legs = args.legs.split(",")
    if "dispatch" in legs:
        bench_dispatch_latency(args.out)
    if "exact" in legs:
        bench_exact_scan(args.out, n=args.n, d=args.d, iters=args.iters)
    if "rescan" in legs:
        bench_rescan_chunk(
            args.out, n=args.rescan_n, col_tile=args.rescan_col_tile,
            chunk_tiles=tuple(int(t) for t in args.rescan_tiles.split(",")),
            iters=args.iters,
        )
    if "ring" in legs:
        bench_ring_scan(
            args.out, n=args.ring_n, d=args.ring_d, iters=args.iters,
        )
    if "finalize" in legs:
        bench_finalize(args.out, n=args.finalize_n, iters=args.iters)
    if "mst_device" in legs:
        bench_mst_device(
            args.out, n=args.mst_n, iters=args.iters,
            round_n=args.mst_round_n,
        )
    if "rpforest" in legs:
        bench_rpforest(
            args.out, n=args.rpf_n, d=args.rpf_d, trees=args.rpf_trees,
            leaf_size=args.rpf_leaf_size, ari_n=args.rpf_ari_n,
        )
    if "fused_forest" in legs:
        bench_fused_forest(
            args.out, n=args.rpf_n, d=args.rpf_d, trees=args.rpf_trees,
            leaf_size=args.rpf_leaf_size, iters=args.iters,
        )
    if "predict" in legs:
        bench_predict(
            args.out, n=args.predict_n, d=args.predict_d,
            iters=max(args.iters, 20),
        )


if __name__ == "__main__":
    main()
