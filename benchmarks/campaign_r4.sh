#!/bin/bash
# Round-4 measurement campaign — strictly sequential: the tunneled single-chip
# host inflates TPU walls 5-10x under concurrent load (ROADMAP bench caveat).
# Each leg runs in its own process (fresh device state) and appends one JSON
# row; per-leg stage traces land in logs_r4/.
set -u
cd /root/repo
mkdir -p logs_r4
B=benchmarks
log() { echo "[campaign $(date +%H:%M:%S)] $*" >> logs_r4/campaign.log; }

log "A: 4M sep7 bound05 default"
python $B/boundary_eval.py 4000000 7.0 bound05 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/4M7_default.log
log "A done rc=$?"

log "B: 4M sep7 bound05 glue_factor=6"
python $B/boundary_eval.py 4000000 7.0 bound05 glue_factor=6 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/4M7_f6.log
log "B done rc=$?"

log "C: skin 45-seed consensus sweep (cons5)"
python $B/seed_sweep.py 45 skin cons5 \
  >> $B/seed_sweep45_skin_r4.jsonl 2> logs_r4/sweep_cons5.log
log "C done rc=$?"

log "D: pallas high-d legs (d=28, d=90)"
python $B/pallas_knn_bench.py --datasets gauss500k_d28,gauss500k_d90 \
  >> $B/pallas_r4.jsonl 2> logs_r4/pallas_highd.log
log "D done rc=$?"

log "E: 4M sep9 bound05"
python $B/boundary_eval.py 4000000 9.0 bound05 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/4M9.log
log "E done rc=$?"

log "F: 8M sep9 bound05"
python $B/boundary_eval.py 8000000 9.0 bound05 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/8M9.log
log "F done rc=$?"

log "G: HEPMASS-class 10.5M x 28d bound05"
python $B/highdim_eval.py 10500000 28 bound05 \
  >> $B/highdim_r4.jsonl 2> logs_r4/hepmass_10M5.log
log "G done rc=$?"

log "campaign complete"
