"""Open/closed-loop load generator for the serving path (stdlib-only core).

Drives a ``submit(k) -> rows`` callable — typically
:func:`http_predict_submitter` posting mixed-size batches to a running
``ClusterServer`` — under one of two arrival disciplines:

``closed``
    N workers issue requests back-to-back: a worker's next request starts
    the moment its previous response lands. Measures the server at its
    natural saturation for that concurrency; latency is response time.
``open``
    Requests arrive on a Poisson process at ``rate_rps`` regardless of how
    fast responses come back, dispatched onto a bounded worker pool.
    Latency is measured from the *scheduled arrival time*, not dispatch —
    so queueing delay caused by a slow server counts against it
    (coordinated-omission-aware, the classic closed-loop blind spot).
``ramp``
    Open-loop arrivals whose instantaneous rate follows a tenant-churn
    profile: linear ramp from ~0 to ``rate_rps`` over the first
    ``ramp_up_frac`` of the window, hold at peak for ``ramp_hold_frac``,
    then drop to ``ramp_idle_rps`` (default 2% of peak, floor 0.5 rps)
    for the remainder. This is the fleet autoscaler's acceptance
    stimulus: the ramp forces scale-up under load, the idle tail forces
    scale-down, in one run.

Warmup exclusion: samples taken during the first ``warmup_s`` seconds (or
the first ``warmup_requests`` requests, whichever bound is given) are
issued but not recorded, so JIT compilation and connection setup never
pollute the percentiles.

Shedding-aware accounting (``expect_shedding=True`` / ``--expect-shedding``):
a server running with a bounded batcher queue deliberately refuses excess
work with 429/503 + Retry-After. In that regime a refusal is correct
behavior, not a failure, so rejections whose status is 429/503 count in
``shed`` while everything else (5xx, socket resets, timeouts) stays in
``errors`` — and ``offered = requests + shed + errors`` lets the chaos
suite reconcile the generator's view against the server's
``requests_shed_total`` metric. With the default ``expect_shedding=False``
every rejection is an error, exactly as before.

Every recorded latency lands both in a raw list and in a
``utils.metrics.Histogram`` with the serving latency buckets; the result
exposes nearest-rank p50/p99/p999 computed BOTH ways plus
:func:`hist_quantile_close`, which asserts the histogram-derived quantile
sits within one bucket width of the raw one — the accuracy contract the
``bench.py slo`` leg and the tier-1 e2e pin.

A tiny CLI is included for ad-hoc runs against a live server::

    python -m benchmarks.loadgen http://127.0.0.1:8787 --mode closed \
        --duration 5 --concurrency 4 --mix 1:0.5,16:0.3,64:0.2
"""

from __future__ import annotations

import bisect
import json
import math
import random
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from hdbscan_tpu.utils.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Histogram,
    MetricsRegistry,
)

__all__ = [
    "LoadResult",
    "run_load",
    "http_predict_submitter",
    "nearest_rank",
    "bucket_width_at",
    "hist_quantile_close",
]

#: Default mixed-batch workload: mostly singletons, some medium, some large
#: — exercises several pow2 buckets and the batcher's coalescing window.
DEFAULT_MIX = ((1, 0.5), (16, 0.3), (64, 0.2))


def nearest_rank(sorted_vals, q: float):
    """Nearest-rank quantile over an already-sorted list (None if empty).

    Same formula as ``utils.telemetry.latency_percentiles`` and
    ``utils.metrics.Histogram.quantile``: index ``ceil(q*n) - 1``.
    """
    n = len(sorted_vals)
    if n == 0:
        return None
    return sorted_vals[min(n - 1, max(0, math.ceil(q * n) - 1))]


def bucket_width_at(edges, value: float) -> float:
    """Width of the histogram bucket that ``value`` falls into.

    The first bucket spans ``(0, edges[0]]``; values beyond the last edge
    land in the +Inf bucket, whose width is infinite (the cross-check is
    vacuous there — the histogram can only answer "bigger than the last
    edge").
    """
    i = bisect.bisect_left(edges, value)
    if i >= len(edges):
        return math.inf
    return edges[i] - (edges[i - 1] if i > 0 else 0.0)


def hist_quantile_close(hist: Histogram, raw_sorted, q: float) -> bool:
    """True when the histogram-derived quantile is within one bucket width
    of the raw nearest-rank quantile (the loadgen accuracy contract)."""
    raw_q = nearest_rank(raw_sorted, q)
    hist_q = hist.quantile(q)
    if raw_q is None or hist_q is None:
        return raw_q is None and hist_q is None
    return abs(hist_q - raw_q) <= bucket_width_at(hist.buckets, raw_q)


@dataclass
class LoadResult:
    """Outcome of one :func:`run_load` run (post-warmup samples only)."""

    mode: str
    latencies: list = field(default_factory=list)  # seconds, arrival order
    hist: Histogram | None = None
    requests: int = 0  # recorded (post-warmup) requests
    warmup_requests: int = 0  # issued but excluded
    rows: int = 0  # rows across recorded requests
    errors: int = 0
    shed: int = 0  # 429/503 refusals (only populated with expect_shedding)
    wall_s: float = 0.0  # measurement window (warmup excluded)
    #: tenant -> {"latencies", "requests", "rows", "errors", "shed"} when
    #: the run spread load over tenants (run_load(tenants=...)).
    per_tenant: dict = field(default_factory=dict)

    @property
    def offered(self) -> int:
        """Post-warmup requests offered to the server (served+shed+failed)."""
        return self.requests + self.shed + self.errors

    def shed_rate(self) -> float:
        return round(self.shed / self.offered, 6) if self.offered else 0.0

    def percentiles(self) -> dict:
        """Raw nearest-rank and histogram-derived p50/p99/p999 + mean/max."""
        walls = sorted(self.latencies)
        n = len(walls)
        out = {
            "count": n,
            "mean_s": round(sum(walls) / n, 6) if n else None,
            "max_s": round(walls[-1], 6) if n else None,
        }
        for q, key in ((0.50, "p50"), (0.99, "p99"), (0.999, "p999")):
            raw = nearest_rank(walls, q)
            out[f"{key}_s"] = round(raw, 6) if raw is not None else None
            hq = self.hist.quantile(q) if self.hist is not None else None
            out[f"{key}_hist_s"] = round(hq, 6) if hq is not None else None
        return out

    def rows_per_s(self) -> float:
        return round(self.rows / self.wall_s, 3) if self.wall_s > 0 else 0.0

    def quantiles_consistent(self, q: float = 0.99) -> bool:
        """The one-bucket-width accuracy contract at quantile ``q``."""
        if self.hist is None:
            return False
        return hist_quantile_close(self.hist, sorted(self.latencies), q)

    def tenant_percentiles(self) -> dict:
        """Per-tenant latency/accounting rows (empty without tenants).

        Each row mirrors :meth:`percentiles` plus the per-tenant
        served/shed/error split, so a fleet bench leg can hand every
        tenant's observed p50/p99 straight to the registry's SLO verdicts.
        """
        out = {}
        for tenant, st in sorted(self.per_tenant.items()):
            walls = sorted(st["latencies"])
            n = len(walls)
            row = {
                "count": n,
                "requests": st["requests"],
                "rows": st["rows"],
                "errors": st["errors"],
                "shed": st["shed"],
                "mean_s": round(sum(walls) / n, 6) if n else None,
                "max_s": round(walls[-1], 6) if n else None,
            }
            for q, key in ((0.50, "p50"), (0.99, "p99"), (0.999, "p999")):
                raw = nearest_rank(walls, q)
                row[f"{key}_s"] = round(raw, 6) if raw is not None else None
            out[tenant] = row
        return out


def _pick_sizes(batch_mix, seed: int):
    """Deterministic weighted batch-size chooser (one RNG, lock-guarded)."""
    sizes = [int(s) for s, _ in batch_mix]
    weights = [float(w) for _, w in batch_mix]
    if not sizes or any(s < 1 for s in sizes) or any(w <= 0 for w in weights):
        raise ValueError(f"bad batch_mix {batch_mix!r}")
    rng = random.Random(seed)
    lock = threading.Lock()

    def pick() -> int:
        with lock:
            return rng.choices(sizes, weights=weights, k=1)[0]

    return pick


def run_load(
    submit,
    *,
    mode: str = "closed",
    concurrency: int = 4,
    batch_mix=DEFAULT_MIX,
    duration_s: float | None = None,
    requests: int | None = None,
    warmup_s: float = 0.0,
    warmup_requests: int = 0,
    rate_rps: float | None = None,
    ramp_up_frac: float = 0.35,
    ramp_hold_frac: float = 0.3,
    ramp_idle_rps: float | None = None,
    seed: int = 0,
    expect_shedding: bool = False,
    tenants=0,
) -> LoadResult:
    """Drive ``submit(batch_size) -> rows`` under load and collect latency.

    Exactly one of ``duration_s`` / ``requests`` bounds the measured
    window (both given = both respected, first hit wins). ``open`` mode
    additionally requires ``rate_rps``. Raises on submit() exceptions
    being swallowed — errors are counted, never recorded as latencies.

    ``tenants`` spreads the load over a multi-tenant server: an int N
    round-robins over tenant ids ``t0..t{N-1}``; a sequence of strings
    round-robins over those names (matching the ``<tenant>.npz`` stems of
    a ``--tenants-dir``). With tenants set, ``submit`` is called as
    ``submit(batch_size, tenant)`` and the result carries per-tenant
    latency accounting in :attr:`LoadResult.per_tenant`.
    """
    if mode not in ("closed", "open", "ramp"):
        raise ValueError(
            f"mode must be 'closed', 'open', or 'ramp', got {mode!r}"
        )
    if duration_s is None and requests is None:
        raise ValueError("one of duration_s / requests is required")
    if mode in ("open", "ramp") and not rate_rps:
        raise ValueError(f"{mode} mode requires rate_rps")
    if mode == "ramp":
        if duration_s is None:
            raise ValueError("ramp mode requires duration_s")
        if not (0.0 < ramp_up_frac and 0.0 <= ramp_hold_frac
                and ramp_up_frac + ramp_hold_frac <= 1.0):
            raise ValueError(
                f"ramp fractions must satisfy 0 < up and up + hold <= 1, "
                f"got up={ramp_up_frac!r} hold={ramp_hold_frac!r}"
            )
    if concurrency < 1:
        raise ValueError(f"concurrency must be >= 1, got {concurrency!r}")
    if isinstance(tenants, int):
        tenant_names = [f"t{i}" for i in range(tenants)]
    else:
        tenant_names = [str(t) for t in tenants]

    pick = _pick_sizes(batch_mix, seed)
    hist = MetricsRegistry().histogram(
        "loadgen_latency_seconds",
        "Request latency observed by the load generator.",
        buckets=DEFAULT_LATENCY_BUCKETS,
    )
    result = LoadResult(mode=mode, hist=hist)
    lock = threading.Lock()
    issued = [0]  # total requests issued (warmup included)
    t_start = time.perf_counter()
    warmup_until = t_start + float(warmup_s)
    deadline = (
        None if duration_s is None else warmup_until + float(duration_s)
    )

    def budget_take() -> bool:
        """Claim one request slot; False once every bound is exhausted."""
        now = time.perf_counter()
        if deadline is not None and now >= deadline:
            return False
        with lock:
            if requests is not None and issued[0] >= warmup_requests + requests:
                return False
            issued[0] += 1
        return True

    def tenant_bin(tenant: str) -> dict:
        # caller holds the lock
        return result.per_tenant.setdefault(
            tenant,
            {"latencies": [], "requests": 0, "rows": 0, "errors": 0, "shed": 0},
        )

    def record(t_sched: float, t_done: float, rows, exc, tenant) -> None:
        in_warmup = t_sched < warmup_until
        with lock:
            if in_warmup:
                result.warmup_requests += 1
                return
            if not in_warmup and warmup_requests:
                # request-count warmup: first warmup_requests recorded
                # arrivals are excluded even without a time window
                if result.warmup_requests < warmup_requests:
                    result.warmup_requests += 1
                    return
            if exc is not None:
                status = getattr(exc, "code", None) or getattr(exc, "status", None)
                if expect_shedding and status in (429, 503):
                    result.shed += 1
                    if tenant is not None:
                        tenant_bin(tenant)["shed"] += 1
                else:
                    result.errors += 1
                    if tenant is not None:
                        tenant_bin(tenant)["errors"] += 1
                return
            lat = t_done - t_sched
            result.latencies.append(lat)
            result.requests += 1
            result.rows += int(rows)
            if tenant is not None:
                st = tenant_bin(tenant)
                st["latencies"].append(lat)
                st["requests"] += 1
                st["rows"] += int(rows)
        hist.observe(lat)  # Histogram has its own lock

    tenant_counter = [0]

    def next_tenant():
        if not tenant_names:
            return None
        with lock:
            i = tenant_counter[0]
            tenant_counter[0] += 1
        return tenant_names[i % len(tenant_names)]

    def one_request(t_sched: float) -> None:
        size = pick()
        tenant = next_tenant()
        try:
            if tenant is None:
                rows, exc = submit(size), None
            else:
                rows, exc = submit(size, tenant), None
        except Exception as e:
            rows, exc = 0, e
        record(t_sched, time.perf_counter(), rows, exc, tenant)

    if mode == "closed":

        def worker() -> None:
            while budget_take():
                one_request(time.perf_counter())

        threads = [
            threading.Thread(target=worker, name=f"loadgen-{i}", daemon=True)
            for i in range(concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:
        # Open loop: Poisson arrivals; latency runs from the SCHEDULED
        # arrival, so server-induced queueing delay is charged to the
        # server even when the dispatch pool briefly backs up. "ramp"
        # shapes the instantaneous rate along the churn profile.
        peak = float(rate_rps)
        idle = (
            float(ramp_idle_rps) if ramp_idle_rps is not None
            else max(0.5, 0.02 * peak)
        )

        def rate_at(t: float) -> float:
            if mode == "open":
                return peak
            frac = min(1.0, max(0.0, (t - warmup_until) / float(duration_s)))
            if frac < ramp_up_frac:
                return max(idle, peak * frac / ramp_up_frac)
            if frac < ramp_up_frac + ramp_hold_frac:
                return peak
            return idle

        arrival_rng = random.Random(seed + 1)
        with ThreadPoolExecutor(max_workers=concurrency) as pool:
            next_at = time.perf_counter()
            futures = []
            while True:
                now = time.perf_counter()
                if next_at > now:
                    time.sleep(next_at - now)
                if not budget_take():
                    break
                futures.append(pool.submit(one_request, next_at))
                next_at += arrival_rng.expovariate(rate_at(next_at))
            for f in futures:
                f.result()

    t_end = time.perf_counter()
    result.wall_s = round(t_end - max(t_start, min(warmup_until, t_end)), 6)
    return result


def http_predict_submitter(base_url: str, sampler, timeout: float = 30.0,
                           headers=None, retry_attempts: int = 0):
    """Build a ``submit(k) -> rows`` posting ``{"points": sampler(k)}`` to
    ``POST /predict``. ``sampler(k)`` returns a (k, dim) array-like.

    ``headers`` adds extra request headers (e.g. ``X-Deadline-Ms``).
    ``retry_attempts > 0`` resubmits requests the server shed with 429/503
    — capped exponential backoff via ``fault.policy.retry_call`` — so a
    polite client rides out a transient overload instead of reporting it.
    The returned callable also accepts ``submit(k, tenant)`` — the form
    ``run_load(tenants=...)`` uses — adding a ``"tenant"`` field to the
    request body for multi-tenant servers (``serve --tenants-dir``).
    """
    url = base_url.rstrip("/") + "/predict"
    extra = dict(headers or {})

    def once(k: int, tenant: str | None = None) -> int:
        points = sampler(k)
        payload = {"points": [list(map(float, row)) for row in points]}
        if tenant is not None:
            payload["tenant"] = tenant
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **extra},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
        return len(out["labels"])

    if retry_attempts <= 0:
        return once

    from hdbscan_tpu.fault.policy import retry_call

    def submit(k: int, tenant: str | None = None) -> int:
        return retry_call(
            lambda: once(k, tenant),
            attempts=retry_attempts + 1, base_s=0.02, cap_s=0.5, seed=k,
            should_retry=lambda e: getattr(e, "code", None) in (429, 503),
        )

    return submit


def _parse_mix(text: str):
    return tuple(
        (int(part.split(":")[0]), float(part.split(":")[1]))
        for part in text.split(",")
    )


def main(argv=None) -> int:
    import argparse

    import numpy as np

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base_url")
    ap.add_argument("--mode", choices=("closed", "open", "ramp"), default="closed")
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--warmup", type=float, default=1.0)
    ap.add_argument("--rate", type=float, default=50.0, help="open/ramp peak rps")
    ap.add_argument("--ramp-up-frac", type=float, default=0.35)
    ap.add_argument("--ramp-hold-frac", type=float, default=0.3)
    ap.add_argument(
        "--ramp-idle-rps", type=float, default=None,
        help="tail rate after the hold window (default 2%% of peak)",
    )
    ap.add_argument("--mix", type=_parse_mix, default=DEFAULT_MIX)
    ap.add_argument("--dim", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--expect-shedding", action="store_true",
        help="count 429/503 refusals as shed load, not errors",
    )
    ap.add_argument(
        "--tenants", type=int, default=0, metavar="N",
        help="spread load round-robin over tenant ids t0..t{N-1} "
        "(multi-tenant server) with per-tenant latency accounting",
    )
    args = ap.parse_args(argv)

    rng = np.random.default_rng(args.seed)

    def sampler(k):
        return rng.normal(0.0, 3.0, size=(k, args.dim))

    result = run_load(
        http_predict_submitter(args.base_url, sampler),
        mode=args.mode,
        concurrency=args.concurrency,
        batch_mix=args.mix,
        duration_s=args.duration,
        warmup_s=args.warmup,
        rate_rps=args.rate if args.mode in ("open", "ramp") else None,
        ramp_up_frac=args.ramp_up_frac,
        ramp_hold_frac=args.ramp_hold_frac,
        ramp_idle_rps=args.ramp_idle_rps,
        seed=args.seed,
        expect_shedding=args.expect_shedding,
        tenants=args.tenants,
    )
    out = {
        "mode": result.mode,
        "requests": result.requests,
        "errors": result.errors,
        "shed": result.shed,
        "offered": result.offered,
        "shed_rate": result.shed_rate(),
        "rows_per_s": result.rows_per_s(),
        "wall_s": result.wall_s,
        "latency": result.percentiles(),
        "hist_p99_consistent": result.quantiles_consistent(0.99),
    }
    if args.tenants:
        out["tenants"] = result.tenant_percentiles()
    print(json.dumps(out, indent=2))
    return 1 if result.errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
