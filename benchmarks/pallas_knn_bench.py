"""Pallas k-NN kernel vs the XLA ``lax.top_k`` scan, one JSON line per leg.

Run on the real TPU chip:

    python benchmarks/pallas_knn_bench.py [--datasets skin,gauss200k,gauss1m]

Measures the round-2 kernel schedule (Morton row sort + near-diagonal-first
column visit order, ``order="diag"``) against both the round-1 schedule
(``order="scan"``) and the default XLA streaming scan, and checks the three
agree numerically. Wall times include the kernel's host-side Morton sort and
permutations (that is the honest drop-in cost).

The adoption rule (VERDICT r1 item 8): the kernel becomes the default
euclidean core-distance backend only where it measurably wins; otherwise the
numbers below get recorded in ROADMAP.md as the negative result.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

SKIN = "/root/reference/数据集/Skin_NonSkin.txt"


def bench(fn, reps: int = 3):
    fn()  # warm / compile
    walls = []
    for _ in range(reps):
        t0 = time.monotonic()
        out = fn()
        walls.append(time.monotonic() - t0)
    return min(walls), out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--datasets", default="skin,gauss200k,gauss1m")
    ap.add_argument("--min-pts", type=int, default=16)
    args = ap.parse_args()

    from hdbscan_tpu.ops.pallas_knn import knn_core_distances_pallas
    from hdbscan_tpu.ops.tiled import knn_core_distances
    from hdbscan_tpu.utils.datasets import make_gauss

    sets = {}
    for name in args.datasets.split(","):
        if name == "skin":
            sets[name] = np.loadtxt(SKIN)[:, :3]
        elif name.startswith("gauss"):
            # gauss<N>[_d<D>]: e.g. gauss200k (d=10), gauss200k_d28,
            # gauss500k_d90 — the d=28-90 legs cover the paper's
            # HEPMASS/HIGGS/YearPrediction dimensionality class, where the
            # round-2 lane-padding verdict against the MXU dot form inverts
            # (K pads to 128 lanes: ~42x waste at d=3, ~1.4x at d=90 —
            # VERDICT r3 item 7).
            base, _, dpart = name.partition("_d")
            n = int(base[5:].replace("k", "000").replace("m", "000000"))
            dims = int(dpart) if dpart else 10
            sets[name], _ = make_gauss(n, dims=dims, n_clusters=30, seed=0)
        else:
            raise SystemExit(f"unknown dataset {name}")

    mp = args.min_pts
    for name, data in sets.items():
        legs = {
            # backend="xla" pins the baseline: at d >= 24 the default now
            # auto-dispatches to the pallas kernel, which would make this
            # leg compare the kernel against itself.
            "xla_scan": lambda d=data: knn_core_distances(d, mp, backend="xla")[0],
            "pallas_scan": lambda d=data: knn_core_distances_pallas(
                d, mp, order="scan"
            )[0],
            "pallas_diag": lambda d=data: knn_core_distances_pallas(
                d, mp, order="diag"
            )[0],
            # The MXU dot form: lane padding wastes ~42x at d=3 but only
            # ~1.4x at d=90 — the high-d legs are where it could win.
            "pallas_dot": lambda d=data: knn_core_distances_pallas(
                d, mp, order="diag", form="dot"
            )[0],
        }
        cores = {}
        for leg, fn in legs.items():
            wall, cores[leg] = bench(fn)
            print(
                json.dumps(
                    {
                        "metric": f"knn_{name}_{leg}",
                        "value": round(wall, 3),
                        "unit": "s",
                        "n": len(data),
                        "d": data.shape[1],
                        "min_pts": mp,
                    }
                ),
                flush=True,
            )
        # The XLA leg's dot-form expansion carries absolute error
        # ~eps_f32 * ||x||² even at Precision.HIGHEST (measured 1.2e-4 at
        # d=28, 5.7e-4 at d=90 vs a float64 oracle — highdim_r3.jsonl), so
        # the agreement tolerance must scale with the squared coordinate
        # norms: a fixed 1e-4 wrongly flags the MORE accurate diff-form
        # kernel once d*side² passes ~2e3. The asserted quantity is a MAX
        # over per-point errors, each ~eps*||x_i||², so the bound uses the
        # max squared norm (the mean can sit 8x below the farthest cluster).
        tol = max(1e-4, 8 * np.finfo(np.float32).eps * float((data**2).sum(axis=1).max()))
        for leg in ("pallas_scan", "pallas_diag"):
            err = float(np.abs(cores[leg] - cores["xla_scan"]).max())
            assert err < tol, f"{name} {leg} diverges from XLA by {err} (tol {tol})"
        # The dot form is approximate near duplicates (~eps·|x|² absolute,
        # documented) — report its deviation instead of asserting.
        err = float(np.abs(cores["pallas_dot"] - cores["xla_scan"]).max())
        print(
            json.dumps(
                {"metric": f"knn_{name}_pallas_dot_max_err", "value": err}
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
