#!/bin/bash
# Round-4 final package — the glue-dial science at 2M sep-7 (stress):
#   exact tree (percolation check: does exact-vs-truth fall with density?)
#   + default bound05 (truth optimum) + glue_rows=-1 (exact-fidelity end),
# all sharing one exact-label cache so ari_exact lands on every row.
set -u
cd /root/repo
mkdir -p logs_r4
B=benchmarks
log() { echo "[campaign3 $(date +%H:%M:%S)] $*" >> logs_r4/campaign.log; }

log "N1: 2M sep7 exact + bound05"
python $B/boundary_eval.py 2000000 7.0 exact,bound05 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/2M7_exact.log
log "N1 done rc=$?"

log "N2: 2M sep7 bound05 glue_rows=-1"
python $B/boundary_eval.py 2000000 7.0 bound05 glue_rows=-1 \
  >> $B/boundary_eval_r4.jsonl 2> logs_r4/2M7_deepglue.log
log "N2 done rc=$?"

log "O: pallas d90 retry (VMEM-fixed col tile)"
python $B/pallas_knn_bench.py --datasets gauss500k_d90 \
  >> $B/pallas_r4.jsonl 2> logs_r4/pallas_d90_retry.log
log "O done rc=$?"

log "campaign3 complete"
