"""probe_tighten adjudication (VERDICT r4 item 8).

The knob shipped opt-in in r4 with only no-op measurements (d >= 8). Its
hypothesized home is LOW-d data (2-3d: forced-split cells have thin
boundaries, so a probe-tightened at-risk test can actually clear interior
rows). This harness runs boundary mode with probe_tighten on/off on:

- Skin (245k x 3, the bundled real dataset, lattice-valued), and
- a 3-d Gauss synthetic (500k x 3, sep 9 — separated, seam-light).

Emits one JSON line per (dataset, probe_tighten) with the boundary-select
trace fields (m kept vs at-risk), wall, and ARI. Keep-or-attic decision
lands in ROADMAP. Rows append to benchmarks/probe_tighten_r5.jsonl.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import mr_hdbscan
from hdbscan_tpu.utils.datasets import make_gauss
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from hdbscan_tpu.utils.io import load_points
from hdbscan_tpu.utils.tracing import Tracer

SKIN_PATH = "/root/reference/数据集/Skin_NonSkin.txt"
OUT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "probe_tighten_r5.jsonl")


def run(name, data, truth, params):
    # Untimed warmup fit: the first pipeline run in a process pays XLA
    # compiles for every engaged shape; without it the first-listed variant
    # absorbs them (measured: 153 vs 48.5 s for a 104-row selection delta —
    # pure compile confound).
    mr_hdbscan.fit(data, params)
    for pt in (False, True):
        tracer = Tracer(stream=None)
        t0 = time.time()
        r = mr_hdbscan.fit(data, params.replace(probe_tighten=pt), trace=tracer)
        wall = time.time() - t0
        sel = [e for e in tracer.events if e.name == "boundary_select"]
        rec = {
            "dataset": name,
            "n": len(data),
            "dims": data.shape[1],
            "probe_tighten": pt,
            "wall_s": round(wall, 2),
            "ari_truth": round(float(adjusted_rand_index(r.labels, truth)), 4)
            if truth is not None
            else None,
            "boundary_select": sel[0].fields if sel else None,
            "params": {
                "min_points": params.min_points,
                "min_cluster_size": params.min_cluster_size,
                "processing_units": params.processing_units,
                "k": params.k,
                "boundary_quality": params.boundary_quality,
                "seed": params.seed,
            },
        }
        line = json.dumps(rec)
        print(line, flush=True)
        with open(OUT_PATH, "a") as f:
            f.write(line + "\n")


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "skin,gauss3d"
    if "skin" in which:
        raw = load_points(SKIN_PATH)
        data, truth = raw[:, :3], raw[:, 3].astype(np.int64)
        run(
            "skin",
            data,
            truth,
            HDBSCANParams(
                min_points=8,
                min_cluster_size=3000,
                processing_units=8192,
                k=0.03,
                seed=0,
                boundary_quality=0.05,
            ),
        )
    if "gauss3d" in which:
        data, truth = make_gauss(
            500_000, dims=3, n_clusters=12, separation=9.0, seed=5
        )
        run(
            "gauss3d",
            data,
            truth,
            HDBSCANParams(
                min_points=8,
                min_cluster_size=5000,
                processing_units=16384,
                k=0.01,
                seed=0,
                boundary_quality=0.05,
            ),
        )


if __name__ == "__main__":
    main()
