"""Seed-sweep quality harness — mean (stddev) ARI over many seeds.

The paper reports stochastic-variant quality as mean (stddev) over 45 runs
(ResearchReport.pdf §5.2; BASELINE.md Table 2: DB stddev <= 0.015, RS <=
0.025). Round 1 quoted single-seed anecdotes; this harness measures the same
protocol: the DB and RS variants, >= 10 seeds each, on the bundled Skin set
and the Gauss synthetic family.

Emits one JSON line per (dataset, variant) with mean/std ARI + wall stats.
Usage: python benchmarks/seed_sweep.py [n_seeds] [dataset1,...] [variant1,...]
Datasets: skin | gauss200k | gauss2_200k | gauss3_200k | gauss2_1m | gauss3_1m.
Variants: db | rs | dbflat (DB + flat-cut refinement to
convergence) | consN (N>=2: DB + consensus over N draws). Results land
in benchmarks/seed_sweep_r*.jsonl via shell redirection.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

enable_persistent_compilation_cache()

from hdbscan_tpu import HDBSCANParams
from hdbscan_tpu.models import mr_hdbscan
from hdbscan_tpu.utils.datasets import make_gauss
from hdbscan_tpu.utils.evaluation import adjusted_rand_index
from hdbscan_tpu.utils.io import load_points

SKIN_PATH = "/root/reference/数据集/Skin_NonSkin.txt"


def load_dataset(name: str):
    if name == "skin":
        raw = load_points(SKIN_PATH)
        data, truth = raw[:, :3], raw[:, 3].astype(np.int64)
        params = dict(
            min_points=8,
            min_cluster_size=3000,
            processing_units=8192,
            k=0.03,
            dedup_points=True,
        )
    elif name == "gauss200k":
        data, truth = make_gauss(200_000, dims=10, n_clusters=20, seed=7)
        params = dict(
            min_points=8, min_cluster_size=1000, processing_units=16384, k=0.01
        )
    elif name in ("gauss2_200k", "gauss3_200k", "gauss2_1m", "gauss3_1m"):
        # The paper's harder synthetic shapes (BASELINE.md Table 1: Gauss2 =
        # 30 clusters, Gauss3 = 50; DB degrades most there — 0.759/0.777 vs
        # exact 0.820/0.801, ResearchReport.pdf §5.3). Separation 8 keeps the
        # exact tree below ARI 1.0 so variant degradation is measurable
        # (VERDICT r2 item 6: round-2 only measured the easiest 20-cluster
        # shape).
        n = 1_000_000 if name.endswith("_1m") else 200_000
        n_cl = 30 if name.startswith("gauss2") else 50
        data, truth = make_gauss(
            n, dims=10, n_clusters=n_cl, separation=8.0, seed=7
        )
        params = dict(
            min_points=8,
            min_cluster_size=max(500, n // 400),
            processing_units=16384,
            k=0.01,
        )
    else:
        raise ValueError(f"unknown dataset {name!r}")
    return data, truth, params


def main() -> None:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    datasets = (sys.argv[2] if len(sys.argv) > 2 else "skin,gauss200k").split(",")
    # Variants: db | rs | dbflat | consN (consensus over N
    # draws, models/consensus.py — the round-4 lever against the Skin
    # lattice-tie bimodality; each sweep seed uses a disjoint draw-seed block).
    # Validated up front: a typo must die before the first leg runs, not
    # hours into a sweep.
    variants = []
    for variant in (sys.argv[3] if len(sys.argv) > 3 else "db,rs").split(","):
        if variant.startswith("cons"):
            if not variant[4:].isdigit() or int(variant[4:]) < 2:
                raise SystemExit(
                    f"variant {variant!r}: consensus needs 'cons<N>' with "
                    "N >= 2 (e.g. cons5)"
                )
            variants.append((variant, int(variant[4:])))
        elif variant in ("db", "rs", "dbflat"):
            variants.append((variant, 1))
        else:
            raise SystemExit(f"unknown variant {variant!r}")

    for ds in datasets:
        data, truth, base = load_dataset(ds)
        if ds.startswith("gauss"):
            # One exact-tree run per synthetic dataset for the vs-exact
            # context column (deterministic — cached across invocations the
            # same way boundary_eval.py caches its exact labels).
            cache = f"/tmp/sweep_exact_{ds}.npy"
            t0 = time.time()
            if os.path.exists(cache):
                labels_x = np.load(cache)
            else:
                from hdbscan_tpu.models import exact

                r_x = exact.fit(
                    data,
                    HDBSCANParams(
                        **{k: v for k, v in base.items() if k != "k"}
                    ),
                )
                labels_x = r_x.labels
                np.save(cache, labels_x)
            print(
                json.dumps(
                    {
                        "dataset": ds,
                        "variant": "exact",
                        "n": len(data),
                        "ari": round(
                            float(
                                adjusted_rand_index(
                                    labels_x, truth, noise_as_singletons=True
                                )
                            ),
                            4,
                        ),
                        "wall_s": round(time.time() - t0, 2),
                    }
                ),
                flush=True,
            )
        for variant, draws in variants:
            aris, walls = [], []
            for seed in range(n_seeds):
                p = HDBSCANParams(
                    **base,
                    variant="rs" if variant == "rs" else "db",
                    seed=seed,
                    consensus_draws=draws,
                    # dbflat: DB + flat-cut-level refinement to convergence
                    # (r5 — the spread closer; 8 bounds the loop, early
                    # stop on fixed labels).
                    refine_flat_iterations=8 if variant == "dbflat" else 0,
                )
                t0 = time.time()
                r = mr_hdbscan.fit(data, p)  # dispatches consensus inside
                walls.append(time.time() - t0)
                aris.append(
                    float(
                        adjusted_rand_index(
                            r.labels, truth, noise_as_singletons=True
                        )
                    )
                )
            rec = {
                "dataset": ds,
                "variant": variant,
                "n": len(data),
                "n_seeds": n_seeds,
                "ari_mean": round(float(np.mean(aris)), 4),
                "ari_std": round(float(np.std(aris)), 4),
                "ari_min": round(float(np.min(aris)), 4),
                "ari_max": round(float(np.max(aris)), 4),
                "wall_mean_s": round(float(np.mean(walls)), 2),
                "wall_std_s": round(float(np.std(walls)), 2),
                "params": {k: v for k, v in base.items()},
            }
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
