"""Resilience policies: deadlines, load shedding, retry/backoff, circuit breaker.

These are the contracts the chaos suite (tests/e2e/test_chaos_e2e.py)
exercises against the fault harness in :mod:`hdbscan_tpu.fault.inject`:

- :class:`DeadlineExceeded` — a request whose deadline passed fails fast
  (HTTP 504) instead of occupying a batch slot; the batcher drops expired
  entries before dispatch.
- :class:`ShedRequest` — bounded-queue load shedding (HTTP 429/503 with a
  Retry-After hint) so an overloaded server degrades by refusing work it
  cannot finish rather than queueing unboundedly.
- :func:`retry_call` / :func:`retry` — capped exponential backoff with
  jitter for transient failures (artifact load during hot-swap, refit
  publish, loadgen resubmits).
- :class:`CircuitBreaker` — trips refit/swap after repeated failures and
  degrades to serving the pinned model generation; state is surfaced in
  /healthz, /metrics (``circuit_state`` gauge), and ``circuit_state``
  trace events.
"""

from __future__ import annotations

import functools
import random
import threading
import time


class DeadlineExceeded(Exception):
    """The request's deadline passed before (or while) it could be served."""


class ShedRequest(Exception):
    """The server refused the request to shed load.

    ``status`` is the HTTP status to return (429 client-rate / 503
    overload), ``retry_after_s`` the Retry-After hint, ``reason`` a short
    machine-readable cause (``queue_full``, ...).
    """

    def __init__(self, message: str, *, status: int = 503,
                 retry_after_s: float = 0.05, reason: str = "queue_full"):
        super().__init__(message)
        if status not in (429, 503):
            raise ValueError(f"ShedRequest status must be 429 or 503, got {status}")
        self.status = int(status)
        self.retry_after_s = float(retry_after_s)
        self.reason = str(reason)


def backoff_s(attempt: int, *, base_s: float = 0.05, cap_s: float = 2.0,
              jitter: float = 0.5, rng: random.Random | None = None) -> float:
    """Capped exponential backoff for 0-based ``attempt``, with jitter.

    Deterministic given ``rng``; with ``jitter=j`` the delay is uniform in
    ``[(1-j)*d, d]`` where ``d = min(cap_s, base_s * 2**attempt)``.
    """
    if attempt < 0:
        raise ValueError(f"attempt must be >= 0, got {attempt}")
    delay = min(float(cap_s), float(base_s) * (2.0 ** int(attempt)))
    if jitter > 0.0 and rng is not None:
        delay *= (1.0 - jitter) + jitter * rng.random()
    return delay


def retry_call(fn, *, attempts: int = 4, base_s: float = 0.05, cap_s: float = 2.0,
               jitter: float = 0.5, retry_on=(Exception,), should_retry=None,
               seed: int | None = None, sleep=time.sleep, tracer=None,
               name: str = ""):
    """Call ``fn()`` with up to ``attempts`` tries and capped backoff between.

    Retries exceptions matching ``retry_on`` (and, if given, passing the
    ``should_retry(exc) -> bool`` predicate); the last failure re-raises.
    ``seed`` makes the jitter deterministic (None = unjittered backoff so
    bare calls stay reproducible). Each retry emits a ``retry_backoff``
    trace event when ``tracer`` is provided.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    rng = random.Random(seed) if seed is not None else None
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as exc:
            if should_retry is not None and not should_retry(exc):
                raise
            if attempt == attempts - 1:
                raise
            delay = backoff_s(attempt, base_s=base_s, cap_s=cap_s,
                              jitter=jitter if rng is not None else 0.0, rng=rng)
            if tracer is not None:
                tracer("retry_backoff", name=name or getattr(fn, "__name__", "call"),
                       attempt=attempt + 1, delay_s=round(delay, 9),
                       error=f"{type(exc).__name__}: {exc}"[:200])
            sleep(delay)
    raise AssertionError("unreachable")


def retry(**retry_kwargs):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(lambda: fn(*args, **kwargs), **retry_kwargs)

        return wrapper

    return deco


# Gauge encoding for /metrics: hdbscan_tpu_circuit_state{name=...}.
CIRCUIT_STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """Classic three-state breaker guarding an unreliable dependency.

    closed: calls allowed; ``failures`` consecutive failures trip it open.
    open: calls refused until ``reset_s`` has elapsed since the trip.
    half_open: trial calls allowed; the first success closes, the first
    failure re-opens. (Trials are not limited to one here — a caller whose
    ``allow()`` never materializes into an attempt must not wedge the
    breaker; the server's refitter serializes attempts anyway.)

    Transitions emit ``circuit_state`` trace events and call ``on_state``
    (the server points this at the ``circuit_state`` gauge). Thread-safe.
    """

    def __init__(self, name: str = "circuit", *, failures: int = 3,
                 reset_s: float = 30.0, tracer=None, on_state=None,
                 clock=time.monotonic):
        if failures < 1:
            raise ValueError(f"failures must be >= 1, got {failures}")
        if reset_s <= 0.0:
            raise ValueError(f"reset_s must be > 0, got {reset_s}")
        self.name = str(name)
        self.failure_threshold = int(failures)
        self.reset_s = float(reset_s)
        self.tracer = tracer
        self.on_state = on_state
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0  # consecutive
        self._opened_at = 0.0
        self._trips = 0

    def _transition(self, state: str) -> None:
        # caller holds the lock
        if state == self._state:
            return
        self._state = state
        if state == "open":
            self._opened_at = self._clock()
            self._trips += 1
        tracer, on_state = self.tracer, self.on_state
        failures = self._failures
        if tracer is not None:
            tracer("circuit_state", name=self.name, state=state, failures=failures)
        if on_state is not None:
            on_state(self.name, state)

    def allow(self) -> bool:
        """True if a call may proceed now (may move open -> half_open)."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at >= self.reset_s:
                    self._transition("half_open")
                    return True
                return False
            return True  # half_open: trials allowed

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._transition("closed")

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == "half_open" or self._failures >= self.failure_threshold:
                self._transition("open")

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def state_info(self) -> dict:
        """Snapshot for /healthz."""
        with self._lock:
            info = {
                "name": self.name,
                "state": self._state,
                "failures": self._failures,
                "trips": self._trips,
            }
            if self._state == "open":
                info["retry_in_s"] = round(
                    max(0.0, self.reset_s - (self._clock() - self._opened_at)), 6
                )
            return info
