"""Deterministic fault-injection harness for the serving/streaming stack.

A *fault plan* names injection sites and, per site, how often and how many
times to fire. The spec grammar (also accepted via the ``HDBSCAN_TPU_FAULTS``
environment variable and the ``faults=...`` config flag) is::

    site[:key=value[,key=value...]][;site2[:...]...]

with keys

- ``p``        firing probability per arrival at the site (default 1.0)
- ``count``    maximum number of fires for the site (default unlimited)
- ``seed``     per-site PRNG seed — same spec, same arrival order, same
               fires (default 0)
- ``mode``     site-specific behavior variant (e.g. ``artifact_save`` has
               ``torn`` and ``digest``); default ``raise``
- ``delay_s``  stall duration for ``slow_request`` (default 0.05)

Example: ``predict_dispatch:p=0.2,count=5,seed=7;artifact_save:mode=torn``.

Sites check the plan through :func:`maybe_fire`. The no-fault fast path is a
module attribute ``is None`` test, so leaving injection compiled into hot
paths costs nothing measurable (the `bench.py slo` overhead guard enforces
this). Every fire emits a ``fault_injected`` trace event and invokes the
installed ``on_fire`` hooks (the server wires these to the
``hdbscan_tpu_faults_injected_total{site}`` counter), so chaos tests can
prove that metrics/trace account for every injected fault.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field

ENV_VAR = "HDBSCAN_TPU_FAULTS"

# Sites wired into the stack. parse_spec rejects unknown names so a typo in
# a chaos config fails loudly instead of silently injecting nothing.
FAULT_SITES = (
    "predict_dispatch",  # predictor device dispatch (fails the coalesced batch)
    "artifact_save",     # model publish; mode=torn crashes pre-rename, mode=digest corrupts bytes
    "artifact_load",     # model load (transient; callers retry with backoff)
    "refit_fit",         # background refit crash
    "batcher_submit",    # micro-batcher enqueue
    "http_reset",        # server drops the connection without a response
    "slow_request",      # server stalls delay_s before handling
    "phase_stall",       # heartbeat beat() sleeps delay_s before refreshing liveness (watchdog tests)
)


class InjectedFault(Exception):
    """Raised at an injection site standing in for a real crash/IO error."""


@dataclass
class SiteSpec:
    """Parsed per-site injection parameters."""

    site: str
    p: float = 1.0
    count: int = -1  # -1 = unlimited
    seed: int = 0
    mode: str = "raise"
    delay_s: float = 0.05

    def __post_init__(self) -> None:
        if self.site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; expected one of {sorted(FAULT_SITES)}"
            )
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"fault site {self.site}: p must be in [0, 1], got {self.p}")
        if self.delay_s < 0.0:
            raise ValueError(f"fault site {self.site}: delay_s must be >= 0, got {self.delay_s}")


def parse_spec(text: str) -> list[SiteSpec]:
    """Parse a ``site:key=val,...;site2:...`` spec into :class:`SiteSpec` list."""
    specs: list[SiteSpec] = []
    seen: set[str] = set()
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        site, _, rest = clause.partition(":")
        site = site.strip()
        kwargs: dict[str, object] = {}
        for pair in rest.split(","):
            pair = pair.strip()
            if not pair:
                continue
            key, eq, value = pair.partition("=")
            key = key.strip()
            value = value.strip()
            if not eq or not value:
                raise ValueError(f"fault spec clause {clause!r}: expected key=value, got {pair!r}")
            if key == "p":
                kwargs["p"] = float(value)
            elif key == "count":
                kwargs["count"] = int(value)
            elif key == "seed":
                kwargs["seed"] = int(value)
            elif key == "mode":
                kwargs["mode"] = value
            elif key == "delay_s":
                kwargs["delay_s"] = float(value)
            else:
                raise ValueError(f"fault spec clause {clause!r}: unknown key {key!r}")
        if site in seen:
            raise ValueError(f"fault spec names site {site!r} twice")
        seen.add(site)
        specs.append(SiteSpec(site=site, **kwargs))
    return specs


@dataclass
class _SiteState:
    spec: SiteSpec
    rng: random.Random
    fired: int = 0


class FaultPlan:
    """An installed set of sites with per-site PRNG state and fire counts.

    Thread-safe: serving sites fire from HTTP handler threads, the batcher
    worker, and the refit daemon concurrently.
    """

    def __init__(self, specs, tracer=None):
        if isinstance(specs, str):
            specs = parse_spec(specs)
        self._sites = {s.site: _SiteState(spec=s, rng=random.Random(s.seed)) for s in specs}
        self._lock = threading.Lock()
        self.tracer = tracer
        self._on_fire: list = []

    def add_on_fire(self, hook) -> None:
        """Register ``hook(site, spec, nth)`` called on every fire."""
        with self._lock:
            if hook not in self._on_fire:
                self._on_fire.append(hook)

    def maybe_fire(self, site: str):
        """Return the :class:`SiteSpec` if ``site`` fires this arrival, else None."""
        state = self._sites.get(site)
        if state is None:
            return None
        with self._lock:
            spec = state.spec
            if 0 <= spec.count <= state.fired:
                return None
            if spec.p < 1.0 and state.rng.random() >= spec.p:
                return None
            state.fired += 1
            nth = state.fired
            hooks = list(self._on_fire)
        tracer = self.tracer
        if tracer is not None:
            tracer("fault_injected", site=site, mode=spec.mode, nth=nth)
        for hook in hooks:
            hook(site, spec, nth)
        return spec

    def fired(self) -> dict[str, int]:
        """Per-site fire counts so far."""
        with self._lock:
            return {name: st.fired for name, st in self._sites.items()}

    def sites(self) -> tuple[str, ...]:
        return tuple(self._sites)


# Module-level plan checked by every injection site. None = no faults: the
# hot-path cost of an uninstalled harness is one attribute load + is-None.
_PLAN: FaultPlan | None = None
_INSTALL_LOCK = threading.Lock()


def install(spec, tracer=None) -> FaultPlan:
    """Install ``spec`` (string or FaultPlan) as the process-wide plan."""
    global _PLAN
    plan = spec if isinstance(spec, FaultPlan) else FaultPlan(spec, tracer=tracer)
    if tracer is not None and plan.tracer is None:
        plan.tracer = tracer
    with _INSTALL_LOCK:
        _PLAN = plan
    return plan


def install_from_env(tracer=None):
    """Install a plan from ``HDBSCAN_TPU_FAULTS`` if set; return it (or None)."""
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        return None
    return install(spec, tracer=tracer)


def clear() -> None:
    """Remove the process-wide plan (sites stop firing)."""
    global _PLAN
    with _INSTALL_LOCK:
        _PLAN = None


def plan() -> FaultPlan | None:
    return _PLAN


def maybe_fire(site: str):
    """Fire ``site`` against the installed plan; None when no plan/no fire."""
    p = _PLAN
    if p is None:
        return None
    return p.maybe_fire(site)
