"""Fault-tolerance layer: deterministic fault injection + resilience policy.

MR-HDBSCAN* inherits fault tolerance from MapReduce/Spark lineage
re-execution for free; the TPU-native serving port has to earn it
explicitly. This package supplies the two halves:

- ``fault/inject.py`` — a deterministic fault-injection harness: named
  sites across the serving/streaming stack (predictor device dispatch,
  artifact save/load, refit fit-crash, batcher submit, HTTP socket resets,
  slow-request stalls) fire with per-site probability/count/seed from the
  ``HDBSCAN_TPU_FAULTS`` spec, emitting ``fault_injected`` trace events so
  every injected failure is accounted for in the trace and metrics.
- ``fault/policy.py`` — the resilience policies the chaos suite exercises:
  per-request deadlines (``DeadlineExceeded`` → 504), bounded-queue load
  shedding (``ShedRequest`` → 429/503 + Retry-After), capped exponential
  backoff with jitter (``retry_call``/``retry``), and a ``CircuitBreaker``
  that trips after repeated failures and degrades to the pinned model
  generation.

Stdlib-only on purpose: injection sites live on serving hot paths, and the
no-fault fast path is a single module-attribute check.
"""

from hdbscan_tpu.fault.inject import (  # noqa: F401
    ENV_VAR,
    FAULT_SITES,
    FaultPlan,
    InjectedFault,
    clear,
    install,
    maybe_fire,
    parse_spec,
)
from hdbscan_tpu.fault.policy import (  # noqa: F401
    CircuitBreaker,
    DeadlineExceeded,
    ShedRequest,
    backoff_s,
    retry,
    retry_call,
)
