"""Native (C) host-runtime components, built on demand via the system cc.

The TPU compute path is JAX/XLA; the host runtime around it keeps its hot
loops in C where Python would dominate (the per-edge Kruskal merge-forest
loop runs once per tree build over every pooled edge). Compilation happens
at first use into ``<repo>/.native_cache`` with a source-mtime check; every
caller falls back to the pure-Python implementation when no compiler is
available, so the native layer is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_DIR = os.path.dirname(__file__)
_CACHE = os.environ.get(
    "HDBSCAN_TPU_NATIVE_CACHE",
    os.path.join(os.path.dirname(os.path.dirname(_DIR)), ".native_cache"),
)

_lib = None
_lib_tried = False


def _build(src: str, so: str) -> bool:
    os.makedirs(os.path.dirname(so), exist_ok=True)
    # Compile to a unique temp name and rename into place: an interrupted or
    # concurrent build must never leave a half-written .so with a fresh mtime
    # (it would pass the rebuild check and disable native acceleration until
    # manually deleted).
    tmp = f"{so}.{os.getpid()}.tmp"
    for cc in ("cc", "gcc", "clang"):
        try:
            subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", "-o", tmp, src],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, so)
            return True
        except (OSError, subprocess.SubprocessError):
            continue
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    return False


def merge_forest_lib():
    """ctypes handle to the merge-forest library, or None (use Python)."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("HDBSCAN_TPU_NO_NATIVE"):
        return None
    src = os.path.join(_DIR, "merge_forest.c")
    so = os.path.join(_CACHE, "merge_forest.so")
    try:
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            if not _build(src, so):
                return None
        lib = ctypes.CDLL(so)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.build_merge_forest_c.restype = ctypes.c_int64
        lib.build_merge_forest_c.argtypes = [
            ctypes.c_int64, ctypes.c_int64,
            i64p, i64p, f64p, f64p, ctypes.c_double,
            i64p, i64p, f64p, f64p, f64p, u8p, i64p, i64p, i64p,
        ]
        lib.flatten_children_c.restype = ctypes.c_int64
        lib.flatten_children_c.argtypes = [
            ctypes.c_int64, u8p, i64p, i64p, i64p, i64p,
        ]
        _lib = lib
    except (OSError, AttributeError):
        # AttributeError: a stale cached .so missing a newer symbol — fall
        # back to Python rather than crash (the mtime check rebuilds next
        # time the source is newer).
        _lib = None
    return _lib
