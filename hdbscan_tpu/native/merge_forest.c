/* Kruskal single-linkage merge forest with equal-weight tie contraction.
 *
 * Native implementation of core/tree.py::build_merge_forest's hot loop (the
 * host-side global merge of the distributed pipeline — the analog of the
 * reference's UnionFindReducer + dendrogram assembly). Edges arrive sorted by
 * (w, u, v); the loop unions components, creates a merge node per accepted
 * edge, and contracts children whose tie-group anchor matches the current
 * weight (relative tolerance) into multi-way nodes.
 *
 * Children lists are kept as intrusive linked lists (head/tail/next indexed
 * by node id) so tie absorption is an O(1) splice; the caller flattens them.
 * Union-find uses path halving.
 *
 * Outputs (preallocated by the caller, m = edge count):
 *   dist[t], anchor[t], absorbed[t]  per created merge node t (0..t_count)
 *   sizes[node]      weighted member count per node, capacity n + m
 *                    (first n = point weights)
 *   child_head/tail  per merge node (capacity m); child_next over node ids
 *                    (capacity n + m) — intrusive child lists
 *   parent/top       POINT-root union-find and per-root merge-tree top,
 *                    capacity n (merge-node ids never enter the union-find)
 * Edge acceptance is implicit: cycle edges create no merge node. Returns
 * t_count (number of merge nodes created).
 */

#include <stdint.h>

static int64_t uf_find(int64_t *parent, int64_t x) {
    while (parent[x] != x) {
        parent[x] = parent[parent[x]];
        x = parent[x];
    }
    return x;
}

static double fabs_(double x) { return x < 0 ? -x : x; }

static int tied(double a, double b, double rtol) {
    double m = fabs_(a) > fabs_(b) ? fabs_(a) : fabs_(b);
    return fabs_(a - b) <= rtol * m;
}

int64_t build_merge_forest_c(
    int64_t n, int64_t m,
    const int64_t *u, const int64_t *v, const double *w,
    const double *point_weights, double tie_rtol,
    /* work + output buffers, all caller-allocated: */
    int64_t *parent,      /* (n) union-find over point ids               */
    int64_t *top,         /* (n) merge-tree root per point UF root       */
    double *sizes,        /* (n + m) weighted counts                     */
    double *dist,         /* (m) per merge node                          */
    double *anchor,       /* (m) tie-group anchor per merge node         */
    uint8_t *absorbed,    /* (m) node was contracted into a parent       */
    int64_t *child_head,  /* (m) first child node id or -1               */
    int64_t *child_tail,  /* (m) last child node id or -1                */
    int64_t *child_next   /* (n + m) next sibling node id or -1          */
) {
    int64_t next_node = n;
    for (int64_t i = 0; i < n; i++) {
        parent[i] = i;
        top[i] = i;
        sizes[i] = point_weights[i];
        child_next[i] = -1;
    }
    for (int64_t i = 0; i < m; i++) {
        int64_t ra = uf_find(parent, u[i]);
        int64_t rb = uf_find(parent, v[i]);
        if (ra == rb) continue;
        int64_t ta = top[ra], tb = top[rb];
        double wi = w[i];
        int64_t node = next_node++;
        int64_t t = node - n;
        dist[t] = wi;
        anchor[t] = wi;
        absorbed[t] = 0;
        child_head[t] = -1;
        child_tail[t] = -1;
        child_next[node] = -1;
        int64_t kids[2] = {ta, tb};
        for (int j = 0; j < 2; j++) {
            int64_t c = kids[j];
            if (c >= n && tied(anchor[c - n], wi, tie_rtol)) {
                /* contract the equal-weight child: splice its list in */
                absorbed[c - n] = 1;
                if (anchor[c - n] < anchor[t]) anchor[t] = anchor[c - n];
                if (child_head[c - n] >= 0) {
                    if (child_tail[t] < 0) {
                        child_head[t] = child_head[c - n];
                    } else {
                        child_next[child_tail[t]] = child_head[c - n];
                    }
                    child_tail[t] = child_tail[c - n];
                }
            } else {
                if (child_tail[t] < 0) {
                    child_head[t] = c;
                } else {
                    child_next[child_tail[t]] = c;
                }
                child_tail[t] = c;
            }
        }
        sizes[node] = sizes[ta] + sizes[tb];
        parent[rb] = ra;
        top[ra] = node;
    }
    return next_node - n;
}

/* Flatten the intrusive child lists into CSR form: kid_flat holds every
 * non-absorbed node's children concatenated in node order (list order
 * preserved — the order the Python builder would produce), kid_count[t] the
 * per-node count (0 for absorbed nodes). Returns the total kid count. The
 * caller slices kid_flat by cumulative kid_count; the array layer
 * (core/tree_vec.py) consumes it directly instead of re-flattening Python
 * lists. */
int64_t flatten_children_c(
    int64_t t_count,
    const uint8_t *absorbed,
    const int64_t *child_head,
    const int64_t *child_next,
    int64_t *kid_flat,   /* (n + m) capacity */
    int64_t *kid_count   /* (t_count) */
) {
    int64_t k = 0;
    for (int64_t t = 0; t < t_count; t++) {
        if (absorbed[t]) {
            kid_count[t] = 0;
            continue;
        }
        int64_t start = k;
        for (int64_t c = child_head[t]; c >= 0; c = child_next[c]) {
            kid_flat[k++] = c;
        }
        kid_count[t] = k - start;
    }
    return k;
}
