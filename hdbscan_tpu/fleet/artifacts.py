"""Per-host zero-copy artifact store: one resident copy per digest.

The static fleet pays tenant model memory once per REPLICA: every replica's
``TenantRegistry`` privately ``ClusterModel.load``s the same ``.npz``, so a
host running R replicas over T tenants holds R×T copies of the training
arrays. :class:`ArtifactStore` collapses that to one copy per host:

* **Digest-keyed** — an artifact is identified by the sha256 of its file
  bytes (the same digest discipline ``serve/artifact.py`` applies to the
  payload). Two tenants publishing byte-identical artifacts share one
  mapping; a republished generation has a new digest and maps fresh.
* **Spool + mmap** — on first touch the store validates the artifact
  through the unchanged ``ClusterModel.load`` path (schema allow-list,
  stored-digest == fingerprint check), then spools each array member to a
  plain ``.npy`` under ``spool_dir/<digest>/`` and re-opens them with
  ``np.load(..., mmap_mode="r")``. Every replica process on the host that
  loads the same digest maps the same spool files, so the training arrays
  live once in the OS page cache no matter how many replicas serve them.
  (``np.load`` cannot mmap *inside* an ``.npz`` zip — compressed or not,
  members are read through zipfile — which is why the spool exists.)
* **Process cache** — within one process, repeat loads of a digest return
  the same :class:`~hdbscan_tpu.serve.artifact.ClusterModel` object, so a
  registry re-warming an evicted tenant pays zero array I/O. Entries live
  for the life of the process: the whole point is that the host-level
  cost is bounded by distinct artifacts, not by LRU traffic.

Every load emits an ``artifact_map`` trace event (validated by
``scripts/check_trace.py``: per process a digest maps fresh — ``hit=false``
— at most once) and the ``hdbscan_tpu_artifact_*`` metric families.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import threading
import time

import numpy as np

__all__ = ["ArtifactStore", "default_store", "file_digest"]

#: Array members every artifact carries (``serve/artifact.ClusterModel``
#: field order); optional ``rpf_*`` members ride alongside.
_MEMBERS = (
    "data", "core", "labels", "last_cluster", "parent", "birth",
    "selected", "sel_anc", "eps_min", "eps_max",
)

_CHUNK = 1 << 20


def file_digest(path: str) -> str:
    """sha256 of the file bytes — the store's identity for an artifact."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(_CHUNK)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _default_spool_dir() -> str:
    env = os.environ.get("HDBSCAN_TPU_ARTIFACT_SPOOL")
    if env:
        return env
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"hdbscan_tpu_artifacts_{uid}")


class ArtifactStore:
    """Host-shared, digest-keyed cache of memory-mapped ClusterModels.

    Args:
      spool_dir: directory for the per-digest ``.npy`` spools. Defaults to
        ``$HDBSCAN_TPU_ARTIFACT_SPOOL`` or a per-user tmp path — every
        replica on the host must resolve the same directory for the page
        cache to be shared.
      mmap: open spooled members with ``mmap_mode="r"`` (default). False
        materializes (still one copy per process per digest) — for
        filesystems where mmap misbehaves.
      tracer / metrics: ``artifact_map`` trace events and the
        ``hdbscan_tpu_artifact_*`` instruments.
    """

    def __init__(self, spool_dir: str | None = None, *, mmap: bool = True,
                 tracer=None, metrics=None):
        self.spool_dir = spool_dir or _default_spool_dir()
        self.mmap = bool(mmap)
        self.tracer = tracer
        self._lock = threading.Lock()
        self._cache: dict = {}  # digest -> ClusterModel
        self._refs: dict = {}  # digest -> load count
        self._bytes: dict = {}  # digest -> resident array bytes
        self._m_loads = self._m_resident = self._m_bytes = None
        if metrics is not None:
            self._m_loads = metrics.counter(
                "hdbscan_tpu_artifact_loads_total",
                "Artifact-store loads by outcome (hit = process cache).",
                ("outcome",),
            )
            self._m_resident = metrics.gauge(
                "hdbscan_tpu_artifact_resident",
                "Distinct artifact digests resident in this process.",
            )
            self._m_bytes = metrics.gauge(
                "hdbscan_tpu_artifact_resident_bytes",
                "Array bytes mapped by resident artifacts (shared per host).",
            )

    # -- spool -------------------------------------------------------------

    def _spool_path(self, digest: str) -> str:
        return os.path.join(self.spool_dir, digest)

    def _write_spool(self, model, digest: str) -> bool:
        """Spool ``model``'s arrays under ``<spool_dir>/<digest>/``;
        returns True when this call published the spool (False when a
        sibling process won the rename race)."""
        final = self._spool_path(digest)
        if os.path.isdir(final):
            return False
        os.makedirs(self.spool_dir, exist_ok=True)
        tmp = tempfile.mkdtemp(dir=self.spool_dir, prefix=f".{digest[:12]}.")
        try:
            meta = {
                "schema": model.schema,
                "mode": model.mode,
                "params": model.params,
                "fingerprint": model.fingerprint,
                "rpf": None if model.rpf is None else {
                    k: int(model.rpf[k])
                    for k in ("trees", "depth", "leaf_size")
                },
            }
            with open(os.path.join(tmp, "meta.json"), "w",
                      encoding="utf-8") as f:
                json.dump(meta, f)
            for name in _MEMBERS:
                np.save(os.path.join(tmp, f"{name}.npy"),
                        np.asarray(getattr(model, name)))
            if model.rpf is not None:
                from hdbscan_tpu.serve.artifact import _RPF_ARRAYS

                for key in _RPF_ARRAYS:
                    np.save(os.path.join(tmp, f"rpf_{key}.npy"),
                            np.asarray(model.rpf[key]))
            try:
                os.rename(tmp, final)
                return True
            except OSError:
                return False  # concurrent spooler won; theirs is complete
        finally:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp, ignore_errors=True)

    def _read_spool(self, digest: str):
        """Reconstruct a ClusterModel from a spool, arrays memory-mapped.
        Returns None when the spool is absent or unreadable (caller falls
        back to the .npz)."""
        from hdbscan_tpu.serve.artifact import (
            _COMPAT_SCHEMAS, _RPF_ARRAYS, ClusterModel,
        )
        from hdbscan_tpu.utils.checkpoint import _data_digest

        root = self._spool_path(digest)
        mode = "r" if self.mmap else None
        try:
            with open(os.path.join(root, "meta.json"),
                      encoding="utf-8") as f:
                meta = json.load(f)
            if meta.get("schema") not in _COMPAT_SCHEMAS:
                return None
            arrays = {
                name: np.load(os.path.join(root, f"{name}.npy"),
                              mmap_mode=mode)
                for name in _MEMBERS
            }
            rpf = None
            if meta.get("rpf") is not None:
                rpf = dict(meta["rpf"])
                for key in _RPF_ARRAYS:
                    rpf[key] = np.load(os.path.join(root, f"rpf_{key}.npy"),
                                       mmap_mode=mode)
            model = ClusterModel(
                mode=meta["mode"], params=meta["params"],
                fingerprint=meta["fingerprint"], schema=meta["schema"],
                rpf=rpf, **arrays,
            )
        except (OSError, ValueError, KeyError):
            return None
        # Same corruption stance as ClusterModel.load: the spooled training
        # data must still hash to the stored fingerprint (a torn or tampered
        # spool must not serve).
        stored = model.fingerprint.get("data")
        if stored is not None and _data_digest(np.asarray(model.data)) != stored:
            return None
        return model

    # -- load --------------------------------------------------------------

    def load(self, path: str):
        """Resolve ``path`` to a (possibly shared) ClusterModel.

        First touch of a digest validates through ``ClusterModel.load``
        (or an existing sibling spool), publishes the spool, and maps it;
        repeat touches return the process-cached model. Raises whatever
        ``ClusterModel.load`` raises on a corrupt/mismatched artifact.
        """
        t0 = time.perf_counter()
        digest = file_digest(path)
        with self._lock:
            model = self._cache.get(digest)
            if model is not None:
                self._refs[digest] += 1
                self._emit(path, digest, hit=True, spooled=False, t0=t0)
                return model
        # Miss: validate + spool outside the lock (loads can be slow), then
        # publish under it. A concurrent same-digest load does duplicate
        # work but both land on one cache entry.
        spooled = False
        model = self._read_spool(digest)
        if model is None:
            from hdbscan_tpu.serve.artifact import ClusterModel

            loaded = ClusterModel.load(path)
            spooled = self._write_spool(loaded, digest)
            model = self._read_spool(digest) or loaded
        with self._lock:
            if digest in self._cache:  # concurrent loader published first
                model = self._cache[digest]
                self._refs[digest] += 1
                self._emit(path, digest, hit=True, spooled=spooled, t0=t0)
                return model
            self._cache[digest] = model
            self._refs[digest] = 1
            self._bytes[digest] = int(
                sum(np.asarray(getattr(model, m)).nbytes for m in _MEMBERS)
            )
            self._emit(path, digest, hit=False, spooled=spooled, t0=t0)
            return model

    def _emit(self, path: str, digest: str, *, hit: bool, spooled: bool,
              t0: float) -> None:
        # caller holds the lock
        if self._m_loads is not None:
            self._m_loads.inc(outcome="hit" if hit else "miss")
            self._m_resident.set(len(self._cache))
            self._m_bytes.set(float(sum(self._bytes.values())))
        if self.tracer is not None:
            self.tracer(
                "artifact_map", digest=digest, path=str(path),
                hit=bool(hit), spooled=bool(spooled),
                resident=len(self._cache),
                bytes=int(self._bytes.get(digest, 0)),
                refs=int(self._refs.get(digest, 0)),
                wall_s=round(time.perf_counter() - t0, 6),
            )

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "spool_dir": self.spool_dir,
                "resident": len(self._cache),
                "resident_bytes": int(sum(self._bytes.values())),
                "refs": dict(self._refs),
            }


_DEFAULT_LOCK = threading.Lock()
_DEFAULT: ArtifactStore | None = None


def default_store(tracer=None, metrics=None) -> ArtifactStore:
    """The process-wide store (created on first use). ``tracer``/
    ``metrics`` attach on the creating call only — later callers share the
    instance as-is."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = ArtifactStore(tracer=tracer, metrics=metrics)
        return _DEFAULT
