"""Fit-as-a-service: per-tenant fit/refit jobs on a bounded worker pool.

The paper's driver schedules fit work across executors; this is the serving
fleet's version of the same idea — one fleet both serves and re-fits.
:class:`FitScheduler` accepts fit jobs per tenant, applies a per-tenant
token-bucket quota (the ``TenantRegistry`` discipline, pointed at fits
instead of predicts) and a global queue bound, runs at most ``workers``
fits concurrently on daemon threads, and publishes each result through the
caller's ``publish`` callback — in the fleet, ``TenantRegistry.swap``, the
per-tenant blue/green generation bump.

Contracts, mirrored from ``stream/refit.Refitter``:

* A failed fit never touches serving: the worker records the error on the
  job (state ``failed``), reports through ``on_result`` (the circuit
  breaker hook), and moves on. Worker threads survive any job exception.
* The fit→distill→publish core is the SAME code path as the single-server
  refitter (:func:`stream.refit.fit_and_publish`): obs phases, atomic
  save, retried publish, ``artifact_save`` fault sites intact.
* Every state transition emits a ``fit_job`` trace event; the
  ``queued → running → published | failed`` machine is validated per job
  by ``scripts/check_trace.py``, and ``hdbscan_tpu_fit_jobs_total`` /
  queue-depth gauges by ``check_metrics.py``.

Jobs publish uncompressed by default (``compress=False``) so the per-host
``ArtifactStore`` can spool-and-mmap the new generation without a
decompression copy.
"""

from __future__ import annotations

import math
import os
import queue
import threading
import time
from dataclasses import dataclass, field

from hdbscan_tpu.fault.policy import ShedRequest

__all__ = ["FitJob", "FitScheduler"]

#: Terminal job states (``queued``/``running`` are transient).
TERMINAL_STATES = ("published", "failed")


@dataclass
class FitJob:
    """One scheduled fit: identity, lifecycle timestamps, and outcome."""

    job_id: str
    tenant: str
    reason: str
    points: object = field(repr=False, default=None)
    params: object = field(repr=False, default=None)
    state: str = "queued"
    submitted_at: float = 0.0
    started_at: float | None = None
    finished_at: float | None = None
    error: str | None = None
    path: str | None = None
    generation: int | None = None
    done: threading.Event = field(default_factory=threading.Event, repr=False)

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the job reaches a terminal state."""
        return self.done.wait(timeout)


@dataclass
class _Bucket:
    tokens: float
    last: float


class FitScheduler:
    """Bounded fit/refit worker pool with per-tenant quotas.

    Args:
      model_dir: artifacts land at ``model_dir/<tenant>_gen<k>.npz``.
      params: default fit params (per-job override via ``submit``).
      fit_fn: fit entry point override (tests); default
        ``models.hdbscan.fit``.
      publish: ``callback(tenant, path, model) -> entry-or-None`` run on
        the worker after a successful save — ``TenantRegistry.swap`` makes
        it the blue/green generation bump. A raising publish fails the job
        (the artifact stays on disk; serving is untouched).
      on_result: ``callback(ok, error)`` per terminal job — the circuit
        breaker hook, same signature as ``Refitter``'s.
      workers: concurrent fits (>= 1).
      queue_bound: max queued-but-not-running jobs; an overflowing submit
        sheds with HTTP 503 semantics.
      quota_rps: sustained per-tenant job rate (token bucket, burst 1);
        0 disables. Over-quota submits shed with HTTP 429 + Retry-After.
      compress: compress published artifacts (default False — see module
        docstring).
    """

    def __init__(self, model_dir: str, *, params=None, fit_fn=None,
                 publish=None, on_result=None, workers: int = 2,
                 queue_bound: int = 16, quota_rps: float = 0.0,
                 compress: bool = False, tracer=None, metrics=None,
                 clock=time.monotonic):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers!r}")
        if queue_bound < 1:
            raise ValueError(f"queue_bound must be >= 1, got {queue_bound!r}")
        if quota_rps < 0.0 or not math.isfinite(quota_rps):
            raise ValueError(
                f"quota_rps must be finite and >= 0, got {quota_rps!r}"
            )
        self.model_dir = str(model_dir)
        self.params = params
        self.fit_fn = fit_fn
        self.publish = publish
        self.on_result = on_result
        self.workers = int(workers)
        self.queue_bound = int(queue_bound)
        self.quota_rps = float(quota_rps)
        self.compress = bool(compress)
        self.tracer = tracer
        self._clock = clock
        self._lock = threading.Lock()
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_bound)
        self._jobs: dict = {}  # job_id -> FitJob
        self._seq = 0
        self._gen: dict = {}  # tenant -> published artifact count
        self._buckets: dict = {}  # tenant -> _Bucket
        self._running = 0
        self._shutdown = threading.Event()
        self.published = 0
        self.failed = 0
        self.shed = 0
        self._m_jobs = self._m_queued = self._m_running = None
        if metrics is not None:
            self._m_jobs = metrics.counter(
                "hdbscan_tpu_fit_jobs_total",
                "Fit-as-a-service jobs by tenant and terminal outcome.",
                ("tenant", "state"),
            )
            self._m_queued = metrics.gauge(
                "hdbscan_tpu_fit_jobs_queued",
                "Fit jobs accepted but not yet running.",
            )
            self._m_running = metrics.gauge(
                "hdbscan_tpu_fit_jobs_running",
                "Fit jobs currently on a worker thread.",
            )
        self._threads = [
            threading.Thread(target=self._worker_loop,
                             name=f"fit-worker-{i}", daemon=True)
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    # -- submission --------------------------------------------------------

    def _acquire_quota(self, tenant: str) -> None:
        # caller holds the lock
        if self.quota_rps <= 0.0:
            return
        now = self._clock()
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = _Bucket(tokens=1.0, last=now)
        b.tokens = min(1.0, b.tokens + (now - b.last) * self.quota_rps)
        b.last = now
        if b.tokens >= 1.0:
            b.tokens -= 1.0
            return
        self.shed += 1
        if self._m_jobs is not None:
            self._m_jobs.inc(tenant=tenant, state="shed")
        retry_s = (1.0 - b.tokens) / self.quota_rps
        raise ShedRequest(
            f"tenant {tenant!r} over fit quota ({self.quota_rps:g} jobs/s)",
            status=429, retry_after_s=retry_s, reason="fit_quota",
        )

    def submit(self, tenant: str, points, *, params=None,
               reason: str = "fit") -> FitJob:
        """Enqueue a fit for ``tenant`` over ``points``.

        Raises :class:`ShedRequest` when the tenant is over its job quota
        (429) or the queue is at its bound (503), and ``RuntimeError``
        after :meth:`close`.
        """
        tenant = str(tenant)
        if self._shutdown.is_set():
            raise RuntimeError("FitScheduler is closed")
        with self._lock:
            self._acquire_quota(tenant)
            self._seq += 1
            job = FitJob(
                job_id=f"{tenant}-{self._seq}", tenant=tenant,
                reason=str(reason), points=points,
                params=params if params is not None else self.params,
                submitted_at=self._clock(),
            )
        try:
            self._queue.put_nowait(job)
        except queue.Full:
            with self._lock:
                self.shed += 1
            if self._m_jobs is not None:
                self._m_jobs.inc(tenant=tenant, state="shed")
            raise ShedRequest(
                f"fit queue at bound ({self.queue_bound})",
                status=503, retry_after_s=1.0, reason="fit_queue_full",
            ) from None
        with self._lock:
            self._jobs[job.job_id] = job
        self._emit(job)
        self._set_gauges()
        return job

    # -- worker ------------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            try:
                job = self._queue.get(timeout=0.1)
            except queue.Empty:
                if self._shutdown.is_set():
                    return
                continue
            try:
                self._run_one(job)
            except BaseException:  # noqa: BLE001 — pool must survive anything
                pass
            finally:
                self._queue.task_done()

    def _run_one(self, job: FitJob) -> None:
        from hdbscan_tpu.stream.refit import fit_and_publish

        with self._lock:
            self._running += 1
            job.state = "running"
            job.started_at = self._clock()
        self._emit(job, queued_s=job.started_at - job.submitted_at)
        self._set_gauges()
        t0 = time.perf_counter()
        try:
            with self._lock:
                self._gen[job.tenant] = self._gen.get(job.tenant, 0) + 1
                gen = self._gen[job.tenant]
            path = os.path.join(
                self.model_dir, f"{job.tenant}_gen{gen:04d}.npz"
            )
            model = fit_and_publish(
                job.points, job.params, path,
                fit_fn=self.fit_fn, tracer=self.tracer, seed=gen,
                compress=self.compress, fault_site="fit_job",
                publish_name="fit_job_publish",
            )
            entry = None
            if self.publish is not None:
                entry = self.publish(job.tenant, path, model)
            with self._lock:
                self._running -= 1
                job.state = "published"
                job.path = path
                job.finished_at = self._clock()
                job.generation = getattr(entry, "generation", None)
                job.points = None  # don't pin the training rows
                self.published += 1
            if self._m_jobs is not None:
                self._m_jobs.inc(tenant=job.tenant, state="published")
            self._emit(job, wall_s=time.perf_counter() - t0)
            if self.on_result is not None:
                self.on_result(True, None)
        except Exception as exc:  # a bad fit never touches serving
            with self._lock:
                self._running -= 1
                job.state = "failed"
                job.error = f"{type(exc).__name__}: {exc}"[:500]
                job.finished_at = self._clock()
                job.points = None
                self.failed += 1
            if self._m_jobs is not None:
                self._m_jobs.inc(tenant=job.tenant, state="failed")
            self._emit(job, wall_s=time.perf_counter() - t0)
            if self.on_result is not None:
                self.on_result(False, job.error)
        finally:
            self._set_gauges()
            job.done.set()

    # -- bookkeeping -------------------------------------------------------

    def _emit(self, job: FitJob, **extra) -> None:
        if self.tracer is None:
            return
        fields = {
            "job": job.job_id, "tenant": job.tenant, "state": job.state,
            "reason": job.reason,
        }
        if job.state == "published" and job.generation is not None:
            fields["generation"] = int(job.generation)
        if job.state == "failed" and job.error:
            fields["error"] = job.error
        for k, v in extra.items():
            fields[k] = round(v, 6) if isinstance(v, float) else v
        self.tracer("fit_job", **fields)

    def _set_gauges(self) -> None:
        if self._m_queued is not None:
            self._m_queued.set(float(self._queue.qsize()))
            self._m_running.set(float(self._running))

    def job(self, job_id: str) -> FitJob:
        with self._lock:
            return self._jobs[job_id]

    def jobs(self) -> list:
        with self._lock:
            return list(self._jobs.values())

    def join(self, timeout: float | None = None) -> bool:
        """Wait for every accepted job to reach a terminal state."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            pending = [j for j in self._jobs.values() if not j.done.is_set()]
        for j in pending:
            left = None if deadline is None else deadline - time.monotonic()
            if left is not None and left <= 0:
                return False
            if not j.wait(left):
                return False
        return True

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting jobs and join the workers (queued jobs finish)."""
        self._shutdown.set()
        for t in self._threads:
            t.join(timeout)

    def stats(self) -> dict:
        with self._lock:
            states: dict = {}
            for j in self._jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            return {
                "workers": self.workers,
                "queue_bound": self.queue_bound,
                "quota_rps": self.quota_rps,
                "queued": self._queue.qsize(),
                "running": self._running,
                "published": self.published,
                "failed": self.failed,
                "shed": self.shed,
                "states": states,
            }
