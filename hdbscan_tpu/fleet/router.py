"""Asyncio fleet router: N replica subprocesses behind one front door.

The paper's MR-HDBSCAN* is a master coordinating many workers over
partitioned data; this is the serving-side analogue — a thin coordination
layer over unchanged per-replica servers, the way PANDA (arxiv 1607.08220)
scales one-node k-NN into a distributed exchange. Each replica is a full
``serve/server.ClusterServer`` process with the PR 8–10 contracts intact
(micro-batching, blue/green swap, deadlines, shedding, WAL); the router
adds only placement and failure handling:

* **Spawn/monitor** — replicas launch as ``python -m hdbscan_tpu serve``
  subprocesses sharing ``--model-dir`` artifacts (digest-guarded loads make
  concurrent loading safe) and report their ephemeral port through a
  ``--port-file``; a crashed replica is respawned (its WAL replays on the
  same ``wal_dir``, so acked ingest survives a SIGKILL).
* **Routing** — ``/predict``/``/ingest`` route by ``consistent_hash``
  (md5 ring over the request's tenant id, falling back to a body digest)
  or ``least_loaded`` (fewest in-flight proxied requests). A replica that
  refuses a connection is marked down *immediately* and the request
  re-routes in place — strictly faster than the one-health-interval bound.
  Re-dispatch after bytes were already sent is only safe for idempotent
  ``/predict``; a torn ``/ingest`` returns 502 rather than risk double
  ingestion (acked writes are WAL-durable either way).
* **Asyncio front-end** — the accept path is a single-threaded
  ``asyncio`` loop: connections are coroutines, not threads, so 10k idle
  keep-alive clients cost file descriptors rather than stacks, and the
  replicas' linger-based coalescing is fed by as many concurrent proxied
  requests as the OS allows.
* **Headers** — ``X-Deadline-Ms`` propagates to the chosen replica (and
  bounds the proxy's own wait); ``Retry-After`` from a shedding replica
  passes through untouched; an all-replicas-down 503 carries the health
  interval as its Retry-After.
* **Aggregation** — ``GET /metrics`` scrapes every live replica, re-parses
  the exposition into a registry tagged ``replica="<id>"``
  (``utils.metrics.registry_from_exposition``), and folds the results plus
  the router's own instruments through ``MetricsRegistry.merge()``.

Trace events: ``fleet_route`` per proxied request, ``replica_health`` per
probe, ``scale_event`` per scale operation — all validated by
``scripts/check_trace.py``.

Elasticity: the replica set is dynamic. :meth:`FleetRouter.scale_up`
spawns a STANDBY replica — port reported, health probe green, AOT warmup
done (warm-started from the shared persistent compile cache every replica
env points at) — before the ring is rebuilt to include it, so the first
routed request is full-speed. :meth:`FleetRouter.scale_down` removes the
replica from the ring first, drains its in-flight requests, then runs the
WAL-safe SIGTERM shutdown; a later scale-up reuses the lowest free rid and
thus the departed replica's WAL directory. The decision loop driving these
lives in ``fleet/controlplane.Autoscaler``, reading :meth:`signals`.

Device pinning: on multi-chip hosts pass ``devices=N`` — replica ``i``
gets ``TPU_VISIBLE_CHIPS``/``CUDA_VISIBLE_DEVICES`` set to ``i % N``
(keyed off ``JAX_PLATFORMS``), so replicas land on distinct chips instead
of all initializing chip 0.
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import itertools
import json
import math
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque

_TENANT_RE = re.compile(rb'"tenant"\s*:\s*(?:"((?:[^"\\]|\\.)*)"|(-?\d+))')

#: Routing policies ``FleetRouter`` accepts (mirrored by the
#: ``fleet_policy`` config knob).
POLICIES = ("consistent_hash", "least_loaded")

_VNODES = 64  # ring points per replica; 64 keeps the max/min load skew < ~20%

_HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding", "server", "date"}


def _h(key: str) -> int:
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


class _ReplicaError(Exception):
    """A proxied request failed against one replica. ``sent`` is True when
    request bytes reached the replica (re-dispatch is then unsafe for
    non-idempotent routes)."""

    def __init__(self, message: str, *, sent: bool):
        super().__init__(message)
        self.sent = sent


class _Replica:
    """One managed replica subprocess and its routing state."""

    def __init__(self, rid: str):
        self.rid = rid
        self.proc: subprocess.Popen | None = None
        self.port: int | None = None
        self.port_file = ""
        self.log_path = ""
        self.up = False
        self.retired = False  # scaled down: never respawn
        self.failures = 0  # consecutive
        self.in_flight = 0
        self.restarts = 0
        self.checks = 0
        # Deep-obs signals lifted from the replica's /healthz body at each
        # probe: watchdog stall count and straggler-flag totals, so one
        # router /healthz shows which replica is hung or on a slow device.
        self.watchdog_stalls = 0
        self.straggler_flags = 0

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class FleetRouter:
    """Spawn and front N ``serve`` replicas on one asyncio accept loop.

    Args:
      model_path: artifact every replica serves (``--model``).
      replicas: process count (>= 1).
      policy: one of :data:`POLICIES`.
      health_interval_s: probe period; also the re-route bound for a dead
        replica and the Retry-After hint when no replica is up.
      drain_s: SIGTERM drain bound per :meth:`close`; a replica still
        alive after it is SIGKILLed and close() reports failure.
      replica_args: extra serve argv entries (``predict_batch=32``, ...).
      replica_env: env overrides for every replica.
      tenants_dir / model_dir / ingest / wal_root: forwarded serving
        features; ``wal_root`` gives each replica ``wal_root/r<id>`` so a
        respawned replica replays its own WAL.
      devices: pin replica i to device ordinal ``i % devices``.
      restart: respawn replicas that exit while the fleet is running.
      tracer: optional ``utils.tracing.Tracer`` (``fleet_route`` /
        ``replica_health`` events).
    """

    def __init__(self, model_path: str, *, replicas: int = 2,
                 policy: str = "least_loaded", health_interval_s: float = 0.5,
                 drain_s: float = 10.0, host: str = "127.0.0.1", port: int = 0,
                 replica_args=(), replica_env: dict | None = None,
                 tenants_dir: str | None = None, model_dir: str | None = None,
                 ingest: bool = False, wal_root: str | None = None,
                 devices: int | None = None, restart: bool = True,
                 startup_timeout_s: float = 180.0, proxy_timeout_s: float = 30.0,
                 run_dir: str | None = None, tracer=None, metrics=None,
                 replica_trace_dir: str | None = None, verbose: bool = False,
                 compile_cache: str | None = "auto"):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas!r}")
        if policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {policy!r}"
            )
        if health_interval_s <= 0.0:
            raise ValueError(
                f"health_interval_s must be > 0, got {health_interval_s!r}"
            )
        if drain_s <= 0.0:
            raise ValueError(f"drain_s must be > 0, got {drain_s!r}")
        self.model_path = str(model_path)
        self.n_replicas = int(replicas)
        self.policy = policy
        self.health_interval_s = float(health_interval_s)
        self.drain_s = float(drain_s)
        self.host = host
        self.port = int(port)  # 0 until bound
        self.replica_args = list(replica_args)
        self.replica_env = dict(replica_env or {})
        self.tenants_dir = tenants_dir
        self.model_dir = model_dir
        self.ingest = bool(ingest)
        self.wal_root = wal_root
        self.devices = devices
        self.restart = bool(restart)
        self.startup_timeout_s = float(startup_timeout_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.run_dir = run_dir or tempfile.mkdtemp(prefix="hdbscan_fleet_")
        self.tracer = tracer
        # Per-replica JSONL traces (``--trace-out``): set a directory to
        # have every replica write replica_<rid>.jsonl there, joinable with
        # this router's ``router_span`` events on the propagated
        # X-Request-Id (``obs/correlate.py``).
        self.replica_trace_dir = replica_trace_dir
        if replica_trace_dir:
            os.makedirs(replica_trace_dir, exist_ok=True)
        # Request ids this router mints when the client didn't send one:
        # pid-qualified so several routers (tests) never collide in a trace.
        self._rids = itertools.count(1)
        self.verbose = bool(verbose)
        # Every replica inherits the SAME persistent XLA compile cache dir
        # (resolve_cache_dir honors the compile_cache knob / env / opt-out),
        # so a respawned or scaled-up replica warm-starts: its AOT warmup
        # replays compiles its siblings already paid for and reports
        # jit_compiles == 0.
        from hdbscan_tpu.utils.cache import resolve_cache_dir

        self.compile_cache_dir = resolve_cache_dir(compile_cache)
        self.replicas = [_Replica(str(i)) for i in range(self.n_replicas)]
        self._rebuild_ring()
        # Rolling window of proxied-request walls — the p99 signal the
        # autoscaler (fleet/controlplane.py) reads alongside queue depth.
        self._lat = deque(maxlen=2048)
        self._scaling = False  # one scale op at a time (loop-serialized)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._server: asyncio.AbstractServer | None = None
        self._ready = threading.Event()
        self._startup_error: BaseException | None = None
        self._shutdown = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()
        self.drain_ok: bool | None = None
        self._requests = {"/predict": 0, "/ingest": 0, "/swap": 0}

        if metrics is None:
            from hdbscan_tpu.utils.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        self._m_requests = metrics.counter(
            "hdbscan_tpu_fleet_requests_total",
            "Requests proxied through the fleet router by outcome.",
            ("replica", "route", "status"),
        )
        self._m_reroutes = metrics.counter(
            "hdbscan_tpu_fleet_reroutes_total",
            "Proxied requests re-dispatched away from a failed replica.",
            ("replica", "route"),
        )
        self._m_up = metrics.gauge(
            "hdbscan_tpu_replica_up",
            "1 when the replica answered its last probe, else 0.",
            ("replica",),
        )
        self._m_checks = metrics.counter(
            "hdbscan_tpu_replica_health_checks_total",
            "Health probes by result.",
            ("replica", "ok"),
        )
        self._m_restarts = metrics.counter(
            "hdbscan_tpu_replica_restarts_total",
            "Replica subprocess respawns after an unexpected exit.",
            ("replica",),
        )
        self._m_in_flight = metrics.gauge(
            "hdbscan_tpu_replica_in_flight",
            "Requests currently proxied to the replica.",
            ("replica",),
        )
        self._m_scale = metrics.counter(
            "hdbscan_tpu_scale_events_total",
            "Fleet scale operations by direction and outcome.",
            ("direction", "ok"),
        )
        self._m_replicas = metrics.gauge(
            "hdbscan_tpu_fleet_replicas",
            "Replicas currently in the routing set.",
        )
        self._m_replicas.set(float(len(self.replicas)))

    # -- replica lifecycle -------------------------------------------------

    def _rebuild_ring(self) -> None:
        """Recompute the consistent-hash ring and rid index from the
        current replica set. Runs on the router's event loop (scale ops)
        or before it exists (__init__), never concurrently with routing."""
        self._ring = sorted(
            (_h(f"{r.rid}#{v}"), r.rid)
            for r in self.replicas for v in range(_VNODES)
        )
        self._ring_keys = [h for h, _ in self._ring]
        self._by_rid = {r.rid: r for r in self.replicas}

    def _replica_cmd(self, r: _Replica) -> list:
        cmd = [
            sys.executable, "-m", "hdbscan_tpu", "serve",
            "--model", self.model_path,
            "--host", "127.0.0.1", "--port", "0",
            "--port-file", r.port_file,
        ]
        if self.model_dir:
            cmd += ["--model-dir", self.model_dir]
        if self.tenants_dir:
            cmd += ["--tenants-dir", self.tenants_dir]
        if self.ingest:
            cmd.append("--ingest")
        if self.wal_root:
            cmd.append(
                f"wal_dir={os.path.join(self.wal_root, 'r' + r.rid)}"
            )
        if self.replica_trace_dir:
            cmd += [
                "--trace-out",
                os.path.join(self.replica_trace_dir, f"replica_{r.rid}.jsonl"),
            ]
        cmd += self.replica_args
        return cmd

    def _replica_environ(self, r: _Replica) -> dict:
        env = dict(os.environ)
        env.update(self.replica_env)
        env["HDBSCAN_TPU_REPLICA_ID"] = r.rid
        if self.compile_cache_dir and "JAX_COMPILATION_CACHE_DIR" not in env:
            env["JAX_COMPILATION_CACHE_DIR"] = self.compile_cache_dir
        if self.devices:
            ordinal = str(int(r.rid) % int(self.devices))
            platform = env.get("JAX_PLATFORMS", "")
            if "tpu" in platform:
                env["TPU_VISIBLE_CHIPS"] = ordinal
            elif "gpu" in platform or "cuda" in platform:
                env["CUDA_VISIBLE_DEVICES"] = ordinal
        return env

    def _spawn(self, r: _Replica) -> None:
        r.port_file = os.path.join(self.run_dir, f"replica_{r.rid}.port")
        r.log_path = os.path.join(self.run_dir, f"replica_{r.rid}.log")
        if os.path.exists(r.port_file):
            os.unlink(r.port_file)
        r.port = None
        log = open(r.log_path, "ab")
        try:
            r.proc = subprocess.Popen(
                self._replica_cmd(r),
                stdout=log, stderr=log, stdin=subprocess.DEVNULL,
                env=self._replica_environ(r),
                start_new_session=True,  # SIGINT to the router can't nuke replicas mid-drain
            )
        finally:
            log.close()

    def _log_tail(self, r: _Replica, n: int = 2000) -> str:
        try:
            with open(r.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - n))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    async def _await_port(self, r: _Replica, deadline: float) -> None:
        while True:
            try:
                with open(r.port_file, encoding="utf-8") as f:
                    text = f.read().strip()
                if text:
                    r.port = int(text)
                    return
            except (OSError, ValueError):
                pass
            if not r.alive():
                raise RuntimeError(
                    f"replica {r.rid} exited (rc={r.proc.returncode}) before "
                    f"binding a port; log tail:\n{self._log_tail(r)}"
                )
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"replica {r.rid} did not report a port within "
                    f"{self.startup_timeout_s:.0f}s; log tail:\n{self._log_tail(r)}"
                )
            await asyncio.sleep(0.05)

    async def _respawn(self, r: _Replica) -> None:
        r.restarts += 1
        self._m_restarts.inc(replica=r.rid)
        self._spawn(r)
        await self._await_port(
            r, time.monotonic() + self.startup_timeout_s
        )

    # -- tiny async HTTP ---------------------------------------------------

    async def _replica_request(self, r: _Replica, method: str, path: str,
                               headers: dict, body: bytes, timeout: float):
        """One request/response against a replica over a fresh connection.
        Returns ``(status, headers, body)``; raises :class:`_ReplicaError`."""
        sent_box = [False]

        async def _one():
            reader, writer = await asyncio.open_connection("127.0.0.1", r.port)
            try:
                head = [
                    f"{method} {path} HTTP/1.1",
                    f"Host: 127.0.0.1:{r.port}",
                    f"Content-Length: {len(body)}",
                    "Connection: close",
                ]
                head += [f"{k}: {v}" for k, v in headers.items()]
                writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
                sent_box[0] = True
                await writer.drain()
                status_line = await reader.readline()
                if not status_line:
                    raise ConnectionResetError("empty response")
                parts = status_line.decode("latin1").split(None, 2)
                status = int(parts[1])
                rheaders: dict = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode("latin1").partition(":")
                    rheaders[k.strip().lower()] = v.strip()
                n = int(rheaders.get("content-length", 0))
                rbody = await reader.readexactly(n) if n else b""
                return status, rheaders, rbody
            finally:
                writer.close()

        try:
            return await asyncio.wait_for(_one(), timeout)
        except (OSError, asyncio.IncompleteReadError, asyncio.TimeoutError,
                TimeoutError, ValueError, IndexError) as exc:
            raise _ReplicaError(
                f"replica {r.rid}: {type(exc).__name__}: {exc}",
                sent=sent_box[0],
            ) from exc

    # -- routing -----------------------------------------------------------

    def _hash_key(self, body: bytes) -> str:
        m = _TENANT_RE.search(body[:4096])
        if m is not None:
            return (m.group(1) or m.group(2)).decode("utf-8", "replace")
        return hashlib.md5(body).hexdigest()

    def _route_order(self, route: str, body: bytes) -> list:
        """Replicas in dispatch-preference order; down replicas go last so
        a request placed while every replica is marked down still probes
        one (it may have just recovered)."""
        if self.policy == "consistent_hash":
            start = bisect.bisect_left(self._ring_keys, _h(self._hash_key(body)))
            order: list = []
            for i in range(len(self._ring)):
                rid = self._ring[(start + i) % len(self._ring)][1]
                r = self._by_rid[rid]
                if r not in order:
                    order.append(r)
                if len(order) == len(self.replicas):
                    break
        else:
            order = sorted(
                self.replicas, key=lambda r: (r.in_flight, r.failures, r.rid)
            )
        return sorted(order, key=lambda r: not r.up)

    def _mark(self, r: _Replica, ok: bool) -> None:
        r.up = ok
        r.failures = 0 if ok else r.failures + 1
        self._m_up.set(1.0 if ok else 0.0, replica=r.rid)

    async def _proxy(self, route: str, headers: dict, body: bytes):
        self._requests[route] = self._requests.get(route, 0) + 1
        # Correlation key: honor a client-supplied X-Request-Id, else mint
        # one. The replica's request_span/request_shed carries the same id
        # (serve/server.py), so the router_span joins it bitwise
        # (obs/correlate.join_spans).
        req_id = headers.get("x-request-id") or f"r{os.getpid()}-{next(self._rids)}"
        fwd = {
            "Content-Type": headers.get("content-type", "application/json"),
            "X-Request-Id": req_id,
        }
        timeout = self.proxy_timeout_s
        if headers.get("x-deadline-ms"):
            fwd["X-Deadline-Ms"] = headers["x-deadline-ms"]
            try:
                timeout = min(timeout, float(headers["x-deadline-ms"]) / 1000.0)
            except ValueError:
                pass
        order = self._route_order(route, body)
        t0 = time.perf_counter()
        attempts = 0
        queue_s = 0.0
        last_rid = order[0].rid if order else "none"
        for r in order:
            if r.port is None:
                continue
            attempts += 1
            last_rid = r.rid
            queue_s = time.perf_counter() - t0
            r.in_flight += 1
            self._m_in_flight.set(r.in_flight, replica=r.rid)
            try:
                status, rheaders, rbody = await self._replica_request(
                    r, "POST", route, fwd, body, timeout
                )
            except _ReplicaError as exc:
                # Connection-refused never reached the replica: always safe
                # to re-dispatch. After bytes were sent, only idempotent
                # /predict (and /swap, a no-op republish) may retry.
                self._mark(r, False)
                self._m_reroutes.inc(replica=r.rid, route=route)
                if exc.sent and route == "/ingest":
                    self._emit_route(
                        route, r.rid, 502, attempts, t0,
                        req_id, queue_s, replied=False,
                    )
                    return 502, {}, _json_body(
                        {"error": f"replica {r.rid} failed mid-ingest: {exc}"}
                    )
                continue
            finally:
                r.in_flight -= 1
                self._m_in_flight.set(r.in_flight, replica=r.rid)
            self._mark(r, True)
            self._emit_route(
                route, r.rid, status, attempts, t0, req_id, queue_s,
                replied=True,
            )
            out_headers = {
                k: v for k, v in rheaders.items() if k not in _HOP_HEADERS
                and k != "content-length"
            }
            out_headers["x-replica"] = r.rid
            out_headers["x-request-id"] = req_id
            return status, out_headers, rbody
        self._emit_route(
            route, last_rid, 503, max(attempts, 1), t0, req_id, queue_s,
            replied=False,
        )
        return 503, {"retry-after": f"{self.health_interval_s:.3f}"}, _json_body(
            {"error": "no replica available", "reason": "fleet_unavailable"}
        )

    def _emit_route(self, route: str, rid: str, status: int, attempts: int,
                    t0: float, req_id: str | None = None,
                    queue_s: float = 0.0, replied: bool = False) -> None:
        wall = time.perf_counter() - t0
        if replied:
            self._lat.append(wall)
        self._m_requests.inc(replica=rid, route=route, status=str(status))
        if self.tracer is not None:
            self.tracer(
                "fleet_route", replica=rid, route=route, policy=self.policy,
                status=int(status), attempts=int(attempts),
                wall_s=round(wall, 9),
            )
            if req_id is not None:
                # router_span: the router's half of the per-request causal
                # chain. ``replied=True`` iff a replica's response was
                # relayed — only those joins a replica-side span
                # (check_trace --join enforces exactly-one).
                self.tracer(
                    "router_span", request_id=req_id, route=route,
                    policy=self.policy, replica=rid, status=int(status),
                    attempts=int(attempts), queue_s=round(queue_s, 9),
                    wall_s=round(wall, 9), replied=bool(replied),
                )

    # -- scaling -----------------------------------------------------------

    def signals(self) -> dict:
        """The autoscaler's inputs, from state the router already tracks:
        total in-flight proxied requests (queue depth), the same per up
        replica, and p50/p99 over the recent replied-request window."""
        replicas = self.replicas
        up = sum(1 for r in replicas if r.up)
        in_flight = sum(r.in_flight for r in replicas)
        lats = sorted(self._lat)
        out = {
            "replicas": len(replicas), "up": up,
            "in_flight": in_flight,
            "in_flight_per_up": in_flight / up if up else float(in_flight),
            "window": len(lats),
        }
        for q, name in ((0.5, "p50_s"), (0.99, "p99_s")):
            if lats:
                rank = max(1, math.ceil(q * len(lats)))
                out[name] = lats[rank - 1]
        return out

    def _free_rid(self) -> str:
        """Lowest non-negative integer rid not in the routing set — a
        scale-up after a scale-down reuses the departed replica's rid and
        therefore its ``wal_root/r<id>`` directory, so acked writes that
        replica WAL'd before draining replay into its successor."""
        used = {int(r.rid) for r in self.replicas if r.rid.isdigit()}
        rid = 0
        while rid in used:
            rid += 1
        return str(rid)

    def _emit_scale(self, direction: str, rid: str, ok: bool, reason: str,
                    t0: float, error: str | None = None) -> None:
        self._m_scale.inc(direction=direction, ok=str(ok).lower())
        self._m_replicas.set(float(len(self.replicas)))
        if self.tracer is not None:
            fields = dict(
                direction=direction, replica=str(rid),
                replicas=len(self.replicas), reason=str(reason),
                ok=bool(ok), wall_s=round(time.perf_counter() - t0, 6),
            )
            if error:
                fields["error"] = str(error)[:300]
            self.tracer("scale_event", **fields)

    async def _scale_up_async(self, reason: str = "manual") -> str | None:
        """Spawn one replica, warm it as a STANDBY (port + healthy probe —
        its AOT warmup has completed before any traffic can route to it),
        then add it to the ring. Returns the new rid, or None on failure
        (the failed standby is killed; the routing set is unchanged)."""
        if self._scaling:
            return None
        self._scaling = True
        t0 = time.perf_counter()
        r = _Replica(self._free_rid())
        try:
            self._spawn(r)
            deadline = time.monotonic() + self.startup_timeout_s
            await self._await_port(r, deadline)
            while not r.up:
                await self._check_one(r)
                if r.up:
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"standby replica {r.rid} not healthy within "
                        f"{self.startup_timeout_s:.0f}s"
                    )
                await asyncio.sleep(0.1)
            self.replicas = self.replicas + [r]
            self._rebuild_ring()
        except Exception as exc:
            if r.proc is not None and r.alive():
                r.proc.kill()
            self._emit_scale("up", r.rid, False, reason, t0, error=str(exc))
            return None
        finally:
            self._scaling = False
        self._emit_scale("up", r.rid, True, reason, t0)
        return r.rid

    async def _scale_down_async(self, rid: str | None = None,
                                reason: str = "manual") -> bool:
        """Remove one replica: out of the ring first (no new dispatch),
        drain its in-flight requests, then the WAL-safe SIGTERM shutdown.
        Defaults to the highest-numbered replica (rid 0 is never chosen
        implicitly, keeping the fleet's anchor stable). Refuses to drop
        the last replica."""
        if self._scaling or len(self.replicas) <= 1:
            return False
        self._scaling = True
        t0 = time.perf_counter()
        try:
            if rid is None:
                r = max(
                    self.replicas,
                    key=lambda x: int(x.rid) if x.rid.isdigit() else -1,
                )
            else:
                r = self._by_rid.get(str(rid))
                if r is None:
                    return False
            r.retired = True
            self.replicas = [x for x in self.replicas if x is not r]
            self._rebuild_ring()
            deadline = time.monotonic() + self.drain_s
            while r.in_flight > 0 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            if r.alive():
                r.proc.send_signal(signal.SIGTERM)
            while r.alive() and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            ok = not r.alive()
            if not ok:
                r.proc.kill()
            self._mark(r, False)
            self._emit_scale("down", r.rid, ok, reason, t0,
                             error=None if ok else "drain timeout; SIGKILLed")
            return ok
        finally:
            self._scaling = False

    def scale_up(self, reason: str = "manual",
                 timeout: float | None = None) -> str | None:
        """Thread-safe scale-up (see :meth:`_scale_up_async`)."""
        if self._loop is None or self._shutdown.is_set():
            return None
        fut = asyncio.run_coroutine_threadsafe(
            self._scale_up_async(reason), self._loop
        )
        return fut.result(timeout or self.startup_timeout_s + 10.0)

    def scale_down(self, rid: str | None = None, reason: str = "manual",
                   timeout: float | None = None) -> bool:
        """Thread-safe scale-down (see :meth:`_scale_down_async`)."""
        if self._loop is None or self._shutdown.is_set():
            return False
        fut = asyncio.run_coroutine_threadsafe(
            self._scale_down_async(rid, reason), self._loop
        )
        return fut.result(timeout or self.drain_s + 10.0)

    # -- health ------------------------------------------------------------

    async def _check_one(self, r: _Replica) -> None:
        probe_timeout = max(0.05, min(2.0, self.health_interval_s))
        ok = False
        if r.port is not None:
            try:
                status, _, body = await self._replica_request(
                    r, "GET", "/healthz", {}, b"", probe_timeout
                )
                ok = status == 200
                if ok:
                    # Lift the replica's deep-obs signals while the body is
                    # in hand: a hung phase (watchdog) or slow device
                    # (straggler) surfaces in the router's own /healthz
                    # without a second probe. Best-effort — the probe's
                    # verdict never depends on the body parsing.
                    try:
                        h = json.loads(body.decode("utf-8"))
                        wd = h.get("watchdog")
                        if isinstance(wd, dict):
                            r.watchdog_stalls = int(wd.get("stalls", 0))
                        sg = h.get("straggler")
                        if isinstance(sg, dict):
                            r.straggler_flags = int(sg.get("flags_total", 0))
                    except (ValueError, TypeError, AttributeError):
                        pass
            except _ReplicaError:
                ok = False
        self._mark(r, ok)
        r.checks += 1
        self._m_checks.inc(replica=r.rid, ok=str(ok).lower())
        if self.tracer is not None:
            self.tracer(
                "replica_health", replica=r.rid, ok=bool(ok),
                failures=int(r.failures), restarts=int(r.restarts),
            )
        if (not ok and not r.alive() and self.restart and not r.retired
                and not self._shutdown.is_set()):
            try:
                await self._respawn(r)
            except RuntimeError:
                pass  # next probe retries; the replica stays down meanwhile

    async def _health_loop(self) -> None:
        while not self._shutdown.is_set():
            await asyncio.gather(
                *(self._check_one(r) for r in self.replicas)
            )
            await asyncio.sleep(self.health_interval_s)

    def health(self) -> dict:
        n_up = sum(1 for r in self.replicas if r.up)
        return {
            "status": "ok" if n_up == len(self.replicas)
            else ("degraded" if n_up else "down"),
            "policy": self.policy,
            "replicas": {
                r.rid: {
                    "up": r.up, "port": r.port,
                    "pid": r.proc.pid if r.proc else None,
                    "failures": r.failures, "in_flight": r.in_flight,
                    "restarts": r.restarts, "checks": r.checks,
                    "watchdog_stalls": r.watchdog_stalls,
                    "straggler_flags": r.straggler_flags,
                }
                for r in self.replicas
            },
            "requests": dict(self._requests),
            "health_interval_s": self.health_interval_s,
            "signals": self.signals(),
        }

    # -- metrics aggregation ----------------------------------------------

    async def _aggregate_metrics(self) -> str:
        from hdbscan_tpu.utils.metrics import (
            MetricsRegistry, registry_from_exposition,
        )

        async def scrape(r: _Replica):
            try:
                status, _, body = await self._replica_request(
                    r, "GET", "/metrics", {}, b"", min(2.0, self.proxy_timeout_s)
                )
                return r.rid, body if status == 200 else None
            except _ReplicaError:
                return r.rid, None

        results = await asyncio.gather(
            *(scrape(r) for r in self.replicas if r.port is not None)
        )
        agg = MetricsRegistry()
        agg.merge(self.metrics)
        for rid, body in results:
            if body is None:
                continue  # down replica: its series drop out of this scrape
            agg.merge(
                registry_from_exposition(
                    body.decode("utf-8", "replace"), {"replica": rid}
                )
            )
        return agg.render()

    # -- front-end ---------------------------------------------------------

    async def _handle_client(self, reader, writer) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    method, target, _ = line.decode("latin1").split(None, 2)
                except ValueError:
                    return
                headers: dict = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                n = int(headers.get("content-length", 0) or 0)
                body = await reader.readexactly(n) if n else b""
                status, out_headers, out_body = await self._dispatch(
                    method, target, headers, body
                )
                keep = headers.get("connection", "").lower() != "close"
                head = [
                    f"HTTP/1.1 {status} {_REASONS.get(status, 'OK')}",
                    f"Content-Length: {len(out_body)}",
                    f"Connection: {'keep-alive' if keep else 'close'}",
                ]
                if "content-type" not in out_headers:
                    head.append("Content-Type: application/json")
                head += [f"{k}: {v}" for k, v in out_headers.items()]
                writer.write(
                    ("\r\n".join(head) + "\r\n\r\n").encode() + out_body
                )
                await writer.drain()
                if not keep:
                    return
        except (OSError, asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, method: str, target: str, headers: dict,
                        body: bytes):
        path = target.split("?", 1)[0]
        if method == "GET" and path == "/healthz":
            return 200, {}, _json_body(self.health())
        if method == "GET" and path == "/metrics":
            text = await self._aggregate_metrics()
            return 200, {"content-type": "text/plain; version=0.0.4"}, \
                text.encode()
        if method == "POST" and path in ("/predict", "/ingest"):
            return await self._proxy(path, headers, body)
        if method == "POST" and path == "/swap":
            return await self._broadcast_swap(headers, body)
        return 404, {}, _json_body({"error": f"unknown route {path}"})

    async def _broadcast_swap(self, headers: dict, body: bytes):
        self._requests["/swap"] = self._requests.get("/swap", 0) + 1
        fwd = {"Content-Type": headers.get("content-type", "application/json")}

        async def one(r: _Replica):
            if r.port is None:
                return r.rid, {"error": "not started"}
            try:
                status, _, rbody = await self._replica_request(
                    r, "POST", "/swap", fwd, body, self.proxy_timeout_s
                )
                try:
                    payload = json.loads(rbody.decode() or "{}")
                except ValueError:
                    payload = {}
                return r.rid, {"status": status, **payload}
            except _ReplicaError as exc:
                self._mark(r, False)
                return r.rid, {"error": str(exc)}

        results = dict(await asyncio.gather(*(one(r) for r in self.replicas)))
        ok = all("error" not in v and v.get("status") == 200
                 for v in results.values())
        return (200 if ok else 502), {}, _json_body({"replicas": results})

    # -- lifecycle ---------------------------------------------------------

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        try:
            deadline = time.monotonic() + self.startup_timeout_s
            await asyncio.gather(
                *(self._await_port(r, deadline) for r in self.replicas)
            )
            # First health pass before accepting: a fleet that reports
            # ready has every replica warmed and answering.
            while not all(r.up for r in self.replicas):
                await asyncio.gather(
                    *(self._check_one(r) for r in self.replicas)
                )
                if all(r.up for r in self.replicas):
                    break
                if time.monotonic() > deadline:
                    bad = [r.rid for r in self.replicas if not r.up]
                    raise RuntimeError(
                        f"replicas {bad} not healthy within "
                        f"{self.startup_timeout_s:.0f}s"
                    )
                await asyncio.sleep(0.1)
            self._server = await asyncio.start_server(
                self._handle_client, self.host, self.port or 0
            )
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as exc:
            self._startup_error = exc
            self._ready.set()
            return
        self._ready.set()
        health = asyncio.ensure_future(self._health_loop())
        try:
            while not self._shutdown.is_set():
                await asyncio.sleep(0.05)
        finally:
            health.cancel()
            self._server.close()
            await self._server.wait_closed()

    def start(self) -> "FleetRouter":
        """Spawn replicas, wait until every one is healthy, bind the front
        port. Blocking; raises (after killing the spawned replicas) if the
        fleet cannot come up."""
        for r in self.replicas:
            self._spawn(r)
        self._thread = threading.Thread(
            target=lambda: asyncio.run(self._main()),
            name="fleet-router", daemon=True,
        )
        self._thread.start()
        self._ready.wait(self.startup_timeout_s + 10.0)
        if self._startup_error is not None or not self._ready.is_set():
            err = self._startup_error or RuntimeError(
                "fleet router startup timed out"
            )
            self.close()
            raise RuntimeError(f"fleet startup failed: {err}") from err
        if self.verbose:
            print(
                f"fleet: {self.n_replicas} replicas behind "
                f"http://{self.host}:{self.port} (policy={self.policy})",
                file=sys.stderr,
            )
        return self

    def close(self, drain_s: float | None = None) -> bool:
        """SIGTERM every replica and wait for the in-flight drain.

        Each replica's SIGTERM handler runs ``ClusterServer.close()`` —
        resolving every accepted request — before exiting. Returns True
        when all replicas exit within the bound; a hung replica is
        SIGKILLed and the result is False (the CLI turns that into a
        nonzero exit). Idempotent; the first call's verdict sticks
        (``drain_ok``).
        """
        with self._close_lock:
            if self._closed:
                return bool(self.drain_ok) if self.drain_ok is not None else True
            self._closed = True
            self._shutdown.set()
            if self._thread is not None:
                self._thread.join(timeout=10.0)
            bound = self.drain_s if drain_s is None else float(drain_s)
            for r in self.replicas:
                if r.alive():
                    r.proc.send_signal(signal.SIGTERM)
            ok = True
            deadline = time.monotonic() + bound
            for r in self.replicas:
                if r.proc is None:
                    continue
                try:
                    r.proc.wait(timeout=max(0.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    ok = False
                    r.proc.kill()
                    try:
                        r.proc.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        pass
                self._mark(r, False)
            self.drain_ok = ok
            return ok

    def serve_forever(self) -> int:
        """Block until SIGTERM/SIGINT, then drain. Exit code 0 on a clean
        drain, 1 when a replica hung past the bound."""
        stop = threading.Event()

        def _stop(signum, frame):
            stop.set()

        signal.signal(signal.SIGTERM, _stop)
        signal.signal(signal.SIGINT, _stop)
        try:
            stop.wait()
        finally:
            clean = self.close()
        return 0 if clean else 1

    def __enter__(self) -> "FleetRouter":
        # `with FleetRouter(...) as router:` implies a running fleet —
        # start() is idempotent via _thread so an explicit
        # `FleetRouter(...).start()` composes with `with` too.
        if self._thread is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found", 408: "Request Timeout",
    429: "Too Many Requests", 500: "Internal Server Error",
    502: "Bad Gateway", 503: "Service Unavailable", 504: "Gateway Timeout",
}


def _json_body(obj) -> bytes:
    return json.dumps(obj).encode()
