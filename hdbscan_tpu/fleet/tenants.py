"""Multi-tenant model registry: many ClusterModels behind one replica.

One fleet, thousands of models: each tenant id maps to a model artifact on
disk, and a bounded LRU of AOT-warmed :class:`serve.predict.Predictor`
instances keeps the hot tenants device-resident while cold ones cost one
load + warmup on first touch. The registry owns the per-tenant contracts
the single-model server already has globally:

* **Generations** — every publish (first load, re-warm after eviction, or
  an explicit :meth:`swap`) bumps the tenant's generation; generations
  strictly increase per tenant for the life of the registry, mirroring the
  blue/green ``model_swap`` invariant.
* **Quotas** — a per-tenant token bucket (``quota_rps`` sustained, burst of
  ``max(1, quota_rps)``); an exhausted bucket raises
  :class:`fault.policy.ShedRequest` with status 429 and a Retry-After hint
  sized to the next token, which the server's shed path already turns into
  the right HTTP response.
* **SLO verdicts** — per-tenant latency windows feed
  :func:`utils.telemetry.slo_verdict`, so one noisy tenant's tail cannot
  hide inside a fleet-wide p99.

Evictions emit ``tenant_evict`` trace events (validated by
``scripts/check_trace.py``) and ``hdbscan_tpu_tenant_evictions_total``.
Because every tenant's Predictor pads to the same pow2 bucket ladder, a
re-warm after eviction hits the process-wide jit cache: ``warmup()``
reports 0 compiles for any tenant whose shapes were seen before — the
zero-steady-state-recompile property survives multi-tenancy.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from hdbscan_tpu.fault.policy import ShedRequest

#: Default per-tenant SLO bounds for :meth:`TenantRegistry.slo_verdicts` —
#: same shape as ``bench.SLO_TARGETS``, scoped to what a tenant window
#: can observe (latency; throughput is a fleet property).
DEFAULT_TENANT_SLO = {"p50_s": {"max": 0.1}, "p99_s": {"max": 0.5}}

#: Per-tenant latency window for SLO verdicts (recent-window semantics,
#: like the Tracer ring: old latencies age out instead of pinning a
#: verdict to startup transients forever).
_SLO_WINDOW = 2048


@dataclass
class _TenantEntry:
    """One resident tenant: an AOT-warmed predictor plus its provenance."""

    tenant: str
    model: object
    predictor: object
    generation: int
    warmup: dict
    loaded_at: float
    requests: int = 0


@dataclass
class _QuotaBucket:
    tokens: float
    last: float


@dataclass
class _TenantStats:
    """Survives eviction (generations/quota/latency are per-tenant, not
    per-residency)."""

    generation: int = 0
    quota: _QuotaBucket | None = None
    latencies: deque = field(default_factory=lambda: deque(maxlen=_SLO_WINDOW))
    requests: int = 0
    shed: int = 0
    evictions: int = 0


class TenantRegistry:
    """LRU cache of warmed Predictors keyed by tenant id.

    Args:
      paths: ``{tenant_id: artifact_path}``. Tenants can also be added
        later via :meth:`add`; an unknown tenant id raises ``KeyError``
        (the server maps it to HTTP 404).
      backend / max_batch / dtype: forwarded to each Predictor.
      lru_size: max resident tenants (>= 1). The coldest resident is
        evicted — with a ``tenant_evict`` trace event — when a miss would
        exceed it.
      quota_rps: sustained per-tenant request rate; 0 disables quotas.
      metrics: optional ``utils.metrics.MetricsRegistry`` — tenant-labeled
        request/eviction/load counters, a resident gauge, and a per-tenant
        latency histogram register here.
      tracer: optional ``utils.tracing.Tracer`` for ``tenant_load`` /
        ``tenant_evict`` events (and each Predictor's ``predict_batch``).
      artifact_store: optional ``fleet.artifacts.ArtifactStore`` — tenant
        artifacts then load through the per-host digest-keyed store (one
        mmap'd copy per host, re-warms after eviction are free) instead of
        a private ``ClusterModel.load`` per registry.
    """

    def __init__(self, paths: dict | None = None, *, backend: str = "auto",
                 max_batch: int = 256, dtype=None, lru_size: int = 8,
                 quota_rps: float = 0.0, metrics=None, tracer=None,
                 artifact_store=None, clock=time.monotonic):
        if lru_size < 1:
            raise ValueError(f"lru_size must be >= 1, got {lru_size!r}")
        if quota_rps < 0.0 or not math.isfinite(quota_rps):
            raise ValueError(f"quota_rps must be finite and >= 0, got {quota_rps!r}")
        self.backend = backend
        self.max_batch = int(max_batch)
        self.dtype = dtype
        self.lru_size = int(lru_size)
        self.quota_rps = float(quota_rps)
        self.metrics = metrics
        self.tracer = tracer
        self.artifact_store = artifact_store
        self._clock = clock
        self._lock = threading.RLock()
        self._paths: dict = dict(paths or {})
        self._resident: "OrderedDict[str, _TenantEntry]" = OrderedDict()
        self._stats: dict = {}  # tenant -> _TenantStats
        self._m_requests = self._m_evictions = self._m_loads = None
        self._m_resident = self._m_latency = None
        if metrics is not None:
            from hdbscan_tpu.utils.metrics import DEFAULT_LATENCY_BUCKETS

            self._m_requests = metrics.counter(
                "hdbscan_tpu_tenant_requests_total",
                "Tenant-scoped predict requests by outcome.",
                ("tenant", "outcome"),
            )
            self._m_evictions = metrics.counter(
                "hdbscan_tpu_tenant_evictions_total",
                "LRU evictions of a warmed tenant predictor.",
                ("tenant",),
            )
            self._m_loads = metrics.counter(
                "hdbscan_tpu_tenant_loads_total",
                "Tenant model loads (first touch, re-warm, or swap).",
                ("tenant",),
            )
            self._m_resident = metrics.gauge(
                "hdbscan_tpu_tenant_resident",
                "Warmed tenant predictors currently resident in the LRU.",
            )
            self._m_latency = metrics.histogram(
                "hdbscan_tpu_tenant_predict_seconds",
                "Per-tenant end-to-end predict latency.",
                ("tenant",),
                buckets=DEFAULT_LATENCY_BUCKETS,
            )

    # -- tenant set --------------------------------------------------------

    @classmethod
    def from_dir(cls, path: str, **kwargs) -> "TenantRegistry":
        """Registry over every ``*.npz`` artifact in ``path``; the tenant
        id is the file stem (``acme.npz`` serves tenant ``acme``)."""
        paths = {
            os.path.splitext(name)[0]: os.path.join(path, name)
            for name in sorted(os.listdir(path))
            if name.endswith(".npz")
        }
        if not paths:
            raise ValueError(f"no .npz model artifacts under {path!r}")
        return cls(paths, **kwargs)

    def add(self, tenant: str, path: str) -> None:
        with self._lock:
            self._paths[str(tenant)] = str(path)

    def tenants(self) -> list:
        with self._lock:
            return sorted(self._paths)

    def resident(self) -> list:
        """Resident tenant ids, coldest first (LRU order)."""
        with self._lock:
            return list(self._resident)

    # -- quota -------------------------------------------------------------

    def _acquire_quota(self, tenant: str, st: _TenantStats) -> None:
        # caller holds the lock
        if self.quota_rps <= 0.0:
            return
        now = self._clock()
        burst = max(1.0, self.quota_rps)
        if st.quota is None:
            st.quota = _QuotaBucket(tokens=burst, last=now)
        b = st.quota
        b.tokens = min(burst, b.tokens + (now - b.last) * self.quota_rps)
        b.last = now
        if b.tokens >= 1.0:
            b.tokens -= 1.0
            return
        st.shed += 1
        if self._m_requests is not None:
            self._m_requests.inc(tenant=tenant, outcome="shed")
        retry_s = (1.0 - b.tokens) / self.quota_rps
        raise ShedRequest(
            f"tenant {tenant!r} over quota ({self.quota_rps:g} rps)",
            status=429, retry_after_s=retry_s, reason="tenant_quota",
        )

    # -- LRU / load --------------------------------------------------------

    def _load(self, tenant: str, path: str, st: _TenantStats) -> _TenantEntry:
        # caller holds the lock; load + warmup happen inline so a tenant is
        # never observable half-warm. Model artifacts are digest-guarded, so
        # concurrent loads of the same file across replicas are safe.
        from hdbscan_tpu.serve.artifact import ClusterModel
        from hdbscan_tpu.serve.predict import Predictor

        t0 = time.perf_counter()
        if self.artifact_store is not None:
            model = self.artifact_store.load(path)
        else:
            model = ClusterModel.load(path)
        kw = {} if self.dtype is None else {"dtype": self.dtype}
        predictor = Predictor(
            model, backend=self.backend, max_batch=self.max_batch,
            tracer=self.tracer, metrics=self.metrics, **kw,
        )
        info = predictor.warmup()
        st.generation += 1
        entry = _TenantEntry(
            tenant=tenant, model=model, predictor=predictor,
            generation=st.generation, warmup=info, loaded_at=self._clock(),
        )
        self._resident[tenant] = entry
        self._resident.move_to_end(tenant)
        if self._m_loads is not None:
            self._m_loads.inc(tenant=tenant)
            self._m_resident.set(len(self._resident))
        if self.tracer is not None:
            self.tracer(
                "tenant_load", tenant=tenant, generation=entry.generation,
                resident=len(self._resident),
                jit_compiles=int(info.get("jit_compiles", 0)),
                wall_s=time.perf_counter() - t0,
            )
        self._evict_over_capacity()
        return entry

    def _evict_over_capacity(self) -> None:
        # caller holds the lock
        while len(self._resident) > self.lru_size:
            tenant, entry = self._resident.popitem(last=False)
            st = self._stats[tenant]
            st.evictions += 1
            if self._m_evictions is not None:
                self._m_evictions.inc(tenant=tenant)
                self._m_resident.set(len(self._resident))
            if self.tracer is not None:
                self.tracer(
                    "tenant_evict", tenant=tenant,
                    generation=entry.generation,
                    resident=len(self._resident),
                    requests=entry.requests,
                )

    def checkout(self, tenant: str) -> _TenantEntry:
        """Resolve a tenant to a warmed entry: quota check, LRU touch,
        load + warmup on miss (evicting the coldest resident if full).

        Raises ``KeyError`` for an unknown tenant and
        :class:`ShedRequest` (status 429) when the tenant is over quota —
        quota is charged before the load so cold tenants cannot buy free
        warmups by thrashing the LRU.
        """
        tenant = str(tenant)
        with self._lock:
            path = self._paths.get(tenant)
            if path is None:
                raise KeyError(f"unknown tenant {tenant!r}")
            st = self._stats.setdefault(tenant, _TenantStats())
            self._acquire_quota(tenant, st)
            entry = self._resident.get(tenant)
            if entry is None:
                entry = self._load(tenant, path, st)
            else:
                self._resident.move_to_end(tenant)
            entry.requests += 1
            st.requests += 1
            return entry

    def swap(self, tenant: str, path: str) -> _TenantEntry:
        """Publish a new artifact for a tenant (generation bumps); the old
        predictor, if resident, is replaced atomically under the lock."""
        tenant = str(tenant)
        with self._lock:
            self._paths[tenant] = str(path)
            st = self._stats.setdefault(tenant, _TenantStats())
            self._resident.pop(tenant, None)
            return self._load(tenant, str(path), st)

    # -- serving -----------------------------------------------------------

    def predict(self, tenant: str, X, with_membership: bool = False):
        """Predict for one tenant. Returns ``(outputs, info)`` where
        ``outputs`` is the Predictor's tuple and ``info`` carries
        ``{"tenant", "generation", "bucket"}`` (plus ``"selected_ids"``
        when membership was requested) for the response body/span."""
        entry = self.checkout(tenant)
        t0 = time.perf_counter()
        out = entry.predictor.predict(X, with_membership=with_membership)
        wall = time.perf_counter() - t0
        with self._lock:
            st = self._stats[str(tenant)]
            st.latencies.append(wall)
        if self._m_requests is not None:
            self._m_requests.inc(tenant=str(tenant), outcome="ok")
            self._m_latency.observe(wall, tenant=str(tenant))
        pred = entry.predictor
        info = {
            "tenant": str(tenant),
            "generation": entry.generation,
            "bucket": pred.bucket_for(min(len(out[0]), pred.max_bucket)),
        }
        if with_membership:
            info["selected_ids"] = entry.model.selected_ids.tolist()
        return out, info

    # -- introspection -----------------------------------------------------

    def generation(self, tenant: str) -> int:
        with self._lock:
            st = self._stats.get(str(tenant))
            return st.generation if st else 0

    def slo_verdicts(self, targets: dict | None = None) -> dict:
        """Per-tenant target-vs-attainment verdicts over the recent latency
        window (``utils.telemetry.slo_verdict`` semantics)."""
        from hdbscan_tpu.utils.telemetry import slo_verdict

        targets = dict(targets or DEFAULT_TENANT_SLO)
        out: dict = {}
        with self._lock:
            snap = {
                t: (list(st.latencies), st.requests, st.shed)
                for t, st in self._stats.items()
            }
        for tenant, (lats, requests, shed) in sorted(snap.items()):
            observed: dict = {"requests": requests, "shed": shed}
            if lats:
                ranked = sorted(lats)
                for q, name in ((0.5, "p50_s"), (0.99, "p99_s")):
                    rank = max(1, math.ceil(q * len(ranked)))
                    observed[name] = ranked[rank - 1]
            out[tenant] = slo_verdict(observed, targets)
            out[tenant]["observed"] = observed
        return out

    def stats(self) -> dict:
        """Snapshot for /healthz."""
        with self._lock:
            return {
                "tenants": len(self._paths),
                "resident": list(self._resident),
                "lru_size": self.lru_size,
                "quota_rps": self.quota_rps,
                "generations": {
                    t: st.generation for t, st in sorted(self._stats.items())
                },
                "requests": {
                    t: st.requests for t, st in sorted(self._stats.items())
                },
                "shed": {t: st.shed for t, st in sorted(self._stats.items())},
                "evictions": {
                    t: st.evictions for t, st in sorted(self._stats.items())
                },
            }
