"""Fleet control plane: the autoscaler loop over the router's signals.

The paper's MapReduce driver sizes the executor pool to the work; the
serving fleet's analogue is this loop. :class:`Autoscaler` periodically
reads :meth:`FleetRouter.signals` — queue depth (in-flight proxied
requests per up replica) and the rolling p99 the router already tracks —
and drives :meth:`FleetRouter.scale_up` / :meth:`~FleetRouter.scale_down`
between ``min_replicas`` and ``max_replicas``:

* **Scale-up** when the per-replica queue depth exceeds ``high_load`` (or
  p99 exceeds ``high_p99_s``) for ``up_after`` consecutive ticks. The
  router spawns a standby, warms it (persistent-compile-cache-backed AOT
  warmup, health probe green) and only then admits it to the ring — the
  autoscaler never routes load at a cold replica.
* **Scale-down** when queue depth stays below ``low_load`` AND p99 below
  ``high_p99_s`` for ``down_after`` consecutive ticks (hysteresis: the
  down window should be the longer one so a bursty arrival process
  doesn't thrash). The router drains the victim before SIGTERM.
* **Cooldown** — after any scale operation the loop holds for
  ``cooldown_s`` so the fleet re-equilibrates (a fresh replica empties
  the queue; judging the new topology on the old window double-scales).

Decisions trace as the router's ``scale_event`` (reason ``queue_depth``,
``p99``, or ``idle``) and count in ``hdbscan_tpu_scale_events_total``;
the loop itself is a daemon thread owned by the CLI ``fleet`` command
(``--autoscale``) or a test/bench harness via :meth:`start`/:meth:`stop`.
"""

from __future__ import annotations

import threading
import time

__all__ = ["Autoscaler"]


class Autoscaler:
    """Hysteresis-bounded scale loop over a running :class:`FleetRouter`.

    Args:
      router: a STARTED ``fleet.router.FleetRouter``.
      min_replicas / max_replicas: inclusive bounds on the routing set.
      high_load: per-up-replica in-flight requests above which a tick
        votes scale-up.
      low_load: per-up-replica in-flight requests below which a tick
        votes scale-down.
      high_p99_s: rolling p99 above which a tick votes scale-up (and
        vetoes scale-down). 0 disables the latency signal.
      up_after / down_after: consecutive votes required (hysteresis).
      interval_s: tick period.
      cooldown_s: hold after any scale operation.
    """

    def __init__(self, router, *, min_replicas: int = 1,
                 max_replicas: int = 4, high_load: float = 4.0,
                 low_load: float = 0.5, high_p99_s: float = 0.0,
                 up_after: int = 2, down_after: int = 5,
                 interval_s: float = 0.5, cooldown_s: float = 2.0):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas!r}"
            )
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas must be >= min_replicas ({min_replicas}), "
                f"got {max_replicas!r}"
            )
        if not high_load > low_load:
            raise ValueError(
                f"high_load ({high_load!r}) must exceed low_load "
                f"({low_load!r}) — equal thresholds thrash"
            )
        if up_after < 1 or down_after < 1:
            raise ValueError(
                f"up_after/down_after must be >= 1, got "
                f"{up_after!r}/{down_after!r}"
            )
        if not interval_s > 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s!r}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s!r}")
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.high_load = float(high_load)
        self.low_load = float(low_load)
        self.high_p99_s = float(high_p99_s)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self._up_votes = 0
        self._down_votes = 0
        self._hold_until = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.scaled_up = 0
        self.scaled_down = 0

    # -- decision ----------------------------------------------------------

    def decide(self, signals: dict) -> tuple[str, str] | None:
        """Pure decision function: ``(direction, reason)`` or None.

        Exposed separately from the loop so tests (and the bench leg) can
        drive it against synthetic signals without a live fleet.
        """
        n = int(signals.get("replicas", 0))
        load = float(signals.get("in_flight_per_up", 0.0))
        p99 = float(signals.get("p99_s", 0.0) or 0.0)
        hot_p99 = self.high_p99_s > 0.0 and p99 > self.high_p99_s
        if load > self.high_load or hot_p99:
            self._down_votes = 0
            self._up_votes += 1
            if self._up_votes >= self.up_after and n < self.max_replicas:
                self._up_votes = 0
                return ("up", "p99" if hot_p99 and load <= self.high_load
                        else "queue_depth")
            return None
        self._up_votes = 0
        if load < self.low_load and not hot_p99:
            self._down_votes += 1
            if self._down_votes >= self.down_after and n > self.min_replicas:
                self._down_votes = 0
                return ("down", "idle")
            return None
        self._down_votes = 0
        return None

    def tick(self, now: float | None = None) -> tuple[str, str] | None:
        """One decision + (maybe) one scale operation. Returns what was
        attempted, or None."""
        now = time.monotonic() if now is None else now
        if now < self._hold_until:
            return None
        verdict = self.decide(self.router.signals())
        if verdict is None:
            return None
        direction, reason = verdict
        if direction == "up":
            ok = self.router.scale_up(reason=reason) is not None
            if ok:
                self.scaled_up += 1
        else:
            ok = self.router.scale_down(reason=reason)
            if ok:
                self.scaled_down += 1
        self._hold_until = time.monotonic() + self.cooldown_s
        return verdict

    # -- loop --------------------------------------------------------------

    def _loop(self) -> None:
        # Bring the fleet inside bounds first: a fleet started below
        # min_replicas (e.g. min raised by config) grows immediately.
        while (not self._stop.is_set()
               and len(self.router.replicas) < self.min_replicas):
            if self.router.scale_up(reason="min_replicas") is None:
                break
            self.scaled_up += 1
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive a
                pass           # failed scale op; the next tick retries

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="autoscaler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def stats(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "high_load": self.high_load,
            "low_load": self.low_load,
            "high_p99_s": self.high_p99_s,
            "scaled_up": self.scaled_up,
            "scaled_down": self.scaled_down,
            "running": self._thread is not None,
        }
