"""Serving fleet: router, tenant registry, and the control plane.

``FleetRouter`` (``fleet/router.py``) fronts a DYNAMIC set of replica
subprocesses on one asyncio accept loop with health-tracked
consistent-hash / least-loaded routing, aggregated ``/metrics``, and
warm-standby scale-up / drain-first scale-down; ``TenantRegistry``
(``fleet/tenants.py``) serves many models per replica behind an LRU of
AOT-warmed Predictors with per-tenant generations, quotas, and SLO
verdicts. The control plane rides on top: ``Autoscaler``
(``fleet/controlplane.py``) drives the router's scale ops off its
queue-depth/p99 signals, ``ArtifactStore`` (``fleet/artifacts.py``) maps
each distinct artifact digest once per host, and ``FitScheduler``
(``fleet/jobs.py``) runs fit-as-a-service jobs that publish through the
per-tenant blue/green swap. See the README "Fleet" section for topology
and the failure matrix.
"""

from hdbscan_tpu.fleet.artifacts import ArtifactStore, default_store
from hdbscan_tpu.fleet.controlplane import Autoscaler
from hdbscan_tpu.fleet.jobs import FitJob, FitScheduler
from hdbscan_tpu.fleet.router import POLICIES, FleetRouter
from hdbscan_tpu.fleet.tenants import DEFAULT_TENANT_SLO, TenantRegistry

__all__ = [
    "ArtifactStore",
    "Autoscaler",
    "FitJob",
    "FitScheduler",
    "FleetRouter",
    "TenantRegistry",
    "POLICIES",
    "DEFAULT_TENANT_SLO",
    "default_store",
]
