"""Serving fleet: multi-replica router + multi-tenant model registry.

``FleetRouter`` (``fleet/router.py``) fronts N replica subprocesses on one
asyncio accept loop with health-tracked consistent-hash / least-loaded
routing and aggregated ``/metrics``; ``TenantRegistry``
(``fleet/tenants.py``) serves many models per replica behind an LRU of
AOT-warmed Predictors with per-tenant generations, quotas, and SLO
verdicts. See the README "Fleet" section for topology and the failure
matrix.
"""

from hdbscan_tpu.fleet.router import POLICIES, FleetRouter
from hdbscan_tpu.fleet.tenants import DEFAULT_TENANT_SLO, TenantRegistry

__all__ = [
    "FleetRouter",
    "TenantRegistry",
    "POLICIES",
    "DEFAULT_TENANT_SLO",
]
