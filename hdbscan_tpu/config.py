"""Configuration: the reference's flag vocabulary as a dataclass.

Mirrors ``Main.checkInputParameters`` / ``HDBSCANStarParameters``
(``main/Main.java:417-528,620-638``): ``file=``, ``clusterName=``,
``constraints=``, ``minPts=``, ``k=`` (sample fraction), ``processing_units=``
(per-partition block capacity), ``minClSize=``, ``compact=``,
``dist_function=`` in {euclidean, cosine, pearson, manhattan, supremum}.
Defaults match the reference (Euclidean, non-compact, ``main/Main.java:419-420``).
The reference shadows argv with hard-coded args (``main/Main.java:71``) —
treated as a bug; ``from_args`` really parses.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass

from hdbscan_tpu.core.distances import METRICS


@dataclass
class HDBSCANParams:
    input_file: str = ""
    min_points: int = 4
    min_cluster_size: int = 4
    processing_units: int = 50  # per-block capacity ("subset fits one worker")
    k: float = 0.2  # stratified sample fraction per oversized subset
    dist_function: str = "euclidean"
    compact_hierarchy: bool = False
    constraints_file: str | None = None
    cluster_name: str = "local"  # Spark master analog; kept for CLI parity
    out_dir: str | None = None
    self_edges: bool = True
    seed: int = 0
    #: Approximation variant for oversized subsets (BASELINE.md columns):
    #: "db" = recursive sampling + data bubbles (the reference's live pipeline);
    #: "rs" = simple recursive sampling (cluster the sample points directly,
    #: the paper's RS baseline — quoted-numbers-only in the reference).
    variant: str = "db"
    #: Harvest exact inter-subset MST "glue" edges with per-level tiled
    #: Borůvka rounds, and re-weight sample-derived inter-edges with true
    #: point-space distances (the reference carries the bubble-corrected
    #: dmreach into the global merge, ``main/Main.java:248-265``, whose
    #: sample-spacing-scale weights fragment the global tree). Set False for
    #: reference-faithful edge pooling.
    exact_inter_edges: bool = True
    #: Compute core distances GLOBALLY (one tiled O(n^2 d) device pass)
    #: instead of per-block. Per-block core distances inflate at partition
    #: boundaries (a point's true neighbors may sit in another block), which
    #: distorts MRD edge weights and noise exit levels and makes quality
    #: depend on where the partitioner cut — the reference's dead exact path
    #: broadcasts the whole dataset for the same reason
    #: (``mappers/CoreDistanceMapper.java:57-112``). Set False for
    #: reference-faithful per-subset core distances (``mappers/FirstStep``).
    global_core_distances: bool = True
    #: Post-merge refinement rounds for the distributed pipeline: seed tiled
    #: Borůvka with the condensed tree's leaf clusters (every point's deepest
    #: cluster), harvest the exact minimum MRD edges between them (true MST
    #: edges by the cut property), rebuild the tree, repeat. Repairs the
    #: saddle edges the per-partition pooling carried at slightly-too-heavy
    #: weights — on lattice-valued data one displaced saddle edge moves a
    #: whole region into a later merge wave and flips the flat cut. 0
    #: disables (reference-faithful: the reference never refines).
    refine_iterations: int = 1
    #: FLAT-CUT-level refinement rounds (r5): after the tree is built, seed
    #: tiled Borůvka with the flat labels (noise points as singleton
    #: components), harvest the exact min MRD edges crossing that partition,
    #: rebuild, repeat until labels stop changing or the budget runs out.
    #: This repairs pool incompleteness at the TOP of the tree — the
    #: measured source of the cross-draw flat-cut spread on lattice data
    #: (draws' pools miss different top-structure MST edges; leaf-cluster
    #: refinement is too fine to see them). Measured on the Skin 45-seed
    #: protocol (seed_sweep45_skin_r5.jsonl): draws converge onto the
    #: exact tree's reading (ARI 0.6925 vs single-draw mean 0.595 std
    #: 0.035). Applies to the global-core (non-boundary) pipeline; 0
    #: disables (reference behavior — the reference never refines).
    refine_flat_iterations: int = 0
    #: Boundary-aware hybrid quality mode (sub-quadratic at DB quality).
    #: When > 0: the fraction of each final block treated as "boundary" —
    #: points whose seam margin (distance to the nearest other-subset sample
    #: minus distance to their own, recorded at every level's assignment) is
    #: smallest. Only those m = boundary_quality·n points pay exact global
    #: core distances (one O(m·n·d) scan) and host the inter-block Borůvka
    #: glue + refinement (O(m²·d) per round); interior points keep per-block
    #: cores (their k-NN ball is inside their block by construction), and the
    #: whole pooled edge set is re-weighted to mutual reachability under the
    #: hybrid core vector. Supersedes ``global_core_distances`` and the
    #: per-level full-set glue scans, replacing every O(n²·d) quality pass —
    #: the scale mode for the paper's 8-11.6M-row datasets (BASELINE.md).
    boundary_quality: float = 0.0
    #: Block-adjacency-aware candidate columns for the boundary phase
    #: (``ops/blockscan.py``): each boundary point's exact-core rescan and
    #: the inter-block glue/refinement rounds scan only the blocks its k-NN
    #: ball (bounded by the per-block core distance) or the per-component
    #: edge bounds can reach — O(m · seam-degree · cap) instead of O(m·n)
    #: and O(m²) — with exactness preserved by conservative f64
    #: centroid/radius bounds (same results as the full sweeps; pinned by
    #: tests/unit/test_blockscan.py). Auto-falls back to the full sweeps on
    #: non-triangle-inequality metrics (cosine/pearson). Set False to force
    #: the full sweeps everywhere.
    boundary_block_pruning: bool = True
    #: Boundary-mode at-risk criterion multiplier: a point joins the exact
    #: core rescan when its seam margin <= boundary_alpha * per-block core
    #: (margin upper-bounds the seam distance, the per-block core
    #: upper-bounds the k-NN ball radius, so 1.0 captures every point whose
    #: ball can cross a seam — the measured-correct default; see
    #: models/mr_hdbscan._BOUNDARY_ALPHA provenance).
    boundary_alpha: float = 1.0
    #: Hard cap on the boundary-set fraction (non-pruned path only; the
    #: block-pruned path has no cap — its rescan cost scales with candidate
    #: windows, not m). The adaptive at-risk criterion is open-ended by
    #: design; past ~half the dataset the non-pruned O(m·n·d) rescan
    #: approaches the full exact scan the mode exists to avoid, so selection
    #: truncates (most-at-risk first, floor preserved) and warns. Promoted
    #: from a module constant (VERDICT r4 weak #6) so a user who accepts the
    #: ~n² cost can buy the cap back without editing source.
    boundary_max_frac: float = 0.5
    #: Glue-set deep-crossing criterion: rows with margin <=
    #: glue_alpha * core join the per-block lowest-margin floor as
    #: candidate hosts of inter-block MST edges (the min-MRD pair is not
    #: necessarily the geometrically closest pair). Measured at 1M sep-7:
    #: floor alone drops vs-exact fidelity 0.95 -> 0.90.
    glue_alpha: float = 0.5
    #: Cap on the glue set as a multiple of the per-block floor set
    #: (smallest margins kept first). Glue/refine round cost scales with
    #: the SQUARE of this factor when rounds go dense; measured at 4M
    #: sep-7: factor 3 scores ARI-vs-truth 0.9558, factor 6 scores 0.9535
    #: at ~1.1x the wall (r4 — growing the deep tier PARTIALLY is not a
    #: quality lever; the 0.9754 high-water mark needs the whole tier,
    #: see glue_row_budget = -1 below).
    glue_max_factor: int = 3
    #: Optional row-count TARGET for the glue/refine subset — the exact-tree
    #: FIDELITY knob. When > 0 and the factor-capped set is below it, the
    #: glue set grows with further at-risk rows (deep-crossing first, then
    #: ascending seam margin) until the budget or the at-risk pool runs out.
    #: -1 = the whole deep-crossing tier with no at-risk filler and no cap
    #: (glue = floor ∪ {margin <= glue_alpha*core}) — the 4M sep-7 quality
    #: high-water composition (see models/mr_hdbscan._select_boundary).
    #: Measured at 1M sep-7 (boundary_eval_r4.jsonl): glue_rows=1048576
    #: lifts ARI-vs-exact 0.9058 -> 0.9507 (the r2 fidelity level) at 2x the
    #: boundary wall and slightly LOWER ARI-vs-truth (0.9459 -> 0.9266 —
    #: the floor-glue tree's deviations from exact act as regularization at
    #: overlapping-cluster difficulty). Default 0 = factor-capped only:
    #: better truth, better wall; set a budget when the contract is
    #: "approximate the exact tree", not "maximize ground-truth ARI".
    glue_row_budget: int = 0
    #: Consensus across sample draws (``models/consensus.py``): > 1 runs the
    #: distributed pipeline that many times with distinct seeds and returns
    #: the evidence-accumulation consensus of the flat cuts — the stabilizer
    #: for lattice-valued data whose flat cut is bimodal across draws (Skin:
    #: per-draw ARI std 0.034 vs the paper's 0.002; the spread is structural,
    #: not fixable by refinement — ROADMAP r3). 1 = single draw (reference
    #: behavior).
    consensus_draws: int = 1
    #: Collapse duplicate rows into weighted unique points before the exact
    #: pipeline (``core/dedup.py``). Semantics-preserving (a duplicate group
    #: is a zero-extent bubble; the member-weighted tree equals the full-row
    #: tree) while the O(n^2 d) device scans shrink to unique-count scale —
    #: 4.8x fewer rows (23x less scan work) on the lattice-valued north-star
    #: set. Off by default for strict row-level reference parity.
    dedup_points: bool = False
    #: Cap on samples drawn per oversized subset (``k`` gives the fraction;
    #: this bounds the absolute count). The bubble model holds a dense
    #: (m, m) corrected-distance matrix plus ~6 same-shape temps on device:
    #: 16384 ≈ 1 GB per matrix ≈ 8 GB peak — the single-chip HBM budget.
    #: At 4M+ points an uncapped k=0.01 draw (40k+ samples, pow2-padded to
    #: 65536) compiles a 17 GB matrix and OOMs a 15.75 GB chip; the cap
    #: trades first-level partition granularity (more recursion levels)
    #: for bounded memory. The reference has the same cliff un-handled: its
    #: sampleByKeyExact fraction is unbounded per worker. The sample axis is
    #: pow2-PADDED on device, so the effective cap is rounded DOWN to a
    #: power of two (a non-pow2 value would silently bound memory at up to
    #: 2x the configured footprint).
    max_samples: int = 16384
    #: Reproduce the reference's LIVE integer-math CF behaviors instead of
    #: the correct double math (``core/compat.py``): CombineStep's
    #: mean-of-per-dim-sqrt extent and collapsed nnDist exponent
    #: (``CombineStep.java:28,42-57``) and the bubble core-distance walk with
    #: its stale shared ``indexBubbles`` buffer, i-vs-index confusion and
    #: integer-division exponents (``HdbscanDataBubbles.java:75-146``). For
    #: output parity with a reference RUN rather than with the paper. Off by
    #: default (SURVEY.md §7 parity-vs-bug decisions).
    compat_cf_int_math: bool = False
    #: Device backend for the exact k-NN scans (``ops/tiled`` core distances
    #: and the boundary-mode window-merge rescan in ``ops/blockscan``):
    #: "auto" (default) picks the Pallas distance kernel + XLA top_k on TPU
    #: and the guarded XLA scan elsewhere; "xla" forces the guarded XLA
    #: scan; "pallas" forces the distance-only Pallas kernel (raises when
    #: ineligible); "fused" selects neighbors on-chip next to the distance
    #: tiles (``ops/pallas_knn.knn_core_distances_fused`` — the r6
    #: selection-bound fix, see utils/flops.py docstring) and silently
    #: falls back to the guarded XLA scan when the shape/metric/platform is
    #: ineligible, so the knob is safe under every parameterization.
    knn_backend: str = "auto"
    #: Distance-tile precision of the FUSED forest-query program
    #: (``knn_backend="fused"`` + ``knn_index="rpforest"``,
    #: ``ops/pallas_forest``): "f32" (default) is bitwise identical to the
    #: unfused engine; "bf16" computes the leaf/rescan distance tiles from
    #: bf16 MXU operands with f32 accumulation and re-distances the
    #: surviving k-best exactly in f32 (``pallas_forest.refine_f32``) —
    #: euclidean only, quality pinned by the recall/ARI gate in
    #: tests/unit/test_pallas_forest.py. Every other path ignores the knob
    #: and stays f32-exact.
    knn_precision: str = "f32"
    #: Neighbor-graph TIER for the core-distance scans — orthogonal to
    #: ``knn_backend`` (which picks the kernel evaluating distance tiles):
    #: "exact" (default) runs the O(n² d) scans bitwise-unchanged,
    #: "rpforest" runs the sub-quadratic random-projection-forest engine
    #: (``ops/rpforest.py`` — T trees, per-leaf dense k-NN, multi-tree lex
    #: merge, neighbor-of-neighbor rescan; README "Approximate neighbors"),
    #: "auto" picks rpforest at ``n >= knn_index_threshold`` points.
    knn_index: str = "exact"
    #: The ``knn_index="auto"`` flip point (points). Below it fits stay
    #: bitwise-exact; at/above it the rp-forest engine runs (measured >= 3x
    #: core-distance win already at 200k on CPU, BENCH_r06.json).
    knn_index_threshold: int = 262144
    #: Random-projection trees per forest. More trees raise recall at
    #: linear build/query cost; 4 trees + 1 rescan round measured >= 0.95
    #: mean recall@16 across the tier-1 sweep datasets.
    rpf_trees: int = 4
    #: Leaf capacity of each tree (points). Per-leaf scan work is
    #: O(n · leaf_size · d) per tree; internally clamped to >= 2k + 2 so
    #: every leaf can supply a full candidate list.
    rpf_leaf_size: int = 1024
    #: Neighbor-of-neighbor rescan rounds after the multi-tree merge
    #: (cross-leaf recall repair, PANDA-style). 0 disables.
    rpf_rescan_rounds: int = 1
    #: Scale-out engine for the exact-path scans (core distances, Borůvka
    #: rounds, the mr-hdbscan glue + boundary rescan): "host" keeps the
    #: column-replicated scans (each device holds a full data copy; the
    #: pre-ring behavior), "ring" shards rows AND columns over the mesh and
    #: circulates column panels via ``lax.ppermute`` (``parallel/ring.py``
    #: — per-device HBM drops to O(n/devices · d), neighbor exchange
    #: overlaps compute), "auto" (default) picks ring on multi-device TPU
    #: meshes and host elsewhere. Outputs are bitwise identical across
    #: backends (ring parity tests, tests/unit/test_ring.py).
    scan_backend: str = "auto"
    #: End-to-end partition tier (``parallel/shard.py``): "replicated" keeps
    #: the existing engines (some phase somewhere holds a full point-set copy
    #: per device — the pre-shard behavior), "sharded" runs ONE partitioned
    #: program — row-sharded core distances (ring k-NN or the per-shard
    #: rp-forest build + ring-circulated candidate-panel exchange) feeding
    #: fully row-sharded Borůvka rounds. With ``mst_backend="host"`` the
    #: rounds contract on host (per-round edge all-gather); with
    #: ``mst_backend="device"``/"auto" the whole contraction cascade runs
    #: in-jit (scatter-min tie-break, cross-device panel reduction,
    #: pointer-doubling collapse inside one ``while_loop``) and the fit makes
    #: exactly ONE host sync — the final edge fetch feeding the device merge
    #: forest. Per-device HBM stays O(n/devices · d) in every phase — the
    #: program the ``--assert-not-replicated`` gate certifies. The MR
    #: pipeline honors the tier too: global cores (weighted dedup scan
    #: included), the boundary rescan, and every Borůvka glue harvest route
    #: through the sharded scanners (block pruning is disabled under sharded
    #: — its windowed scans keep replicated geometry panels). "auto"
    #: (default) picks sharded on multi-device TPU meshes and replicated
    #: elsewhere. With ``knn_index="exact"`` the sharded fit is bitwise
    #: identical to the replicated one (forced-8-device parity tests).
    fit_sharding: str = "auto"
    #: Host finalize engine for the condensed-tree tail (``core/tree.py`` vs
    #: ``core/tree_vec.py``): "reference" keeps the per-node Python
    #: condense/EOM/label walk (the parity oracle), "vectorized" runs the
    #: array-level engine (pointer-jumped chain/exit propagation +
    #: ``np.add.at`` segment-sum stabilities — bitwise-identical outputs),
    #: "auto" (default) picks vectorized whenever the inputs support it
    #: (integral point weights; ``tree_vec.supports_inputs``) and falls back
    #: to reference otherwise. Applies to every finalize call site, including
    #: the per-iteration rebuilds of the refine/refine_flat loops.
    tree_backend: str = "auto"
    #: MST -> merge-forest engine for the exact path (``core/mst_device.py``):
    #: "host" keeps the per-round host contraction glue plus the sequential
    #: host forest builder (the parity oracle), "device" runs every Borůvka
    #: round in one jitted program and builds the merge forest from a single
    #: device union-find scan — exactly ONE host sync per fit (trace event
    #: ``host_sync``), "auto" (default) picks device at/above
    #: ``core/mst_device.MST_DEVICE_THRESHOLD`` vertices when the edge pool
    #: is eligible (``mst_device.supports_inputs`` — no near-tied-but-unequal
    #: weights, integral point weights) and host otherwise. Device output is
    #: bitwise-identical to host on every MergeForest/CondensedTree field;
    #: ineligible pools fall back to the host builder (flagged in the trace).
    mst_backend: str = "auto"
    #: Persistent XLA compilation cache: "auto" (default) enables it at the
    #: default directory (``utils/cache.py`` — ``$JAX_COMPILATION_CACHE_DIR``
    #: or ``~/.cache/hdbscan_tpu_xla``), "off" disables it, any other value
    #: is used as the cache directory path. Cache hits vs fresh compiles are
    #: recorded in the run report (``utils/telemetry.cache_hit_counter``).
    compile_cache: str = "auto"
    #: Serving k-NN engine for ``serve/predict`` (the ``predict``/``serve``
    #: CLI commands): "xla" runs the guarded tiled scan, "fused" the Pallas
    #: fused-selection kernel (falls back to xla when the shape/metric/
    #: platform is ineligible — same safety contract as ``knn_backend``),
    #: "rpforest" queries the model artifact's random-projection-forest
    #: index (requires a model saved from a ``knn_index=rpforest`` fit —
    #: approximate neighbors, O(trees · leaf_size) per query instead of
    #: O(n)), "auto" (default) picks fused on eligible TPU shapes.
    predict_backend: str = "auto"
    #: Largest serving bucket: query batches pad into power-of-two buckets
    #: up to this many rows (floor 8) and larger requests chunk. Every
    #: bucket is AOT-warmed at server start, so steady-state serving
    #: recompiles nothing.
    predict_max_batch: int = 256
    #: Streaming ingest (``serve --ingest`` / ``hdbscan_tpu/stream``):
    #: near-duplicate absorb slack — an arriving point is folded into its
    #: cluster's bubble summary when its attachment mutual-reachability
    #: level is within ``(1 + frac)`` of the cluster's ``eps_min`` density
    #: level (0 absorbs only probability-1.0 rows + exact duplicates).
    stream_absorb_eps_frac: float = 0.25
    #: Drift statistic over the streaming GLOSH-score histogram vs the
    #: fit-time baseline: "psi" (Population Stability Index) or "ks"
    #: (Kolmogorov-Smirnov distance over the same bins).
    stream_drift_stat: str = "psi"
    #: Drift flag level for ``stream_drift_stat`` (and the assignment-rate
    #: PSI). The baseline histogram is the *training rows'* GLOSH scores,
    #: and fresh in-distribution draws score systematically higher than the
    #: rows the model was fit on, so the textbook PSI scale (0.2 =
    #: significant) does not transfer: in-distribution streams read ~0.3-0.5
    #: here while genuine shift reads an order of magnitude above (see
    #: tests/e2e/test_stream_e2e.py). 2.0 separates the two regimes.
    stream_drift_threshold: float = 2.0
    #: Novel-row budget: a background re-fit also triggers once this many
    #: non-absorbed rows are buffered, drift or not.
    stream_refit_budget: int = 2048
    #: What happens when a re-fit publishes an artifact: "auto" hot-swaps it
    #: in (blue/green), "manual" stages it for an operator ``POST /swap``.
    stream_reload: str = "auto"
    #: Bound on the serving micro-batcher's request queue (``serve`` CLI /
    #: ``ClusterServer``): a submit arriving with this many requests already
    #: queued is refused with HTTP 503 + Retry-After (load shedding) instead
    #: of queueing unboundedly — under sustained overload the server sheds
    #: rather than growing an unservable backlog. 0 = unbounded (the
    #: pre-fault-layer behavior).
    serve_queue_bound: int = 1024
    #: Server-wide default request deadline in milliseconds (0 = none; the
    #: ``X-Deadline-Ms`` request header overrides per request). A request
    #: past its deadline fails fast with HTTP 504 — at enqueue or at batch
    #: assembly — instead of occupying a batch slot.
    serve_deadline_ms: float = 0.0
    #: Fault-injection spec for the chaos harness (``hdbscan_tpu/fault``):
    #: ``site:key=val,...;site2:...`` clauses (see fault/inject.py for the
    #: grammar and site names). "" = no injection; the
    #: ``HDBSCAN_TPU_FAULTS`` environment variable is the fallback source.
    fault_spec: str = ""
    #: Consecutive refit/swap failures that trip the refit circuit breaker
    #: open (the server then degrades to serving the pinned generation).
    circuit_failures: int = 3
    #: Seconds an open refit circuit waits before allowing a half-open
    #: trial re-fit.
    circuit_reset_s: float = 30.0
    #: Crash-safe stream durability (``stream/wal.StreamJournal``): journal
    #: directory for the fsync'd ingest WAL + periodic state snapshots.
    #: "" disables (ingest state is lost on crash, the pre-WAL behavior).
    stream_wal_dir: str = ""
    #: Ingest WAL appends between state snapshots (each snapshot truncates
    #: the WAL, bounding recovery replay).
    stream_snapshot_every: int = 64
    #: Online hierarchy maintenance (``hdbscan_tpu/incremental``): "off"
    #: (default) keeps the PR-8 behavior — novel rows buffer until a full
    #: background re-fit; "incremental" maintains the mutual-reachability
    #: MST in place per novel point (bounded rp-forest candidate query,
    #: cuSLINK-style cycle-edge replacement) and republishes the model via
    #: a cheap handle refresh, demoting the full re-fit to the
    #: circuit-gated fallback. Euclidean metric only.
    stream_maintain: str = "off"
    #: Per-point maintenance wall budget in milliseconds; an insert over
    #: budget is *counted* (``hdbscan_tpu_maintain_total{outcome=
    #: "over_budget"}``) but never changes state, so WAL replay stays a
    #: deterministic fold. 0 = unbounded.
    maintain_budget_ms: float = 0.0
    #: Dirty-work ceiling for one maintenance step, as the fraction of MST
    #: edges (and merge-forest nodes) the splice/finalize would have to
    #: reprocess. Above it the step raises ``MaintainFallback`` and the
    #: server falls back to the full re-fit. 1.0 = never refuse.
    maintain_dirty_max_frac: float = 1.0
    #: Inserts between maintained-model refreshes: the MST splice, the
    #: dirty-subtree finalize, and the blue/green handle refresh run every
    #: this many absorbed novel points (per-insert work stays O(candidates)
    #: regardless).
    maintain_refresh_every: int = 64
    #: Replica subprocesses behind the ``fleet`` CLI router
    #: (``hdbscan_tpu/fleet``): each is a full ``serve`` process sharing the
    #: model artifact / ``--model-dir``; the router spawns, health-checks,
    #: and routes across them.
    fleet_replicas: int = 2
    #: Fleet routing policy: "consistent_hash" pins a tenant (or body
    #: digest) to a stable replica via an md5 ring; "least_loaded" picks the
    #: replica with the fewest in-flight proxied requests.
    fleet_policy: str = "least_loaded"
    #: Fleet health-probe period in seconds — also the bound within which a
    #: dead replica stops receiving traffic, and the Retry-After hint when
    #: every replica is down.
    fleet_health_interval_s: float = 0.5
    #: SIGTERM drain bound for the fleet: a replica still alive this many
    #: seconds after the router forwards SIGTERM is SIGKILLed and the
    #: router exits nonzero.
    fleet_drain_s: float = 10.0
    #: Multi-tenant serving (``--tenants-dir``): max AOT-warmed tenant
    #: Predictors resident per replica; the coldest is evicted (with a
    #: ``tenant_evict`` trace event) when a miss would exceed it.
    tenant_lru_size: int = 8
    #: Per-tenant sustained request quota in requests/second (token bucket,
    #: burst = max(1, quota)); an over-quota request is refused with HTTP
    #: 429 + Retry-After. 0 = unlimited.
    tenant_quota_rps: float = 0.0
    #: Fleet autoscaler (``fleet/controlplane.py``): when enabled the
    #: ``fleet`` CLI runs the hysteresis loop over the router's queue-depth
    #: and p99 signals, scaling between the min/max bounds with
    #: warm-standby adds and drain-first removes.
    fleet_autoscale: bool = False
    #: Autoscaler lower bound on the replica set (>= 1).
    fleet_min_replicas: int = 1
    #: Autoscaler upper bound on the replica set (>= min).
    fleet_max_replicas: int = 4
    #: Per-up-replica in-flight requests above which an autoscaler tick
    #: votes scale-up (hysteresis: 2 consecutive votes scale).
    fleet_scale_high_load: float = 4.0
    #: Per-up-replica in-flight requests below which a tick votes
    #: scale-down (5 consecutive idle votes scale; must be < high).
    fleet_scale_low_load: float = 0.5
    #: Rolling fleet p99 (seconds) above which a tick votes scale-up and
    #: vetoes scale-down. 0 disables the latency signal.
    fleet_scale_p99_s: float = 0.0
    #: Hold after any scale operation before the next decision, so the
    #: fleet re-equilibrates on the new topology.
    fleet_scale_cooldown_s: float = 2.0
    #: Per-host zero-copy artifact store (``fleet/artifacts.py``):
    #: "shared" loads tenant artifacts through the digest-keyed mmap spool
    #: (one resident copy per host, shared across replicas); "off"
    #: (default) keeps private per-registry loads.
    fleet_artifact_store: str = "off"
    #: Fit-as-a-service worker pool size (``fleet/jobs.py``): concurrent
    #: background fits a scheduler runs.
    fit_job_workers: int = 2
    #: Bound on queued-but-not-running fit jobs; an overflowing submit is
    #: refused with HTTP 503 semantics.
    fit_job_queue_bound: int = 16
    #: Sustained per-tenant fit-job rate (token bucket, burst 1); an
    #: over-quota submit is refused with HTTP 429 + Retry-After.
    #: 0 = unlimited.
    fit_job_quota_rps: float = 0.0
    #: Minimum spacing between emitted ``heartbeat`` trace events per
    #: progress task (``hdbscan_tpu/obs`` — Borůvka rounds, ring panel
    #: sweeps, rpforest tree builds, refits). Beats arriving faster are
    #: throttled; the liveness clock still refreshes on every beat.
    heartbeat_s: float = 1.0
    #: Hang-watchdog stall budget in seconds: with fit/refit tasks active
    #: and no heartbeat for this long, a watchdog thread dumps every Python
    #: thread's stack to the trace (``watchdog_stall``) and stderr, and
    #: bumps ``hdbscan_tpu_watchdog_stalls_total``. 0 (default) disables
    #: the watchdog thread.
    watchdog_s: float = 0.0
    #: Bound on the Tracer's in-memory event list (0 = unbounded). Sinks
    #: (the on-disk JSONL trace) always see every event; the bound only
    #: rings the in-memory view so a long-running ``serve --ingest``
    #: process — one predict_batch + stream_ingest + request_span per
    #: request, forever — cannot grow without limit. Dropped events are
    #: counted (``Tracer.events_dropped``) and noted in the summary.
    trace_max_events: int = 100_000
    #: Straggler trip ratio for the per-device timeline recorder
    #: (``hdbscan_tpu/obs/timeline.py``): a device whose per-round wall is
    #: >= this multiple of the round's median wall counts as slow. Must be
    #: >= 1.
    obs_skew_threshold: float = 2.0
    #: Consecutive slow rounds before a ``straggler_flag`` event fires (and
    #: ``hdbscan_tpu_straggler_flags_total{device}`` increments). Must be
    #: >= 1.
    obs_straggler_rounds: int = 3
    #: JSONL trace-file rotation bound in bytes (``JsonlSink``): when the
    #: next line would push ``trace.jsonl`` past this size it moves to
    #: ``trace.jsonl.1`` and a fresh file opens (seq continues; at most two
    #: files ever exist). 0 disables rotation. Default 256 MiB.
    trace_rotate_bytes: int = 268_435_456
    # Output file names derived from the input path (main/Main.java:516-526):

    def __post_init__(self):
        if self.dist_function not in METRICS:
            raise ValueError(
                f"dist_function must be one of {METRICS}, got {self.dist_function!r}"
            )
        if self.min_points < 1 or self.min_cluster_size < 1:
            raise ValueError("minPts and minClSize must be >= 1")
        if not (0.0 < self.k <= 1.0):
            raise ValueError("k (sample fraction) must be in (0, 1]")
        if self.processing_units < 1:
            raise ValueError("processing_units must be >= 1")
        if self.max_samples < 2:
            raise ValueError("max_samples must be >= 2")
        if self.variant not in ("db", "rs"):
            raise ValueError(f"variant must be 'db' or 'rs', got {self.variant!r}")
        if not (0.0 <= self.boundary_quality < 1.0):
            raise ValueError("boundary_quality must be in [0, 1)")
        if self.boundary_alpha <= 0 or self.glue_alpha < 0:
            raise ValueError("boundary_alpha must be > 0, glue_alpha >= 0")
        if not (0.0 < self.boundary_max_frac <= 1.0):
            raise ValueError("boundary_max_frac must be in (0, 1]")
        if self.glue_max_factor < 1:
            raise ValueError("glue_max_factor must be >= 1")
        if self.glue_row_budget < -1:
            raise ValueError("glue_row_budget must be >= 0, or -1 for the "
                             "uncapped deep-crossing tier")
        if self.consensus_draws < 1:
            raise ValueError("consensus_draws must be >= 1")
        if self.scan_backend not in ("auto", "host", "ring"):
            raise ValueError(
                "scan_backend must be 'auto', 'host' or 'ring', "
                f"got {self.scan_backend!r}"
            )
        if self.fit_sharding not in ("auto", "replicated", "sharded"):
            raise ValueError(
                "fit_sharding must be 'auto', 'replicated' or 'sharded', "
                f"got {self.fit_sharding!r}"
            )
        if self.tree_backend not in ("auto", "reference", "vectorized"):
            raise ValueError(
                "tree_backend must be 'auto', 'reference' or 'vectorized', "
                f"got {self.tree_backend!r}"
            )
        if self.mst_backend not in ("auto", "host", "device"):
            raise ValueError(
                "mst_backend must be 'auto', 'host' or 'device', "
                f"got {self.mst_backend!r}"
            )
        if not self.compile_cache:
            raise ValueError(
                "compile_cache must be 'auto', 'off' or a directory path"
            )
        if self.knn_backend not in ("auto", "xla", "pallas", "fused"):
            raise ValueError(
                "knn_backend must be 'auto', 'xla', 'pallas' or 'fused', "
                f"got {self.knn_backend!r}"
            )
        if self.knn_precision not in ("f32", "bf16"):
            raise ValueError(
                "knn_precision must be 'f32' or 'bf16', "
                f"got {self.knn_precision!r}"
            )
        if self.predict_backend not in ("auto", "xla", "fused", "rpforest"):
            raise ValueError(
                "predict_backend must be 'auto', 'xla', 'fused' or "
                f"'rpforest', got {self.predict_backend!r}"
            )
        if self.knn_index not in ("auto", "exact", "rpforest"):
            raise ValueError(
                "knn_index must be 'auto', 'exact' or 'rpforest', "
                f"got {self.knn_index!r}"
            )
        if self.knn_index_threshold < 1:
            raise ValueError("knn_index_threshold must be >= 1")
        if self.rpf_trees < 1:
            raise ValueError("rpf_trees must be >= 1")
        if self.rpf_leaf_size < 4:
            raise ValueError("rpf_leaf_size must be >= 4")
        if self.rpf_rescan_rounds < 0:
            raise ValueError("rpf_rescan_rounds must be >= 0")
        if self.predict_max_batch < 1:
            raise ValueError("predict_max_batch must be >= 1")
        if self.stream_absorb_eps_frac < 0:
            raise ValueError(
                "stream_absorb_eps_frac must be >= 0, "
                f"got {self.stream_absorb_eps_frac!r}"
            )
        if self.stream_drift_stat not in ("psi", "ks"):
            raise ValueError(
                "stream_drift_stat must be 'psi' or 'ks', "
                f"got {self.stream_drift_stat!r}"
            )
        if not self.stream_drift_threshold > 0:
            raise ValueError(
                "stream_drift_threshold must be > 0, "
                f"got {self.stream_drift_threshold!r}"
            )
        if self.stream_refit_budget < 1:
            raise ValueError(
                "stream_refit_budget must be >= 1, "
                f"got {self.stream_refit_budget!r}"
            )
        if self.stream_reload not in ("auto", "manual"):
            raise ValueError(
                "stream_reload must be 'auto' or 'manual', "
                f"got {self.stream_reload!r}"
            )
        if self.serve_queue_bound < 0:
            raise ValueError(
                "serve_queue_bound must be >= 0 (0 = unbounded), "
                f"got {self.serve_queue_bound!r}"
            )
        if self.serve_deadline_ms < 0:
            raise ValueError(
                "serve_deadline_ms must be >= 0 (0 = no deadline), "
                f"got {self.serve_deadline_ms!r}"
            )
        if self.fault_spec:
            from hdbscan_tpu.fault.inject import parse_spec

            parse_spec(self.fault_spec)  # eager validation: bad specs fail here
        if self.circuit_failures < 1:
            raise ValueError(
                f"circuit_failures must be >= 1, got {self.circuit_failures!r}"
            )
        if not self.circuit_reset_s > 0:
            raise ValueError(
                f"circuit_reset_s must be > 0, got {self.circuit_reset_s!r}"
            )
        if self.stream_snapshot_every < 1:
            raise ValueError(
                "stream_snapshot_every must be >= 1, "
                f"got {self.stream_snapshot_every!r}"
            )
        if self.stream_maintain not in ("off", "incremental"):
            raise ValueError(
                "stream_maintain must be 'off' or 'incremental', "
                f"got {self.stream_maintain!r}"
            )
        if self.maintain_budget_ms < 0:
            raise ValueError(
                "maintain_budget_ms must be >= 0 (0 = unbounded), "
                f"got {self.maintain_budget_ms!r}"
            )
        if not (0.0 < self.maintain_dirty_max_frac <= 1.0):
            raise ValueError(
                "maintain_dirty_max_frac must be in (0, 1], "
                f"got {self.maintain_dirty_max_frac!r}"
            )
        if self.maintain_refresh_every < 1:
            raise ValueError(
                "maintain_refresh_every must be >= 1, "
                f"got {self.maintain_refresh_every!r}"
            )
        if self.fleet_replicas < 1:
            raise ValueError(
                f"fleet_replicas must be >= 1, got {self.fleet_replicas!r}"
            )
        if self.fleet_policy not in ("consistent_hash", "least_loaded"):
            raise ValueError(
                "fleet_policy must be 'consistent_hash' or 'least_loaded', "
                f"got {self.fleet_policy!r}"
            )
        if not self.fleet_health_interval_s > 0:
            raise ValueError(
                "fleet_health_interval_s must be > 0, "
                f"got {self.fleet_health_interval_s!r}"
            )
        if not self.fleet_drain_s > 0:
            raise ValueError(
                f"fleet_drain_s must be > 0, got {self.fleet_drain_s!r}"
            )
        if self.tenant_lru_size < 1:
            raise ValueError(
                f"tenant_lru_size must be >= 1, got {self.tenant_lru_size!r}"
            )
        if self.tenant_quota_rps < 0:
            raise ValueError(
                "tenant_quota_rps must be >= 0 (0 = unlimited), "
                f"got {self.tenant_quota_rps!r}"
            )
        if self.fleet_min_replicas < 1:
            raise ValueError(
                f"fleet_min_replicas must be >= 1, got {self.fleet_min_replicas!r}"
            )
        if self.fleet_max_replicas < self.fleet_min_replicas:
            raise ValueError(
                "fleet_max_replicas must be >= fleet_min_replicas "
                f"({self.fleet_min_replicas}), got {self.fleet_max_replicas!r}"
            )
        if not self.fleet_scale_high_load > self.fleet_scale_low_load:
            raise ValueError(
                "fleet_scale_high_load must exceed fleet_scale_low_load, "
                f"got {self.fleet_scale_high_load!r} <= "
                f"{self.fleet_scale_low_load!r}"
            )
        if self.fleet_scale_p99_s < 0:
            raise ValueError(
                "fleet_scale_p99_s must be >= 0 (0 = latency signal off), "
                f"got {self.fleet_scale_p99_s!r}"
            )
        if self.fleet_scale_cooldown_s < 0:
            raise ValueError(
                "fleet_scale_cooldown_s must be >= 0, "
                f"got {self.fleet_scale_cooldown_s!r}"
            )
        if self.fleet_artifact_store not in ("shared", "off"):
            raise ValueError(
                "fleet_artifact_store must be 'shared' or 'off', "
                f"got {self.fleet_artifact_store!r}"
            )
        if self.fit_job_workers < 1:
            raise ValueError(
                f"fit_job_workers must be >= 1, got {self.fit_job_workers!r}"
            )
        if self.fit_job_queue_bound < 1:
            raise ValueError(
                "fit_job_queue_bound must be >= 1, "
                f"got {self.fit_job_queue_bound!r}"
            )
        if self.fit_job_quota_rps < 0:
            raise ValueError(
                "fit_job_quota_rps must be >= 0 (0 = unlimited), "
                f"got {self.fit_job_quota_rps!r}"
            )
        if not self.heartbeat_s > 0:
            raise ValueError(
                f"heartbeat_s must be > 0, got {self.heartbeat_s!r}"
            )
        if self.watchdog_s < 0:
            raise ValueError(
                "watchdog_s must be >= 0 (0 = watchdog off), "
                f"got {self.watchdog_s!r}"
            )
        if not self.obs_skew_threshold >= 1.0:
            raise ValueError(
                "obs_skew_threshold must be >= 1.0, "
                f"got {self.obs_skew_threshold!r}"
            )
        if self.obs_straggler_rounds < 1:
            raise ValueError(
                "obs_straggler_rounds must be >= 1, "
                f"got {self.obs_straggler_rounds!r}"
            )
        if self.trace_rotate_bytes < 0:
            raise ValueError(
                "trace_rotate_bytes must be >= 0 (0 = rotation off), "
                f"got {self.trace_rotate_bytes!r}"
            )
        if self.trace_max_events < 0:
            raise ValueError(
                "trace_max_events must be >= 0 (0 = unbounded), "
                f"got {self.trace_max_events!r}"
            )
        if self.boundary_quality > 0 and self.dedup_points:
            raise ValueError(
                "boundary_quality and dedup_points are mutually exclusive "
                "(dedup requires global core distances; boundary mode "
                "replaces them)"
            )

    @property
    def base_name(self) -> str:
        stem = os.path.basename(self.input_file) or "output"
        return os.path.splitext(stem)[0]

    def output_path(self, kind: str) -> str:
        """The 5 canonical outputs (main/Main.java:534-614): hierarchy, tree,
        partition, outlier_scores, visualization."""
        suffix = {
            "hierarchy": "_hierarchy.csv",
            "tree": "_tree.csv",
            "partition": "_partition.csv",
            "outlier_scores": "_outlier_scores.csv",
            "visualization": "_visualization.vis",
        }[kind]
        out_dir = self.out_dir or os.path.dirname(self.input_file) or "."
        return os.path.join(out_dir, self.base_name + suffix)

    @classmethod
    def from_args(cls, argv: list[str]) -> "HDBSCANParams":
        """Parse the reference's ``key=value`` flag strings."""
        kwargs = {}
        for arg in argv:
            if "=" not in arg:
                raise ValueError(f"malformed flag {arg!r}; expected key=value")
            key, _, value = arg.partition("=")
            if key not in FLAG_FIELDS:
                raise ValueError(f"unknown flag {key!r}")
            field, conv = FLAG_FIELDS[key]
            kwargs[field] = conv(value)
        return cls(**kwargs)

    def replace(self, **kw) -> "HDBSCANParams":
        return dataclasses.replace(self, **kw)


def _bool(s: str) -> bool:
    return s.lower() == "true"


#: CLI flag -> (dataclass field, converter). Module-level so harnesses that
#: need the flag->field correspondence (e.g. benchmarks/boundary_eval.py
#: override echoing) share one copy instead of re-declaring it.
FLAG_FIELDS = {
    "file": ("input_file", str),
    "minPts": ("min_points", int),
    "minClSize": ("min_cluster_size", int),
    "processing_units": ("processing_units", int),
    "k": ("k", float),
    "dist_function": ("dist_function", str),
    "compact": ("compact_hierarchy", _bool),
    "constraints": ("constraints_file", str),
    "clusterName": ("cluster_name", str),
    "out_dir": ("out_dir", str),
    "seed": ("seed", int),
    "variant": ("variant", str),
    "dedup": ("dedup_points", _bool),
    "exact_inter_edges": ("exact_inter_edges", _bool),
    "global_cores": ("global_core_distances", _bool),
    "refine": ("refine_iterations", int),
    "refine_flat": ("refine_flat_iterations", int),
    "boundary": ("boundary_quality", float),
    "boundary_alpha": ("boundary_alpha", float),
    "boundary_max_frac": ("boundary_max_frac", float),
    "glue_alpha": ("glue_alpha", float),
    "glue_factor": ("glue_max_factor", int),
    "glue_rows": ("glue_row_budget", int),
    "consensus": ("consensus_draws", int),
    "block_pruning": ("boundary_block_pruning", _bool),
    "knn_backend": ("knn_backend", str),
    "knn_precision": ("knn_precision", str),
    "knn_index": ("knn_index", str),
    "knn_index_threshold": ("knn_index_threshold", int),
    "rpf_trees": ("rpf_trees", int),
    "rpf_leaf_size": ("rpf_leaf_size", int),
    "rpf_rescan": ("rpf_rescan_rounds", int),
    "scan_backend": ("scan_backend", str),
    "fit_sharding": ("fit_sharding", str),
    "tree_backend": ("tree_backend", str),
    "mst_backend": ("mst_backend", str),
    "compile_cache": ("compile_cache", str),
    "predict_backend": ("predict_backend", str),
    "predict_batch": ("predict_max_batch", int),
    "absorb_eps": ("stream_absorb_eps_frac", float),
    "drift_stat": ("stream_drift_stat", str),
    "drift_threshold": ("stream_drift_threshold", float),
    "refit_budget": ("stream_refit_budget", int),
    "stream_reload": ("stream_reload", str),
    "queue_bound": ("serve_queue_bound", int),
    "deadline_ms": ("serve_deadline_ms", float),
    "faults": ("fault_spec", str),
    "circuit_failures": ("circuit_failures", int),
    "circuit_reset": ("circuit_reset_s", float),
    "wal_dir": ("stream_wal_dir", str),
    "snapshot_every": ("stream_snapshot_every", int),
    "maintain": ("stream_maintain", str),
    "maintain_budget": ("maintain_budget_ms", float),
    "maintain_dirty_frac": ("maintain_dirty_max_frac", float),
    "maintain_refresh": ("maintain_refresh_every", int),
    "fleet_replicas": ("fleet_replicas", int),
    "fleet_policy": ("fleet_policy", str),
    "fleet_health_interval": ("fleet_health_interval_s", float),
    "fleet_drain": ("fleet_drain_s", float),
    "tenant_lru": ("tenant_lru_size", int),
    "tenant_quota": ("tenant_quota_rps", float),
    "autoscale": ("fleet_autoscale", _bool),
    "fleet_min": ("fleet_min_replicas", int),
    "fleet_max": ("fleet_max_replicas", int),
    "scale_high_load": ("fleet_scale_high_load", float),
    "scale_low_load": ("fleet_scale_low_load", float),
    "scale_p99": ("fleet_scale_p99_s", float),
    "scale_cooldown": ("fleet_scale_cooldown_s", float),
    "artifact_store": ("fleet_artifact_store", str),
    "fit_workers": ("fit_job_workers", int),
    "fit_queue_bound": ("fit_job_queue_bound", int),
    "fit_quota": ("fit_job_quota_rps", float),
    "heartbeat": ("heartbeat_s", float),
    "watchdog": ("watchdog_s", float),
    "trace_max_events": ("trace_max_events", int),
    "skew_threshold": ("obs_skew_threshold", float),
    "straggler_rounds": ("obs_straggler_rounds", int),
    "trace_rotate_bytes": ("trace_rotate_bytes", int),
    "max_samples": ("max_samples", int),
    "compat_cf": ("compat_cf_int_math", _bool),
}
