from hdbscan_tpu.core.distances import (  # noqa: F401
    METRICS,
    pairwise_distance,
    self_distance_matrix,
)
from hdbscan_tpu.core.knn import (  # noqa: F401
    core_distances,
    core_distances_from_matrix,
    mutual_reachability,
    mutual_reachability_block,
)
from hdbscan_tpu.core.mst import boruvka_mst, mst_edges_with_self_edges  # noqa: F401
from hdbscan_tpu.core.tree import (  # noqa: F401
    CondensedTree,
    build_merge_forest,
    condense_forest,
    extract_clusters,
    flat_labels,
    outlier_scores,
    propagate_tree,
)
