"""Pairwise distance kernels (L1 of the reference's layer map).

TPU-native re-design of the reference's ``distance/`` package
(``distance/DistanceCalculator.java:8-20`` and its five implementations:
``EuclideanDistance.java:27-35``, ``ManhattanDistance.java:27-35``,
``SupremumDistance.java:27-37``, ``CosineSimilarity.java:27-40``,
``PearsonCorrelation.java:27-52``). Instead of a scalar ``computeDistance(double[], double[])``
interface called inside O(n^2) Java loops, every metric here is a *pairwise-matrix*
kernel ``(n, d) x (m, d) -> (n, m)`` so the MXU/VPU sees one large batched op.

All kernels are jit/vmap-compatible and dtype-polymorphic (float32 on TPU,
float64 on host/CPU parity runs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: The metric vocabulary of the reference CLI flag ``dist_function``
#: (``main/Main.java:475-488``).
METRICS = ("euclidean", "manhattan", "supremum", "cosine", "pearson")

DEFAULT_METRIC = "euclidean"  # reference default: main/Main.java:419


#: Broadcast-element budget for the difference-form Euclidean kernel. The
#: dot-product expansion ``|x|^2 + |y|^2 - 2xy`` maps onto the MXU but
#: cancels catastrophically in float32 when points are much closer together
#: than their norms (error ~1e-7 * |x|^2 swamps small d^2). The difference
#: form is exact but materializes/streams (n, m, d) elementwise work on the
#: VPU — cheap for the low-dimensional tile shapes of the tiled scans, too
#: much for large dense blocks (which parity-test in float64 on host anyway).
_DIFF_FORM_BUDGET = 1 << 25


def _cross_f32(x: jax.Array, y: jax.Array) -> jax.Array:
    """x @ y.T at FULL input precision on the MXU.

    TPU matmuls default to bf16 passes, which is a ~0.8% relative error on
    the cross term — at production tile shapes (where the dot form is
    selected) that surfaced as ~1e-2 absolute error on 10-d core distances
    (caught by the Pallas kernel's exact diff-form cross-check, round 2).
    ``Precision.HIGHEST`` keeps the MXU but runs enough passes for full f32;
    the cross matmul is a small share of scan cost next to top-k selection.
    """
    return jax.lax.dot_general(
        x,
        y,
        (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=x.dtype,
    )


def _sq_euclidean(x: jax.Array, y: jax.Array) -> jax.Array:
    """Squared Euclidean distances; picks the accurate or the MXU form by shape."""
    if x.shape[0] * y.shape[0] * x.shape[-1] <= _DIFF_FORM_BUDGET:
        diff = x[:, None, :] - y[None, :, :]
        return jnp.sum(diff * diff, axis=-1)
    x_sq = jnp.sum(x * x, axis=-1)
    y_sq = jnp.sum(y * y, axis=-1)
    cross = _cross_f32(x, y)
    d2 = x_sq[:, None] + y_sq[None, :] - 2.0 * cross
    return jnp.maximum(d2, 0.0)


def euclidean(x: jax.Array, y: jax.Array) -> jax.Array:
    """sqrt(sum (x_i - y_i)^2) — reference ``EuclideanDistance.java:27-35``."""
    return jnp.sqrt(_sq_euclidean(x, y))


def manhattan(x: jax.Array, y: jax.Array) -> jax.Array:
    """sum |x_i - y_i| — reference ``ManhattanDistance.java:27-35``."""
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def supremum(x: jax.Array, y: jax.Array) -> jax.Array:
    """max |x_i - y_i| (Chebyshev) — reference ``SupremumDistance.java:27-37``."""
    return jnp.max(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def cosine(x: jax.Array, y: jax.Array) -> jax.Array:
    """1 - X.Y / (|X||Y|) — reference ``CosineSimilarity.java:27-40``."""
    cross = _cross_f32(x, y)
    nx = jnp.sqrt(jnp.sum(x * x, axis=-1))
    ny = jnp.sqrt(jnp.sum(y * y, axis=-1))
    denom = nx[:, None] * ny[None, :]
    return 1.0 - cross / denom


def pearson(x: jax.Array, y: jax.Array) -> jax.Array:
    """1 - cov(X,Y) / (sigma_X sigma_Y) — reference ``PearsonCorrelation.java:27-52``.

    The reference computes population covariance/stddev over the attribute axis.
    """
    xc = x - jnp.mean(x, axis=-1, keepdims=True)
    yc = y - jnp.mean(y, axis=-1, keepdims=True)
    return cosine(xc, yc)


_METRIC_FNS = {
    "euclidean": euclidean,
    "manhattan": manhattan,
    "supremum": supremum,
    "cosine": cosine,
    "pearson": pearson,
}


def pairwise_distance(x: jax.Array, y: jax.Array, metric: str = DEFAULT_METRIC) -> jax.Array:
    """Full (n, m) distance matrix between row sets ``x`` and ``y``.

    ``metric`` must be static (resolved at trace time) — it selects the kernel,
    mirroring the reference's ``dist_function`` plug-in point
    (``distance/DistanceCalculator.java:8-20``).
    """
    try:
        fn = _METRIC_FNS[metric]
    except KeyError:
        raise ValueError(f"unknown metric {metric!r}; expected one of {METRICS}") from None
    return fn(x, y)


def rowwise_distance_np(a, b, metric: str = DEFAULT_METRIC):
    """Distance between corresponding rows of two host arrays (numpy path).

    Host-side helper for small edge lists (inter-partition edge re-weighting);
    semantics match the device kernels above.
    """
    import numpy as np

    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    if metric == "euclidean":
        return np.sqrt(np.sum((a - b) ** 2, axis=-1))
    if metric == "manhattan":
        return np.sum(np.abs(a - b), axis=-1)
    if metric == "supremum":
        return np.max(np.abs(a - b), axis=-1)
    if metric == "cosine":
        num = np.sum(a * b, axis=-1)
        den = np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1)
        return 1.0 - num / den
    if metric == "pearson":
        ac = a - a.mean(axis=-1, keepdims=True)
        bc = b - b.mean(axis=-1, keepdims=True)
        num = np.sum(ac * bc, axis=-1)
        den = np.linalg.norm(ac, axis=-1) * np.linalg.norm(bc, axis=-1)
        return 1.0 - num / den
    raise ValueError(f"unknown metric {metric!r}; expected one of {METRICS}")


def self_distance_matrix(x: jax.Array, metric: str = DEFAULT_METRIC) -> jax.Array:
    """(n, n) distance matrix of a point block against itself, exact-zero diagonal."""
    d = pairwise_distance(x, x, metric)
    n = x.shape[0]
    return jnp.where(jnp.eye(n, dtype=bool), jnp.zeros((), d.dtype), d)
