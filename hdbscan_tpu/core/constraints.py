"""Must-link / cannot-link constraints (semi-supervised extraction).

Capability parity with the reference's constraint machinery
(``hdbscanstar/Constraint.java:17-23``, ``HDBSCANStar.calculateNumConstraintsSatisfied``
``hdbscanstar/HDBSCANStar.java:738-789``, virtual-child accounting
``hdbscanstar/Cluster.java:145-171``) — advertised in the live help text
(``main/Main.java:590-597``) but never wired into the live driver; here it is
first-class.

Semantics (derived from the reference's per-iteration credit): a cluster is
credited exactly once, at its creation level —

- must-link (a, b): if both points are members of cluster C at C's birth
  (C is an ancestor-or-self of both points' deepest clusters), C earns +2.
- cannot-link (a, b): each side's cluster C earns +1 at birth when the other
  point is NOT a member of C then (different cluster or already noise).
- cannot-link with a noise endpoint: the credit goes to the *virtual child*
  of the cluster the point went noise from (``Cluster.java:145-171``) — kept
  in a separate per-cluster array (the ``vGamma`` column of the tree file),
  matching the reference's separate bookkeeping. The reference counts a
  virtual child only when its owner appears among the "parents of new
  clusters" (``HDBSCANStar.java:744-750``) — i.e. only clusters that
  actually *split* are credited; a cluster that shattered or narrowed away
  never is. A point whose last cluster split necessarily went noise at or
  before the split, so membership in the virtual child reduces to
  ``point_last_cluster == C and has_children[C]``.

The root cluster is pre-credited before the hierarchy loop in the reference
(``HDBSCANStar.java:241-244``, all points labeled 1): must-links earn root +2
each, cannot-links nothing. Root is also a parent of new clusters, so its
virtual child can be credited.

File format (``main/Main.java:590-597``): CSV lines
``<idx_a>,<idx_b>,<ml|cl>``, zero-indexed.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from hdbscan_tpu.core.tree import CondensedTree

MUST_LINK = "ml"
CANNOT_LINK = "cl"


@dataclass(frozen=True)
class Constraint:
    point_a: int
    point_b: int
    kind: str  # "ml" | "cl"

    def __post_init__(self):
        if self.kind not in (MUST_LINK, CANNOT_LINK):
            raise ValueError(f"constraint type must be 'ml' or 'cl', got {self.kind!r}")


def load_constraints(path: str) -> list[Constraint]:
    """Parse the reference's constraint CSV (``a,b,ml`` / ``a,b,cl``)."""
    out = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            parts = [p.strip() for p in line.split(",")]
            if len(parts) != 3:
                raise ValueError(f"{path}:{line_no}: expected 'a,b,ml|cl', got {line!r}")
            out.append(Constraint(int(parts[0]), int(parts[1]), parts[2].lower()))
    return out


def _ancestor_chains(tree: CondensedTree) -> list[set]:
    """chains[c] = set of ancestor-or-self labels of cluster c (root included)."""
    c = tree.n_clusters
    chains: list[set] = [set() for _ in range(c + 1)]
    for label in range(1, c + 1):
        par = int(tree.parent[label])
        chains[label] = {label} | (chains[par] if par > 0 else set())
    return chains


def count_constraints_satisfied(
    tree: CondensedTree, constraints: list[Constraint]
) -> tuple[np.ndarray, np.ndarray]:
    """Per-cluster (num_constraints_satisfied, virtual_child_constraints).

    Feed the first array to ``propagate_tree`` (constraint satisfaction
    dominates stability in EOM competition, ``Cluster.java:114-142``); the
    second is the tree file's vGamma column.
    """
    c = tree.n_clusters
    num = np.zeros(c + 1, np.int64)
    vnum = np.zeros(c + 1, np.int64)
    if not constraints:
        return num, vnum
    chains = _ancestor_chains(tree)
    last = tree.point_last_cluster

    for con in constraints:
        pa, pb = int(con.point_a), int(con.point_b)
        chain_a = chains[int(last[pa])]
        chain_b = chains[int(last[pb])]
        if con.kind == MUST_LINK:
            # Root included: the reference pre-credits cluster 1 before the
            # hierarchy loop (HDBSCANStar.java:241-244) — every must-link
            # earns root +2 while all points are labeled 1.
            for lbl in chain_a & chain_b:
                num[lbl] += 2
        else:
            # Root never appears in a chain difference (it is in every
            # chain), matching the reference: labelA == labelB == 1 at the
            # pre-loop call, so cannot-links earn root nothing.
            for lbl in chain_a - chain_b:
                num[lbl] += 1
            for lbl in chain_b - chain_a:
                num[lbl] += 1
            # Noise endpoints credit the virtual child of the cluster the
            # point went noise from (its deepest cluster) — but only if that
            # cluster split, mirroring the reference's parents-of-new-clusters
            # scoping (HDBSCANStar.java:744-750,765-781).
            for p in (pa, pb):
                lbl = int(last[p])
                if tree.has_children[lbl]:
                    vnum[lbl] += 1
    return num, vnum
