"""Must-link / cannot-link constraints (semi-supervised extraction).

Capability parity with the reference's constraint machinery
(``hdbscanstar/Constraint.java:17-23``, ``HDBSCANStar.calculateNumConstraintsSatisfied``
``hdbscanstar/HDBSCANStar.java:738-789``, virtual-child accounting
``hdbscanstar/Cluster.java:145-171``) — advertised in the live help text
(``main/Main.java:590-597``) but never wired into the live driver; here it is
first-class.

Semantics (derived from the reference's per-iteration credit): a cluster is
credited exactly once, at its creation level —

- must-link (a, b): if both points are members of cluster C at C's birth
  (C is an ancestor-or-self of both points' deepest clusters), C earns +2.
- cannot-link (a, b): each side's cluster C earns +1 at birth when the other
  point is NOT a member of C then (different cluster or already noise).
- cannot-link with a noise endpoint: the credit goes to the *virtual child*
  of the cluster the point went noise from (``Cluster.java:145-171``) — kept
  in a separate per-cluster array (the ``vGamma`` column of the tree file),
  matching the reference's separate bookkeeping. The reference counts a
  virtual child only when its owner appears among the "parents of new
  clusters" (``HDBSCANStar.java:744-750``) — i.e. only clusters that
  actually *split* are credited; a cluster that shattered or narrowed away
  never is. A point whose last cluster split necessarily went noise at or
  before the split, so membership in the virtual child reduces to
  ``point_last_cluster == C and has_children[C]``.

The root cluster is pre-credited before the hierarchy loop in the reference
(``HDBSCANStar.java:241-244``, all points labeled 1): must-links earn root +2
each, cannot-links nothing. Root is also a parent of new clusters, so its
virtual child can be credited.

File format (``main/Main.java:590-597``): CSV lines
``<idx_a>,<idx_b>,<ml|cl>``, zero-indexed.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from hdbscan_tpu.core.tree import CondensedTree

MUST_LINK = "ml"
CANNOT_LINK = "cl"


@dataclass(frozen=True)
class Constraint:
    point_a: int
    point_b: int
    kind: str  # "ml" | "cl"

    def __post_init__(self):
        if self.kind not in (MUST_LINK, CANNOT_LINK):
            raise ValueError(f"constraint type must be 'ml' or 'cl', got {self.kind!r}")


def load_constraints(path: str) -> list[Constraint]:
    """Parse the reference's constraint CSV (``a,b,ml`` / ``a,b,cl``)."""
    out = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            parts = [p.strip() for p in line.split(",")]
            if len(parts) != 3:
                raise ValueError(f"{path}:{line_no}: expected 'a,b,ml|cl', got {line!r}")
            out.append(Constraint(int(parts[0]), int(parts[1]), parts[2].lower()))
    return out


def _lca_vectorized(parent: np.ndarray, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Lowest common ancestor for label-pair arrays via binary lifting.

    ``parent[c] < c`` holds by construction of the condensed tree's labeling
    (children are created after their parent), ``parent[root] <= 0``. Cost:
    an (K, C) ancestor table with K = ceil(log2 max_depth), then O(K) vector
    ops per pair array — millions of constraints resolve in milliseconds.
    """
    c_count = len(parent) - 1
    depth = np.zeros(c_count + 1, np.int64)
    up0 = np.arange(c_count + 1, dtype=np.int64)
    for c in range(2, c_count + 1):
        p = int(parent[c])
        if p > 0:
            depth[c] = depth[p] + 1
            up0[c] = p
    k_levels = max(1, int(depth.max()).bit_length())
    up = np.empty((k_levels, c_count + 1), np.int64)
    up[0] = up0
    for k in range(1, k_levels):
        up[k] = up[k - 1][up[k - 1]]

    a = a.copy()
    b = b.copy()
    # Equalize depths (lift the deeper side by the depth difference, one
    # binary digit per table level).
    diff = depth[a] - depth[b]
    ha = np.maximum(diff, 0)
    hb = np.maximum(-diff, 0)
    for k in range(k_levels):
        bit = 1 << k
        a = np.where(ha & bit != 0, up[k][a], a)
        b = np.where(hb & bit != 0, up[k][b], b)
    # Simultaneous binary descent: keep lifting both while ancestors differ.
    neq = a != b
    for k in range(k_levels - 1, -1, -1):
        lift = neq & (up[k][a] != up[k][b])
        a = np.where(lift, up[k][a], a)
        b = np.where(lift, up[k][b], b)
    return np.where(neq, up[0][a], a)


def count_constraints_satisfied(
    tree: CondensedTree, constraints: list[Constraint]
) -> tuple[np.ndarray, np.ndarray]:
    """Per-cluster (num_constraints_satisfied, virtual_child_constraints).

    Feed the first array to ``propagate_tree`` (constraint satisfaction
    dominates stability in EOM competition, ``Cluster.java:114-142``); the
    second is the tree file's vGamma column.

    Fully vectorized: the per-constraint ancestor-chain walks reduce to LCA
    algebra. A must-link credits every label on chain(a) ∩ chain(b) =
    ancestors-or-self of LCA — +2 placed at the LCA. A cannot-link credits
    chain(a) Δ chain(b) — +1 at each endpoint's deepest cluster, −2 at the
    LCA (root always cancels, matching the reference's pre-loop crediting,
    ``HDBSCANStar.java:241-244``). One bottom-up subtree-sum then turns the
    point credits into per-label chain sums. O(P·log D + C) total instead of
    O(P·D) chain walks.
    """
    c_count = tree.n_clusters
    num = np.zeros(c_count + 1, np.int64)
    vnum = np.zeros(c_count + 1, np.int64)
    if not constraints:
        return num, vnum
    last = tree.point_last_cluster
    pa = np.array([c.point_a for c in constraints], np.int64)
    pb = np.array([c.point_b for c in constraints], np.int64)
    is_ml = np.array([c.kind == MUST_LINK for c in constraints], bool)
    la, lb = last[pa], last[pb]
    lca = _lca_vectorized(tree.parent, la, lb)

    # Credits placed at tree nodes; the subtree-sum below distributes each
    # credit to every ancestor-or-self label.
    credit = np.zeros(c_count + 1, np.int64)
    np.add.at(credit, lca[is_ml], 2)
    cl = ~is_ml
    np.add.at(credit, la[cl], 1)
    np.add.at(credit, lb[cl], 1)
    np.add.at(credit, lca[cl], -2)
    # parent[c] < c, so one descending pass accumulates whole subtrees.
    for c in range(c_count, 1, -1):
        p = int(tree.parent[c])
        if p > 0:
            credit[p] += credit[c]
    num = credit
    num[0] = 0

    # Noise endpoints credit the virtual child of the cluster the point went
    # noise from (its deepest cluster) — but only if that cluster split,
    # mirroring the reference's parents-of-new-clusters scoping
    # (HDBSCANStar.java:744-750,765-781).
    ends = np.concatenate([la[cl], lb[cl]])
    ends = ends[tree.has_children[ends]]
    np.add.at(vnum, ends, 1)
    vnum[0] = 0
    return num, vnum
