"""Reference bug-compat CF math (opt-in, ``HDBSCANParams.compat_cf_int_math``).

The framework defaults to the CORRECT double math everywhere the reference's
live pipeline has integer-division or indexing bugs (SURVEY.md §7
"parity-vs-bug decisions", ``core/bubbles.py`` module docstring). This module
is the other half of that decision: faithful host-side reproductions of the
reference behaviors, for users who need output parity with a reference run
rather than with the paper's formulas. Behaviors reproduced:

- ``CombineStep.computeExtentBubble`` (``mappers/CombineStep.java:46-57``):
  extent is the MEAN of per-dimension sqrt variances, each clamped at zero —
  not the sqrt of the summed variance the correct variant uses
  (``datastructure/ClusterFeatureDataBubbles.java:200-208``).
- ``CombineStep.computeNNDistBubble`` (``CombineStep.java:42-44``): the
  exponent ``(1 / numberOfAttributes)`` is integer division — 0 for d > 1 —
  so ``nnDist == extent``; for d == 1 it degenerates to ``extent / n``.
- ``CombineStep.call``'s ``n₁ + 1`` count merge (``CombineStep.java:28``):
  under a left fold over singleton CFs (one point at a time, the shape the
  live pipeline feeds it) the count comes out CORRECT — n only undercounts
  when two already-merged partials meet, which in the reference depends on
  Spark's nondeterministic combine tree. Byte-faithful reproduction of a
  nondeterministic quantity is ill-defined; this module fixes the merge
  order to the left fold, the one deterministic reading.
- ``HdbscanDataBubbles.calculateCoreDistancesBubbles``
  (``HdbscanDataBubbles.java:75-146``): exponent collapse (``1 / dims`` and
  the integer quotients ``numNeighbors / nB``, ``aux / nB``), the
  ``indexBubbles`` buffer that is shared across the point loop and only
  overwritten at insertion positions (never shifted with ``kNNDistances``,
  so it carries stale neighbor ids), and the covering walk that indexes
  bubbles by the loop COUNTER ``i`` instead of the found neighbor ``index``
  (``HdbscanDataBubbles.java:136-142``).

Everything here is deliberately host-side NumPy: bubble counts are sample
sized (hundreds), the control flow is the point of the exercise, and keeping
it off-device means zero cost to the default path.
"""

from __future__ import annotations

import numpy as np

__all__ = ["combinestep_bubble_stats", "reference_bubble_core_distances"]

#: Java's Double.MAX_VALUE — the reference's "unset" k-NN slot sentinel
#: (``HdbscanDataBubbles.java:94``). Not inf: a real distance can equal it in
#: principle, and faithful means faithful.
_JAVA_DOUBLE_MAX = np.finfo(np.float64).max


def combinestep_bubble_stats(
    points: np.ndarray,
    assign: np.ndarray,
    num_bubbles: int,
    weights: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """CF statistics with ``CombineStep``'s live math (see module docstring).

    Same contract as :func:`hdbscan_tpu.core.bubbles.bubble_stats` (host
    arrays out): points with ``assign >= num_bubbles`` are dropped (padding),
    empty bubbles get n = 0 / rep = 0. ``weights`` folds duplicate
    multiplicities into the sums (n then counts members, the left-fold
    reading of the ``n₁+1`` merge over one CF per member).
    """
    points = np.asarray(points, np.float64)
    assign = np.asarray(assign)
    d = points.shape[1]
    keep = assign < num_bubbles
    pts, asg = points[keep], assign[keep]
    w = None if weights is None else np.asarray(weights, np.float64)[keep]
    ls = np.zeros((num_bubbles, d))
    ss = np.zeros((num_bubbles, d))
    wcol = np.ones(len(pts)) if w is None else w
    np.add.at(ls, asg, pts * wcol[:, None])
    np.add.at(ss, asg, pts * pts * wcol[:, None])
    n = np.bincount(asg, weights=wcol, minlength=num_bubbles).astype(np.float64)

    n_safe = np.maximum(n, 1.0)
    rep = ls / n_safe[:, None]
    # computeExtentBubble (CombineStep.java:46-57): per-dim sqrt, negative
    # variance terms skipped, MEAN over dims (``extent / ls.length``).
    var = (2.0 * n[:, None] * ss - 2.0 * ls * ls) / np.maximum(
        n * (n - 1.0), 1.0
    )[:, None]
    extent = np.sqrt(np.maximum(var, 0.0)).sum(axis=1) / d
    extent = np.where(n > 1, extent, 0.0)
    # computeNNDistBubble (CombineStep.java:42-44): Math.pow(1/n, 1/d) with an
    # integer-division exponent — 0 for d > 1 (nnDist = extent), 1 for d == 1.
    nn_dist = extent if d > 1 else extent / n_safe
    return rep, extent, nn_dist, n


def reference_bubble_core_distances(
    dist: np.ndarray,
    n_b: np.ndarray,
    extent: np.ndarray,
    min_pts: int,
    dims: int = 2,
) -> np.ndarray:
    """``calculateCoreDistancesBubbles`` exactly as the reference executes it
    (``HdbscanDataBubbles.java:75-146``), stale buffers and all.

    Args:
      dist: (m, m) bubble-corrected distance matrix (the walk's
        ``distanceBubbles(...)`` values — precomputed; the reference computes
        them inline, same numbers).
      n_b: (m,) integer member counts.
      extent: (m,) bubble extents (``eB``).
      min_pts: the reference's ``k``.
      dims: point dimensionality — only d == 1 changes anything (the integer
        exponent ``1 // d`` is 1 there and the integer quotients survive;
        for every d > 1 it is 0 and ``pow(x, 0) == 1`` erases them).

    Returns (m,) core distances. Raises ``IndexError`` exactly where the Java
    would throw ``ArrayIndexOutOfBoundsException`` (covering walk running off
    the k-1 slot buffer — possible when total membership is short of
    ``min_pts - 1``); callers guard subset sizes the same way the reference's
    driver does.
    """
    m = dist.shape[0]
    n_b = np.asarray(n_b, np.int64)
    num_neighbors = min_pts - 1
    core = np.zeros(m)
    if min_pts == 1:
        return core
    # Shared across points — NOT reinitialized per point (the reference bug).
    index_bubbles = np.zeros(num_neighbors, np.int64)
    for point in range(m):
        knn = np.full(num_neighbors, _JAVA_DOUBLE_MAX)
        for neighbor in range(m):
            if neighbor == point:
                continue
            distance = dist[point, neighbor]
            pos = num_neighbors
            while pos >= 1 and distance < knn[pos - 1]:
                pos -= 1
            if pos < num_neighbors:
                knn[pos + 1 :] = knn[pos:-1]  # kNNDistances shifts...
                knn[pos] = distance
                index_bubbles[pos] = neighbor  # ...indexBubbles does not
        if n_b[point] >= num_neighbors:
            # Math.pow(numNeighbors / nB, 1 / d): integer exponent 0 -> 1.0
            # regardless of the (integer) quotient — pow(x, 0) == 1 in Java.
            # At d == 1 the exponent is 1 and the integer quotient survives.
            if dims == 1:
                core[point] = float(num_neighbors // n_b[point]) * extent[point]
            else:
                core[point] = extent[point]
        else:
            n_x = int(n_b[point])
            i = 0
            while n_x < num_neighbors:
                n_x += n_b[index_bubbles[i]]  # IndexError == Java's AIOOBE
                i += 1
            s = int(n_b[point])
            aux = 0
            for j in range(i):
                # The reference compares against dist(indexBubbles[j], i) —
                # ``i`` is the loop COUNTER, used as a bubble id (the
                # i-vs-index bug, HdbscanDataBubbles.java:136-142).
                distance_c = dist[index_bubbles[j], i]
                if s < num_neighbors and knn[j] < distance_c:
                    aux = num_neighbors - s
                s += n_b[index_bubbles[j]]
            # kNNDistances[i] + Math.pow(aux / nB[i], 1 / d) * eB[i]: counter
            # ``i`` again (both as slot and bubble id), exponent 0 -> + eB[i]
            # (at d == 1 the integer quotient aux // nB[i] survives).
            if dims == 1:
                core[point] = knn[i] + float(aux // n_b[i]) * extent[i]
            else:
                core[point] = knn[i] + extent[i]
    return core
