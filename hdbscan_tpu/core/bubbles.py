"""Data-bubble summarization (L4) — CF statistics and bubble-corrected HDBSCAN*.

TPU-native re-design of the reference's summarization layer:

- CF-vector math (``datastructure/ClusterFeatureDataBubbles.java:223-247``:
  ``calculateRep``/``calculateExtent``/``calculateNndist``) as segment ops over
  the whole point block — one ``segment_sum`` per statistic instead of a Java
  merge loop per bubble pair (``mappers/CombineStep.java:18-40``).
- Bubble-corrected distance (``databubbles/HdbscanDataBubbles.distanceBubbles``,
  ``HdbscanDataBubbles.java:592-600``) as a fused matrix op.
- Bubble core distances (``HdbscanDataBubbles.calculateCoreDistancesBubbles``,
  ``HdbscanDataBubbles.java:75-146``) as a sorted-cumsum vector program.
- Bubble MST / condensed tree / flat extraction reuse the L3 kernels
  (``hdbscan_tpu.core.mst`` / ``hdbscan_tpu.core.tree``) with member weights.
- Noise-bubble reassignment + inter-cluster edge harvest
  (``HdbscanDataBubbles.java:485-527``).

Parity decisions (SURVEY.md §7): we use the *correct* double math everywhere the
reference has integer-division bugs —

- ``CombineStep.computeNNDistBubble`` computes ``(1/numberOfAttributes)`` in int
  arithmetic (``CombineStep.java:42-44``), collapsing the exponent to 0 so
  ``nnDist == extent`` for d > 1. We compute ``(1/n)^(1/d) * extent`` in floats
  (matching ``ClusterFeatureDataBubbles.calculateNndist``, the correct variant).
- ``CombineStep.call`` merges counts as ``n1 + 1`` (``CombineStep.java:28``);
  segment-sum gives the correct ``sum(n)`` by construction (matching
  ``partition/reducers/UpdateBubblesReducer.java:23-37``).
- ``calculateCoreDistancesBubbles`` collapses ``(numNeighbors/nB)`` and
  ``(1/dims)`` the same way (``HdbscanDataBubbles.java:122,142``) and indexes
  the extrapolation bubble inconsistently (``i`` vs ``index``,
  ``HdbscanDataBubbles.java:136-142``); we implement the paper formula with
  float exponents and the k-covering neighbor bubble.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from hdbscan_tpu.core.distances import pairwise_distance
from hdbscan_tpu.core.knn import mutual_reachability

__all__ = [
    "bubble_stats",
    "bubble_distance_matrix",
    "bubble_core_distances",
    "bubble_mutual_reachability",
    "reassign_noise_bubbles",
    "inter_cluster_edge_mask",
]


@partial(jax.jit, static_argnames=("num_bubbles",))
def bubble_stats_weighted(
    points: jax.Array, assign: jax.Array, weights: jax.Array, num_bubbles: int
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """:func:`bubble_stats` over WEIGHTED points (deduplicated rows carry
    their duplicate multiplicity): LS/SS/n become weighted segment sums, so a
    weighted point behaves exactly like that many coincident rows. Same
    padding/empty-bubble contract as :func:`bubble_stats` (which delegates
    here with unit weights — one copy of the CF formulas)."""
    d = points.shape[-1]
    dt = points.dtype
    w = weights.astype(dt)
    ls = jax.ops.segment_sum(points * w[:, None], assign, num_segments=num_bubbles)
    ss = jax.ops.segment_sum(
        points * points * w[:, None], assign, num_segments=num_bubbles
    )
    n = jax.ops.segment_sum(w, assign, num_segments=num_bubbles)
    n_safe = jnp.maximum(n, 1.0)
    rep = ls / n_safe[:, None]
    var = (2.0 * n[:, None] * ss - 2.0 * ls * ls) / jnp.maximum(n * (n - 1.0), 1.0)[:, None]
    extent = jnp.sqrt(jnp.maximum(jnp.sum(var, axis=-1), 0.0))
    extent = jnp.where(n > 1, extent, jnp.zeros((), dt))
    nn_dist = jnp.power(1.0 / n_safe, 1.0 / d) * extent
    return rep, extent, nn_dist, n


@partial(jax.jit, static_argnames=("num_bubbles",))
def bubble_stats(
    points: jax.Array, assign: jax.Array, num_bubbles: int
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Cluster-feature statistics per bubble via segment sums.

    Args:
      points: (n, d) point block.
      assign: (n,) int32 bubble id per point (nearest-sample assignment); ids
        must be < num_bubbles. Points with id >= num_bubbles (e.g. padding
        rows assigned ``num_bubbles``) are dropped by the segment ops.
      num_bubbles: static bubble count.

    Returns:
      (rep, extent, nn_dist, n) with rep (m, d); extent/nn_dist/n (m,).
      Statistics follow ``ClusterFeatureDataBubbles.java:223-247``:
      ``rep = LS/n``; ``extent = sqrt(sum_dims (2 n SS - 2 LS^2) / (n (n-1)))``;
      ``nnDist = (1/n)^(1/d) * extent``. Singleton bubbles get extent = nnDist
      = 0 (the reference's singleton CFs start that way,
      ``mappers/FirstStep.java:92-101``). Empty bubbles get n = 0, rep = 0.
    """
    return bubble_stats_weighted(
        points, assign, jnp.ones(points.shape[0], points.dtype), num_bubbles
    )


def bubble_distance_matrix(
    rep: jax.Array,
    extent: jax.Array,
    nn_dist: jax.Array,
    metric: str = "euclidean",
) -> jax.Array:
    """(m, m) bubble-corrected distance matrix, exact-zero diagonal.

    ``distanceBubbles`` (``HdbscanDataBubbles.java:592-600``): for
    non-overlapping bubbles the rep distance is shrunk by both extents and
    re-expanded by both expected nearest-neighbor distances; overlapping
    bubbles collapse to ``max(nnDist_B, nnDist_C)``.
    """
    d = pairwise_distance(rep, rep, metric)
    e_sum = extent[:, None] + extent[None, :]
    corrected = jnp.where(
        d - e_sum >= 0,
        d - e_sum + nn_dist[:, None] + nn_dist[None, :],
        jnp.maximum(nn_dist[:, None], nn_dist[None, :]),
    )
    m = rep.shape[0]
    return jnp.where(jnp.eye(m, dtype=bool), jnp.zeros((), d.dtype), corrected)


@partial(jax.jit, static_argnames=("min_pts", "d"))
def bubble_core_distances(
    dist: jax.Array,
    n_b: jax.Array,
    extent: jax.Array,
    min_pts: int,
    d: int,
    valid: jax.Array | None = None,
) -> jax.Array:
    """Expected-neighbor core distance per bubble.

    Re-design of ``calculateCoreDistancesBubbles``
    (``HdbscanDataBubbles.java:75-146``), with the paper semantics and float
    math (see module docstring). For bubble B with k' = minPts - 1 needed
    neighbors:

    - if ``n_B >= k'``: the k'-th neighbor is expected inside B, so
      ``core = (k'/n_B)^(1/d) * e_B``;
    - else walk neighbor bubbles in corrected-distance order, accumulating
      member counts until k' is covered by bubble C; the remainder ``aux``
      of the k' neighbors falls in C, so
      ``core = dist(B, C) + (aux/n_C)^(1/d) * e_C``.

    Args:
      dist: (m, m) bubble-corrected distance matrix (zero diagonal).
      n_b: (m,) member counts (float). Padding/empty bubbles must have
        n_b = 0 and be masked via ``valid``.
      extent: (m,) bubble extents.
      min_pts: the reference's ``k`` (``minPts``); ``min_pts == 1`` -> zeros.
      d: point dimensionality (static).
      valid: optional (m,) mask for padded blocks; invalid bubbles get +inf
        core distance and are excluded as neighbors.
    """
    m = dist.shape[0]
    dt = dist.dtype
    inf = jnp.array(jnp.inf, dt)
    if min_pts <= 1:
        core = jnp.zeros((m,), dt)
        if valid is not None:
            core = jnp.where(valid, core, inf)
        return core
    k = jnp.asarray(min_pts - 1, dt)

    ok = n_b > 0 if valid is None else (valid & (n_b > 0))
    knn_dist = jnp.where(ok[None, :] & ok[:, None], dist, inf)
    knn_dist = jnp.where(jnp.eye(m, dtype=bool), inf, knn_dist)

    # The covering walk needs at most k' = minPts - 1 neighbor bubbles (every
    # valid bubble holds >= 1 member), so a bounded top_k replaces the full
    # O(m^2 log m) row sort — the compile- and runtime-heavy op at large m.
    kk = int(min(m, min_pts))
    neg_d, order = jax.lax.top_k(-knn_dist, kk)
    sorted_d = -neg_d
    nb_sorted = jnp.where(jnp.isfinite(sorted_d), n_b[order], 0.0)
    cover = n_b[:, None] + jnp.cumsum(nb_sorted, axis=1)

    # Self-contained case: k' neighbors expected inside the bubble itself.
    inner = jnp.power(k / jnp.maximum(n_b, 1.0), 1.0 / d) * extent

    # Covering-neighbor case: first sorted position where cover >= k'.
    reached = cover >= k
    j = jnp.argmax(reached, axis=1).astype(jnp.int32)  # first True (0 if none)
    any_reached = jnp.any(reached, axis=1)
    last = jnp.take_along_axis(order, j[:, None], axis=1)[:, 0]
    d_last = jnp.take_along_axis(sorted_d, j[:, None], axis=1)[:, 0]
    cover_before = jnp.where(
        j > 0,
        jnp.take_along_axis(cover, jnp.maximum(j - 1, 0)[:, None], axis=1)[:, 0],
        n_b,
    )
    aux = jnp.maximum(k - cover_before, 0.0)
    outer = d_last + jnp.power(aux / jnp.maximum(n_b[last], 1.0), 1.0 / d) * extent[last]
    # Not enough members anywhere (tiny subset): fall back to the farthest
    # finite neighbor distance (degenerate, mirrors exact k > n clamping).
    fallback = jnp.max(jnp.where(jnp.isfinite(sorted_d), sorted_d, 0.0), axis=1)
    outer = jnp.where(any_reached, outer, fallback)

    core = jnp.where(n_b >= k, inner, outer)
    core = jnp.where(ok, core, inf)
    return core


#: MRD over bubble-corrected distances (``HdbscanDataBubbles.java:209-219``) —
#: the same max-chain as the exact path, applied to corrected distances.
bubble_mutual_reachability = mutual_reachability


def reassign_noise_bubbles(
    dist: jax.Array, labels: jax.Array, valid: jax.Array | None = None
) -> jax.Array:
    """Assign each noise bubble the flat label of its nearest non-noise bubble.

    Mirrors ``HdbscanDataBubbles.java:485-502`` (single pass: only originally
    non-noise bubbles donate labels — fixed vs the reference's in-place update,
    which lets an already-reassigned noise bubble donate depending on scan
    order). If every bubble is noise, labels are returned unchanged.
    """
    m = dist.shape[0]
    inf = jnp.array(jnp.inf, dist.dtype)
    donor = labels != 0
    if valid is not None:
        donor = donor & valid
    masked = jnp.where(donor[None, :], dist, inf)
    masked = jnp.where(jnp.eye(m, dtype=bool), inf, masked)
    nearest = jnp.argmin(masked, axis=1)
    has_donor = jnp.any(donor)
    new = jnp.where((labels == 0) & has_donor, labels[nearest], labels)
    return new


def inter_cluster_edge_mask(u: jax.Array, v: jax.Array, labels: jax.Array) -> jax.Array:
    """Mask of MST edges crossing flat-cluster boundaries
    (``HdbscanDataBubbles.findInterClusterEdges``, ``HdbscanDataBubbles.java:506-527``)."""
    return labels[u] != labels[v]
