"""Duplicate-point collapsing — weighted exact clustering over unique points.

Real datasets on integer/lattice grids carry heavy duplication (the bundled
Skin set: 245,057 rows, 51,433 unique points, 4.8x). A duplicate group is a
zero-extent data bubble: collapsing it to one point with a member count
preserves the exact HDBSCAN* semantics —

- core distance: the (minPts-1)-th smallest distance over the row MULTISET
  (self included — the reference's kNN-buffer semantics, ``HDBSCANStar.java:
  71-106``, where a duplicate contributes a 0 distance per copy) equals the
  first unique-neighbor distance at which the cumulative member count reaches
  minPts - 1; it is 0 iff the group itself holds >= minPts - 1 members;
- mutual-reachability MST: within-group edges all carry weight core_i (d=0),
  so the group contracts to one merge-forest node — exactly what the
  member-weighted merge forest does with ``point_weights=counts`` and
  ``self_levels=core`` (``core/tree.py``);
- flat labels / GLOSH broadcast back over the inverse index (duplicates share
  label and score by symmetry).

The O(n^2 d) device scans then run at unique-count scale: ~23x less work on
the north-star dataset.
"""

from __future__ import annotations

import numpy as np


def deduplicate(data: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(unique_rows, counts, inverse): ``data == unique_rows[inverse]``."""
    uniq, inverse, counts = np.unique(
        np.ascontiguousarray(data), axis=0, return_inverse=True, return_counts=True
    )
    return uniq, counts.astype(np.float64), inverse.astype(np.int64)


def weighted_core_distances(
    knn_d: np.ndarray,
    knn_i: np.ndarray,
    counts: np.ndarray,
    min_pts: int,
) -> np.ndarray:
    """Core distance per unique point from its k nearest UNIQUE neighbors.

    ``knn_d``/``knn_i``: (m, k) ascending distances + ids over unique points,
    self included at distance 0 (``ops.tiled.knn_core_distances`` with
    ``return_indices=True``); k >= minPts guarantees coverage because every
    unique neighbor contributes >= 1 member. ``counts``: members per unique
    point. Matches the multiset semantics above.
    """
    if min_pts <= 1:
        return np.zeros(len(counts), np.float64)
    m, k = knn_d.shape
    need = min_pts - 1  # reference semantics: (minPts-1)-th smallest, self incl.
    if k < need:
        raise ValueError(f"need k >= min_pts - 1 ({need}), got {k}")
    # Unique points cannot duplicate each other, so the cumulative member
    # count over the ascending neighbor list (self first at distance 0) is
    # counts[knn_i] summed along the row. Padding slots (id -1 / +inf
    # distance, present when k exceeds the unique-point count) contribute
    # nothing — unmasked they would wrap to counts[-1] and fake coverage.
    valid_nb = (knn_i >= 0) & np.isfinite(knn_d)
    neigh_counts = np.where(valid_nb, counts[np.clip(knn_i, 0, len(counts) - 1)], 0.0)
    cum = np.cumsum(neigh_counts, axis=1)
    reached = cum >= need
    # First column where the cumulative member count covers minPts.
    j = np.argmax(reached, axis=1)
    core = knn_d[np.arange(m), j]
    # Rows never reaching minPts (tiny datasets): clamp to the farthest
    # FINITE distance, matching the full-row kernel's min(minPts-1, n) clamp
    # (the trailing knn columns are +inf padding when k exceeds the number of
    # valid unique points).
    none = ~reached.any(axis=1)
    if none.any():
        finite = np.where(np.isfinite(knn_d[none]), knn_d[none], -np.inf)
        core[none] = np.max(finite, axis=1)
    return core


def global_weighted_core_distances(
    data: np.ndarray,
    counts: np.ndarray,
    min_pts: int,
    metric: str,
    row_tile: int = 1024,
    col_tile: int = 8192,
    dtype=np.float32,
    *,
    mesh=None,
    trace=None,
    fit_sharding: str = "replicated",
) -> np.ndarray:
    """One tiled scan + multiset cumsum: the weighted global core distances.

    Shared by the exact and MR dedup paths so the k-selection rule and the
    coverage invariant live in one place. Under ``fit_sharding="sharded"``
    the (m, k) neighbor scan rides the row-sharded ring engine (queries,
    panels and per-point lists all shard with their rows; bitwise the host
    scan), so the dedup tier honors the residency contract too — only the
    (m, k) host fetch feeding the multiset cumsum leaves the devices.
    """
    from hdbscan_tpu.parallel.shard import resolve_fit_sharding

    k = max(min_pts, 2)
    if resolve_fit_sharding(fit_sharding, mesh) == "sharded":
        from hdbscan_tpu.parallel.ring import ring_knn_core_distances

        _, knn_d, knn_i = ring_knn_core_distances(
            data,
            min_pts,
            metric,
            k=k,
            row_tile=row_tile,
            col_tile=col_tile,
            dtype=dtype,
            return_indices=True,
            mesh=mesh,
            trace=trace,
        )
    else:
        from hdbscan_tpu.ops.tiled import knn_core_distances

        _, knn_d, knn_i = knn_core_distances(
            data,
            min_pts,
            metric,
            k=k,
            row_tile=row_tile,
            col_tile=col_tile,
            dtype=dtype,
            return_indices=True,
            trace=trace,
        )
    return weighted_core_distances(knn_d, knn_i, counts, min_pts)


def expand_heavy_groups(
    u: np.ndarray,
    v: np.ndarray,
    w: np.ndarray,
    core: np.ndarray,
    counts: np.ndarray,
    min_cluster_size: int | float,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Expand duplicate groups heavy enough to pass minClusterSize back into
    unit leaves before tree extraction.

    An atomic weighted vertex of count g >= minClusterSize diverges from the
    full-row tree exactly when its internal merge level (its core distance)
    TIES with external edge weights: full-row tie contraction dissolves the
    group into g singleton children (none big), while the weighted vertex
    stays one big child and forces a split. Expanding such vertices into g
    unit leaves joined by (g-1) edges at weight core (the literal full-row
    MST edges between coincident rows) restores exact row-level semantics;
    light groups (g < minClusterSize) are provably equivalent unexpanded.

    Host-side only — device scans stay at unique-point scale. Returns
    (u2, v2, w2, core2, weights2); appended pseudo-leaves alias their base
    vertex (same coordinates), so row results broadcast from the base.
    """
    counts = np.asarray(counts, np.float64)
    heavy = np.nonzero((counts >= min_cluster_size) & (counts >= 2))[0]
    if len(heavy) == 0:
        return u, v, w, core, counts
    n = len(counts)
    extras = (counts[heavy] - 1).astype(np.int64)
    total = int(extras.sum())
    base = np.repeat(heavy, extras)  # base vertex per pseudo-leaf
    new_ids = n + np.arange(total)
    u2 = np.concatenate([u, base])
    v2 = np.concatenate([v, new_ids])
    w2 = np.concatenate([w, core[base]])
    core2 = np.concatenate([core, core[base]])
    weights2 = counts.copy()
    weights2[heavy] = 1.0
    weights2 = np.concatenate([weights2, np.ones(total)])
    return u2, v2, w2, core2, weights2
