"""Minimum spanning tree over mutual-reachability distances — dense Borůvka.

TPU-native replacement for the reference's sequential Prim construction
(``hdbscanstar/HDBSCANStar.constructMST``, ``hdbscanstar/HDBSCANStar.java:124-205``)
and its string-based Kruskal merge (``partition/reducers/UnionFindReducer.java:20-70``).
Prim is inherently sequential (one attached vertex per step); Borůvka's round —
"every component finds its minimum outgoing edge, all components hook at once" —
is a handful of masked row-argmin + segment-min ops, which XLA maps onto the
VPU/MXU, and converges in <= ceil(log2 n) rounds. The whole MST is a single
``jit``-compiled, ``vmap``-compatible fixed-shape program, so many per-partition
MSTs (the ``mapPartitionsToPair(new FirstStep(...))`` analog,
``main/Main.java:166-169``) batch into one device launch.

Determinism: ties are broken by the canonical undirected edge key
``(weight, min(u, v), max(u, v))``. Per-row ``argmin`` (first index) already
realizes this order within a row; the per-component selection does an explicit
two-stage lexicographic segment-min. Consistent total order on edges guarantees
hooking cycles have length exactly 2, which the root-election step resolves —
without it, equal-weight edges can form longer hook cycles and pointer jumping
diverges. The reference has no deterministic contract here (its quicksort at
``hdbscanstar/UndirectedGraph.java:93-124`` is tie-unstable); we make ours
reproducible.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["boruvka_mst", "mst_edges_with_self_edges"]


def _pointer_jump(parent: jax.Array, rounds: int) -> jax.Array:
    def body(_, p):
        return p[p]

    return jax.lax.fori_loop(0, rounds, body, parent)


@partial(jax.jit, static_argnames=("num_rounds",))
def _boruvka(weights: jax.Array, num_valid: jax.Array, num_rounds: int):
    n = weights.shape[0]
    dt = weights.dtype
    inf = jnp.array(jnp.inf, dt)
    idx = jnp.arange(n, dtype=jnp.int32)
    valid = idx < num_valid

    w = jnp.where(valid[:, None] & valid[None, :], weights, inf)
    w = jnp.where(jnp.eye(n, dtype=bool), inf, w)

    # Hook chains can be as long as the component count, so pointer jumping
    # needs the same log2 bound as the outer loop.
    jump_rounds = num_rounds

    def round_body(_, state):
        labels, eu, ev, ew, count = state

        masked = jnp.where(labels[:, None] == labels[None, :], inf, w)
        # Per-vertex minimum outgoing edge; first-index argmin == canonical
        # (weight, min(u,v), max(u,v)) order within a row.
        j_min = jnp.argmin(masked, axis=1).astype(jnp.int32)
        w_min = jnp.take_along_axis(masked, j_min[:, None], axis=1)[:, 0]

        # Per-component lexicographic min over candidate vertices.
        comp_w = jax.ops.segment_min(w_min, labels, num_segments=n)
        cand = jnp.isfinite(w_min) & (w_min == comp_w[labels])
        lo = jnp.minimum(idx, j_min)
        hi = jnp.maximum(idx, j_min)
        sent = jnp.int32(n)
        comp_lo = jax.ops.segment_min(jnp.where(cand, lo, sent), labels, num_segments=n)
        cand = cand & (lo == comp_lo[labels])
        comp_hi = jax.ops.segment_min(jnp.where(cand, hi, sent), labels, num_segments=n)
        cand = cand & (hi == comp_hi[labels])
        v_sel = jax.ops.segment_min(jnp.where(cand, idx, sent), labels, num_segments=n)

        has_edge = v_sel < sent
        v_safe = jnp.clip(v_sel, 0, n - 1)
        edge_u = v_safe
        edge_v = j_min[v_safe]
        edge_w = w_min[v_safe]
        target = labels[edge_v]

        comp_ids = idx
        parent = jnp.where(has_edge, target, comp_ids)
        # Resolve 2-cycles (the same undirected edge picked from both sides):
        # the smaller root survives; only the hooked side emits the edge.
        two_cycle = (parent != comp_ids) & (parent[parent] == comp_ids)
        parent = jnp.where(two_cycle & (comp_ids < parent), comp_ids, parent)
        added = has_edge & (parent != comp_ids)

        parent = _pointer_jump(parent, jump_rounds)
        labels = parent[labels]

        pos = count + jnp.cumsum(added, dtype=jnp.int32) - 1
        pos = jnp.where(added, pos, n)  # out-of-range -> dropped
        eu = eu.at[pos].set(edge_u, mode="drop")
        ev = ev.at[pos].set(edge_v, mode="drop")
        ew = ew.at[pos].set(edge_w, mode="drop")
        count = count + jnp.sum(added, dtype=jnp.int32)
        return labels, eu, ev, ew, count

    m = max(n - 1, 1)
    init = (
        idx,
        jnp.zeros((m,), jnp.int32),
        jnp.zeros((m,), jnp.int32),
        jnp.full((m,), jnp.inf, dt),
        jnp.int32(0),
    )
    labels, eu, ev, ew, count = jax.lax.fori_loop(0, num_rounds, round_body, init)
    mask = jnp.arange(m, dtype=jnp.int32) < count
    return eu, ev, ew, mask, labels


def boruvka_mst(weights: jax.Array, num_valid: jax.Array | int | None = None):
    """MST of a dense symmetric weight matrix (mutual reachability distances).

    Args:
      weights: (n, n) symmetric matrix. The diagonal is ignored.
      num_valid: number of valid leading vertices (for padded blocks); vertices
        ``>= num_valid`` are isolated and produce no edges. Defaults to n.

    Returns:
      ``(u, v, w, mask, labels)`` with u/v/w of shape (n-1,): edge endpoints
      (local indices), weights, a validity mask (count = num_valid - 1 for a
      connected block), and the final component label per vertex.
      jit-compiled; vmap over a leading batch axis works (pass per-block
      ``num_valid`` as an array).
    """
    n = weights.shape[0]
    if num_valid is None:
        num_valid = n
    num_valid = jnp.asarray(num_valid, jnp.int32)
    num_rounds = max(1, math.ceil(math.log2(n)) + 1) if n > 1 else 1
    return _boruvka(weights, num_valid, num_rounds)


def mst_edges_with_self_edges(u, v, w, mask, core, valid=None):
    """Append per-point self edges weighted by core distance.

    Mirrors ``hdbscanstar/HDBSCANStar.java:196-203``: the hierarchy uses the
    self edge (i, i, core_i) to record the level at which point i becomes
    noise. Device helper (jnp arrays, traceable under jit); returns
    concatenated (u, v, w, mask).
    """
    n = core.shape[0]
    idx = jnp.arange(n, dtype=u.dtype)
    self_mask = jnp.ones((n,), bool) if valid is None else valid
    uu = jnp.concatenate([u, idx])
    vv = jnp.concatenate([v, idx])
    ww = jnp.concatenate([w, core.astype(w.dtype)])
    mm = jnp.concatenate([mask, self_mask])
    return uu, vv, ww, mm
