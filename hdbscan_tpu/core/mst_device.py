"""Device-resident MST -> merge-forest engine (``mst_backend=device``).

ROADMAP item 2: after the device Borůvka scans, the seed pipeline
round-tripped through host NumPy/C twice per fit — ``contract_min_edges``
glued every Borůvka round and ``core/tree.py::build_merge_forest`` walked
the sorted edge list one union at a time. This module keeps both stages on
device (cuSLINK arXiv 2306.16354 / PANDORA arXiv 2401.06089 direction:
segment-min contraction rounds + pointer-doubling union-find, parallel
forest reconstruction) so an exact fit performs exactly ONE host sync —
the final fetch of the forest/result arrays (trace event ``host_sync``).

Engine shape — one device scan plus vectorized host reconstruction:

- Kruskal union order is inherently sequential, so the union-find runs as
  a ``lax.scan`` over the lexsorted edge list. XLA handles the carried
  parent array well ONLY in a narrow shape: carry ``par`` alone, resolve
  both roots in one fused ``while_loop`` (``_find2``), path-compress at
  the *xs* indices, and make exactly one write at a while-derived index
  (``par[rb] = ra``). Every richer variant that was tried — union by
  size, carried top/size/count arrays (even with purely xs-derived
  indices and a single extra array), select-derived winner indices — hits
  a copy-inserting alias-analysis path and regresses the 245k-edge scan
  from 0.2 s to 19 s..timeout. The scan therefore emits only the union
  event stream ``(ra, rb)`` (a step is a merge iff ``ra != rb``).
- EVERYTHING else reconstructs from that stream with O(m log m)
  vectorized numpy on host AFTER the single fetch (host numpy gathers run
  ~10x faster than XLA CPU's scattered gathers and pay no per-shape
  compile; none of it is per-edge Python):

  * merge-tree child tops ``(ta, tb)`` — a 2t-row (value, time) sweep:
    per merge one fused query+publish row and one query row, one argsort
    on the packed key, then a segmented running-max over event payloads.
  * absorption flags by exact weight equality (see eligibility below),
    owner (= nearest non-absorbed ancestor) via pointer doubling.
  * one global Euler tour over the merge forest (roots chained in
    ascending order, so a single distance-to-terminal pointer-doubling
    list ranking orders every slot), giving DFS preorder — kids of one
    owner sort by their entry rank, which reproduces the host builder's
    a-side-before-b-side splice order — and subtree leaf intervals.
  * sizes as leaf-interval prefix-sum differences over the tour order,
    and roots via pointer-jumped flattening of the element parent map.

Survivor convention matches the host reference exactly: ``parent[rb] =
ra`` with no union-by-size (``core/tree.py::build_merge_forest``), so the
event stream replays the same unions the host loop performs.

Eligibility contract (``supports_inputs``): the host builder absorbs a
child node into its parent when their weights are *near*-tied
(``_tied(anchor, w, 1e-9)`` against the child's tie-group anchor). On
device that chained-anchor recursion is replaced by exact equality, which
is equivalent IFF the edge pool contains no near-tied-but-unequal weight
pair: then every tie group is exactly equal, group anchors equal group
weights, and ``absorb(parent, child) <=> w_child == w_parent``. The
adjacent-pair check on the sorted weights certifies this (for sorted
a <= b <= c, gaps (a,b) and (b,c) both far implies (a,c) far). Sizes are
interval sums rather than the host's per-merge nested additions, so point
weights must be integral with an exactly-representable total (< 2**53) —
integer f64 sums in that range are exact in any association order, hence
bitwise equal to the host's. Unit weights always qualify.
``mst_backend=auto`` only attempts the device engine when this predicate
holds; a pool that fails the post-fetch re-check falls back to the host
builder (flagged in the trace) rather than diverge.

Bitwise parity with the host reference on every ``MergeForest`` field —
children (including ``None`` for absorbed), dist, roots, sizes, kids CSR —
is pinned by the randomized sweep in ``tests/unit/test_mst_device.py``.
"""

from __future__ import annotations

import math
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from hdbscan_tpu.core.tree import TIE_RTOL, MergeForest

__all__ = [
    "supports_inputs",
    "resolve_mst_backend",
    "forest_events_device",
    "assemble_merge_forest",
    "build_merge_forest_device",
    "boruvka_mst_device",
]

#: ``mst_backend=auto`` flip point (vertices). Below it the host builder
#: (C fast path) wins on latency and per-shape compile cost; the device
#: engine pays one compile per (n, m) shape, which tier-1's many tiny fits
#: must not re-pay hundreds of times.
MST_DEVICE_THRESHOLD = 65536

def _ties_exact(w, tie_rtol: float = TIE_RTOL) -> bool:
    """No near-tied-but-unequal pair among the (finite) weights."""
    w = np.asarray(w, np.float64)
    w = w[np.isfinite(w)]
    if w.size < 2:
        return True
    sw = np.sort(w)
    a, b = sw[:-1], sw[1:]
    gap = b - a
    near = gap <= tie_rtol * np.maximum(np.abs(a), np.abs(b))
    return not bool(np.any(near & (gap != 0)))


def supports_inputs(
    w,
    point_weights=None,
    tie_rtol: float = TIE_RTOL,
) -> bool:
    """Host-side predicate: device forest output is bitwise-equal to host.

    True iff no two distinct edge weights are near-tied within ``tie_rtol``
    (so exact-equality absorption matches the host's anchor-chained
    ``_tied``) and point weights sum exactly in any association order
    (integral, total < 2**53; unit weights always do) — sizes come from
    interval prefix sums, not the host's per-merge addition order.
    """
    if not _ties_exact(w, tie_rtol):
        return False
    if point_weights is not None:
        pw = np.asarray(point_weights, np.float64)
        if pw.size and (
            bool(np.any(pw != np.floor(pw))) or float(np.sum(pw)) >= 2**53
        ):
            return False
    return True


def resolve_mst_backend(
    params=None,
    n: int | None = None,
    mst_backend: str | None = None,
) -> str:
    """The MST/forest engine a fit will *attempt*: "host" or "device".

    ``auto`` picks device only above :data:`MST_DEVICE_THRESHOLD` vertices
    (per-shape compile cost; see the constant's note). Input eligibility
    (``supports_inputs``) is checked later against the actual edge pool —
    an ineligible pool falls back to the host builder even when this
    resolves "device".
    """
    backend = mst_backend or getattr(params, "mst_backend", "auto")
    if backend in ("host", "device"):
        return backend
    if n is not None and n >= MST_DEVICE_THRESHOLD:
        return "device"
    return "host"


# ---------------------------------------------------------------------------
# Device stage: lexsort + two scans
# ---------------------------------------------------------------------------


def _find2(par, x, y):
    """Resolve both roots in ONE while loop (fused termination test)."""

    def cond(s):
        a, b = s
        return (par[a] != a) | (par[b] != b)

    def body(s):
        a, b = s
        return (
            jnp.where(par[a] != a, par[a], a),
            jnp.where(par[b] != b, par[b], b),
        )

    return lax.while_loop(cond, body, (x, y))


def _uf_scan(su, sv, n: int):
    """Kruskal union-find over lexsorted edges -> (final par, (ra, rb)).

    Keep this carry shape EXACTLY as is (see module docstring): ``par``
    alone, compression writes at xs indices, one union write at the raw
    while output. Padded edges arrive as self-loops (u = v = 0) and fall
    out as non-merges.
    """
    par0 = jnp.arange(n, dtype=jnp.int32)

    def step(par, xs):
        ue, ve = xs
        ra, rb = _find2(par, ue, ve)
        par = par.at[ue].set(ra).at[ve].set(rb)
        par = par.at[rb].set(ra)  # no-op self-write when ra == rb
        return par, (ra, rb)

    # unroll=8 amortizes XLA CPU's per-step loop overhead (measured 0.22 s
    # -> 0.095 s at 245k edges) without touching the op sequence.
    return lax.scan(step, par0, (su, sv), unroll=8)


@partial(jax.jit, static_argnames=("n", "presorted"))
def forest_events_device(u, v, w, n: int, presorted: bool = False):
    """Edge pool -> union event stream, on device.

    ``u``/``v``: (m,) endpoints (self-loops and duplicate/cycle edges are
    skipped, matching the host Kruskal; +inf-weight padding rows sort last
    and must be self-loops). Returns the device pytree
    ``assemble_merge_forest`` consumes after ONE fetch. ``presorted``
    callers (host edge pools) skip the device lexsort.
    """
    if presorted:
        su, sv, sw = u.astype(jnp.int32), v.astype(jnp.int32), w
    else:
        # Canonical (w, u, v) order — np.lexsort's key, as three stable
        # passes from the least-significant key up (int32 keys only: the
        # production default runs without jax_enable_x64).
        o = jnp.argsort(v.astype(jnp.int32), stable=True)
        o = o[jnp.argsort(u[o].astype(jnp.int32), stable=True)]
        order = o[jnp.argsort(w[o], stable=True)]
        su = u[order].astype(jnp.int32)
        sv = v[order].astype(jnp.int32)
        sw = w[order]

    _, (ra, rb) = _uf_scan(su, sv, n)
    return {"sw": sw, "ra": ra, "rb": rb}  # merge steps: ra != rb


# ---------------------------------------------------------------------------
# Host stage: vectorized reconstruction from the fetched event records
# ---------------------------------------------------------------------------


def _doubling_rounds(size: int) -> int:
    return max(1, int(math.ceil(math.log2(max(size, 2)))) + 1)


def _merge_tops(n: int, t: int, ra_m, rb_m):
    """Per-merge child tops (ta, tb) from the union event stream.

    2t-row (value, time) sweep: merge k contributes one fused row at value
    ``ra`` (query the component's current top, then publish node k as its
    new top) and one query row at value ``rb``. One argsort groups rows by
    root value in time order; a running max over event payloads (later
    events have larger node ids, and the value dominates the packed key so
    segments can't bleed) answers every query with the latest preceding
    event — exclusive of the fused row's own event (``prevmax``) — or the
    leaf itself when none. The fused row can't leak into the same step's
    ``rb`` query because a merge has ``ra != rb``.
    """
    if t == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    rows = 2 * t
    vals = np.empty(rows, np.int64)
    vals[0::2] = ra_m
    vals[1::2] = rb_m
    payload = np.full(rows, -1, np.int64)
    payload[0::2] = np.arange(t)
    ordk = np.argsort(vals * rows + np.arange(rows), kind="stable")
    base = vals[ordk] * np.int64(t + 2)
    runmax = np.maximum.accumulate(base + payload[ordk] + 1)
    prevmax = np.empty_like(runmax)
    prevmax[0] = -1
    prevmax[1:] = np.where(base[1:] == base[:-1], runmax[:-1], -1)
    fused = (np.arange(rows) % 2 == 0)[ordk]
    last = np.empty(rows, np.int64)
    last[ordk] = np.where(fused, prevmax, runmax) - base - 1
    ta = np.where(last[0::2] >= 0, n + last[0::2], ra_m)
    tb = np.where(last[1::2] >= 0, n + last[1::2], rb_m)
    return ta, tb


def assemble_merge_forest(
    n: int, out: dict, point_weights=None, build_children: bool = True
) -> MergeForest | None:
    """Fetched ``forest_events_device`` pytree -> host ``MergeForest``.

    Vectorized numpy only (pointer-doubling loops run log2 rounds of
    whole-array gathers; nothing is per-edge Python). Returns ``None``
    when the sorted weights fail the exact-tie gate — the caller falls
    back to the host builder. ``build_children=False`` skips the Python
    ``children`` list cut — ``core/tree_vec.py`` consumes ``kids_csr``
    directly, so the default device fit never pays it; the reference
    engine (``tree_backend=reference``) needs the lists.
    """
    sw = np.asarray(out["sw"], np.float64)
    if not _ties_exact(sw):
        return None
    ra_all = np.asarray(out["ra"], np.int64)
    rb_all = np.asarray(out["rb"], np.int64)
    mi = np.nonzero(ra_all != rb_all)[0]  # merge steps joined two roots
    t = len(mi)
    dist = sw[mi]  # node k's weight: merges are numbered in step order
    ra_m = ra_all[mi]
    rb_m = rb_all[mi]
    nid = n + np.arange(t, dtype=np.int64)
    el_n = n + t
    el = np.arange(el_n, dtype=np.int64)
    ta, tb = _merge_tops(n, t, ra_m, rb_m)

    # Absorption by exact equality (see module docstring): child node's
    # weight equals the merge weight.
    safe_ta = np.clip(ta - n, 0, max(t - 1, 0))
    safe_tb = np.clip(tb - n, 0, max(t - 1, 0))
    absorb_a = (ta >= n) & (dist[safe_ta] == dist) if t else np.zeros(0, bool)
    absorb_b = (tb >= n) & (dist[safe_tb] == dist) if t else np.zeros(0, bool)

    par_el = np.full(el_n, -1, np.int64)
    par_el[ta] = nid
    par_el[tb] = nid
    absorbed = np.zeros(el_n, bool)
    absorbed[ta[absorb_a]] = True
    absorbed[tb[absorb_b]] = True

    # One global Euler tour: en(x) = 2x, ex(x) = 2x + 1; a node's entry
    # leads to its a-child (the host's splice order), roots chain in
    # ascending order so a single distance-to-terminal list ranking orders
    # every slot of every tree.
    slots = 2 * el_n
    s = np.empty(slots, np.int32)
    s[0::2] = np.arange(1, slots, 2, dtype=np.int32)  # childless: en -> ex
    s[2 * nid] = 2 * ta
    s[2 * ta + 1] = 2 * tb
    s[2 * tb + 1] = 2 * nid + 1
    roots_el = np.nonzero(par_el < 0)[0]
    s[2 * roots_el[:-1] + 1] = 2 * roots_el[1:]
    term = 2 * roots_el[-1] + 1
    s[term] = term
    nxt = s
    dd = (nxt != np.arange(slots, dtype=np.int32)).astype(np.int32)
    for _ in range(_doubling_rounds(slots)):
        dd = dd + dd[nxt]  # terminal keeps dd 0, so no mask needed
        nxt = nxt[nxt]
    rk = slots - dd  # int32: ascending along the tour, unique

    # Owner of a kid = nearest non-absorbed ancestor: pointer-double the
    # "absorbed forwards to its parent" map (parents always outrank kids;
    # absorption chains are usually shallow, so stop once settled).
    g = np.where(absorbed, par_el, el).astype(np.int32)
    for _ in range(_doubling_rounds(el_n)):
        g2 = g[g]
        if np.array_equal(g2, g):
            break
        g = g2
    is_kid = (par_el >= 0) & ~absorbed
    owner = np.where(par_el >= 0, g[np.clip(par_el, 0, None)].astype(np.int64), -1)

    # Kid lists: within one owner, DFS preorder = ascending entry rank.
    big = np.int64(slots + 1)
    ckey = np.where(
        is_kid, owner * big + rk[2 * el].astype(np.int64), np.iinfo(np.int64).max
    )
    kid_flat = np.argsort(ckey, kind="stable")[: int(is_kid.sum())]
    kid_count = np.zeros(max(t, 1), np.int64)
    np.add.at(kid_count, owner[is_kid] - n, 1)
    kid_count = kid_count[:t]

    # Sizes: a node's subtree leaves occupy the open rank interval
    # (rk[en], rk[ex]); prefix sums over the tour-ordered leaf weights.
    pw = (
        np.ones(n, np.float64)
        if point_weights is None
        else np.asarray(point_weights, np.float64)
    )
    lr = rk[0: 2 * n: 2]
    lord = np.argsort(lr, kind="stable")
    cum = np.zeros(n + 1, np.float64)
    np.cumsum(pw[lord], out=cum[1:])
    lr_sorted = lr[lord]
    node_sizes = (
        cum[np.searchsorted(lr_sorted, rk[2 * nid + 1])]
        - cum[np.searchsorted(lr_sorted, rk[2 * nid])]
    )
    sizes = np.concatenate([pw, node_sizes])

    children = None
    absorbed_nodes = absorbed[n:]
    if build_children:
        flat_list = kid_flat.tolist()
        offs = np.zeros(t + 1, np.int64)
        np.cumsum(kid_count, out=offs[1:])
        children = [
            flat_list[offs[k]: offs[k + 1]] if not absorbed_nodes[k] else None
            for k in range(t)
        ]

    # Roots: exactly the parentless elements (every final component's top
    # has no parent; isolated points are their own top), ascending — the
    # host's np.unique-over-tops order.
    roots = [int(r) for r in roots_el]

    return MergeForest(
        n_points=n,
        children=children,
        dist=dist,
        roots=roots,
        sizes=sizes,
        kids_csr=(kid_flat, kid_count),
    )


def build_merge_forest_device(
    n: int,
    u,
    v,
    w,
    point_weights=None,
    trace=None,
    build_children: bool = True,
) -> MergeForest | None:
    """Device twin of ``core/tree.py::build_merge_forest`` (one host sync).

    Accepts host or device-resident edge arrays. Returns ``None`` when the
    pool fails the runtime eligibility gate (near-tied unequal weights) —
    the caller falls back to the host builder; a ``None`` here costs the
    device attempt but never a wrong tree. Emits ``tree_build_device`` and
    exactly one ``host_sync`` event.
    """
    m = int(np.shape(u)[0])
    if m == 0 or n == 0:
        return None  # trivial pools: the host builder is already O(1)
    if point_weights is not None and not supports_inputs([], point_weights):
        return None  # non-integral weights: interval sums would diverge
    from hdbscan_tpu import obs

    t0 = time.monotonic()
    with obs.mem_phase("tree_build_device"):
        # Host pools pre-sort here (np.lexsort beats the device sort on CPU
        # and the scan needs the canonical order either way); device-resident
        # pools go through the in-program lexsort instead.
        if not isinstance(u, jax.Array):
            u = np.asarray(u)
            v = np.asarray(v)
            w = np.asarray(w)
            # Without jax_enable_x64 (the production default) a float64 host
            # pool would silently downcast to float32 on device and the forest
            # dists would no longer be bitwise-equal to the host builder's.
            # Decline unless the weights are exactly float32-representable
            # (device-native f32 pools and lattice weights always are).
            if w.dtype == np.float64 and not jax.config.jax_enable_x64:
                if not np.array_equal(w, w.astype(np.float32).astype(np.float64)):
                    return None
            order = np.lexsort((v, u, w))
            out = forest_events_device(
                jnp.asarray(u[order]),
                jnp.asarray(v[order]),
                jnp.asarray(w[order]),
                n,
                presorted=True,
            )
        else:
            out = forest_events_device(u, v, w, n)
        build_wall = time.monotonic() - t0
        t0 = time.monotonic()
        fetched = jax.device_get(out)
        sync_wall = time.monotonic() - t0
    tl = obs.timeline()
    if tl is not None:
        # Single-device phase: the event stream lives on one chip and the
        # only host segment is the one fetch. No ring traffic -> the whole
        # exec wall attributes to compute.
        try:
            leaf = jax.tree_util.tree_leaves(out)[0]
            dev_id = min(d.id for d in leaf.devices())
        except Exception:
            dev_id = 0
        tl.record_round(
            "tree_build_device", 0, [(dev_id, build_wall)],
            fetch_s=sync_wall, trace=trace,
        )
    if trace is not None:
        trace(
            "host_sync",
            arrays=len(fetched),
            bytes=int(sum(a.nbytes for a in fetched.values())),
            wall_s=round(sync_wall, 6),
        )
    t0 = time.monotonic()
    forest = assemble_merge_forest(
        n, fetched, point_weights=point_weights, build_children=build_children
    )
    if trace is not None:
        trace(
            "tree_build_device",
            n=n,
            edges=m,
            nodes=-1 if forest is None else len(forest.dist),
            backend="device",
            fallback=forest is None,
            wall_s=round(build_wall + (time.monotonic() - t0), 6),
        )
    return forest


# ---------------------------------------------------------------------------
# Device Borůvka rounds (contraction stays on device)
# ---------------------------------------------------------------------------


def _collapse_labels(comp, valid, has_edge, tgt_comp, n: int):
    """Shared pointer-doubling collapse over per-LABEL winners.

    ``comp``/``valid``: (n_pad,) labels + realness mask (labels are
    representative vertex ids < n); ``has_edge``/``tgt_comp``: (n,) per-label
    winner existence + the winning edge's TARGET component label. Both the
    replicated contraction (:func:`_contract_round`) and the sharded in-jit
    rounds (``parallel/shard``) funnel through this exact code, so the
    cycle-resolution and emission-order semantics cannot drift between them.

    Returns (emit_mask(n,), rep(n,), n_comp, edges_added) with ``emit_mask``
    in ascending-label order (the host's emission order).
    """
    labels = jnp.arange(n, dtype=jnp.int32)
    t = jnp.where(has_edge, tgt_comp, labels)

    # Pointer doubling with orbit-min accumulation: every label lands on
    # its group's cycle and the cycle minimum becomes the group root.
    mn = labels

    def dbl(_, c):
        mn, s = c
        return jnp.minimum(mn, mn[s]), s[s]

    mn, s = lax.fori_loop(0, _doubling_rounds(n), dbl, (mn, t))
    rep = mn[s]
    is_root = rep == labels
    active = (
        jnp.zeros((n,), bool)
        .at[jnp.where(valid, comp, n)]
        .set(True, mode="drop")
    )
    emit_mask = active & ~is_root & has_edge
    n_comp = jnp.sum(active & is_root)
    return emit_mask, rep, n_comp, jnp.sum(emit_mask)


def _contract_round(comp, bw, bj, valid, n: int):
    """One Borůvka contraction in label space — the in-jit twin of
    ``utils/unionfind.contract_min_edges``.

    ``comp``: (n_pad,) labels; values are representative VERTEX ids in
    [0, n), so segment reductions run over fixed-size (n,) label arrays and
    no ``np.unique`` compaction is needed. Winner per component: minimum by
    the shared (w, lo, hi) key then lowest row id — the host's stable
    lexsort tie-break — found with a weight scatter-min followed by a
    cascade of int32 scatter-mins (lo, then hi, then row) over the rows
    still tied at each stage (int32 throughout: the production default
    runs without jax_enable_x64).

    Returns (emit_mask(n,), win_row(n,), rep(n,), n_comp, edges_added) with
    ``emit_mask`` in ascending-label order (the host's emission order).
    """
    n_pad = comp.shape[0]
    rows = jnp.arange(n_pad, dtype=jnp.int32)
    bj_c = jnp.clip(bj, 0, n_pad - 1)
    cross = valid & (bj >= 0) & (comp != comp[bj_c])
    lab = jnp.where(cross, comp, n)

    wmin = (
        jnp.full((n,), jnp.inf, bw.dtype)
        .at[lab]
        .min(bw, mode="drop")
    )
    tied = cross & (bw == wmin[jnp.clip(comp, 0, n - 1)])
    comp_c = jnp.clip(comp, 0, n - 1)
    sentinel = jnp.iinfo(jnp.int32).max

    def _seg_min(mask, val):
        return (
            jnp.full((n,), sentinel, jnp.int32)
            .at[jnp.where(mask, lab, n)]
            .min(val, mode="drop")
        )

    lo = jnp.minimum(rows, bj_c)
    hi = jnp.maximum(rows, bj_c)
    lo_min = _seg_min(tied, lo)
    tied = tied & (lo == lo_min[comp_c])
    hi_min = _seg_min(tied, hi)
    tied = tied & (hi == hi_min[comp_c])
    row_min = _seg_min(tied, rows)
    has_edge = row_min < sentinel
    win_row = jnp.where(has_edge, row_min, 0)

    tgt_comp = comp[jnp.clip(bj[win_row], 0, n_pad - 1)]
    emit_mask, rep, n_comp, added = _collapse_labels(
        comp, valid, has_edge, tgt_comp, n
    )
    return emit_mask, win_row, rep, n_comp, added


@partial(
    jax.jit,
    static_argnames=("n", "metric", "row_tile", "col_tile", "max_rounds"),
)
def _boruvka_rounds_device(
    data_p, core_p, valid, n: int, metric: str, row_tile: int, col_tile: int,
    max_rounds: int,
):
    """All Borůvka rounds in ONE device program (no per-round host glue).

    Emits into fixed (n-1,) edge buffers (weights init +inf, endpoints 0,
    so unused tail rows pass straight through ``forest_events_device`` as
    inert self-loop padding) and records per-round (components,
    edges_added) for the retrospective ``mst_round`` trace events.
    """
    from hdbscan_tpu.ops.pallas_segmin import min_outgoing_all_rows

    n_pad = data_p.shape[0]
    buf = max(n - 1, 1)
    state = dict(
        comp=jnp.arange(n_pad, dtype=jnp.int32),
        eu=jnp.zeros((buf,), jnp.int32),
        ev=jnp.zeros((buf,), jnp.int32),
        ew=jnp.full((buf,), jnp.inf, data_p.dtype),
        count=jnp.int32(0),
        rnd=jnp.int32(0),
        n_comp=jnp.int32(n),
        progress=jnp.asarray(True),
        stat_comp=jnp.zeros((max_rounds,), jnp.int32),
        stat_edges=jnp.zeros((max_rounds,), jnp.int32),
    )

    def cond(st):
        return (st["rnd"] < max_rounds) & (st["n_comp"] > 1) & st["progress"]

    def body(st):
        bw, bj = min_outgoing_all_rows(
            data_p, core_p, st["comp"], valid, metric, row_tile, col_tile
        )
        emit_mask, win_row, rep, n_comp, added = _contract_round(
            st["comp"], bw, bj, valid, n
        )
        pos = st["count"] + jnp.cumsum(emit_mask.astype(jnp.int32)) - 1
        slot = jnp.where(emit_mask, pos, buf)
        wr = jnp.clip(win_row, 0, n_pad - 1)
        eu = st["eu"].at[slot].set(wr, mode="drop")
        ev = st["ev"].at[slot].set(
            jnp.clip(bj[wr], 0, n_pad - 1).astype(jnp.int32), mode="drop"
        )
        ew = st["ew"].at[slot].set(bw[wr], mode="drop")
        comp = rep[st["comp"]]
        rnd = st["rnd"]
        return dict(
            comp=comp,
            eu=eu,
            ev=ev,
            ew=ew,
            count=st["count"] + added.astype(jnp.int32),
            rnd=rnd + 1,
            n_comp=n_comp.astype(jnp.int32),
            progress=added > 0,
            stat_comp=st["stat_comp"].at[rnd].set(n_comp.astype(jnp.int32)),
            stat_edges=st["stat_edges"].at[rnd].set(added.astype(jnp.int32)),
        )

    st = lax.while_loop(cond, body, state)
    return {
        "u": st["eu"],
        "v": st["ev"],
        "w": st["ew"],
        "count": st["count"],
        "rounds": st["rnd"],
        "stat_comp": st["stat_comp"],
        "stat_edges": st["stat_edges"],
    }


#: Round cap for the in-jit Borůvka ``while_loop`` (this module and the
#: sharded twin, ``parallel/shard.shard_boruvka_mst``). Borůvka at least
#: halves the component count every productive round, so 64 covers any
#: addressable n; hitting the cap means the contraction is broken, not
#: that the input is large. Checked after the fetch by
#: :func:`assert_rounds_converged`.
DEFAULT_MAX_ROUNDS = 64


def assert_rounds_converged(
    rounds: int,
    count: int,
    n: int,
    *,
    max_rounds: int = DEFAULT_MAX_ROUNDS,
    stat_comp=None,
    stat_edges=None,
    where: str = "boruvka_mst_device",
) -> None:
    """Raise if the fixed-round Borůvka ``while_loop`` exited at its cap
    with components still unmerged.

    The in-jit loop (``_boruvka_rounds_device`` and the sharded twin)
    cannot raise from inside the program, and a capped exit is silent: the
    edge buffers simply come back short, which downstream reads as a
    forest with spurious extra roots — exactly the failure mode a
    miscontraction (or a metric emitting NaN weights, which stalls
    ``progress``) produces. Callers check the FETCHED ``rounds``/``count``
    scalars here, after the one host sync they already perform.

    A clean exit is either ``count == n - 1`` (spanning tree complete) or
    a final round that added no edges (``progress`` False — genuinely
    disconnected data under a finite-break metric, every component
    saturated). Hitting ``max_rounds`` while the last round still added
    edges is neither, and raises with the per-round component/edge tail so
    the divergence is diagnosable from the exception alone.
    """
    if rounds < max_rounds or count >= max(n - 1, 0):
        return
    last_added = None
    survivors = None
    tail = ""
    if stat_comp is not None:
        survivors = int(np.asarray(stat_comp)[max_rounds - 1])
    if stat_edges is not None:
        stat_edges = np.asarray(stat_edges)
        last_added = int(stat_edges[max_rounds - 1])
        if last_added == 0:
            return  # saturated (disconnected input), not capped mid-merge
        show = min(4, max_rounds)
        comps = (
            np.asarray(stat_comp)[-show:].tolist()
            if stat_comp is not None
            else "?"
        )
        tail = (
            f"; last {show} rounds: components={comps}, "
            f"edges_added={stat_edges[-show:].tolist()}"
        )
    surviving = (
        f"{survivors} components still unmerged"
        if survivors is not None
        # Without per-round stats the edge count still bounds the survivor
        # count exactly: a forest with `count` edges over n vertices has
        # n - count components.
        else f"{max(n - count, 1)} components still unmerged (from edge count)"
    )
    raise RuntimeError(
        f"{where}: Borůvka round cap hit without convergence — "
        f"{rounds} rounds (max_rounds={max_rounds}) emitted {count} of "
        f"{max(n - 1, 0)} spanning edges with {surviving} and the loop "
        f"was still merging{tail}. Borůvka halves components every round, "
        f"so a capped exit indicates a contraction/scan defect (or NaN "
        f"edge weights), not input size; rerun with a larger max_rounds "
        f"only to gather diagnostics."
    )


def boruvka_mst_device(
    data: np.ndarray,
    core: np.ndarray,
    metric: str = "euclidean",
    row_tile: int = 1024,
    col_tile: int = 8192,
    dtype=np.float32,
    max_rounds: int = DEFAULT_MAX_ROUNDS,
):
    """Device-resident Borůvka MST: pad once, run every round in one jit.

    Same tiling/padding as ``ops.tiled.BoruvkaScanner`` so per-round
    candidates are bitwise-identical to the host loop's; the contraction
    replays ``contract_min_edges`` exactly (see ``_contract_round``).
    Returns DEVICE arrays — callers feed them straight into
    ``forest_events_device`` and fetch once.
    """
    from hdbscan_tpu import obs
    from hdbscan_tpu.ops.tiled import _pad_rows, _tile_sizes

    n = len(data)
    row_tile, col_tile, n_pad = _tile_sizes(n, row_tile, col_tile)
    with obs.mem_phase("boruvka_rounds_device"):
        data_p = jnp.asarray(_pad_rows(np.asarray(data, dtype), n_pad))
        core_p = jnp.asarray(_pad_rows(np.asarray(core, dtype), n_pad))
        valid = jnp.asarray(np.arange(n_pad) < n)
        return _boruvka_rounds_device(
            data_p, core_p, valid, n, metric, row_tile, col_tile, max_rounds
        )
