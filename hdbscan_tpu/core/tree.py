"""Condensed cluster tree, stability, EOM flat extraction, GLOSH (host side).

Re-design of the reference's hierarchy/cluster-tree layer:

- ``HdbscanDataBubbles.constructClusterTree`` (``databubbles/HdbscanDataBubbles.java:256-374``):
  top-down edge removal in weight-tie groups, BFS component discovery,
  member-weighted minClusterSize, multi-way splits, ``detachPoints`` stability.
- ``Cluster.detachPoints`` / ``Cluster.propagate``
  (``hdbscanstar/Cluster.java:80-88,98-142``): stability
  ``sum(n) * (1/level - 1/birthLevel)`` and excess-of-mass propagation with
  constraint priority and parent-wins ties.
- ``HDBSCANStar.propagateTree`` / ``findProminentClusters`` /
  ``calculateOutlierScores`` (``hdbscanstar/HDBSCANStar.java:505,567,653``).

The irregular, data-dependent tree extraction stays on host (numpy + python),
operating on the MST edge list produced by the device Borůvka kernel — the
inputs are O(n), not O(n^2). Device work ends at the edge list.

Equivalence note: instead of literally removing edges heaviest-to-lightest and
BFS-ing components (O(n * levels)), we build the single-linkage merge forest
bottom-up with union-find, contract equal-weight merge chains into multi-way
nodes, and condense top-down over that forest. Level-wise component structure
of a graph is identical either way, and tie groups are handled exactly (merge
nodes at equal weight that touch are one multi-way split), so the condensed
tree equals the reference's — independent of MST tie-breaking.

Deliberate bug fixes vs the reference (SURVEY.md §7 "parity decisions"):
- tie groups that split one cluster into several components process each
  component once (the reference re-BFS-es a component once per affected vertex,
  ``HdbscanDataBubbles.java:307-312``, duplicating detaches);
- flat extraction follows the correct ``Cluster.propagate`` EOM (the live
  bubble variant ``findProminentClustersAndClassificationNoiseBubbles``
  drops leaf clusters from its solution set and lets shallow clusters
  overwrite deep ones, ``HdbscanDataBubbles.java:377-504``);
- the root cluster's birth level is +inf (1/birth = 0) rather than NaN
  (``HdbscanDataBubbles.java:276``), so root stability is well defined.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

NOISE = 0  # reference noise label (currentClusterLabels[v] = 0)
ROOT_LABEL = 1  # reference root cluster label (HdbscanDataBubbles.java:276)


# ---------------------------------------------------------------------------
# Single-linkage merge forest (union-find Kruskal + tie contraction)
# ---------------------------------------------------------------------------


@dataclass
class MergeForest:
    """Multi-way single-linkage merge forest over n points.

    Internal node ids are ``n + t``; ``children[t]`` lists the node ids merged
    at distance ``dist[t]``. Equal-weight merges that touch are contracted into
    one multi-way node, which makes the forest invariant to MST tie order.
    """

    n_points: int
    children: list  # list[list[int]]
    dist: np.ndarray  # (t,) float64
    roots: list  # node ids of the final components
    sizes: np.ndarray  # (n + t,) weighted member count per node
    #: Optional CSR twin of ``children`` from the native builder:
    #: ``(kid_flat, kid_count)`` with ``kid_count[t] == 0`` for absorbed
    #: nodes and kids in list order. ``core/tree_vec.py`` consumes it
    #: directly; ``None`` (pure-Python build) falls back to flattening the
    #: lists.
    kids_csr: tuple | None = None


#: Relative tolerance for grouping equal-weight edges into one hierarchy
#: level. Mathematically-tied distances (grid data, duplicate points) round
#: differently depending on summation order — e.g. sqrt(0.07) from two Iris
#: pairs differs at 1e-12 — and exact float equality (the reference's
#: ``mst.getEdgeWeightAtIndex(i) == currentEdgeWeight``,
#: ``HdbscanDataBubbles.java:284``) then splits a true tie into two levels,
#: creating spurious zero-stability clusters. SURVEY.md §7 "hard parts"
#: decision: epsilon tie-grouping, anchored at the first weight of a group.
TIE_RTOL = 1e-9


def _tied(a: float, b: float, rtol: float) -> bool:
    return abs(a - b) <= rtol * max(abs(a), abs(b))


def build_merge_forest(
    n: int,
    u: np.ndarray,
    v: np.ndarray,
    w: np.ndarray,
    point_weights: np.ndarray | None = None,
    tie_rtol: float = TIE_RTOL,
) -> MergeForest:
    """Kruskal over an arbitrary edge pool (cycle edges skipped).

    Accepts the merged multi-level edge pool of the distributed pipeline
    (local MSTs + inter-cluster edges, ``main/Main.java:304-348`` analog), not
    just a clean MST.
    """
    u = np.asarray(u, np.int64)
    v = np.asarray(v, np.int64)
    w = np.asarray(w, np.float64)
    if point_weights is None:
        point_weights = np.ones(n, np.int64)
    order = np.lexsort((v, u, w))
    u, v, w = u[order], v[order], w[order]

    from hdbscan_tpu.native import merge_forest_lib

    lib = merge_forest_lib()
    if lib is not None:
        return _build_merge_forest_native(lib, n, u, v, w, point_weights, tie_rtol)

    max_nodes = n + len(w)
    parent = np.arange(max_nodes, dtype=np.int64)  # union-find over node ids
    top = np.arange(n, dtype=np.int64)  # root of the merge-tree per UF root
    sizes = np.zeros(max_nodes, np.float64)
    sizes[:n] = point_weights
    children: list = []
    dists: list = []
    anchors: list = []  # first (smallest) weight of each node's tie group
    next_node = n

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for i in range(len(w)):
        ra, rb = find(u[i]), find(v[i])
        if ra == rb:
            continue
        ta, tb = top[ra], top[rb]
        wi = float(w[i])
        kids = []
        anchor = wi
        for t in (ta, tb):
            # Compare against the child's group ANCHOR (first weight of its
            # tie group), not its own weight — pairwise comparison would let
            # chains of near-ties drift past the tolerance.
            if t >= n and _tied(anchors[t - n], wi, tie_rtol):
                kids.extend(children[t - n])  # contract equal-weight chain
                anchor = min(anchor, anchors[t - n])
                children[t - n] = None  # absorbed
            else:
                kids.append(t)
        node = next_node
        next_node += 1
        children.append(kids)
        dists.append(wi)
        anchors.append(anchor)
        sizes[node] = sizes[ta] + sizes[tb]
        parent[rb] = ra
        top[ra] = node

    roots = sorted({top[find(p)] for p in range(n)})
    t = next_node - n
    return MergeForest(
        n_points=n,
        children=children[:t],
        dist=np.asarray(dists, np.float64),
        roots=list(roots),
        sizes=sizes[: n + t],
    )


def _build_merge_forest_native(lib, n, u, v, w, point_weights, tie_rtol):
    """C fast path of :func:`build_merge_forest` (same semantics; the per-edge
    union/tie-contraction loop dominates host time at 100k+ edges)."""
    import ctypes

    m = len(w)
    pw = np.ascontiguousarray(point_weights, np.float64)
    parent = np.empty(n, np.int64)  # the C side unions POINT roots only
    top = np.empty(n, np.int64)
    sizes = np.empty(n + m, np.float64)
    dist = np.empty(max(m, 1), np.float64)
    anchor = np.empty(max(m, 1), np.float64)
    absorbed = np.zeros(max(m, 1), np.uint8)
    child_head = np.empty(max(m, 1), np.int64)
    child_tail = np.empty(max(m, 1), np.int64)
    child_next = np.empty(n + m, np.int64)

    def p(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    i64, f64, u8 = ctypes.c_int64, ctypes.c_double, ctypes.c_uint8
    t_count = lib.build_merge_forest_c(
        n, m,
        p(np.ascontiguousarray(u), i64), p(np.ascontiguousarray(v), i64),
        p(np.ascontiguousarray(w), f64), p(pw, f64), float(tie_rtol),
        p(parent, i64), p(top, i64), p(sizes, f64),
        p(dist, f64), p(anchor, f64), p(absorbed, u8),
        p(child_head, i64), p(child_tail, i64), p(child_next, i64),
    )
    # Flatten the intrusive child lists in C (CSR in list order), then cut
    # the Python lists from one tolist() pass — the per-kid Python walk this
    # replaces dominated wrapper time at 100k+ points.
    kid_flat = np.empty(n + m, np.int64)
    kid_count = np.empty(max(t_count, 1), np.int64)
    n_kids = lib.flatten_children_c(
        t_count, p(absorbed, u8), p(child_head, i64), p(child_next, i64),
        p(kid_flat, i64), p(kid_count, i64),
    )
    kid_flat = kid_flat[:n_kids]
    kid_count = kid_count[:t_count]
    flat_list = kid_flat.tolist()
    offs = np.zeros(t_count + 1, np.int64)
    np.cumsum(kid_count, out=offs[1:])
    children: list = [
        flat_list[offs[t]:offs[t + 1]] if not absorbed[t] else None
        for t in range(t_count)
    ]
    # roots: flatten the POINT union-find (the C side unions point roots
    # only; entries past n are uninitialized), then take each component
    # root's merge-tree top.
    pref = parent[:n].copy()
    while True:
        q = pref[pref]
        if np.array_equal(q, pref):
            break
        pref = q
    roots = sorted({int(top[r]) for r in np.unique(pref)})
    return MergeForest(
        n_points=n,
        children=children,
        dist=dist[:t_count].copy(),
        roots=roots,
        sizes=sizes[: n + t_count],
        kids_csr=(kid_flat, kid_count),
    )


# ---------------------------------------------------------------------------
# Condensed cluster tree
# ---------------------------------------------------------------------------


@dataclass
class CondensedTree:
    """The simplified cluster tree plus per-point exit records.

    Cluster arrays are indexed by ``label`` (0 unused, 1 = root), mirroring
    the reference's label scheme (``nextClusterLabel`` starting at 2,
    ``HdbscanDataBubbles.java:259``).
    """

    n_points: int
    parent: np.ndarray  # (C+1,) label of parent, -1 for root, 0 unused slot
    birth: np.ndarray  # (C+1,) eps at which cluster appeared
    death: np.ndarray  # (C+1,) eps at which it died (0 = never died)
    stability: np.ndarray  # (C+1,)
    has_children: np.ndarray  # (C+1,) bool
    num_members: np.ndarray  # (C+1,) weighted member count at birth
    point_exit_level: np.ndarray  # (n,) eps at which each point became noise (0 = never)
    point_last_cluster: np.ndarray  # (n,) deepest cluster label the point belonged to
    # filled by propagate():
    propagated_stability: np.ndarray | None = None
    lowest_child_death: np.ndarray | None = None
    num_constraints_satisfied: np.ndarray | None = None
    virtual_child_constraints: np.ndarray | None = None  # vGamma column
    selected: np.ndarray | None = field(default=None)  # (C+1,) bool after propagate

    @property
    def n_clusters(self) -> int:
        return len(self.parent) - 1

    @property
    def infinite_stability(self) -> bool:
        return bool(np.any(np.isinf(self.stability[1:])))


def condense_forest(
    forest: MergeForest,
    min_cluster_size: int | float,
    point_weights: np.ndarray | None = None,
    self_levels: np.ndarray | None = None,
) -> CondensedTree:
    """Top-down condensation of the merge forest.

    ``point_weights``: member count per vertex (``nB`` in the reference) —
    ones for raw points, bubble member counts for the bubble tree
    (``countMembers += nB[v]``, ``HdbscanDataBubbles.java:330-338``).
    ``self_levels``: per-point self-edge levels (core distances,
    ``HDBSCANStar.java:196-203``); only consulted when a cluster narrows to a
    single vertex that still meets ``min_cluster_size`` (possible with
    ``min_cluster_size == 1`` or member weights), matching the reference's
    self-edge removal semantics.
    """
    n = forest.n_points
    if point_weights is None:
        point_weights = np.ones(n, np.float64)
    point_weights = np.asarray(point_weights, np.float64)
    sizes = forest.sizes

    # Cluster storage, 1-indexed by label.
    parent_l = [0, -1]
    birth = [0.0, np.inf]
    death = [0.0, 0.0]
    stability = [0.0, 0.0]
    has_children = [False, False]
    num_members = [0.0, float(sizes[forest.roots].sum())]
    n_alive_points = {ROOT_LABEL: num_members[ROOT_LABEL]}

    point_exit_level = np.zeros(n, np.float64)
    point_last_cluster = np.full(n, ROOT_LABEL, np.int64)

    def subtree_points(node: int) -> list:
        out, stack = [], [node]
        while stack:
            x = stack.pop()
            if x < n:
                out.append(x)
            else:
                stack.extend(forest.children[x - n])
        return out

    def detach(label: int, count: float, level: float) -> None:
        # Cluster.detachPoints (hdbscanstar/Cluster.java:80-88). Zero levels
        # (duplicate points) follow Java's IEEE semantics: 1/0 = +inf, which
        # surfaces as the reference's infinite-stability warning
        # (HDBSCANStar.java:40-47) rather than an error.
        inv_level = np.inf if level == 0 else 1.0 / level
        b = birth[label]
        inv_birth = 0.0 if np.isinf(b) else (np.inf if b == 0 else 1.0 / b)
        stability[label] += count * (inv_level - inv_birth)
        n_alive_points[label] -= count
        if n_alive_points[label] <= 0:
            death[label] = level

    def exit_points(node: int, label: int, level: float) -> None:
        pts = subtree_points(node)
        for p in pts:
            point_exit_level[p] = level
            point_last_cluster[p] = label
        detach(label, float(point_weights[pts].sum()), level)

    def new_cluster(parent_label: int, birth_level: float, size: float) -> int:
        label = len(parent_l)
        parent_l.append(parent_label)
        birth.append(birth_level)
        death.append(0.0)
        stability.append(0.0)
        has_children.append(False)
        num_members.append(float(size))
        n_alive_points[label] = float(size)
        has_children[parent_label] = True
        detach(parent_label, float(size), birth_level)
        return label

    # Work stack of (node, cluster_label).
    if len(forest.roots) == 1:
        stack = [(forest.roots[0], ROOT_LABEL)]
    else:
        # Disconnected edge pool: the root "splits" into the components at
        # eps = +inf. min_cluster_size still applies to each component.
        stack = []
        big = [r for r in forest.roots if sizes[r] >= min_cluster_size]
        small = [r for r in forest.roots if sizes[r] < min_cluster_size]
        for r in small:
            exit_points(r, ROOT_LABEL, np.inf)
        if len(big) == 1:
            stack.append((big[0], ROOT_LABEL))
        else:
            for r in big:
                stack.append((r, new_cluster(ROOT_LABEL, np.inf, float(sizes[r]))))

    while stack:
        node, label = stack.pop()
        if node < n:
            # Cluster narrowed to one vertex: dies at its self-edge level.
            point_last_cluster[node] = label
            if self_levels is not None:
                lvl = float(self_levels[node])
                point_exit_level[node] = lvl
                detach(label, float(point_weights[node]), lvl)
            continue
        t = node - n
        delta = float(forest.dist[t])
        kids = forest.children[t]
        big = [c for c in kids if sizes[c] >= min_cluster_size]
        small = [c for c in kids if sizes[c] < min_cluster_size]

        if len(big) >= 2:
            # True split (newClusters.size() >= 2, HdbscanDataBubbles.java:353):
            # each big component becomes a new cluster born at delta.
            for c in big:
                stack.append((c, new_cluster(label, delta, float(sizes[c]))))
            for c in small:
                exit_points(c, label, delta)
        elif len(big) == 1:
            # Cluster continues into the lone big component.
            for c in small:
                exit_points(c, label, delta)
            stack.append((big[0], label))
        else:
            # Cluster shatters: everything exits, cluster dies at delta.
            for c in kids:
                exit_points(c, label, delta)

    return CondensedTree(
        n_points=n,
        parent=np.asarray(parent_l, np.int64),
        birth=np.asarray(birth, np.float64),
        death=np.asarray(death, np.float64),
        stability=np.asarray(stability, np.float64),
        has_children=np.asarray(has_children, bool),
        num_members=np.asarray(num_members, np.float64),
        point_exit_level=point_exit_level,
        point_last_cluster=point_last_cluster,
    )


# ---------------------------------------------------------------------------
# Propagation (EOM) and flat extraction
# ---------------------------------------------------------------------------


def propagate_tree(
    tree: CondensedTree,
    num_constraints_satisfied: np.ndarray | None = None,
    virtual_child_constraints: np.ndarray | None = None,
) -> bool:
    """``HDBSCANStar.propagateTree`` (``HDBSCANStar.java:505-540``).

    Processes labels in descending order (children before parents — child
    labels are always larger), applying ``Cluster.propagate``
    (``Cluster.java:98-142``): constraint satisfaction dominates; stability
    breaks ties with the parent winning equality; the lowest descendant death
    level is propagated for GLOSH. Returns the infinite-stability flag.

    ``virtual_child_constraints``: per-cluster credits earned by the virtual
    (noise) child — the reference adds these straight into
    ``propagatedNumConstraintsSatisfied`` (``Cluster.java:157-159``), so they
    compete against the cluster's own count and flow upward only when the
    descendants win.
    """
    c = tree.n_clusters
    if num_constraints_satisfied is None:
        num_constraints_satisfied = np.zeros(c + 1, np.int64)
    prop_stab = np.zeros(c + 1, np.float64)
    if virtual_child_constraints is None:
        prop_cons = np.zeros(c + 1, np.int64)
    else:
        prop_cons = np.asarray(virtual_child_constraints, np.int64).copy()
    lowest_death = np.full(c + 1, np.inf)  # Double.MAX_VALUE analog
    # Winning-descendant bookkeeping as per-cluster linked lists
    # (head/tail/next) instead of list-of-lists: the reference's
    # ``descendants[par].extend(descendants[label])`` copies every surviving
    # label once per tree level — quadratic on deep cluster chains. Each
    # label sits in at most one list and each list is spliced into its unique
    # parent exactly once, so an O(1) splice is equivalent.
    head = np.full(c + 1, -1, np.int64)
    tail = np.full(c + 1, -1, np.int64)
    nxt = np.full(c + 1, -1, np.int64)

    for label in range(c, 0, -1):
        par = tree.parent[label]
        if lowest_death[label] == np.inf:
            lowest_death[label] = tree.death[label]
        if par <= 0:
            continue
        lowest_death[par] = min(lowest_death[par], lowest_death[label])
        own_cons = num_constraints_satisfied[label]
        own_stab = tree.stability[label]
        self_wins = (
            not tree.has_children[label]
            or own_cons > prop_cons[label]
            or (own_cons == prop_cons[label] and own_stab >= prop_stab[label])
        )
        if self_wins:
            prop_cons[par] += own_cons
            prop_stab[par] += own_stab
            if head[par] < 0:
                head[par] = label
            else:
                nxt[tail[par]] = label
            tail[par] = label
        else:
            prop_cons[par] += prop_cons[label]
            prop_stab[par] += prop_stab[label]
            if head[label] >= 0:  # splice the subtree's winner list upward
                if head[par] < 0:
                    head[par] = head[label]
                else:
                    nxt[tail[par]] = head[label]
                tail[par] = tail[label]

    selected = np.zeros(c + 1, bool)
    if c >= 1:
        node = head[ROOT_LABEL]
        while node >= 0:
            selected[node] = True
            node = nxt[node]

    tree.propagated_stability = prop_stab
    tree.lowest_child_death = lowest_death
    tree.num_constraints_satisfied = num_constraints_satisfied
    tree.virtual_child_constraints = virtual_child_constraints
    tree.selected = selected
    return tree.infinite_stability


def flat_labels(tree: CondensedTree) -> np.ndarray:
    """``HDBSCANStar.findProminentClusters`` (``HDBSCANStar.java:567-625``).

    A point gets a selected cluster's label iff it was a member of that
    cluster at the cluster's birth — i.e. the selected cluster is an ancestor
    (or equal) of the point's deepest cluster. Noise = 0. Equivalent to the
    reference's hierarchy-file offset mechanism, without the file.
    """
    if tree.selected is None:
        raise ValueError("propagate_tree() must run before flat_labels()")
    c = tree.n_clusters
    # For each cluster label, the selected ancestor-or-self (or 0): labels are
    # topologically ordered (parent < child), one ascending pass suffices.
    sel_anc = np.zeros(c + 1, np.int64)
    for label in range(1, c + 1):
        if tree.selected[label]:
            sel_anc[label] = label
        else:
            par = tree.parent[label]
            sel_anc[label] = sel_anc[par] if par > 0 else 0
    return sel_anc[tree.point_last_cluster]


def outlier_scores(tree: CondensedTree, core_distances: np.ndarray) -> np.ndarray:
    """GLOSH — ``HDBSCANStar.calculateOutlierScores`` (``HDBSCANStar.java:653-686``).

    score(p) = 1 - eps_max / eps(p), with eps(p) the level at which p became
    noise and eps_max the lowest death level among descendants of p's last
    cluster; 0 when eps(p) == 0. ``core_distances`` are carried for the
    sorted output record (``OutlierScore.java:36-50``), not the score itself.
    """
    if tree.lowest_child_death is None:
        raise ValueError("propagate_tree() must run before outlier_scores()")
    eps = tree.point_exit_level
    eps_max = tree.lowest_child_death[tree.point_last_cluster]
    with np.errstate(divide="ignore", invalid="ignore"):
        score = np.where(eps != 0, 1.0 - eps_max / eps, 0.0)
    return score


def cluster_eps_min(
    tree: CondensedTree, labels: np.ndarray | None = None
) -> np.ndarray:
    """Per-cluster minimum point exit eps — the "max lambda" record of the
    serving artifact (``serve/artifact.py``), in this repo's eps-level
    representation: ``eps_min[c]`` is the lowest level at which any flat
    member of selected cluster ``c`` exited (``lambda_max = 1/eps_min``).
    Membership probability of a query attaching at level ``eps_q`` is
    ``min(1, eps_min[c] / eps_q)`` — 1.0 at the cluster's densest point,
    falling toward the fringe, exactly the reference semantics of
    ``probabilities_`` rendered in eps space. Zero for label 0 and for
    unselected labels (no flat members).

    ``labels``: flat labels over the tree's point space (vertex space for
    deduplicated fits); recomputed via :func:`flat_labels` when omitted.
    """
    if tree.selected is None:
        raise ValueError("propagate_tree() must run before cluster_eps_min()")
    if labels is None:
        labels = flat_labels(tree)
    c = tree.n_clusters
    eps_min = np.full(c + 1, np.inf)
    mask = labels > 0
    np.minimum.at(eps_min, labels[mask], tree.point_exit_level[mask])
    eps_min[~np.isfinite(eps_min)] = 0.0
    eps_min[0] = 0.0
    return eps_min


def extract_clusters(
    n: int,
    u: np.ndarray,
    v: np.ndarray,
    w: np.ndarray,
    min_cluster_size: int | float,
    point_weights: np.ndarray | None = None,
    self_levels: np.ndarray | None = None,
    num_constraints_satisfied: np.ndarray | None = None,
    virtual_child_constraints: np.ndarray | None = None,
) -> tuple[CondensedTree, np.ndarray]:
    """Edge pool -> (propagated condensed tree, flat labels). One-call helper."""
    forest = build_merge_forest(n, u, v, w, point_weights)
    tree = condense_forest(forest, min_cluster_size, point_weights, self_levels)
    propagate_tree(tree, num_constraints_satisfied, virtual_child_constraints)
    return tree, flat_labels(tree)
