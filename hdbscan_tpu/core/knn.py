"""Core distances and mutual reachability (L3 kernel inputs).

Re-design of ``hdbscanstar/HDBSCANStar.calculateCoreDistances``
(``hdbscanstar/HDBSCANStar.java:71-106``) as a `lax.top_k` over dense distance
rows, and of the mutual-reachability computation embedded in ``constructMST``
(``hdbscanstar/HDBSCANStar.java:160-170``) as one fused matrix op.

Reference semantics (intent, with the buffer-reset bug at
``HDBSCANStar.java:79-81`` fixed — the reference hoists the kNN buffer out of
the per-point loop, which leaks state across points; the original HDBSCAN*
release resets per point, and we follow that): the core distance of a point is
the largest of its ``minPts - 1`` smallest distances over the whole row of the
self-distance matrix, whose diagonal (self-distance 0) participates — so for
``minPts == 2`` every core distance is 0 (self + 1 slot), matching
``kNNDistances[numNeighbors - 1]`` with self included in the reference scan.
``minPts == 1`` yields all zeros (``HDBSCANStar.java:75-77``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hdbscan_tpu.core.distances import self_distance_matrix


def resolve_index_for(params, n: int) -> tuple[str, dict]:
    """Resolve the configured neighbor-graph tier for an n-point job.

    Returns ``(index, index_opts)`` ready for the ``ops.tiled`` /
    ``ops.blockscan`` core-distance entry points: ``index`` is "exact" or
    "rpforest" (``config.knn_index`` with "auto" resolved at the
    ``knn_index_threshold`` flip point), and ``index_opts`` carries the
    forest knobs (trees / leaf_size / rescan_rounds / seed, plus the
    ``knn_backend``/``knn_precision`` pair that gates the fused Pallas
    forest program, ``ops/pallas_forest``) — empty for the exact tier so
    the exact call sites stay byte-identical.
    """
    from hdbscan_tpu.ops.rpforest import resolve_knn_index

    index = resolve_knn_index(
        params.knn_index, n, params.knn_index_threshold
    )
    if index == "exact":
        return index, {}
    return index, {
        "trees": params.rpf_trees,
        "leaf_size": params.rpf_leaf_size,
        "rescan_rounds": params.rpf_rescan_rounds,
        "seed": params.seed,
        "knn_backend": params.knn_backend,
        "knn_precision": params.knn_precision,
    }


def core_distances_from_matrix(
    dist: jax.Array, min_pts: int, valid: jax.Array | None = None
) -> jax.Array:
    """Core distance per row of a dense (n, n) self-distance matrix.

    ``valid``: optional (n,) bool mask for padded blocks — invalid columns are
    ignored (treated as infinitely far), invalid rows get core distance +inf so
    any downstream mutual-reachability edge through them is masked out.
    """
    n = dist.shape[0]
    inf = jnp.array(jnp.inf, dist.dtype)
    if valid is not None:
        dist = jnp.where(valid[None, :], dist, inf)
    if min_pts <= 1:
        core = jnp.zeros((n,), dist.dtype)
    else:
        k = min(min_pts - 1, n)
        neg_topk, _ = jax.lax.top_k(-dist, k)
        core = -neg_topk[:, -1]
        if valid is not None:
            # Padded block with fewer valid columns than k: top_k picked a
            # masked +inf column. Clamp to the farthest valid distance, the
            # same behavior the static min(k, n) clamp gives unpadded blocks.
            row_max = jnp.max(jnp.where(valid[None, :], dist, -inf), axis=1)
            core = jnp.where(jnp.isinf(core), row_max, core)
    if valid is not None:
        core = jnp.where(valid, core, inf)
    return core


def core_distances(x: jax.Array, min_pts: int, metric: str = "euclidean") -> jax.Array:
    """Core distances of a point block (dense O(n^2 d) path, one matmul + top_k)."""
    return core_distances_from_matrix(self_distance_matrix(x, metric), min_pts)


def mutual_reachability(dist: jax.Array, core: jax.Array) -> jax.Array:
    """MRD[i, j] = max(dist[i, j], core[i], core[j]).

    Mirrors the scalar max-chain at ``hdbscanstar/HDBSCANStar.java:163-169``,
    fused over the whole matrix. The diagonal becomes ``core[i]`` (the
    self-edge weight of ``HDBSCANStar.java:196-203``); MST construction masks
    it, and self-edges are appended explicitly by the caller.
    """
    return jnp.maximum(dist, jnp.maximum(core[:, None], core[None, :]))


def mutual_reachability_block(
    x: jax.Array, min_pts: int, metric: str = "euclidean", valid: jax.Array | None = None
) -> tuple[jax.Array, jax.Array]:
    """(MRD matrix, core distances) for one point block. jit/vmap friendly."""
    dist = self_distance_matrix(x, metric)
    core = core_distances_from_matrix(dist, min_pts, valid)
    return mutual_reachability(dist, core), core
