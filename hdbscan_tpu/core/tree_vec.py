"""Vectorized condensed-tree finalize engine (``tree_backend=vectorized``).

Array-level reimplementation of the host finalize tail in ``core/tree.py``:

- :func:`condense_forest` — condensation as a structural pass over the merge
  forest (alive-set, chain and terminal arrays via pointer doubling) plus one
  ``np.add.at`` segment-sum for stabilities, instead of the reference's
  per-node Python stack with per-exit ``subtree_points`` re-walks;
- :func:`propagate_tree` — EOM propagation as bottom-up depth rounds with
  boolean selected-set arrays (no descendant-list concatenation);
- :func:`flat_labels` — nearest-selected-ancestor pointer jumping.

Output is **bitwise identical** to the reference backend on every
``CondensedTree`` field (cuSLINK/PANDORA-style array extraction, with the
reference kept as the parity oracle — see ``tests/unit/test_tree_vec.py``).
The ordering argument: ``np.add.at`` applies repeated-index additions
sequentially in index order, so arranging the global detach-event array so
each label's events appear in the reference DFS order (chain nodes top-down =
merge-node ids descending; within a split, big kids in child order before
small kids in child order; terminal self-level events last because point ids
sort below merge ids; multi-root virtual events prepended) reproduces the
reference's float accumulation exactly. Counts are exact because detached
subtree weights equal the forest's ``sizes`` entries when point weights are
integral — :func:`supports_inputs` gates the ``auto`` backend on exactly
that.

Cluster labels must also match: the reference assigns them at split time
during a LIFO-stack DFS, so a tiny O(C) Python walk over the *cluster
skeleton* (chains + split fan-outs, not points) replays the numbering; every
per-point and per-event quantity stays vectorized.
"""

from __future__ import annotations

import numpy as np

from hdbscan_tpu.core.tree import ROOT_LABEL, CondensedTree, MergeForest


def supports_inputs(point_weights) -> bool:
    """True when the vectorized backend is bitwise-safe for these inputs.

    The one assumption the event-ordering proof needs is that a detached
    subtree's weight sum equals the forest's accumulated ``sizes`` entry,
    which holds exactly for integral (finite) point weights — ones for raw
    points, member counts for deduplicated/bubble vertices. Non-integral
    weights fall back to the reference backend under ``tree_backend=auto``.
    """
    if point_weights is None:
        return True
    pw = np.asarray(point_weights, np.float64)
    return bool(np.all(np.isfinite(pw)) and np.all(pw == np.floor(pw)))


def _fixpoint_jump(jump: np.ndarray) -> np.ndarray:
    """Pointer-double ``jump`` to its fixpoint (~log2(depth) rounds).

    Runs in int32 — node ids stay far below 2**31 and halving the gather
    bandwidth roughly halves the per-round cost at production sizes.
    """
    jump = jump.astype(np.int32, copy=False)
    while True:
        nxt = jump[jump]
        if np.array_equal(nxt, jump):
            return jump
        jump = nxt


def condense_forest(
    forest: MergeForest,
    min_cluster_size: int | float,
    point_weights: np.ndarray | None = None,
    self_levels: np.ndarray | None = None,
) -> CondensedTree:
    """Array-level :func:`hdbscan_tpu.core.tree.condense_forest`."""
    n = forest.n_points
    if point_weights is None:
        point_weights = np.ones(n, np.float64)
    point_weights = np.asarray(point_weights, np.float64)
    sizes = forest.sizes
    t = len(forest.dist)
    total = n + t
    ids = np.arange(total, dtype=np.int64)

    # --- CSR over active (non-absorbed) merge nodes ----------------------
    # The native builder ships the CSR directly (kids in list order); a
    # pure-Python forest falls back to flattening the lists.
    if forest.kids_csr is not None:
        kids_flat, kid_count = forest.kids_csr
        active_mask = kid_count > 0
        act_nodes = np.flatnonzero(active_mask).astype(np.int64) + n
        lens = kid_count[active_mask]
        n_kids = len(kids_flat)
    else:
        from itertools import chain

        kid_lists = [c for c in forest.children if c is not None]
        active_mask = np.fromiter(
            map(lambda c: c is not None, forest.children), bool, count=t
        )
        act_nodes = np.flatnonzero(active_mask).astype(np.int64) + n
        lens = np.fromiter(map(len, kid_lists), np.int64, count=len(kid_lists))
        n_kids = int(lens.sum())
        kids_flat = np.fromiter(
            chain.from_iterable(kid_lists), np.int64, count=n_kids
        )
    kid_owner = np.repeat(act_nodes, lens)
    offs = np.zeros(len(lens) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    kid_pos = np.arange(n_kids, dtype=np.int64) - np.repeat(offs[:-1], lens)
    csr_off = np.full(total, -1, np.int64)
    csr_len = np.zeros(total, np.int64)
    csr_off[act_nodes] = offs[:-1]
    csr_len[act_nodes] = lens

    par = np.full(total, -1, np.int64)
    par[kids_flat] = kid_owner

    roots = np.asarray(forest.roots, np.int64)  # ascending
    big = sizes >= min_cluster_size
    single_root = len(roots) == 1
    if single_root:
        processed_roots = roots  # the lone root is walked regardless of size
    else:
        processed_roots = roots[big[roots]]
        small_roots = roots[~big[roots]]

    # --- alive set: nodes the reference traversal actually visits --------
    # A node is alive iff it and every ancestor is big (roots per the rules
    # above). Climb to the nearest bad-or-root stop via pointer doubling:
    # stopping on a bad node means some ancestor was small.
    bad = ~big
    absorbed = np.ones(t, bool)
    absorbed[act_nodes - n] = False
    bad[n:][absorbed] = True
    bad[processed_roots] = False  # walked regardless of size
    stop = _fixpoint_jump(np.where(bad | (par < 0), ids, par))
    alive = ~bad[stop]

    # --- chain structure -------------------------------------------------
    nb = np.bincount(kid_owner[big[kids_flat]], minlength=total)  # big kids
    is_start = np.zeros(total, bool)
    nonroot_alive = alive & (par >= 0)
    is_start[nonroot_alive] = nb[par[nonroot_alive]] >= 2
    is_start[processed_roots] = True
    rep = _fixpoint_jump(np.where(is_start | ~alive, ids, par))

    is_merge = ids >= n
    terminal = alive & (~is_merge | (nb != 1))
    term_nodes = np.flatnonzero(terminal)
    term_of_start = np.full(total, -1, np.int64)
    term_of_start[rep[term_nodes]] = term_nodes

    big_kid_mask = big[kids_flat]

    # --- label numbering: O(C) replay of the reference's LIFO DFS --------
    # new_cluster() hands out labels when a split is *processed*; children
    # get consecutive labels in child order and are then explored in reverse
    # (stack pop order). Multi-root pools label big roots 2.. ascending.
    label_of_start: dict[int, int] = {}
    stack: list[int] = []
    next_label = 2
    if single_root or len(processed_roots) == 1:
        if len(processed_roots):
            s0 = int(processed_roots[0])
            label_of_start[s0] = ROOT_LABEL
            stack.append(s0)
    else:
        for r in processed_roots:  # >= 2 big roots: new clusters at +inf
            label_of_start[int(r)] = next_label
            next_label += 1
            stack.append(int(r))
    while stack:
        s = stack.pop()
        T = int(term_of_start[s])
        if T >= n and nb[T] >= 2:
            lo = csr_off[T]
            sl = slice(lo, lo + csr_len[T])
            for c in kids_flat[sl][big_kid_mask[sl]]:
                label_of_start[int(c)] = next_label
                next_label += 1
                stack.append(int(c))
    C = next_label - 1

    node_label = np.zeros(total, np.int64)
    if label_of_start:
        starts_arr = np.fromiter(label_of_start.keys(), np.int64, len(label_of_start))
        labels_arr = np.fromiter(
            label_of_start.values(), np.int64, len(label_of_start)
        )
        node_label[starts_arr] = labels_arr
    chain_label = np.zeros(total, np.int64)
    alive_idx = np.flatnonzero(alive)
    chain_label[alive_idx] = node_label[rep[alive_idx]]

    # --- per-cluster arrays ----------------------------------------------
    parent_l = np.zeros(C + 1, np.int64)
    birth = np.zeros(C + 1, np.float64)
    death = np.zeros(C + 1, np.float64)
    has_children = np.zeros(C + 1, bool)
    num_members = np.zeros(C + 1, np.float64)
    parent_l[ROOT_LABEL] = -1
    birth[ROOT_LABEL] = np.inf
    num_members[ROOT_LABEL] = float(sizes[forest.roots].sum())

    if label_of_start:
        sn = starts_arr
        lb = labels_arr
        nonroot = lb != ROOT_LABEL
        psn = par[sn]  # split node that created the cluster (-1 for roots)
        from_split = nonroot & (psn >= 0)
        parent_l[lb[from_split]] = chain_label[psn[from_split]]
        birth[lb[from_split]] = forest.dist[psn[from_split] - n]
        from_pool = nonroot & (psn < 0)  # multi-root big roots
        parent_l[lb[from_pool]] = ROOT_LABEL
        birth[lb[from_pool]] = np.inf
        num_members[lb[nonroot]] = sizes[sn[nonroot]]

        # Terminal kind decides has_children and death.
        tm = term_of_start[sn]
        split_t = (tm >= n) & (nb[tm] >= 2)
        has_children[lb] = split_t
        merge_t = tm >= n
        death[lb[merge_t]] = forest.dist[tm[merge_t] - n]
        point_t = np.flatnonzero(~merge_t)
        if self_levels is not None and len(point_t):
            death[lb[point_t]] = np.asarray(self_levels, np.float64)[tm[point_t]]
        # else: a chain ending on a point without self levels never dies (0).

    if not single_root:
        if len(processed_roots) >= 2:
            has_children[ROOT_LABEL] = True  # virtual split at +inf
        if len(processed_roots) != 1:
            death[ROOT_LABEL] = np.inf  # all mass leaves at +inf

    # --- stability: one ordered np.add.at segment sum --------------------
    # Every kid of an alive merge node detaches from the node's chain label,
    # except the lone big kid the chain continues into.
    owner_alive = alive[kid_owner]
    ev_mask = owner_alive & ~((nb[kid_owner] == 1) & big_kid_mask)
    ev_node = kid_owner[ev_mask]
    ev_label = chain_label[ev_node]
    ev_count = sizes[kids_flat[ev_mask]]
    ev_level = forest.dist[ev_node - n]
    ev_small = ~big_kid_mask[ev_mask]
    ev_pos = kid_pos[ev_mask]
    # Alive point terminals detach themselves at their self level, last in
    # their chain (point ids < n < merge ids under the descending-node key).
    alive_pts = alive_idx[alive_idx < n]
    if self_levels is not None and len(alive_pts):
        sl_arr = np.asarray(self_levels, np.float64)
        ev_node = np.concatenate([ev_node, alive_pts])
        ev_label = np.concatenate([ev_label, chain_label[alive_pts]])
        ev_count = np.concatenate([ev_count, point_weights[alive_pts]])
        ev_level = np.concatenate([ev_level, sl_arr[alive_pts]])
        ev_small = np.concatenate([ev_small, np.ones(len(alive_pts), bool)])
        ev_pos = np.concatenate([ev_pos, np.zeros(len(alive_pts), np.int64)])
    order = np.lexsort((ev_pos, ev_small, -ev_node))
    ev_label, ev_count, ev_level = ev_label[order], ev_count[order], ev_level[order]
    if not single_root:
        # Virtual root split, processed before everything else: small roots
        # exit into ROOT at +inf, then (>= 2 big) each big root detaches.
        v_nodes = [small_roots]
        if len(processed_roots) >= 2:
            v_nodes.append(processed_roots)
        v_nodes = np.concatenate(v_nodes)
        ev_label = np.concatenate(
            [np.full(len(v_nodes), ROOT_LABEL, np.int64), ev_label]
        )
        ev_count = np.concatenate([sizes[v_nodes], ev_count])
        ev_level = np.concatenate([np.full(len(v_nodes), np.inf), ev_level])

    with np.errstate(divide="ignore", invalid="ignore"):
        inv_level = np.where(ev_level == 0, np.inf, 1.0 / ev_level)
        b = birth[ev_label]
        inv_birth = np.where(np.isinf(b), 0.0, np.where(b == 0, np.inf, 1.0 / b))
        contrib = ev_count * (inv_level - inv_birth)
    stability = np.zeros(C + 1, np.float64)
    np.add.at(stability, ev_label, contrib)

    # --- per-point exit records ------------------------------------------
    point_exit_level = np.zeros(n, np.float64)
    point_last_cluster = np.full(n, ROOT_LABEL, np.int64)
    # Dead points exit where their topmost dead ancestor hangs off an alive
    # node (or at +inf into ROOT when that ancestor is an unprocessed root).
    stop_dead = alive | (par < 0) | alive[np.maximum(par, 0)]
    top_dead = _fixpoint_jump(np.where(stop_dead, ids, par))
    dead_pts = np.flatnonzero(~alive[:n])
    if len(dead_pts):
        ta = top_dead[dead_pts]
        exit_par = par[ta]
        pooled = exit_par < 0
        point_exit_level[dead_pts[pooled]] = np.inf
        inpar = dead_pts[~pooled]
        xp = exit_par[~pooled]
        point_exit_level[inpar] = forest.dist[xp - n]
        point_last_cluster[inpar] = chain_label[xp]
    if len(alive_pts):
        point_last_cluster[alive_pts] = chain_label[alive_pts]
        if self_levels is not None:
            point_exit_level[alive_pts] = np.asarray(self_levels, np.float64)[
                alive_pts
            ]

    return CondensedTree(
        n_points=n,
        parent=parent_l,
        birth=birth,
        death=death,
        stability=stability,
        has_children=has_children,
        num_members=num_members,
        point_exit_level=point_exit_level,
        point_last_cluster=point_last_cluster,
    )


def _depths(parent: np.ndarray) -> np.ndarray:
    """Per-label depth (root = 0) via pointer doubling on the parent array."""
    idx = np.arange(len(parent), dtype=np.int64)
    jump = np.where(parent > 0, parent, idx)
    depth = (parent > 0).astype(np.int64)
    while True:
        nxt = depth + depth[jump]
        if np.array_equal(nxt, depth):
            return depth
        depth = nxt
        jump = jump[jump]


def propagate_tree(
    tree: CondensedTree,
    num_constraints_satisfied: np.ndarray | None = None,
    virtual_child_constraints: np.ndarray | None = None,
) -> bool:
    """Array-level :func:`hdbscan_tpu.core.tree.propagate_tree`.

    Bottom-up depth rounds: all children of a label share one depth, and
    within a round the ``np.add.at`` index arrays are ordered by label
    descending, which is exactly the per-parent accumulation order of the
    reference's descending-label loop — so propagated stabilities match
    bitwise. Selection is the boolean form of the descendant-list mechanics:
    a label is selected iff it wins against its own subtree and no proper
    non-root ancestor also wins.
    """
    c = tree.n_clusters
    if num_constraints_satisfied is None:
        num_constraints_satisfied = np.zeros(c + 1, np.int64)
    prop_stab = np.zeros(c + 1, np.float64)
    if virtual_child_constraints is None:
        prop_cons = np.zeros(c + 1, np.int64)
    else:
        prop_cons = np.asarray(virtual_child_constraints, np.int64).copy()
    lowest_death = np.full(c + 1, np.inf)
    parent = tree.parent
    depth = _depths(parent)

    labels = np.arange(1, c + 1, dtype=np.int64)
    order = np.lexsort((-labels, -depth[labels]))  # depth desc, label desc
    labels = labels[order]
    bounds = np.flatnonzero(np.diff(depth[labels])) + 1
    groups = np.split(labels, bounds)

    self_wins = np.zeros(c + 1, bool)
    for grp in groups:  # deepest first; parents always in a later round
        fix = lowest_death[grp] == np.inf
        lowest_death[grp[fix]] = tree.death[grp[fix]]
        up = parent[grp]
        m = up > 0
        lbl, up = grp[m], up[m]
        if not len(lbl):
            continue
        own_cons = num_constraints_satisfied[lbl]
        own_stab = tree.stability[lbl]
        wins = (
            ~tree.has_children[lbl]
            | (own_cons > prop_cons[lbl])
            | ((own_cons == prop_cons[lbl]) & (own_stab >= prop_stab[lbl]))
        )
        self_wins[lbl] = wins
        np.add.at(prop_cons, up, np.where(wins, own_cons, prop_cons[lbl]))
        np.add.at(prop_stab, up, np.where(wins, own_stab, prop_stab[lbl]))
        np.minimum.at(lowest_death, up, lowest_death[lbl])

    # blocked[L]: some proper non-root ancestor self-wins (its subtree list
    # never reaches the root's descendant set). Top-down rounds.
    blocked = np.zeros(c + 1, bool)
    for grp in groups[::-1]:
        up = parent[grp]
        m = up > 0
        lbl, up = grp[m], up[m]
        blocked[lbl] = blocked[up] | (self_wins[up] & (parent[up] > 0))
    selected = self_wins & ~blocked & (parent > 0)

    tree.propagated_stability = prop_stab
    tree.lowest_child_death = lowest_death
    tree.num_constraints_satisfied = num_constraints_satisfied
    tree.virtual_child_constraints = virtual_child_constraints
    tree.selected = selected
    return tree.infinite_stability


def selected_ancestors(tree: CondensedTree) -> np.ndarray:
    """Per-label nearest selected ancestor-or-self (0 = noise) via pointer
    doubling — the jump table behind :func:`flat_labels`, exposed on its own
    because serving (``serve/predict.py``) indexes it with *query* attachment
    clusters rather than the training points' last clusters."""
    if tree.selected is None:
        raise ValueError("propagate_tree() must run before selected_ancestors()")
    c = tree.n_clusters
    idx = np.arange(c + 1, dtype=np.int64)
    jump = np.where(tree.selected, idx, np.where(tree.parent > 0, tree.parent, 0))
    return _fixpoint_jump(jump).astype(np.int64)


def flat_labels(tree: CondensedTree) -> np.ndarray:
    """Array-level :func:`hdbscan_tpu.core.tree.flat_labels`."""
    return selected_ancestors(tree)[tree.point_last_cluster]
