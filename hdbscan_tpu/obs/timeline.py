"""Per-device phase timelines: comm/compute/host attribution + stragglers.

Every sharded/ring round (``parallel/ring.py``, ``parallel/shard.py``,
``core/mst_device.py``) reports its measured per-device walls here, and the
recorder decomposes each device's round into three telescoping segments:

- ``host_s`` — the measured host segments bracketing the round (operand
  ``device_put`` upload + contraction fetch). These serialize every
  device, so the same measured value lands on each device's row.
- ``comm_s`` — the ppermute / panel-exchange share of the device-exec
  wall, attributed from the bytes the device moved over the ring.
- ``compute_s`` — the remainder of the device-exec wall (local panel
  scans).

Separating fused collective time from compute inside one jitted program
is impossible without a hardware profiler, so the comm/compute split is a
*cost-model attribution* of the measured exec wall (``attribution:
"model"`` rides every event): the model times ``comm_bytes /
MODEL_COMM_BYTES_S`` vs ``flops / PEAK_FLOPS`` only set the *ratio*; the
measured wall sets the total. The invariant every consumer
(``scripts/check_trace.py``, the forced-8-device tests) holds us to is

    ``compute_s + comm_s + host_s == wall_s``  (within 1e-6)

for every ``device_timeline`` event.

Per-round skew stats (max/median device wall) feed the straggler
detector: a device whose raw wall is ``>= skew_threshold x`` the round
median for ``straggler_rounds`` consecutive rounds is flagged — a
``straggler_flag`` trace event, one
``hdbscan_tpu_straggler_flags_total{device}`` increment per flagged
round, and the ``/healthz`` ``straggler`` block all carry it.
"""

from __future__ import annotations

import threading

__all__ = [
    "TimelineRecorder",
    "DEFAULT_SKEW_THRESHOLD",
    "DEFAULT_STRAGGLER_ROUNDS",
    "MODEL_COMM_BYTES_S",
]

#: Default straggler trip: a device at 2x the round-median wall is slow
#: enough to matter and rare enough not to false-positive on a shared-core
#: CPU mesh (config knob ``obs_skew_threshold``).
DEFAULT_SKEW_THRESHOLD = 2.0

#: Default K: consecutive flagged rounds before a straggler_flag fires
#: (config knob ``obs_straggler_rounds``).
DEFAULT_STRAGGLER_ROUNDS = 3

#: Cost-model link bandwidth for the comm share of an exec wall (~one ICI
#: link). Only the ratio against ``flops.PEAK_FLOPS`` matters — both legs
#: scale the same measured wall.
MODEL_COMM_BYTES_S = 45e9


def _split_exec(exec_s: float, comm_bytes: float, flops: float):
    """Split a measured device-exec wall into (compute_s, comm_s) by the
    cost-model ratio. ``compute_s = exec_s - comm_s`` exactly, so the two
    always telescope back to the measured wall."""
    from hdbscan_tpu.utils import flops as _flops

    if exec_s <= 0.0:
        return 0.0, 0.0
    comm_t = max(float(comm_bytes), 0.0) / MODEL_COMM_BYTES_S
    comp_t = max(float(flops), 0.0) / float(_flops.PEAK_FLOPS)
    denom = comm_t + comp_t
    if denom <= 0.0:
        return exec_s, 0.0
    comm_s = exec_s * (comm_t / denom)
    return exec_s - comm_s, comm_s


def _median(values: list[float]) -> float:
    s = sorted(values)
    n = len(s)
    if n % 2:
        return s[n // 2]
    return 0.5 * (s[n // 2 - 1] + s[n // 2])


class TimelineRecorder:
    """Accumulates per-device round timelines and detects stragglers.

    Parameters
    ----------
    skew_threshold:
        A device is flagged in a round when its raw wall is
        ``>= skew_threshold * median`` of the round's device walls
        (requires >= 2 devices and a positive median). Must be >= 1.
    straggler_rounds:
        K consecutive flagged rounds before ``straggler_flag`` fires
        (and keeps firing each further flagged round). Must be >= 1.
    straggler_counter:
        Optional metrics counter; ``inc(1.0, device=<id>)`` per flagged
        round (``hdbscan_tpu_straggler_flags_total{device}``).
    trace:
        Default ``Tracer`` for emission; ``record_round``'s ``trace=``
        argument overrides per call.
    """

    def __init__(self, skew_threshold: float = DEFAULT_SKEW_THRESHOLD,
                 straggler_rounds: int = DEFAULT_STRAGGLER_ROUNDS,
                 straggler_counter=None, trace=None):
        skew_threshold = float(skew_threshold)
        if not skew_threshold >= 1.0:
            raise ValueError(
                f"skew_threshold must be >= 1.0, got {skew_threshold!r}"
            )
        straggler_rounds = int(straggler_rounds)
        if straggler_rounds < 1:
            raise ValueError(
                f"straggler_rounds must be >= 1, got {straggler_rounds!r}"
            )
        self.skew_threshold = skew_threshold
        self.straggler_rounds = straggler_rounds
        self.straggler_counter = straggler_counter
        self.trace = trace
        self._lock = threading.Lock()
        # phase -> running totals joined by roofline.py / the report
        self._phases: dict[str, dict] = {}
        # device id -> consecutive flagged rounds / total flags fired
        self._streaks: dict[int, int] = {}
        self._flags: dict[int, int] = {}
        self._rounds = 0

    # -- recording ---------------------------------------------------------

    def record_round(self, phase: str, rnd: int, walls, *, upload_s=0.0,
                     fetch_s=0.0, comm_bytes=0, flops=0.0,
                     trace=None) -> dict | None:
        """Record one sharded/ring round and emit its timeline events.

        ``walls`` is ``[(device_id, exec_wall_s), ...]`` — each device's
        measured wall from round *dispatch* to its shard ready, the shape
        ``parallel/ring._per_device_walls`` produces. ``upload_s`` /
        ``fetch_s`` are the measured host segments bracketing the
        dispatch (operand ``device_put`` before, contraction fetch
        after); a device's timeline wall is ``upload_s + exec + fetch_s``
        so the three segments telescope exactly. ``comm_bytes`` is the
        bytes ONE device moved over the ring this round; ``flops`` is
        the round's total FLOPs across devices. Returns the round's skew
        stats (also folded into the phase table), or None for an empty
        round.
        """
        trace = trace if trace is not None else self.trace
        walls = [(int(d), float(w)) for d, w in walls]
        if not walls:
            return None
        n_dev = len(walls)
        upload_s = max(float(upload_s), 0.0)
        fetch_s = max(float(fetch_s), 0.0)
        comm_bytes = max(int(comm_bytes), 0)
        raw = [w for _, w in walls]
        median = _median(raw)
        max_wall = max(raw)
        skew = (max_wall / median) if median > 0 else 1.0

        rows = []  # (device, wall_s, compute_s, comm_s, host_s)
        for dev, w in walls:
            # A device's round = upload (host) + exec (its measured wall
            # from dispatch) + fetch (host): the segments telescope by
            # construction, never by clamping.
            wall_d = upload_s + w + fetch_s
            host_s = upload_s + fetch_s
            comp, comm = _split_exec(w, comm_bytes, flops / n_dev)
            rows.append((dev, wall_d, comp, comm, host_s))

        flagged = []  # (device, wall, streak)
        with self._lock:
            self._rounds += 1
            for dev, w in walls:
                slow = n_dev >= 2 and median > 0 and (
                    w >= self.skew_threshold * median
                )
                streak = self._streaks.get(dev, 0) + 1 if slow else 0
                self._streaks[dev] = streak
                if streak >= self.straggler_rounds:
                    self._flags[dev] = self._flags.get(dev, 0) + 1
                    flagged.append((dev, w, streak))
            ph = self._phases.setdefault(phase, {
                "rounds": 0,
                "devices": 0,
                "wall_s": 0.0,
                "compute_s": 0.0,
                "comm_s": 0.0,
                "host_s": 0.0,
                "comm_bytes": 0,
                "flops": 0.0,
                "max_skew": 1.0,
            })
            ph["rounds"] += 1
            ph["devices"] = max(ph["devices"], n_dev)
            # Critical path: the slowest device bounds the round.
            ph["wall_s"] += max(r[1] for r in rows)
            ph["compute_s"] += sum(r[2] for r in rows) / n_dev
            ph["comm_s"] += sum(r[3] for r in rows) / n_dev
            ph["host_s"] += sum(r[4] for r in rows) / n_dev
            ph["comm_bytes"] += comm_bytes * n_dev
            ph["flops"] += max(float(flops), 0.0)
            ph["max_skew"] = max(ph["max_skew"], skew)

        counter = self.straggler_counter
        if counter is not None:
            for dev, _, _ in flagged:
                counter.inc(1.0, device=str(dev))

        if trace is not None:
            for dev, wall_d, comp, comm, host_s in rows:
                trace(
                    "device_timeline",
                    wall_s=round(wall_d, 9),
                    phase=phase,
                    round=int(rnd),
                    device=dev,
                    compute_s=round(comp, 9),
                    comm_s=round(comm, 9),
                    host_s=round(host_s, 9),
                    comm_bytes=comm_bytes,
                    attribution="model",
                )
            for dev, w, streak in flagged:
                trace(
                    "straggler_flag",
                    device=dev,
                    phase=phase,
                    round=int(rnd),
                    streak=streak,
                    wall_s=round(w, 9),
                    median_s=round(median, 9),
                    ratio=round(w / median, 6),
                    threshold=self.skew_threshold,
                )
        return {
            "skew": round(skew, 6),
            "max_wall_s": round(max_wall, 9),
            "median_wall_s": round(median, 9),
            "flagged": [dev for dev, _, _ in flagged],
        }

    def record_modeled_rounds(self, phase: str, rounds: int, walls, *,
                              upload_s=0.0, fetch_s=0.0, comm_bytes=0,
                              flops=0.0, trace=None) -> dict | None:
        """Record an in-jit multi-round program as modeled per-round rows.

        A ``while_loop`` over Borůvka rounds executes all rounds inside ONE
        dispatch (``parallel/shard.shard_boruvka_mst``), so per-round host
        walls do not exist — only the program's per-device walls and the
        round-count counter the fetch landed. This splits each device's
        measured wall evenly across ``rounds`` and replays them through
        :meth:`record_round` so the ``device_timeline`` rows, phase totals
        and straggler detector see the same shape as host-stepped rounds.
        The host segments stay where they physically happened — ``upload_s``
        on round 0, ``fetch_s`` on the last — and ``comm_bytes``/``flops``
        split evenly (round 0 takes the integer remainder). The split is a
        model, same as the comm/compute attribution (``attribution:
        "model"`` already rides every row). Returns the LAST round's skew
        stats, or None for an empty program.
        """
        rounds = max(int(rounds), 1)
        walls = [(int(d), float(w) / rounds) for d, w in walls]
        comm_bytes = max(int(comm_bytes), 0)
        per_comm, rem_comm = divmod(comm_bytes, rounds)
        stats = None
        for r in range(rounds):
            stats = self.record_round(
                phase, r, walls,
                upload_s=upload_s if r == 0 else 0.0,
                fetch_s=fetch_s if r == rounds - 1 else 0.0,
                comm_bytes=per_comm + (rem_comm if r == 0 else 0),
                flops=max(float(flops), 0.0) / rounds,
                trace=trace,
            )
        return stats

    # -- reporting ---------------------------------------------------------

    def phase_table(self) -> dict[str, dict]:
        """Per-phase timeline totals with derived ``comm_frac``/``skew``
        (deep-copied; safe to serialize into the report)."""
        with self._lock:
            out = {}
            for name, ph in self._phases.items():
                total = ph["compute_s"] + ph["comm_s"] + ph["host_s"]
                out[name] = {
                    "rounds": ph["rounds"],
                    "devices": ph["devices"],
                    "wall_s": round(ph["wall_s"], 9),
                    "compute_s": round(ph["compute_s"], 9),
                    "comm_s": round(ph["comm_s"], 9),
                    "host_s": round(ph["host_s"], 9),
                    "comm_bytes": ph["comm_bytes"],
                    "flops": ph["flops"],
                    "comm_frac": (
                        round(ph["comm_s"] / total, 6) if total > 0 else 0.0
                    ),
                    "skew": round(ph["max_skew"], 6),
                }
            return out

    def state(self) -> dict:
        """Live detector state for ``/healthz`` (``straggler`` block)."""
        with self._lock:
            return {
                "skew_threshold": self.skew_threshold,
                "straggler_rounds": self.straggler_rounds,
                "rounds": self._rounds,
                "flags_total": sum(self._flags.values()),
                "flags": {str(d): n for d, n in sorted(self._flags.items())},
                "streaks": {
                    str(d): s for d, s in sorted(self._streaks.items()) if s
                },
            }
