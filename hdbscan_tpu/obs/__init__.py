"""Fit-path deep observability (README "Observability").

Three layers, all opt-in and process-global (mirroring the
``hdbscan_tpu/fault`` harness's install pattern):

- :class:`~hdbscan_tpu.obs.audit.MemoryAuditor` — a per-phase device-memory
  auditor. Instrumented pipeline sites wrap their work in
  :func:`mem_phase`, which samples per-device bytes synchronously at entry/
  exit plus on a background thread, emits ``mem_sample`` / ``mem_phase_peak``
  trace events, and accumulates a per-phase watermark table for the run
  report. ``assert_not_replicated(n, itemsize)`` turns ROADMAP item 1's
  "no replicated O(n) buffer survives on any single device" into a hard
  gate over those watermarks.
- :class:`~hdbscan_tpu.obs.heartbeat.Heartbeats` — progress heartbeats and
  a hang watchdog. Long loops (Borůvka rounds, ring panel sweeps, rpforest
  tree builds, background refits) open a :func:`task` and ``beat(done,
  total)`` each iteration; throttled ``heartbeat`` trace events carry a
  monotone progress fraction and ETA, and a watchdog thread dumps every
  Python thread's stack to the trace and stderr when no beat arrives
  within ``watchdog_s``.
- :mod:`~hdbscan_tpu.obs.correlate` — fleet trace correlation: joins the
  router's ``router_span`` events with replica ``request_span`` /
  ``request_shed`` events on the propagated ``X-Request-Id``, so one
  request's causal chain reconstructs across processes.
- :class:`~hdbscan_tpu.obs.timeline.TimelineRecorder` — per-device phase
  timelines: every sharded/ring round decomposes into telescoping
  ``compute_s``/``comm_s``/``host_s`` segments (``device_timeline``
  events), per-round skew stats feed the straggler detector
  (``straggler_flag`` events + ``hdbscan_tpu_straggler_flags_total``).
- :class:`~hdbscan_tpu.obs.flightrec.FlightRecorder` — the crash/stall
  black box: a bounded ring of recent trace events that dumps a
  self-contained post-mortem bundle on watchdog stall, replication-gate
  trip, SLO breach, unhandled exception, or SIGTERM.

The uninstalled fast path is one module-attribute load + ``is None`` test
per instrumented site (the same contract ``fault/inject.py`` keeps): fit
paths pay nothing unless :func:`install` ran.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager, nullcontext

from hdbscan_tpu.obs.audit import (
    MemoryAuditor,
    ReplicatedBufferError,
    donation_guard,
)
from hdbscan_tpu.obs.correlate import join_spans, merge_fleet_traces
from hdbscan_tpu.obs.flightrec import FlightRecorder
from hdbscan_tpu.obs.heartbeat import Heartbeats
from hdbscan_tpu.obs.timeline import TimelineRecorder

__all__ = [
    "MemoryAuditor",
    "ReplicatedBufferError",
    "donation_guard",
    "Heartbeats",
    "TimelineRecorder",
    "FlightRecorder",
    "join_spans",
    "merge_fleet_traces",
    "install",
    "clear",
    "auditor",
    "heartbeats",
    "timeline",
    "flight",
    "mem_phase",
    "task",
    "beat",
    "watchdog_state",
    "straggler_state",
    "assert_not_replicated",
]


class _NullTask:
    """No-op stand-in yielded by :func:`task` when heartbeats are off."""

    __slots__ = ()

    def beat(self, done, total=None) -> None:
        pass


_NULL_TASK = _NullTask()

# Process-wide installs checked by every instrumented site. None = off: the
# hot-path cost of the uninstalled layer is one attribute load + is-None.
_AUDITOR: MemoryAuditor | None = None
_HEARTBEATS: Heartbeats | None = None
_TIMELINE: TimelineRecorder | None = None
_FLIGHT: FlightRecorder | None = None
_INSTALL_LOCK = threading.Lock()


def install(auditor=None, heartbeats=None, timeline=None, flight=None) -> None:
    """Install the process-wide auditor / heartbeat hub / timeline recorder
    / flight recorder. Passing None for any layer leaves it as it was
    (install them independently)."""
    global _AUDITOR, _HEARTBEATS, _TIMELINE, _FLIGHT
    with _INSTALL_LOCK:
        if auditor is not None:
            _AUDITOR = auditor
        if heartbeats is not None:
            _HEARTBEATS = heartbeats
        if timeline is not None:
            _TIMELINE = timeline
        if flight is not None:
            _FLIGHT = flight


def clear() -> None:
    """Remove every layer (instrumented sites go back to no-ops)."""
    global _AUDITOR, _HEARTBEATS, _TIMELINE, _FLIGHT
    with _INSTALL_LOCK:
        if _HEARTBEATS is not None:
            _HEARTBEATS.close()
        _AUDITOR = None
        _HEARTBEATS = None
        _TIMELINE = None
        _FLIGHT = None


def auditor() -> MemoryAuditor | None:
    return _AUDITOR


def heartbeats() -> Heartbeats | None:
    return _HEARTBEATS


def timeline() -> TimelineRecorder | None:
    return _TIMELINE


def flight() -> FlightRecorder | None:
    return _FLIGHT


def mem_phase(name: str):
    """Context manager auditing device memory around a traced phase; a
    ``nullcontext`` when no auditor is installed."""
    aud = _AUDITOR
    if aud is None:
        return nullcontext()
    return aud.phase(name)


def task(phase: str, total=None):
    """Context manager opening a heartbeat task for a progress loop; yields
    an object with ``beat(done, total=None)`` (a no-op when heartbeats are
    off, so call sites never branch)."""
    hb = _HEARTBEATS
    if hb is None:
        return nullcontext(_NULL_TASK)
    return hb.task(phase, total=total)


def beat(phase: str, done, total=None) -> None:
    """One-shot heartbeat outside a :func:`task` scope (rarely needed —
    prefer the task context so the watchdog knows what is in flight)."""
    hb = _HEARTBEATS
    if hb is None:
        return
    with hb.task(phase, total=total) as t:
        t.beat(done, total=total)


def watchdog_state() -> dict | None:
    """The heartbeat hub's live state for ``/healthz``; None when off."""
    hb = _HEARTBEATS
    if hb is None:
        return None
    return hb.state()


def straggler_state() -> dict | None:
    """The timeline recorder's straggler-detector state for ``/healthz``;
    None when no timeline recorder is installed."""
    tl = _TIMELINE
    if tl is None:
        return None
    return tl.state()


def assert_not_replicated(n, itemsize, slack=0.5, phases=None) -> dict:
    """Delegate to the installed auditor's replication gate. Raises
    :class:`RuntimeError` when no auditor is installed — a gate that was
    requested but never armed must fail loudly, not pass vacuously."""
    aud = _AUDITOR
    if aud is None:
        raise RuntimeError(
            "assert_not_replicated: no MemoryAuditor installed "
            "(obs.install(auditor=...) before the fit)"
        )
    return aud.assert_not_replicated(n, itemsize, slack=slack, phases=phases)


@contextmanager
def installed(auditor=None, heartbeats=None, timeline=None, flight=None):
    """Scoped install for tests: install, yield, restore previous layers."""
    global _AUDITOR, _HEARTBEATS, _TIMELINE, _FLIGHT
    with _INSTALL_LOCK:
        prev = (_AUDITOR, _HEARTBEATS, _TIMELINE, _FLIGHT)
        if auditor is not None:
            _AUDITOR = auditor
        if heartbeats is not None:
            _HEARTBEATS = heartbeats
        if timeline is not None:
            _TIMELINE = timeline
        if flight is not None:
            _FLIGHT = flight
    try:
        yield
    finally:
        with _INSTALL_LOCK:
            if heartbeats is not None and heartbeats is not prev[1]:
                heartbeats.close()
            _AUDITOR, _HEARTBEATS, _TIMELINE, _FLIGHT = prev
