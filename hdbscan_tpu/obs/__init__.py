"""Fit-path deep observability (README "Observability").

Three layers, all opt-in and process-global (mirroring the
``hdbscan_tpu/fault`` harness's install pattern):

- :class:`~hdbscan_tpu.obs.audit.MemoryAuditor` — a per-phase device-memory
  auditor. Instrumented pipeline sites wrap their work in
  :func:`mem_phase`, which samples per-device bytes synchronously at entry/
  exit plus on a background thread, emits ``mem_sample`` / ``mem_phase_peak``
  trace events, and accumulates a per-phase watermark table for the run
  report. ``assert_not_replicated(n, itemsize)`` turns ROADMAP item 1's
  "no replicated O(n) buffer survives on any single device" into a hard
  gate over those watermarks.
- :class:`~hdbscan_tpu.obs.heartbeat.Heartbeats` — progress heartbeats and
  a hang watchdog. Long loops (Borůvka rounds, ring panel sweeps, rpforest
  tree builds, background refits) open a :func:`task` and ``beat(done,
  total)`` each iteration; throttled ``heartbeat`` trace events carry a
  monotone progress fraction and ETA, and a watchdog thread dumps every
  Python thread's stack to the trace and stderr when no beat arrives
  within ``watchdog_s``.
- :mod:`~hdbscan_tpu.obs.correlate` — fleet trace correlation: joins the
  router's ``router_span`` events with replica ``request_span`` /
  ``request_shed`` events on the propagated ``X-Request-Id``, so one
  request's causal chain reconstructs across processes.

The uninstalled fast path is one module-attribute load + ``is None`` test
per instrumented site (the same contract ``fault/inject.py`` keeps): fit
paths pay nothing unless :func:`install` ran.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager, nullcontext

from hdbscan_tpu.obs.audit import (
    MemoryAuditor,
    ReplicatedBufferError,
    donation_guard,
)
from hdbscan_tpu.obs.correlate import join_spans, merge_fleet_traces
from hdbscan_tpu.obs.heartbeat import Heartbeats

__all__ = [
    "MemoryAuditor",
    "ReplicatedBufferError",
    "donation_guard",
    "Heartbeats",
    "join_spans",
    "merge_fleet_traces",
    "install",
    "clear",
    "auditor",
    "heartbeats",
    "mem_phase",
    "task",
    "beat",
    "watchdog_state",
    "assert_not_replicated",
]


class _NullTask:
    """No-op stand-in yielded by :func:`task` when heartbeats are off."""

    __slots__ = ()

    def beat(self, done, total=None) -> None:
        pass


_NULL_TASK = _NullTask()

# Process-wide installs checked by every instrumented site. None = off: the
# hot-path cost of the uninstalled layer is one attribute load + is-None.
_AUDITOR: MemoryAuditor | None = None
_HEARTBEATS: Heartbeats | None = None
_INSTALL_LOCK = threading.Lock()


def install(auditor=None, heartbeats=None) -> None:
    """Install the process-wide auditor and/or heartbeat hub. Passing None
    for either leaves that layer as it was (install them independently)."""
    global _AUDITOR, _HEARTBEATS
    with _INSTALL_LOCK:
        if auditor is not None:
            _AUDITOR = auditor
        if heartbeats is not None:
            _HEARTBEATS = heartbeats


def clear() -> None:
    """Remove both layers (instrumented sites go back to no-ops)."""
    global _AUDITOR, _HEARTBEATS
    with _INSTALL_LOCK:
        if _HEARTBEATS is not None:
            _HEARTBEATS.close()
        _AUDITOR = None
        _HEARTBEATS = None


def auditor() -> MemoryAuditor | None:
    return _AUDITOR


def heartbeats() -> Heartbeats | None:
    return _HEARTBEATS


def mem_phase(name: str):
    """Context manager auditing device memory around a traced phase; a
    ``nullcontext`` when no auditor is installed."""
    aud = _AUDITOR
    if aud is None:
        return nullcontext()
    return aud.phase(name)


def task(phase: str, total=None):
    """Context manager opening a heartbeat task for a progress loop; yields
    an object with ``beat(done, total=None)`` (a no-op when heartbeats are
    off, so call sites never branch)."""
    hb = _HEARTBEATS
    if hb is None:
        return nullcontext(_NULL_TASK)
    return hb.task(phase, total=total)


def beat(phase: str, done, total=None) -> None:
    """One-shot heartbeat outside a :func:`task` scope (rarely needed —
    prefer the task context so the watchdog knows what is in flight)."""
    hb = _HEARTBEATS
    if hb is None:
        return
    with hb.task(phase, total=total) as t:
        t.beat(done, total=total)


def watchdog_state() -> dict | None:
    """The heartbeat hub's live state for ``/healthz``; None when off."""
    hb = _HEARTBEATS
    if hb is None:
        return None
    return hb.state()


def assert_not_replicated(n, itemsize, slack=0.5, phases=None) -> dict:
    """Delegate to the installed auditor's replication gate. Raises
    :class:`RuntimeError` when no auditor is installed — a gate that was
    requested but never armed must fail loudly, not pass vacuously."""
    aud = _AUDITOR
    if aud is None:
        raise RuntimeError(
            "assert_not_replicated: no MemoryAuditor installed "
            "(obs.install(auditor=...) before the fit)"
        )
    return aud.assert_not_replicated(n, itemsize, slack=slack, phases=phases)


@contextmanager
def installed(auditor=None, heartbeats=None):
    """Scoped install for tests: install, yield, restore previous layers."""
    global _AUDITOR, _HEARTBEATS
    with _INSTALL_LOCK:
        prev = (_AUDITOR, _HEARTBEATS)
        if auditor is not None:
            _AUDITOR = auditor
        if heartbeats is not None:
            _HEARTBEATS = heartbeats
    try:
        yield
    finally:
        with _INSTALL_LOCK:
            if heartbeats is not None and heartbeats is not prev[1]:
                heartbeats.close()
            _AUDITOR, _HEARTBEATS = prev
