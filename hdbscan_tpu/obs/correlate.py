"""Fleet trace correlation: join router spans to replica spans.

The fleet router stamps (or propagates) an ``X-Request-Id`` on every
proxied request and emits a ``router_span`` trace event per request
(route decision, chosen replica, queue wait, attempt count). The replica
that served it emits its existing ``request_span`` — or ``request_shed``
when it refused — carrying the same id. :func:`join_spans` reconstructs
the causal chain: every *replied* router span must join exactly one
replica-side event, bitwise on the request id.

:func:`merge_fleet_traces` is the fleet analogue of
``telemetry.merge_host_traces``: it reads the router's trace plus each
replica's, computes per-side phase aggregates, and attaches the join so
one artifact answers "what happened to request X, end to end".
"""

from __future__ import annotations

from hdbscan_tpu.utils import telemetry

_REPLICA_SPAN_STAGES = ("request_span", "request_shed")


def _as_dict(ev):
    return ev if isinstance(ev, dict) else {**ev.fields, "stage": ev.name}


def _stage(ev) -> str:
    return ev.get("stage", "") if isinstance(ev, dict) else ev.name


def join_spans(router_events, replica_events) -> dict:
    """Join ``router_span`` events against replica request spans by id.

    Returns a stats dict: total router spans, how many were ``replied``
    (the router actually relayed a replica response — only those can
    join), matched count, plus the offending ids in ``orphans`` (no
    replica event) and ``duplicates`` (more than one). A chain
    reconstruction is 100% when ``matched == replied`` and both lists
    are empty.
    """
    replica_ids: dict[str, int] = {}
    for ev in replica_events:
        if _stage(ev) in _REPLICA_SPAN_STAGES:
            d = _as_dict(ev)
            rid = d.get("request_id")
            if rid:
                replica_ids[str(rid)] = replica_ids.get(str(rid), 0) + 1

    total = replied = matched = 0
    orphans: list[str] = []
    duplicates: list[str] = []
    for ev in router_events:
        if _stage(ev) != "router_span":
            continue
        total += 1
        d = _as_dict(ev)
        if not d.get("replied"):
            continue
        replied += 1
        rid = str(d.get("request_id", ""))
        count = replica_ids.get(rid, 0)
        if count == 0:
            orphans.append(rid)
        elif count > 1:
            duplicates.append(rid)
        else:
            matched += 1
    return {
        "router_spans": total,
        "replied": replied,
        "matched": matched,
        "orphans": orphans,
        "duplicates": duplicates,
        "complete": replied > 0 and matched == replied,
    }


def merge_fleet_traces(router_path, replica_paths) -> dict:
    """Merge a router trace with its replicas' traces into one summary.

    Mirrors ``telemetry.merge_host_traces``'s shape: per-side phase
    aggregates keyed by trace path, plus the router↔replica span join.
    """
    router_events = telemetry.read_trace(router_path)
    replica_events = []
    replicas = {}
    for path in replica_paths:
        events = telemetry.read_trace(path)
        replica_events.extend(events)
        replicas[str(path)] = {
            "events": len(events),
            "phases": telemetry.phase_aggregates(events),
        }
    return {
        "router": {
            "path": str(router_path),
            "events": len(router_events),
            "phases": telemetry.phase_aggregates(router_events),
        },
        "replicas": replicas,
        "join": join_spans(router_events, replica_events),
    }
