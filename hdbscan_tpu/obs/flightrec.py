"""Crash/stall flight recorder: a bounded black box that survives the run.

When the watchdog fires or a bench leg regresses, the evidence usually
evaporates with the process. The :class:`FlightRecorder` is an always-on
bounded ring of the most recent trace events (it attaches to the run's
:class:`~hdbscan_tpu.utils.tracing.Tracer` as one more sink, so it costs
one deque append per event) plus the last N heartbeats, and on a trigger
dumps one self-contained post-mortem bundle to ``--flight-dir``:

- the event tail (the stalling phase's last events included),
- the last N ``heartbeat`` events,
- every Python thread's stack at dump time,
- the installed auditor's per-phase watermarks + per-device peaks,
- the heartbeat hub's watchdog state and the timeline recorder's
  straggler state,
- the run manifest (when the CLI provided one) and the trigger's extra
  context.

Triggers: ``watchdog_stall`` (automatic — the recorder sniffs the event
stream), ``ReplicatedBufferError`` / unhandled fit exception / SIGTERM
(``cli.py`` calls :meth:`FlightRecorder.dump`), and SLO breach
(``bench.py slo``). ``scripts/check_flight.py`` validates and
pretty-prints bundles.

Schema ``hdbscan-tpu-flight/1``; one JSON file per dump, named
``flight-<pid>-<seq>-<reason>.json``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

__all__ = [
    "FLIGHT_SCHEMA",
    "DUMP_REASONS",
    "FlightRecorder",
]

#: Version tag carried by every bundle; ``scripts/check_flight.py``
#: validates the prefix.
FLIGHT_SCHEMA = "hdbscan-tpu-flight/1"

#: The trigger vocabulary. ``check_flight.py`` rejects unknown reasons so
#: a typo'd ad-hoc dump can't slip into a post-mortem unnoticed.
DUMP_REASONS = (
    "watchdog_stall",
    "replication_gate",
    "slo_breach",
    "exception",
    "sigterm",
    "manual",
)


class FlightRecorder:
    """Bounded trace-event ring + post-mortem bundle writer.

    Parameters
    ----------
    out_dir:
        Directory bundles dump into (created on first need, not at
        construction — an armed recorder on a healthy run leaves no
        filesystem trace).
    capacity:
        Ring size: the newest ``capacity`` events are retained. >= 16.
    heartbeat_tail:
        ``heartbeat`` events kept in their own tail (they drown in a
        busy ring otherwise). >= 1.
    manifest:
        Optional run-manifest dict embedded in every bundle.
    tracer:
        Optional ``Tracer``; explicit :meth:`dump` calls emit a
        ``flight_dump`` event through it. The automatic watchdog dump
        never re-enters the tracer (it runs inside the tracer's emit
        lock), so it records the dump in the bundle alone.
    """

    def __init__(self, out_dir: str, capacity: int = 2048,
                 heartbeat_tail: int = 32, manifest: dict | None = None,
                 tracer=None):
        capacity = int(capacity)
        if capacity < 16:
            raise ValueError(f"capacity must be >= 16, got {capacity!r}")
        heartbeat_tail = int(heartbeat_tail)
        if heartbeat_tail < 1:
            raise ValueError(
                f"heartbeat_tail must be >= 1, got {heartbeat_tail!r}"
            )
        self.out_dir = str(out_dir)
        self.capacity = capacity
        self.manifest = manifest
        self.tracer = tracer
        self._events: deque = deque(maxlen=capacity)
        self._heartbeats: deque = deque(maxlen=heartbeat_tail)
        self._seen = 0
        self._lock = threading.Lock()
        self.dumps: list[str] = []

    # -- Tracer sink protocol ----------------------------------------------

    def emit(self, ev) -> None:
        from hdbscan_tpu.utils.telemetry import json_sanitize

        rec = {
            "stage": ev.name,
            "wall_s": float(ev.wall_s),
            **json_sanitize(ev.fields),
        }
        with self._lock:
            self._seen += 1
            self._events.append(rec)
            if ev.name == "heartbeat":
                self._heartbeats.append(rec)
        if ev.name == "watchdog_stall":
            # Sink emits run inside the tracer's emit lock: write the
            # bundle but do NOT re-enter the tracer (deadlock).
            self.dump("watchdog_stall", extra={"stall": rec},
                      emit_event=False)

    def close(self) -> None:  # sinks own no file handle between dumps
        pass

    # -- dumping -----------------------------------------------------------

    def snapshot(self) -> dict:
        """The ring's current contents (tests and /healthz peeks)."""
        with self._lock:
            return {
                "events": list(self._events),
                "heartbeats": list(self._heartbeats),
                "events_seen": self._seen,
                "dumps": list(self.dumps),
            }

    def dump(self, reason: str, extra: dict | None = None,
             emit_event: bool = True) -> str:
        """Write one self-contained post-mortem bundle; returns its path.

        Never raises on best-effort sections (auditor/watchdog/timeline
        state): a flight recorder that crashes the crash path is worse
        than a partial bundle.
        """
        from hdbscan_tpu import obs
        from hdbscan_tpu.obs.heartbeat import _format_stacks
        from hdbscan_tpu.utils.telemetry import json_sanitize

        if reason not in DUMP_REASONS:
            raise ValueError(
                f"reason must be one of {DUMP_REASONS}, got {reason!r}"
            )
        with self._lock:
            seq = len(self.dumps)
            events = list(self._events)
            heartbeats = list(self._heartbeats)
            seen = self._seen
        bundle: dict = {
            "schema": FLIGHT_SCHEMA,
            "reason": reason,
            "pid": os.getpid(),
            "created_unix": time.time(),
            "events_seen": seen,
            "events": events,
            "heartbeats": heartbeats,
            "stacks": _format_stacks(),
        }
        try:
            wd = obs.watchdog_state()
            if wd is not None:
                bundle["watchdog"] = wd
            tl = obs.timeline()
            if tl is not None:
                bundle["straggler"] = tl.state()
            aud = obs.auditor()
            if aud is not None:
                bundle["watermarks"] = aud.watermark_table()
                bundle["device_peaks"] = aud.device_peaks()
        except Exception as exc:  # best-effort: record, don't crash
            bundle["state_error"] = repr(exc)
        if self.manifest is not None:
            bundle["manifest"] = self.manifest
        if extra:
            bundle["extra"] = extra
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(
            self.out_dir, f"flight-{os.getpid()}-{seq:03d}-{reason}.json"
        )
        with open(path, "w", encoding="utf-8") as f:
            json.dump(json_sanitize(bundle), f, indent=2)
            f.write("\n")
        with self._lock:
            self.dumps.append(path)
        if emit_event and self.tracer is not None:
            self.tracer(
                "flight_dump", reason=reason, path=path, events=len(events)
            )
        return path
