"""Progress heartbeats and the hang watchdog.

A long fit phase (Borůvka rounds, ring panel sweeps, rpforest tree
builds, background refits) is indistinguishable from a hang without a
liveness signal. Instrumented loops open ``obs.task(phase, total=N)`` and
call ``beat(done)`` each iteration:

- the task emits an unthrottled ``heartbeat`` at entry (progress 0.0) and
  exit (progress 1.0), and throttled ones in between (at most one per
  ``heartbeat_s``), each carrying a *monotone* progress fraction in [0,1]
  and an ETA extrapolated from elapsed wall time;
- every ``beat`` — emitted or throttled — refreshes the hub's liveness
  clock. A daemon watchdog thread (armed when ``watchdog_s > 0``) fires
  when tasks are active but no beat has arrived within ``watchdog_s``:
  it dumps every Python thread's stack to stderr, emits a
  ``watchdog_stall`` trace event with the (truncated) stacks, and bumps
  the ``hdbscan_tpu_watchdog_stalls_total`` counter. The hub's
  :meth:`Heartbeats.state` is surfaced in the server's ``/healthz``.

Both knobs come from ``HDBSCANConfig`` (``heartbeat_s`` / ``watchdog_s``)
and are eagerly validated here as well, since the hub is also built
directly by serving code. Tests stall a phase deterministically through
the existing fault harness: ``beat`` fires the ``phase_stall`` injection
site *before* refreshing the liveness clock, so an injected delay is
exactly what the watchdog sees.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
import traceback

from hdbscan_tpu.fault import inject

_STACK_DUMP_LIMIT = 4000  # chars of stack text carried in the trace event


def _format_stacks() -> str:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in frames.items():
        name = names.get(ident, "?")
        parts.append(f"--- thread {name} ({ident}) ---")
        parts.append("".join(traceback.format_stack(frame)))
    return "\n".join(parts)


class _Task:
    """Handle yielded by :meth:`Heartbeats.task`; not built directly."""

    def __init__(self, hub: "Heartbeats", task_id: int, phase: str, total):
        self._hub = hub
        self.task_id = task_id
        self.phase = phase
        self.total = total
        self._t0 = time.monotonic()
        self._progress = 0.0
        self._last_emit = 0.0

    def beat(self, done, total=None) -> None:
        """Record one unit of progress; may emit a throttled heartbeat."""
        spec = inject.maybe_fire("phase_stall")
        if spec is not None and spec.delay_s > 0:
            # The stall happens BEFORE the liveness clock refresh, so the
            # watchdog observes exactly the injected delay.
            time.sleep(spec.delay_s)
        hub = self._hub
        now = time.monotonic()
        hub._last_beat = now
        if total is not None:
            self.total = total
        if self.total:
            frac = min(max(float(done) / float(self.total), 0.0), 1.0)
            self._progress = max(self._progress, frac)
        if now - self._last_emit >= hub.heartbeat_s:
            self._emit(done, now, final=False)

    def _emit(self, done, now: float, final: bool) -> None:
        self._last_emit = now
        if final:
            self._progress = 1.0
        p = self._progress
        fields = {
            "phase": self.phase,
            "task": self.task_id,
            "progress": round(p, 6),
            "done": int(done) if done is not None else None,
        }
        if fields["done"] is None:
            del fields["done"]
        if self.total is not None:
            fields["total"] = int(self.total)
        elapsed = now - self._t0
        if 0.0 < p <= 1.0:
            fields["eta_s"] = round(elapsed * (1.0 - p) / p, 9)
        tracer = self._hub.tracer
        if tracer is not None:
            tracer("heartbeat", **fields)


class Heartbeats:
    """Hub owning the liveness clock, heartbeat throttle, and watchdog.

    Parameters
    ----------
    tracer:
        Optional ``Tracer`` receiving ``heartbeat`` / ``watchdog_stall``
        events.
    heartbeat_s:
        Minimum spacing between emitted heartbeats per task (> 0).
    watchdog_s:
        Stall budget: with active tasks and no beat for this long, the
        watchdog dumps stacks. 0 disables the watchdog thread entirely.
    stall_counter:
        Optional metrics counter (``.inc()``) bumped once per stall dump.
    """

    def __init__(self, tracer=None, heartbeat_s: float = 1.0,
                 watchdog_s: float = 0.0, stall_counter=None):
        if not heartbeat_s > 0:
            raise ValueError(f"heartbeat_s must be > 0, got {heartbeat_s!r}")
        if watchdog_s < 0:
            raise ValueError(f"watchdog_s must be >= 0, got {watchdog_s!r}")
        self.tracer = tracer
        self.heartbeat_s = float(heartbeat_s)
        self.watchdog_s = float(watchdog_s)
        self._stall_counter = stall_counter
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._active: dict[int, str] = {}
        self._last_beat = time.monotonic()
        self.stalls = 0
        self._stop = threading.Event()
        self._watchdog_thread = None
        if self.watchdog_s > 0:
            self._watchdog_thread = threading.Thread(
                target=self._watch, name="obs-watchdog", daemon=True
            )
            self._watchdog_thread.start()

    # -- tasks -------------------------------------------------------------

    def task(self, phase: str, total=None):
        return _TaskScope(self, phase, total)

    # -- watchdog ----------------------------------------------------------

    def _watch(self) -> None:
        tick = max(0.01, min(self.watchdog_s / 4.0, 1.0))
        while not self._stop.wait(tick):
            with self._lock:
                phases = sorted(set(self._active.values()))
            if not phases:
                continue
            stalled = time.monotonic() - self._last_beat
            if stalled <= self.watchdog_s:
                continue
            stacks = _format_stacks()
            sys.stderr.write(
                f"[obs-watchdog] no heartbeat for {stalled:.3f}s "
                f"(budget {self.watchdog_s}s); active phases: "
                f"{', '.join(phases)}\n{stacks}\n"
            )
            sys.stderr.flush()
            self.stalls += 1
            if self._stall_counter is not None:
                try:
                    self._stall_counter.inc()
                except Exception:
                    pass
            if self.tracer is not None:
                self.tracer(
                    "watchdog_stall",
                    phases=phases,
                    stalled_s=round(stalled, 9),
                    threads=threading.active_count(),
                    stacks=stacks[:_STACK_DUMP_LIMIT],
                )
            # One dump per stall: reset the clock so a still-stalled phase
            # produces the next dump only after another full budget.
            self._last_beat = time.monotonic()

    def state(self) -> dict:
        """Live snapshot for ``/healthz``."""
        with self._lock:
            active = sorted(set(self._active.values()))
        return {
            "heartbeat_s": self.heartbeat_s,
            "watchdog_s": self.watchdog_s,
            "active_tasks": active,
            "last_beat_age_s": round(time.monotonic() - self._last_beat, 6),
            "stalls": self.stalls,
        }

    def close(self) -> None:
        self._stop.set()
        t = self._watchdog_thread
        if t is not None:
            t.join(timeout=5.0)
            self._watchdog_thread = None


class _TaskScope:
    def __init__(self, hub: Heartbeats, phase: str, total):
        self._hub = hub
        self._phase = phase
        self._total = total
        self._task = None

    def __enter__(self) -> _Task:
        hub = self._hub
        task = _Task(hub, next(hub._ids), self._phase, self._total)
        with hub._lock:
            hub._active[task.task_id] = self._phase
            hub._last_beat = time.monotonic()
        task._emit(0, time.monotonic(), final=False)
        self._task = task
        return task

    def __exit__(self, exc_type, exc, tb) -> None:
        hub = self._hub
        task = self._task
        with hub._lock:
            hub._active.pop(task.task_id, None)
            hub._last_beat = time.monotonic()
        if exc_type is None:
            task._emit(task.total, time.monotonic(), final=True)
