"""Per-phase device-memory auditor.

ROADMAP item 1's acceptance — "no replicated O(n) buffer survives on any
single device" — needs per-device *peak* bytes inside each pipeline phase,
not the start/end snapshot `telemetry.sample_device_memory()` gives. The
:class:`MemoryAuditor` wraps every traced phase (via ``obs.mem_phase``):
it samples synchronously at phase entry/exit and from a background thread
in between, emits a ``mem_sample`` trace event per sample and one
``mem_phase_peak`` at phase exit, and keeps a per-phase watermark table
(merged by max across repeated phases) that lands in the run report and
``bench.py`` output.

Sampling sources, in order of fidelity:

- ``memory_stats``: real accelerators expose ``Device.memory_stats()``
  with ``bytes_in_use`` — cheap and includes everything resident.
- ``live_arrays``: the CPU fallback (also forced in tests) walks
  ``jax.live_arrays()`` and attributes each addressable shard's nbytes to
  its device. It only sees arrays Python still references, but that is
  exactly the population a replicated-buffer bug lives in.

``assert_not_replicated(n, itemsize, slack)`` is the gate: any phase where
a single device's peak *above its construction-time baseline* reaches
``slack * n * itemsize`` implies an O(n) buffer was materialized whole on
that device, and the fit fails with :class:`ReplicatedBufferError`. With
the default ``slack=0.5``, a ring-sharded scan on an 8-device mesh
(~n/8 per device) passes with 4x headroom while a fully replicated
buffer (>= 1.0 * n * itemsize) trips it.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager


class ReplicatedBufferError(RuntimeError):
    """A single device's phase peak implies a replicated O(n) buffer."""


# The live_arrays collector materializes ``addressable_shards`` views of
# every resident array and pins buffers via ``unsafe_buffer_pointer``.
# Neither may overlap a dispatch that DONATES a buffer: an external
# reference acquired from the sampler thread mid-donation leaves PJRT
# buffer ownership undefined. (This guard is exclusion for that latent
# hazard; the garbage-MST corruption once blamed on it was traced to
# donating zero-copy ``device_put`` views of host memory — see
# ``parallel/shard._owned_row_panel``.) ``memory_stats`` never touches
# buffers, so real accelerators don't need the guard. RLock, not Lock:
# the main thread takes synchronous entry/exit samples inside its own
# guarded dispatch window, and same-thread sampling cannot race
# same-thread dispatch.
_DONATION_GUARD = threading.RLock()


@contextmanager
def donation_guard():
    """Hold while dispatching a computation with donated operands (from
    operand creation until the outputs are known ready). Excludes the
    live-arrays sampler thread for the duration; no-op cost off-thread."""
    with _DONATION_GUARD:
        yield


def _device_key(d) -> str:
    return f"{d.platform}:{d.id}"


def _memory_stats_sample(devices) -> dict[str, int] | None:
    """Per-device bytes_in_use, or None when any device lacks the stat
    (CPU backends return None / empty dicts)."""
    out: dict[str, int] = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            return None
        if not stats or "bytes_in_use" not in stats:
            return None
        out[_device_key(d)] = int(stats["bytes_in_use"])
    return out


def _live_arrays_sample(devices) -> dict[str, int]:
    """Attribute every live array's addressable shards to their devices.

    Shards are deduplicated by their underlying buffer pointer: a global
    NamedSharding'd array and the per-device views jit dispatch creates of
    it alias the SAME memory (so do donation-aliased outputs), and real
    accelerators' ``bytes_in_use`` would count that memory once. Without
    the dedup a concurrent sample taken mid-dispatch double-counts every
    sharded operand and the replication gate trips on phantom bytes.
    """
    import jax

    per_dev: dict[str, int] = {_device_key(d): 0 for d in devices}
    seen: set[tuple[str, int]] = set()
    # The whole walk sits inside the donation guard: ``addressable_shards``
    # creates per-device views and ``unsafe_buffer_pointer`` pins the
    # underlying buffer, and neither may overlap a dispatch that donates
    # the buffer (see ``_DONATION_GUARD``).
    with _DONATION_GUARD:
        for a in jax.live_arrays():
            try:
                if a.is_deleted():
                    continue
                shards = a.addressable_shards
            except Exception:
                continue
            for sh in shards:
                key = _device_key(sh.device)
                try:
                    nbytes = int(sh.data.nbytes)
                    try:
                        ptr = sh.data.unsafe_buffer_pointer()
                    except Exception:
                        ptr = None
                    if ptr is not None:
                        if (key, ptr) in seen:
                            continue
                        seen.add((key, ptr))
                    per_dev[key] = per_dev.get(key, 0) + nbytes
                except Exception:
                    continue
    return per_dev


def sample_per_device(source: str = "auto") -> tuple[dict[str, int], str]:
    """One sample of per-device resident bytes.

    Returns ``(per_device_bytes, source_used)`` where ``source_used`` is
    ``"memory_stats"`` or ``"live_arrays"``. ``source`` forces one
    collector (tests force ``"live_arrays"`` for determinism on CPU).
    """
    if source not in ("auto", "memory_stats", "live_arrays"):
        raise ValueError(
            f"source must be auto|memory_stats|live_arrays, got {source!r}"
        )
    import jax

    devices = jax.devices()
    if source in ("auto", "memory_stats"):
        stats = _memory_stats_sample(devices)
        if stats is not None:
            return stats, "memory_stats"
        if source == "memory_stats":
            raise RuntimeError(
                "memory_stats unavailable on this backend "
                "(CPU devices expose no bytes_in_use); use live_arrays"
            )
    return _live_arrays_sample(devices), "live_arrays"


class MemoryAuditor:
    """Samples per-device memory around traced phases, keeping watermarks.

    Parameters
    ----------
    tracer:
        Optional ``Tracer``; when set, every sample emits ``mem_sample``
        and every phase exit emits ``mem_phase_peak``.
    interval_s:
        Background sampling period inside a phase. Phases shorter than
        this still get the synchronous entry/exit samples.
    source:
        ``auto`` (default) picks memory_stats when available, else
        live_arrays; tests force ``live_arrays``.
    """

    def __init__(self, tracer=None, interval_s: float = 0.05,
                 source: str = "auto"):
        if not interval_s > 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s!r}")
        if source not in ("auto", "memory_stats", "live_arrays"):
            raise ValueError(
                f"source must be auto|memory_stats|live_arrays, got {source!r}"
            )
        self.tracer = tracer
        self.interval_s = float(interval_s)
        self._source_pref = source
        self._lock = threading.Lock()
        # phase -> watermark dict (merged by max across repeated phases)
        self._watermarks: dict[str, dict] = {}
        self._depth = 0
        self.baseline, self.source = sample_per_device(source)

    # -- sampling ----------------------------------------------------------

    def _sample(self, phase: str, acc: dict) -> None:
        per_dev, src = sample_per_device(self._source_pref)
        max_dev = max(per_dev.values(), default=0)
        total = sum(per_dev.values())
        with self._lock:
            acc["samples"] += 1
            acc["source"] = src
            acc["max_device_bytes"] = max(acc["max_device_bytes"], max_dev)
            acc["total_bytes"] = max(acc["total_bytes"], total)
            for key, v in per_dev.items():
                if v > acc["per_device"].get(key, -1):
                    acc["per_device"][key] = v
        if self.tracer is not None:
            self.tracer(
                "mem_sample",
                phase=phase,
                source=src,
                max_device_bytes=max_dev,
                total_bytes=total,
            )

    @contextmanager
    def phase(self, name: str):
        """Audit device memory for the duration of the block."""
        acc = {
            "samples": 0,
            "source": self.source,
            "max_device_bytes": 0,
            "total_bytes": 0,
            "per_device": defaultdict(int),
        }
        stop = threading.Event()

        def _pump():
            while not stop.wait(self.interval_s):
                try:
                    self._sample(name, acc)
                except Exception:
                    return

        t0 = time.monotonic()
        # Sync entry/exit samples are best-effort: a phase whose sampling
        # failed (or raced a teardown) must still land in the watermark
        # table as a ``sampled: false`` row — an omitted phase key-misses
        # every report/bench_compare consumer downstream.
        try:
            self._sample(name, acc)
        except Exception:
            pass
        pump = threading.Thread(
            target=_pump, name=f"mem-audit-{name}", daemon=True
        )
        pump.start()
        try:
            yield acc
        finally:
            stop.set()
            pump.join(timeout=5.0)
            try:
                self._sample(name, acc)
            except Exception:
                pass
            wall_s = time.monotonic() - t0
            self._merge_watermark(name, acc, wall_s)
            if self.tracer is not None:
                self.tracer(
                    "mem_phase_peak",
                    phase=name,
                    source=acc["source"],
                    samples=acc["samples"],
                    sampled=acc["samples"] > 0,
                    devices=len(acc["per_device"]),
                    max_device_bytes=acc["max_device_bytes"],
                    total_bytes=acc["total_bytes"],
                    wall_s=round(wall_s, 9),
                )

    def _merge_watermark(self, name: str, acc: dict, wall_s: float) -> None:
        with self._lock:
            wm = self._watermarks.get(name)
            if wm is None:
                # Zero-sample phases (sampling failed, or a repeat faster
                # than any sampler tick) still get a row — ``sampled``
                # distinguishes "audited and small" from "never measured".
                self._watermarks[name] = {
                    "source": acc["source"],
                    "samples": acc["samples"],
                    "sampled": acc["samples"] > 0,
                    "max_device_bytes": acc["max_device_bytes"],
                    "total_bytes": acc["total_bytes"],
                    "per_device": dict(acc["per_device"]),
                    "wall_s": round(wall_s, 9),
                }
                return
            wm["samples"] += acc["samples"]
            wm["sampled"] = bool(wm.get("sampled")) or acc["samples"] > 0
            wm["max_device_bytes"] = max(
                wm["max_device_bytes"], acc["max_device_bytes"]
            )
            wm["total_bytes"] = max(wm["total_bytes"], acc["total_bytes"])
            wm["wall_s"] = round(wm["wall_s"] + wall_s, 9)
            for key, v in acc["per_device"].items():
                if v > wm["per_device"].get(key, -1):
                    wm["per_device"][key] = v

    # -- reporting ---------------------------------------------------------

    def watermark_table(self) -> dict[str, dict]:
        """Per-phase watermarks (deep-copied; safe to serialize)."""
        with self._lock:
            return {
                name: {**wm, "per_device": dict(wm["per_device"])}
                for name, wm in self._watermarks.items()
            }

    def device_peaks(self) -> dict[str, int]:
        """Per-device peak bytes across all audited phases (for gauges)."""
        peaks: dict[str, int] = {}
        with self._lock:
            for wm in self._watermarks.values():
                for key, v in wm["per_device"].items():
                    if v > peaks.get(key, -1):
                        peaks[key] = v
        return peaks

    # -- the gate ----------------------------------------------------------

    def assert_not_replicated(self, n, itemsize, slack: float = 0.5,
                              phases=None) -> dict:
        """Fail if any device's phase peak implies a replicated O(n) buffer.

        The threshold is ``slack * n * itemsize`` bytes of growth above the
        device's construction-time baseline. Returns a summary dict
        (threshold, phases checked, worst offender margin) on success;
        raises :class:`ReplicatedBufferError` listing every offending
        (phase, device, peak) otherwise.
        """
        n = int(n)
        itemsize = int(itemsize)
        if n <= 0:
            raise ValueError(f"n must be > 0, got {n!r}")
        if itemsize <= 0:
            raise ValueError(f"itemsize must be > 0, got {itemsize!r}")
        if not slack > 0:
            raise ValueError(f"slack must be > 0, got {slack!r}")
        threshold = slack * n * itemsize
        table = self.watermark_table()
        if phases is not None:
            wanted = set(phases)
            missing = wanted - set(table)
            if missing:
                raise ValueError(
                    f"assert_not_replicated: phases never audited: "
                    f"{sorted(missing)} (have {sorted(table)})"
                )
            table = {k: v for k, v in table.items() if k in wanted}
        if not table:
            raise RuntimeError(
                "assert_not_replicated: no phases were audited — the gate "
                "cannot pass vacuously"
            )
        devices = set()
        for wm in table.values():
            devices.update(wm["per_device"])
        if len(devices) <= 1:
            # One device holds the whole problem by definition — "replicated
            # vs sharded" is only meaningful across a multi-device mesh.
            return {
                "threshold_bytes": threshold,
                "phases": sorted(table),
                "worst_fraction": 0.0,
                "single_device": True,
            }
        offenders = []
        worst = 0.0
        for phase, wm in sorted(table.items()):
            for dev, peak in sorted(wm["per_device"].items()):
                growth = peak - self.baseline.get(dev, 0)
                worst = max(worst, growth / threshold)
                if growth >= threshold:
                    offenders.append((phase, dev, peak, growth))
        if offenders:
            lines = "; ".join(
                f"{phase}/{dev}: peak={peak}B growth={growth}B"
                for phase, dev, peak, growth in offenders
            )
            raise ReplicatedBufferError(
                f"replicated O(n) buffer: {len(offenders)} device-phase "
                f"peak(s) grew >= slack*n*itemsize = {slack}*{n}*{itemsize} "
                f"= {threshold:.0f}B above baseline ({lines})"
            )
        return {
            "threshold_bytes": threshold,
            "phases": sorted(table),
            "worst_fraction": round(worst, 6),
        }
