"""Roofline / MFU report section: timelines joined with FLOPs counters.

``utils/flops`` credits every dispatch site with analytic FLOPs and
modeled HBM bytes; ``obs/timeline.TimelineRecorder`` measures per-phase
walls and attributes their comm share. This module joins the two into the
run report's ``roofline`` section (schema ``hdbscan-tpu-report/3``): per
traced phase, achieved GFLOP/s, achieved GB/s, arithmetic intensity
(FLOPs/byte), MFU against :data:`~hdbscan_tpu.utils.flops.PEAK_FLOPS`,
and a bound classification —

- ``comm`` when the timeline attributes >= ``COMM_BOUND_FRAC`` of the
  phase to ring transfers,
- ``compute`` when arithmetic intensity sits at or above the ridge point
  ``PEAK_FLOPS / PEAK_BYTES_S``,
- ``memory`` otherwise (including phases with bytes but no FLOPs).

The tags ride the section so a CPU-mesh smoke number can never
masquerade as a hardware claim: ``cpu_smoke`` whenever the default
backend is CPU, ``interpret`` when the caller ran Pallas kernels in
interpret mode. ``bench.py mesh`` and ``scripts/bench_compare.py``
consume the same rows.
"""

from __future__ import annotations

import os

__all__ = [
    "PEAK_BYTES_S",
    "COMM_BOUND_FRAC",
    "default_tags",
    "roofline_section",
]

#: Advertised HBM bandwidth of one v5e chip (bytes/s, public spec);
#: env-overridable for other hardware generations — the ridge point of the
#: roofline is PEAK_FLOPS / PEAK_BYTES_S.
PEAK_BYTES_S = float(os.environ.get("HDBSCAN_TPU_PEAK_BYTES_S", 819e9))

#: A phase whose timeline attributes at least this fraction of its wall to
#: ring transfers classifies ``comm``-bound regardless of intensity.
COMM_BOUND_FRAC = 0.5


def default_tags() -> list[str]:
    """Honesty tags for the current backend: ``cpu_smoke`` on a CPU
    default backend (the forced-8-device mesh shares one core — rates are
    smoke figures, not hardware claims)."""
    import jax

    return ["cpu_smoke"] if jax.default_backend() == "cpu" else []


def classify_bound(intensity, ridge, comm_frac) -> str:
    """compute / memory / comm for one phase (see module docstring)."""
    if comm_frac is not None and comm_frac >= COMM_BOUND_FRAC:
        return "comm"
    if intensity is not None and intensity >= ridge:
        return "compute"
    return "memory"


def roofline_section(aggregates: dict, timeline_table: dict | None = None,
                     tags=None) -> dict | None:
    """Build the report's ``roofline`` section.

    ``aggregates`` is :func:`~hdbscan_tpu.utils.telemetry.phase_aggregates`
    output (summed gflops/gbytes per stage); ``timeline_table`` is
    :meth:`~hdbscan_tpu.obs.timeline.TimelineRecorder.phase_table` (or
    None when no timeline recorder ran). Phases appear when either side
    knows about them; a phase with neither FLOPs, bytes, nor a timeline
    row is skipped. Returns None when no phase qualifies (the section is
    omitted, not empty — the report convention)."""
    from hdbscan_tpu.utils import flops as _flops

    timeline_table = timeline_table or {}
    tags = list(tags) if tags is not None else default_tags()
    ridge = _flops.PEAK_FLOPS / PEAK_BYTES_S
    phases: dict[str, dict] = {}
    for name in sorted(set(aggregates) | set(timeline_table)):
        agg = aggregates.get(name, {})
        tl = timeline_table.get(name, {})
        gflops = float(agg.get("gflops", 0.0) or 0.0)
        gbytes = float(agg.get("gbytes", 0.0) or 0.0)
        if gflops <= 0 and gbytes <= 0 and not tl:
            continue
        wall = float(tl.get("wall_s") or agg.get("wall_s", 0.0) or 0.0)
        comm_frac = tl.get("comm_frac")
        intensity = (
            round(gflops / gbytes, 6) if gflops > 0 and gbytes > 0 else None
        )
        row: dict = {
            "wall_s": round(wall, 9),
            "gflops": gflops,
            "gbytes": gbytes,
            "arithmetic_intensity": intensity,
            "bound": classify_bound(intensity, ridge, comm_frac),
        }
        if wall > 0:
            row["achieved_gflops_s"] = round(gflops / wall, 3)
            row["achieved_gbytes_s"] = round(gbytes / wall, 3)
            row["mfu"] = round(gflops * 1e9 / wall / _flops.PEAK_FLOPS, 9)
        if comm_frac is not None:
            row["comm_frac"] = comm_frac
        if tl.get("skew") is not None:
            row["skew"] = tl["skew"]
        if tl.get("comm_bytes") is not None:
            row["comm_bytes"] = int(tl["comm_bytes"])
        if tl.get("rounds") is not None:
            row["rounds"] = int(tl["rounds"])
            row["devices"] = int(tl.get("devices", 0))
        phases[name] = row
    if not phases:
        return None
    return {
        "peak_gflops_s": round(_flops.PEAK_FLOPS / 1e9, 3),
        "peak_gbytes_s": round(PEAK_BYTES_S / 1e9, 3),
        "ridge_intensity": round(ridge, 6),
        "tags": tags,
        "phases": phases,
    }
