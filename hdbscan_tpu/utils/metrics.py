"""Stdlib-only metrics registry for the serving stack.

The serving/streaming path (``serve/server.py``) needs Prometheus-style
instrumentation — request totals by route and status, an in-flight gauge,
latency and batch-size histograms, swap/refit/drift counters — without
adding a dependency: the container bakes in the JAX toolchain and nothing
else, so this module uses only the standard library.

Three instrument kinds, all safe to mutate from many threads at once
(HTTP handler threads, the micro-batcher worker, the background refitter):

* :class:`Counter` — monotonically increasing float per label combination.
* :class:`Gauge` — settable float (in-flight requests, model generation).
* :class:`Histogram` — fixed log-spaced buckets with cumulative counts, a
  running sum, and the max observed value; state is mergeable across
  instances (multi-replica aggregation) and supports nearest-rank
  quantile estimates straight from the bucket counts.

:meth:`MetricsRegistry.render` emits the Prometheus text exposition format
(version 0.0.4) served by ``GET /metrics``; ``scripts/check_metrics.py``
validates the output with nothing but the stdlib on the other side.
"""

from __future__ import annotations

import math
import re
import threading

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def log_buckets(start: float, factor: float, count: int) -> tuple:
    """Geometric bucket upper edges: ``start * factor**i`` for i in [0, count)."""
    if start <= 0 or factor <= 1.0 or count < 1:
        raise ValueError(
            f"log_buckets needs start > 0, factor > 1, count >= 1; got "
            f"{start!r}, {factor!r}, {count!r}"
        )
    edges, v = [], float(start)
    for _ in range(count):
        edges.append(v)
        v *= factor
    return tuple(edges)


#: 100 us .. ~105 s in doublings — covers a single-row CPU predict up to a
#: pathological sustained-load stall; 21 buckets keep the exposition small.
DEFAULT_LATENCY_BUCKETS = log_buckets(1e-4, 2.0, 21)

#: 1 .. 4096 rows in doublings — matches the predictor's pow2 bucket ladder.
DEFAULT_SIZE_BUCKETS = log_buckets(1.0, 2.0, 13)


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Metric:
    """Shared label plumbing. Children are keyed by the tuple of label
    values in declared label-name order; the registry-wide lock serializes
    every mutation and the render pass."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple, lock):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"bad label name {ln!r} for metric {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._children: dict = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _label_str(self, key: tuple, extra: str = "") -> str:
        parts = [
            f'{ln}="{_escape_label(v)}"' for ln, v in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def samples(self) -> list:
        """``[(labels_dict, value), ...]`` snapshot (counters/gauges)."""
        with self._lock:
            return [
                (dict(zip(self.labelnames, key)), value)
                for key, value in sorted(self._children.items())
            ]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease ({amount!r})")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + float(amount)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._children.get(key, 0.0)

    def merge(self, other: "Counter") -> None:
        """Fold another counter's children into this one (label-wise sum).

        Two-phase: snapshot ``other`` under its lock, then fold under ours —
        the locks never nest, so merging from a live registry while it is
        being scraped (or merged elsewhere) cannot deadlock.
        """
        if other.labelnames != self.labelnames:
            raise ValueError(f"cannot merge {other.name!r} into {self.name!r}")
        with other._lock:
            items = list(other._children.items())
        with self._lock:
            for key, v in items:
                self._children[key] = self._children.get(key, 0.0) + v

    def render(self, out: list) -> None:
        with self._lock:
            for key, v in sorted(self._children.items()):
                out.append(f"{self.name}{self._label_str(key)} {_fmt_value(v)}")


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + float(amount)

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._children.get(key, 0.0)

    def merge(self, other: "Gauge") -> None:
        """Fold another gauge's children into this one (label-wise SUM —
        fleet aggregation semantics: in-flight counts and up/down flags add
        across replicas; per-replica series stay distinct because the
        aggregator tags each source with a ``replica`` label first)."""
        if other.labelnames != self.labelnames:
            raise ValueError(f"cannot merge {other.name!r} into {self.name!r}")
        with other._lock:
            items = list(other._children.items())
        with self._lock:
            for key, v in items:
                self._children[key] = self._children.get(key, 0.0) + v

    def render(self, out: list) -> None:
        with self._lock:
            for key, v in sorted(self._children.items()):
                out.append(f"{self.name}{self._label_str(key)} {_fmt_value(v)}")


class _HistState:
    __slots__ = ("counts", "sum", "vmax")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1: the +Inf overflow bucket
        self.sum = 0.0
        self.vmax = -math.inf


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, lock, buckets):
        super().__init__(name, help, labelnames, lock)
        edges = tuple(float(b) for b in buckets)
        if not edges or any(later <= prev for later, prev in zip(edges[1:], edges)):
            raise ValueError(f"histogram {name!r} buckets must strictly increase")
        self.buckets = edges

    def _state(self, labels: dict) -> _HistState:
        key = self._key(labels)
        st = self._children.get(key)
        if st is None:
            st = self._children[key] = _HistState(len(self.buckets))
        return st

    def observe(self, value: float, **labels) -> None:
        v = float(value)
        with self._lock:
            st = self._state(labels)
            # Linear scan beats bisect for ~20 buckets and keeps this
            # allocation-free on the request path.
            for i, edge in enumerate(self.buckets):
                if v <= edge:
                    st.counts[i] += 1
                    break
            else:
                st.counts[-1] += 1
            st.sum += v
            if v > st.vmax:
                st.vmax = v

    def count(self, **labels) -> int:
        with self._lock:
            st = self._children.get(self._key(labels))
            return sum(st.counts) if st else 0

    def total(self, **labels) -> float:
        with self._lock:
            st = self._children.get(self._key(labels))
            return st.sum if st else 0.0

    def quantile(self, q: float, **labels):
        """Nearest-rank quantile from bucket state.

        Returns the upper edge of the bucket holding the rank-``ceil(q*n)``
        observation, or the max observed value when that rank lands in the
        +Inf overflow bucket — so the estimate is always within one bucket
        width of the raw-sample nearest-rank quantile. None when empty.
        """
        if not 0.0 < q <= 1.0:
            raise ValueError(f"quantile q must be in (0, 1], got {q!r}")
        with self._lock:
            st = self._children.get(self._key(labels))
            if st is None:
                return None
            n = sum(st.counts)
            if n == 0:
                return None
            rank = max(1, math.ceil(q * n))
            cum = 0
            for i, edge in enumerate(self.buckets):
                cum += st.counts[i]
                if cum >= rank:
                    return edge
            return st.vmax

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram's state into this one (same bucket edges).
        Snapshot-then-fold, like :meth:`Counter.merge`."""
        if other.labelnames != self.labelnames or other.buckets != self.buckets:
            raise ValueError(f"cannot merge {other.name!r} into {self.name!r}")
        with other._lock:
            items = [
                (key, list(ost.counts), ost.sum, ost.vmax)
                for key, ost in other._children.items()
            ]
        with self._lock:
            for key, counts, osum, ovmax in items:
                st = self._children.get(key)
                if st is None:
                    st = self._children[key] = _HistState(len(self.buckets))
                for i, c in enumerate(counts):
                    st.counts[i] += c
                st.sum += osum
                if ovmax > st.vmax:
                    st.vmax = ovmax

    def _load_state(self, labels: dict, counts, total: float, vmax: float) -> None:
        """Restore per-bucket state parsed back from an exposition scrape
        (:func:`registry_from_exposition`). Additive, like :meth:`merge`."""
        if len(counts) != len(self.buckets) + 1:
            raise ValueError(
                f"histogram {self.name!r} expects {len(self.buckets) + 1} "
                f"bucket counts, got {len(counts)}"
            )
        with self._lock:
            st = self._state(labels)
            for i, c in enumerate(counts):
                st.counts[i] += c
            st.sum += float(total)
            if vmax > st.vmax:
                st.vmax = vmax

    def render(self, out: list) -> None:
        with self._lock:
            for key, st in sorted(self._children.items()):
                cum = 0
                for i, edge in enumerate(self.buckets):
                    cum += st.counts[i]
                    le = f'le="{edge!r}"'
                    out.append(
                        f"{self.name}_bucket{self._label_str(key, le)} {cum}"
                    )
                total = cum + st.counts[-1]
                inf_le = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket{self._label_str(key, inf_le)} {total}"
                )
                out.append(
                    f"{self.name}_sum{self._label_str(key)} {_fmt_value(st.sum)}"
                )
                out.append(f"{self.name}_count{self._label_str(key)} {total}")


class MetricsRegistry:
    """Instrument factory + Prometheus text renderer.

    ``counter``/``gauge``/``histogram`` are get-or-create: asking for an
    existing name returns the existing instrument (so decoupled layers —
    the ingest buffer, the refitter — can each grab the same counter by
    name), and a kind or label mismatch is an eager ``ValueError``.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict = {}  # name -> instrument, insertion-ordered

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.labelnames}"
                    )
                return m
            m = cls(name, help, tuple(labelnames), self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames=(), buckets=None
    ) -> Histogram:
        return self._get_or_create(
            Histogram,
            name,
            help,
            labelnames,
            buckets=tuple(buckets) if buckets else DEFAULT_LATENCY_BUCKETS,
        )

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def merge(self, other: "MetricsRegistry") -> None:
        """Fold another registry's state into this one, instrument-wise.

        The fleet-aggregation hook: the router parses each replica's
        ``/metrics`` scrape back into a registry
        (:func:`registry_from_exposition`, which tags every series with a
        ``replica`` label) and folds them all into one. Instruments missing
        here are created with the other's name/help/labels/buckets; existing
        ones merge by kind — counters and gauges sum label-wise, histograms
        fold bucket counts + sum + vmax exactly. A kind/label/bucket
        mismatch raises, same as :meth:`_get_or_create`.
        """
        with other._lock:
            theirs = list(other._metrics.values())
        for om in theirs:
            if isinstance(om, Histogram):
                mine = self.histogram(
                    om.name, om.help, om.labelnames, buckets=om.buckets
                )
            elif isinstance(om, Counter):
                mine = self.counter(om.name, om.help, om.labelnames)
            elif isinstance(om, Gauge):
                mine = self.gauge(om.name, om.help, om.labelnames)
            else:  # pragma: no cover - only three kinds exist
                raise ValueError(f"unknown instrument kind for {om.name!r}")
            mine.merge(om)

    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4), trailing newline."""
        out: list = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            m.render(out)
        return "\n".join(out) + "\n"


# -- cross-process aggregation ------------------------------------------------
#
# Fleet replicas are separate OS processes: the router holds their /metrics
# TEXT, not their registries. registry_from_exposition() inverts render() so
# the text folds back through the same merge() machinery the in-process path
# uses — scrape each replica, re-parse with a replica label, merge, re-render.

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$"
)
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPES = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape_label(value: str) -> str:
    return re.sub(
        r'\\[\\"n]', lambda m: _UNESCAPES[m.group(0)], value
    )


def _parse_labels(body: str) -> dict:
    return {
        k: _unescape_label(v) for k, v in _LABEL_PAIR_RE.findall(body or "")
    }


def registry_from_exposition(
    text: str, static_labels: dict | None = None
) -> MetricsRegistry:
    """Parse Prometheus 0.0.4 exposition text back into a live registry.

    The inverse of :meth:`MetricsRegistry.render`, up to one lossy corner:
    a reconstructed histogram's max-observed value is only known to bucket
    resolution (the highest non-empty finite edge, or +Inf when the
    overflow bucket is populated), so ``quantile()`` answers that land in
    the overflow bucket degrade from exact-max to edge/+Inf.

    ``static_labels`` are prepended to every series — the fleet router
    passes ``{"replica": rid}`` so per-replica series never collide when
    the parsed registries merge into the aggregate.

    Unparseable lines raise ``ValueError`` naming the line: a replica
    emitting garbage on /metrics should fail its scrape loudly, not
    vanish into a silently-smaller aggregate.
    """
    static = {str(k): str(v) for k, v in (static_labels or {}).items()}
    kinds: dict = {}
    helps: dict = {}
    samples: list = []  # (name, labels_dict, value) in file order
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                raise ValueError(f"metrics line {lineno}: malformed TYPE {raw!r}")
            kinds[parts[2]] = parts[3]
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 3:
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"metrics line {lineno}: unparseable sample {raw!r}")
        samples.append((m.group(1), _parse_labels(m.group(2)), m.group(3)))

    def family_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and kinds.get(base) == "histogram":
                return base
        return name

    reg = MetricsRegistry()
    # Histogram series accumulate per (family, non-le key) before creation:
    # the bucket ladder is only known once every le edge has been seen.
    hists: dict = {}  # family -> {"labelnames", key -> {"les", "sum", "count"}}
    for name, labels, value_s in samples:
        family = family_of(name)
        kind = kinds.get(family)
        if kind is None:
            raise ValueError(
                f"metrics sample {name!r} has no preceding # TYPE line"
            )
        if kind == "histogram":
            le = labels.pop("le", None)
            merged = {**static, **labels}
            fam = hists.setdefault(
                family,
                {"labelnames": tuple(merged), "series": {}},
            )
            key = tuple(merged[ln] for ln in fam["labelnames"])
            series = fam["series"].setdefault(
                key, {"les": {}, "sum": 0.0, "count": 0}
            )
            if name.endswith("_bucket"):
                if le is None:
                    raise ValueError(
                        f"histogram bucket sample for {family!r} lacks an "
                        f"le label"
                    )
                series["les"][float(le)] = float(value_s)
            elif name.endswith("_sum"):
                series["sum"] = float(value_s)
            elif name.endswith("_count"):
                series["count"] = float(value_s)
            continue
        merged = {**static, **labels}
        if kind == "counter":
            inst = reg.counter(family, helps.get(family, ""), tuple(merged))
            inst.inc(float(value_s), **merged)
        elif kind == "gauge":
            inst = reg.gauge(family, helps.get(family, ""), tuple(merged))
            inst.inc(float(value_s), **merged)
        else:
            raise ValueError(
                f"metric {family!r} has unsupported TYPE {kind!r}"
            )

    for family, fam in hists.items():
        edges = None
        for key, series in fam["series"].items():
            finite = sorted(le for le in series["les"] if math.isfinite(le))
            if edges is None:
                edges = finite
            elif finite != edges:
                raise ValueError(
                    f"histogram {family!r} has inconsistent bucket edges "
                    f"across series"
                )
        if not edges:
            raise ValueError(f"histogram {family!r} has no finite buckets")
        hist = reg.histogram(
            family, helps.get(family, ""), fam["labelnames"], buckets=edges
        )
        for key, series in fam["series"].items():
            cum = [series["les"][e] for e in edges]
            total = series["les"].get(math.inf, series["count"])
            counts = [int(c - p) for c, p in zip(cum, [0.0] + cum[:-1])]
            counts.append(int(total - cum[-1]))
            if any(c < 0 for c in counts):
                raise ValueError(
                    f"histogram {family!r} bucket counts are not cumulative"
                )
            vmax = -math.inf
            if counts[-1] > 0:
                vmax = math.inf
            else:
                for edge, c in zip(reversed(edges), reversed(counts[:-1])):
                    if c > 0:
                        vmax = edge
                        break
            labels = dict(zip(fam["labelnames"], key))
            hist._load_state(labels, counts, series["sum"], vmax)
    return reg
