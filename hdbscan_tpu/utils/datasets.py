"""Synthetic dataset generators mirroring the paper's evaluation family.

The reference evaluates on Gauss1/2/3 — synthetic 10-dimensional Gaussian
mixtures with 20/30/50 clusters (ResearchReport.pdf §5.1 Table 1; quoted from
the paper). The generators here reproduce that shape so the approximate
pipelines can be validated against the exact tree on continuous
(off-lattice) data of arbitrary size, not just the bundled integer-grid
Skin set.
"""

from __future__ import annotations

import numpy as np


def make_gauss(
    n: int,
    dims: int = 10,
    n_clusters: int = 20,
    spread: float = 1.0,
    separation: float = 12.0,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Gaussian mixture in the paper's Gauss1/2/3 shape.

    Cluster centers are drawn uniformly in a hypercube scaled so clusters are
    ``separation`` standard deviations apart on average; cluster sizes are
    drawn from a symmetric Dirichlet so they vary realistically. Returns
    (points (n, dims) float64, labels (n,) int64). Labels are 1-based so they
    compose directly with the evaluation convention that 0 means noise.
    """
    rng = np.random.default_rng(seed)
    side = separation * spread * n_clusters ** (1.0 / dims)
    # Rejection-sample centers so no pair is closer than ``separation`` * sigma
    # (uniform placement alone can collide, silently merging two "clusters").
    centers = np.empty((n_clusters, dims))
    placed = 0
    attempts = 0
    while placed < n_clusters:
        if attempts >= 10_000:
            raise ValueError(
                f"could not place {n_clusters} centers at separation {separation}; "
                "lower n_clusters or separation"
            )
        attempts += 1
        cand = rng.uniform(0.0, side, size=dims)
        if placed == 0 or np.min(
            np.linalg.norm(centers[:placed] - cand, axis=1)
        ) >= separation * spread:
            centers[placed] = cand
            placed += 1
    weights = rng.dirichlet(np.full(n_clusters, 5.0))
    assign = rng.choice(n_clusters, size=n, p=weights)
    pts = centers[assign] + rng.normal(0.0, spread, size=(n, dims))
    return pts, assign.astype(np.int64) + 1


def make_directional(
    n: int,
    dims: int = 8,
    n_clusters: int = 6,
    angular_spread: float = 0.05,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Clusters of DIRECTIONS: magnitude is noise, angle carries the class.

    Points lie along cluster-specific unit directions with small angular
    jitter and uniformly random radii in [0.5, 10]. Cosine distance separates
    the clusters cleanly while Euclidean mixes them (radius swamps angle) —
    the structure the cosine plug-in config exists to demonstrate. Skin RGB
    rows are the OPPOSITE regime: near-collinear rays (13.8% of pairs at
    cosine distance < 1e-3, minPts=16 cosine core distances ~1e-5, 256
    all-zero rows where cosine is undefined), so any cosine clustering of
    Skin collapses to one cluster — a dataset degeneracy, not a plug-in bug
    (``distance/CosineSimilarity.java:27-40`` has the same geometry).
    """
    rng = np.random.default_rng(seed)
    dirs = rng.normal(size=(n_clusters, dims))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    assign = rng.integers(0, n_clusters, size=n)
    pts = dirs[assign] + rng.normal(0.0, angular_spread, size=(n, dims))
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    radii = rng.uniform(0.5, 10.0, size=(n, 1))
    return pts * radii, assign.astype(np.int64) + 1


#: The paper's three synthetic configurations (cluster counts; Table 1).
GAUSS_CONFIGS = {"gauss1": 20, "gauss2": 30, "gauss3": 50}


def make_paper_gauss(name: str, n: int, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Gauss1/2/3 by name at a chosen size (the paper does not publish point
    counts for these sets — only dims=10 and the cluster counts)."""
    return make_gauss(n, dims=10, n_clusters=GAUSS_CONFIGS[name], seed=seed)
