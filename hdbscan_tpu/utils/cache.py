"""Persistent XLA compilation cache for benches and the CLI.

The tunneled chip pays ~7-40 s per XLA compile; the windowed boundary phase
compiles ~8 shapes per kernel and the bench campaign re-runs the same
configs across processes. jax's persistent compilation cache (verified to
work on the axon platform, r5) makes every shape a one-time cost per
MACHINE instead of per process. Opt-out with HDBSCAN_TPU_NO_CACHE=1.

The reference has no analog (the JVM warms per Spark executor); this is
TPU-deployment table stakes — production JAX serving enables the same
cache.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.expanduser("~/.cache/hdbscan_tpu_xla")


def resolve_cache_dir(path: str | None = None) -> str | None:
    """The on-disk cache directory the ``compile_cache`` knob resolves to,
    or None when the cache is disabled — without importing jax or touching
    its config. The fleet router uses this to point every replica's
    ``JAX_COMPILATION_CACHE_DIR`` at the same directory, so a respawned or
    scaled-up replica warm-starts from the compiles its siblings (and the
    previous incarnation of itself) already paid for."""
    if os.environ.get("HDBSCAN_TPU_NO_CACHE"):
        return None
    if path == "off":
        return None
    if path == "auto":
        path = None
    return path or os.environ.get("JAX_COMPILATION_CACHE_DIR") or _DEFAULT_DIR


def enable_persistent_compilation_cache(path: str | None = None) -> str | None:
    """Enable jax's on-disk compile cache (idempotent). Returns the dir, or
    None when disabled.

    ``path`` follows the ``compile_cache`` config knob: ``"off"`` disables
    the cache for this process (equivalent to HDBSCAN_TPU_NO_CACHE=1),
    ``"auto"``/``None`` resolves JAX_COMPILATION_CACHE_DIR then the
    per-user default, and anything else is taken as the cache directory
    itself (created if missing)."""
    path = resolve_cache_dir(path)
    if path is None:
        return None
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # jax only persists compiles slower than ~1 s by default, which silently
    # skips every CPU-sized program (and the smaller TPU shapes) — the cache
    # then looks enabled but never hits. Persist everything: entries are tiny
    # and the whole point of the knob is one-time-per-machine compiles.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
