"""Persistent XLA compilation cache for benches and the CLI.

The tunneled chip pays ~7-40 s per XLA compile; the windowed boundary phase
compiles ~8 shapes per kernel and the bench campaign re-runs the same
configs across processes. jax's persistent compilation cache (verified to
work on the axon platform, r5) makes every shape a one-time cost per
MACHINE instead of per process. Opt-out with HDBSCAN_TPU_NO_CACHE=1.

The reference has no analog (the JVM warms per Spark executor); this is
TPU-deployment table stakes — production JAX serving enables the same
cache.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.expanduser("~/.cache/hdbscan_tpu_xla")


def enable_persistent_compilation_cache(path: str | None = None) -> str | None:
    """Enable jax's on-disk compile cache (idempotent). Returns the dir, or
    None when disabled via HDBSCAN_TPU_NO_CACHE."""
    if os.environ.get("HDBSCAN_TPU_NO_CACHE"):
        return None
    import jax

    path = path or os.environ.get("JAX_COMPILATION_CACHE_DIR") or _DEFAULT_DIR
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    return path
