"""Analytic FLOP/byte accounting for the tiled device scans.

The reference's perf story is wall-clock tables (ResearchReport.pdf §5.4
Table 3); on a tunneled single-chip host with measured ~4x run-to-run
variance, wall clock alone cannot distinguish compute-bound from
transfer-bound phases (VERDICT r3 "what's missing" #1). Every tiled scan has
a KNOWN arithmetic shape — the O(rows x cols x d) MXU distance expansion —
so each dispatch site credits a module-global counter with its analytic
FLOPs and modeled HBM bytes, and phase boundaries (``models/mr_hdbscan``
trace events, ``bench.py``) snapshot the counter to report achieved FLOP/s
and MFU per phase.

Conventions (documented, not measured):

- FLOPs: ``2 * rows * cols * d`` per distance tile — the dominant matmul
  term of the euclidean expansion (manhattan/supremum do comparable VPU
  work per element; the same count keeps phases comparable). Selection
  (top_k) and masking are NOT credited — but not because they are cheap:
  the r5 devicebench measured selection at ~90% of the on-chip scan TIME
  (devicebench_r5.jsonl, 500k x 28: scan_e2e_guarded 694 GFLOP/s vs the
  3.5-3.6 TFLOP/s matmul_floor on identical shapes — the distance+min
  floor is ~0.5 s of a ~5 s guarded scan). The counter stays
  distance-FLOPs-only as a comparable WORK unit across backends and
  rounds; achieved-GFLOP gaps against the matmul floor are the selection
  overhead, which is what the fused kernel (``ops/pallas_knn``,
  ``scan_e2e_fused`` devicebench leg) attacks.
- Pad FLOPs: window chunks padded up to ``_MIN_CHUNK_TILES`` (compile-storm
  cap, ops/blockscan) scan dummy tiles whose work is real device time but
  not useful output. Dispatch sites credit those tiles to the SEPARATE
  ``pad_flops`` counter (``add_pad_scan``) so phase GFLOP/MFU rows stay
  comparable to pre-r5 data — counting them as useful work inflated
  1-tile jobs up to 64x. ``phase_stats`` reports ``pad_gflops`` when
  nonzero.
- Bytes: modeled HBM traffic of the streaming schedule — every ROW TILE
  re-reads its full column window from HBM (``cols * d * itemsize`` per
  tile), plus one pass over the row block. VMEM reuse within a tile is
  invisible to (and the point of) this model.
- MFU: achieved FLOP/s over ``PEAK_FLOPS``. The default peak is the v5e
  bf16 MXU figure (197 TFLOP/s, public spec). The euclidean cross matmul
  runs ``Precision.HIGHEST`` (~6 bf16 passes for f32 accuracy —
  ``core/distances._cross_f32``), so a perfectly MXU-bound euclidean scan
  tops out near peak/6 ~ 16%; report MFU against the raw peak and judge
  phases RELATIVE to that ceiling. Override with HDBSCAN_TPU_PEAK_FLOPS.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

#: Advertised bf16 peak of one v5e chip (FLOP/s); env-overridable for other
#: hardware generations.
PEAK_FLOPS = float(os.environ.get("HDBSCAN_TPU_PEAK_FLOPS", 197e12))

#: Practical ceiling factor for the f32-accurate euclidean scans (6-pass
#: HIGHEST-precision cross matmul).
F32_SCAN_CEILING = 1.0 / 6.0


@dataclass
class ScanCounter:
    """Monotonic analytic counters; phases diff :meth:`snapshot` tuples."""

    flops: float = 0.0
    bytes: float = 0.0
    #: Distance FLOPs burned on PAD tiles (chunk padding to the compile-storm
    #: floor) — real device time, not useful work; kept out of ``flops`` so
    #: achieved-GFLOP rows measure the useful scan.
    pad_flops: float = 0.0

    def add(self, flops: float, nbytes: float) -> None:
        self.flops += flops
        self.bytes += nbytes

    def add_scan(self, rows: int, cols: int, d: int, itemsize: int = 4,
                 row_tile: int = 1) -> None:
        """Credit one streaming scan: ``rows`` row slots against ``cols``
        columns of ``d`` features, column window re-read once per row tile."""
        n_row_tiles = max(1, -(-rows // max(row_tile, 1)))
        self.add(
            2.0 * rows * cols * d,
            (n_row_tiles * cols * d + rows * d) * itemsize,
        )

    def add_pad_scan(self, rows: int, cols: int, d: int) -> None:
        """Credit pad-tile distance work (same model, separate bucket)."""
        self.pad_flops += 2.0 * rows * cols * d

    def snapshot(self) -> tuple[float, float, float]:
        return self.flops, self.bytes, self.pad_flops


#: The process-wide counter every dispatch site credits.
counter = ScanCounter()


def phase_stats(t0_snap: tuple, wall_s: float) -> dict:
    """Trace-field dict for a phase: FLOPs/bytes since ``t0_snap``, achieved
    GFLOP/s + GB/s, and MFU vs :data:`PEAK_FLOPS` (0 fields dropped).
    Accepts legacy 2-tuple snapshots (no pad counter)."""
    df = counter.flops - t0_snap[0]
    db = counter.bytes - t0_snap[1]
    dp = counter.pad_flops - (t0_snap[2] if len(t0_snap) > 2 else 0.0)
    if df <= 0 and db <= 0 and dp <= 0:
        return {}
    out = {"gflops": round(df / 1e9, 1), "gbytes": round(db / 1e9, 2)}
    if dp > 0:
        out["pad_gflops"] = round(dp / 1e9, 1)
    if wall_s > 0:
        out["gflops_s"] = round(df / wall_s / 1e9, 1)
        out["gbytes_s"] = round(db / wall_s / 1e9, 2)
        out["mfu"] = round(df / wall_s / PEAK_FLOPS, 6)
    return out
