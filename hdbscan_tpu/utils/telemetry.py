"""Run telemetry: manifests, reports, memory/compile tracking, host merge.

The durable half of the observability layer (``utils/tracing.py`` is the
in-process half): a run can persist (1) a JSONL event trace per process
(:class:`~hdbscan_tpu.utils.tracing.JsonlSink`), and (2) a single JSON
**run report** tying together a manifest (config, resolved backends, device
topology, env overrides, package version), per-phase aggregates (count, wall,
and the analytic GFLOP/GB/MFU figures the dispatch sites credit through
``utils/flops``), sampled device memory, and per-phase jit compile counts.
Multi-host runs write one trace file per process
(``trace.<process_index>.jsonl``) and the coordinator merges them into the
report's ``per_host`` section so a straggling host's phase walls are visible
next to its peers'.

Everything here is host-side bookkeeping: no device computation, no effect
on traced code beyond the ``trace`` hooks models already expose, and zero
file I/O unless a sink or report path was requested.
"""

from __future__ import annotations

import json
import os

from hdbscan_tpu.utils.tracing import TRACE_SCHEMA, Tracer

#: Version tag carried by the run report. Bump the integer suffix on any
#: backwards-incompatible report-shape change. /2: ``memory`` gained the
#: per-phase ``watermarks`` table (``obs/audit.MemoryAuditor`` peaks) next
#: to the start/end samples. /3: the mesh-observability sections —
#: ``timeline`` (per-phase comm/compute/host decomposition + skew from the
#: ``device_timeline`` events) and ``roofline`` (achieved GFLOP/s / GB/s,
#: arithmetic intensity, bound classification, honest tags) — and
#: watermark rows carry ``sampled``.
REPORT_SCHEMA = "hdbscan-tpu-report/3"

#: Env vars echoed into the manifest when set: anything that changes what the
#: run computes or how its figures are derived, without appearing in argv.
_MANIFEST_ENV_VARS = (
    "HDBSCAN_TPU_PEAK_FLOPS",
    "HDBSCAN_TPU_TRACE",
    "HDBSCAN_TPU_CACHE_DIR",
    "HDBSCAN_TPU_SLOW",
    "JAX_PLATFORMS",
    "JAX_ENABLE_X64",
    "XLA_FLAGS",
)

#: The jax.monitoring duration event emitted once per backend (XLA) compile.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def json_sanitize(obj):
    """Recursively coerce numpy scalars/arrays, tuples and other non-JSON
    values to plain Python so ``json.dumps`` never trips on a trace field."""
    import numpy as np

    if isinstance(obj, dict):
        return {str(k): json_sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_sanitize(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return json_sanitize(obj.tolist())
    if isinstance(obj, (np.bool_, bool)):
        return bool(obj)
    if isinstance(obj, (np.integer, int)):
        return int(obj)
    if isinstance(obj, (np.floating, float)):
        return float(obj)
    if obj is None or isinstance(obj, str):
        return obj
    return str(obj)


# --------------------------------------------------------------------------
# Compile tracking
# --------------------------------------------------------------------------

_compile_count = [0]
_compile_listener_installed = [False]


def compile_counter():
    """A zero-arg callable returning the process-wide XLA backend-compile
    count. Pass it as a :class:`Tracer` counter (``{"jit_compiles": ...}``)
    to attribute compiles to phases. The ``jax.monitoring`` listener is
    installed once per process on first call (jax exposes no unregister, so
    installation is permanent — an int increment per compile, nothing more).
    """
    if not _compile_listener_installed[0]:
        import jax.monitoring

        def _on_duration(name, secs, **kw):
            if name == _COMPILE_EVENT:
                _compile_count[0] += 1

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _compile_listener_installed[0] = True
    return lambda: _compile_count[0]


_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_cache_hit_count = [0]
_cache_hit_listener_installed = [False]


def cache_hit_counter():
    """A zero-arg callable returning the process-wide persistent-compile-
    cache hit count (jax's ``/jax/compilation_cache/cache_hits`` monitoring
    event). Pair it with :func:`compile_counter` as a :class:`Tracer`
    counter (``{"cache_hits": ...}``) so run reports show how much of the
    compile bill the on-disk cache absorbed: a warmed machine reports
    ``cache_hits ~= jit_compiles`` of a cold run, while ``cache_hits == 0``
    with a cache dir configured means the cache never matched (key drift —
    jaxlib/flag change). Install-once semantics match
    :func:`compile_counter` (jax exposes no unregister)."""
    if not _cache_hit_listener_installed[0]:
        import jax.monitoring

        def _on_event(name, **kw):
            if name == _CACHE_HIT_EVENT:
                _cache_hit_count[0] += 1

        jax.monitoring.register_event_listener(_on_event)
        _cache_hit_listener_installed[0] = True
    return lambda: _cache_hit_count[0]


# --------------------------------------------------------------------------
# Manifest: what did this run resolve to
# --------------------------------------------------------------------------


def device_topology() -> dict:
    """Device/process topology from ``jax.devices()`` — enough to read a
    report without the machine: platform, counts, and per-device kind/host."""
    import jax

    devices = jax.devices()
    return {
        "platform": devices[0].platform if devices else "none",
        "device_count": len(devices),
        "local_device_count": jax.local_device_count(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "devices": [
            {
                "id": d.id,
                "kind": d.device_kind,
                "process_index": d.process_index,
            }
            for d in devices
        ],
    }


def env_overrides() -> dict:
    """The run-shaping env vars that are actually set (see
    ``_MANIFEST_ENV_VARS``) — the manifest's answer to "what did the
    environment quietly change"."""
    return {k: os.environ[k] for k in _MANIFEST_ENV_VARS if k in os.environ}


def run_manifest(params=None, argv=None, extra: dict | None = None) -> dict:
    """The run's identity card: config dataclass, resolved backends, device
    topology, env overrides, package version. ``params`` is an
    ``HDBSCANParams`` (or None for library runs without one)."""
    import dataclasses

    import jax

    from hdbscan_tpu import __version__
    from hdbscan_tpu.utils import flops

    manifest = {
        "package_version": __version__,
        "jax_version": jax.__version__,
        "argv": list(argv) if argv is not None else None,
        "params": (
            json_sanitize(dataclasses.asdict(params)) if params is not None else None
        ),
        "backends": {
            "default_backend": jax.default_backend(),
            "knn_backend": getattr(params, "knn_backend", None),
            "scan_backend": getattr(params, "scan_backend", None),
            "fit_sharding": getattr(params, "fit_sharding", None),
            "tree_backend": getattr(params, "tree_backend", None),
            "mst_backend": getattr(params, "mst_backend", None),
        },
        "topology": device_topology(),
        "env": env_overrides(),
        "peak_flops": flops.PEAK_FLOPS,
    }
    if getattr(params, "fit_sharding", None) is not None:
        # The reviewable record of which fit state shards and which
        # replicates — the partition-rule table the sharded program pins at
        # phase boundaries (``parallel/shard.py``).
        from hdbscan_tpu.parallel.shard import partition_rule_table

        manifest["sharding"] = {
            "fit_sharding": params.fit_sharding,
            "partition_rules": partition_rule_table(),
        }
    if extra:
        manifest.update(json_sanitize(extra))
    return manifest


# --------------------------------------------------------------------------
# Device memory sampling
# --------------------------------------------------------------------------


def sample_device_memory() -> dict:
    """Per-device memory figures: ``device.memory_stats()`` where the backend
    implements it (TPU/GPU — bytes_in_use, peak_bytes_in_use), else the
    ``jax.live_arrays()`` fallback (CPU backends return no allocator stats;
    summed live-array bytes is the observable proxy)."""
    import jax

    per_device = []
    any_stats = False
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            any_stats = True
            per_device.append(
                {
                    "id": d.id,
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
            )
        else:
            per_device.append({"id": d.id})
    sample = {"source": "memory_stats" if any_stats else "live_arrays"}
    if any_stats:
        sample["devices"] = per_device
    else:
        live = jax.live_arrays()
        sample["live_array_count"] = len(live)
        sample["live_array_bytes"] = int(sum(int(a.nbytes) for a in live))
    return json_sanitize(sample)


# --------------------------------------------------------------------------
# Report: per-phase aggregates over the trace
# --------------------------------------------------------------------------

#: Event fields summed into the per-phase aggregates (the analytic figures
#: ``utils/flops.phase_stats`` attaches, plus the compile counter field).
_SUMMED_FIELDS = ("gflops", "gbytes", "pad_gflops", "jit_compiles", "cache_hits")


def phase_aggregates(events) -> dict:
    """``{stage: {count, wall_s, gflops?, gbytes?, pad_gflops?,
    jit_compiles?, gflops_s?, mfu?}}`` over a list of
    :class:`~hdbscan_tpu.utils.tracing.TraceEvent` (or JSONL line dicts).
    Wall totals are plain float sums of the events' ``wall_s`` — exactly
    ``Tracer.total(stage)``. Rates re-derive from the SUMMED figures (a
    phase's aggregate MFU over its total wall, not a mean of per-event
    rates)."""
    from hdbscan_tpu.utils import flops

    agg: dict[str, dict] = {}
    for ev in events:
        if isinstance(ev, dict):
            name, wall, fields = ev.get("stage"), ev.get("wall_s", 0.0), ev
        else:
            name, wall, fields = ev.name, ev.wall_s, ev.fields
        row = agg.setdefault(name, {"count": 0, "wall_s": 0.0})
        row["count"] += 1
        row["wall_s"] += float(wall)
        for key in _SUMMED_FIELDS:
            val = fields.get(key)
            if val is not None:
                row[key] = row.get(key, 0.0) + float(val)
    for row in agg.values():
        gf = row.get("gflops")
        if gf and row["wall_s"] > 0:
            row["gflops_s"] = round(gf / row["wall_s"], 1)
            row["mfu"] = round(gf * 1e9 / row["wall_s"] / flops.PEAK_FLOPS, 6)
        if "jit_compiles" in row:
            row["jit_compiles"] = int(row["jit_compiles"])
        if "cache_hits" in row:
            row["cache_hits"] = int(row["cache_hits"])
    # Expensive phases first, matching Tracer.summary().
    return dict(sorted(agg.items(), key=lambda kv: -kv[1]["wall_s"]))


def build_report(
    tracer: Tracer,
    manifest: dict | None = None,
    memory: dict | None = None,
    per_host: dict | None = None,
    timeline: dict | None = None,
    roofline_tags=None,
) -> dict:
    """Assemble the run report dict from a tracer's collected events.

    ``memory``: e.g. ``{"start": sample, "end": sample}`` from
    :func:`sample_device_memory`. ``per_host``: the
    :func:`merge_host_traces` result for multi-host runs. ``timeline``:
    a :meth:`~hdbscan_tpu.obs.timeline.TimelineRecorder.phase_table`
    (the exact figures when a recorder ran; otherwise the section
    reconstructs from the trace's ``device_timeline`` events).
    ``roofline_tags``: honesty tags for the roofline section; None picks
    :func:`~hdbscan_tpu.obs.roofline.default_tags`.
    """
    phases = phase_aggregates(tracer.events)
    report = {
        "schema": REPORT_SCHEMA,
        "manifest": manifest or {},
        "phases": phases,
        "total_wall_s": round(sum(p["wall_s"] for p in phases.values()), 6),
        "event_count": len(tracer.events),
    }
    knn_index = knn_index_section(tracer)
    if knn_index is not None:
        report["knn_index"] = knn_index
    mst_device = mst_device_section(tracer)
    if mst_device is not None:
        report["mst_device"] = mst_device
    stream = stream_section(tracer)
    if stream is not None:
        report["stream"] = stream
    spans = request_span_section(tracer)
    if spans is not None:
        report["request_spans"] = spans
    controlplane = controlplane_section(tracer)
    if controlplane is not None:
        report["controlplane"] = controlplane
    watermarks = memory_watermark_section(tracer)
    if memory is not None or watermarks is not None:
        mem = dict(memory) if memory is not None else {}
        if watermarks is not None:
            mem["watermarks"] = watermarks
        report["memory"] = json_sanitize(mem)
    tl_table = timeline
    if tl_table is None:
        tl_table = timeline_section(tracer)
    if tl_table:
        report["timeline"] = json_sanitize(tl_table)
    from hdbscan_tpu.obs.roofline import roofline_section

    roofline = roofline_section(phases, tl_table, tags=roofline_tags)
    if roofline is not None:
        report["roofline"] = json_sanitize(roofline)
    if per_host is not None:
        report["per_host"] = per_host
    return report


def latency_percentiles(walls: list[float] | tuple[float, ...]) -> dict:
    """Nearest-rank p50/p95/p99/p999 (plus count/mean/max) over per-batch
    walls.

    Nearest-rank (index ``ceil(q*n) - 1`` into the sorted walls) rather than
    interpolation so ``scripts/check_trace.py`` can recompute the exact same
    numbers stdlib-only and cross-check the report against the trace at
    1e-6.
    """
    ws = sorted(float(w) for w in walls)
    n = len(ws)
    if n == 0:
        return {"count": 0}
    import math

    def rank(q: float) -> float:
        return ws[max(0, math.ceil(q * n) - 1)]

    return {
        "count": n,
        "mean_s": round(sum(ws) / n, 6),
        "p50_s": round(rank(0.50), 6),
        "p95_s": round(rank(0.95), 6),
        "p99_s": round(rank(0.99), 6),
        "p999_s": round(rank(0.999), 6),
        "max_s": round(ws[-1], 6),
    }


def slo_verdict(observed: dict, targets: dict) -> dict:
    """Target-vs-attainment verdict for the SLO bench leg.

    ``targets`` maps a metric name in ``observed`` to a bound dict with
    ``"max"`` (upper bound: latencies) and/or ``"min"`` (lower bound:
    throughput). Returns per-metric rows ``{observed, max?/min?, ok}``
    plus an overall ``ok`` — a metric missing from ``observed`` fails its
    target rather than passing silently."""
    rows: dict = {}
    all_ok = True
    for metric, bound in targets.items():
        value = observed.get(metric)
        row = {"observed": value}
        ok = value is not None
        if "max" in bound:
            row["max"] = bound["max"]
            ok = ok and value <= bound["max"]
        if "min" in bound:
            row["min"] = bound["min"]
            ok = ok and value >= bound["min"]
        row["ok"] = bool(ok)
        all_ok = all_ok and ok
        rows[metric] = row
    return {"targets": rows, "ok": bool(all_ok)}


def request_span_section(tracer: Tracer) -> dict | None:
    """The run report's ``request_spans`` section: per-request serving
    aggregates over every ``request_span`` event — span-wall percentiles,
    rows served, the per-segment wall decomposition (parse / queue /
    assemble / predict / respond totals), and the mean coalesced-peer
    count. None when the run emitted no spans (section omitted)."""
    spans = [e for e in tracer.events if e.name == "request_span"]
    if not spans:
        return None
    section = latency_percentiles([e.wall_s for e in spans])
    rows = sum(int(e.fields.get("rows", 0)) for e in spans)
    wall = sum(e.wall_s for e in spans)
    section["rows"] = rows
    if wall > 0:
        section["rows_per_s"] = round(rows / wall, 1)
    section["segments_s"] = {
        seg: round(sum(float(e.fields.get(seg, 0.0)) for e in spans), 6)
        for seg in ("parse_s", "queue_s", "assemble_s", "predict_s", "respond_s")
    }
    section["coalesced_mean"] = round(
        sum(int(e.fields.get("coalesced", 1)) for e in spans) / len(spans), 3
    )
    return section


def knn_index_section(tracer: Tracer) -> dict | None:
    """The run report's ``knn_index`` section: build/query/rescan aggregates
    for the rp-forest approximate-neighbor tier (``config.knn_index``).
    Walls sum per stage; ``recall_at_k`` reports the LAST query event's
    sampled recall (the post-merge figure — earlier events are per-stage
    diagnostics) and ``rescan_improved`` totals the rows each
    neighbor-of-neighbor round tightened. None when the run never built an
    index (exact tier), so the section is omitted rather than empty."""
    build = [e for e in tracer.events if e.name == "knn_index_build"]
    query = [e for e in tracer.events if e.name == "knn_index_query"]
    rescan = [e for e in tracer.events if e.name == "knn_index_rescan"]
    if not build and not query and not rescan:
        return None
    section: dict = {
        "builds": len(build),
        "build_wall_s": round(sum(e.wall_s for e in build), 6),
        "queries": len(query),
        "query_wall_s": round(sum(e.wall_s for e in query), 6),
        "rescan_rounds": len(rescan),
        "rescan_wall_s": round(sum(e.wall_s for e in rescan), 6),
    }
    if build:
        last = build[-1].fields
        for key in ("trees", "depth", "leaf_size", "max_leaf", "n"):
            if last.get(key) is not None:
                section[key] = int(last[key])
    recalls = [
        e.fields["recall_at_k"]
        for e in query
        if e.fields.get("recall_at_k") is not None
    ]
    if recalls:
        section["recall_at_k"] = float(recalls[-1])
    if rescan:
        section["rescan_improved"] = int(
            sum(int(e.fields.get("improved", 0)) for e in rescan)
        )
    return section


def mst_device_section(tracer: Tracer) -> dict | None:
    """The run report's ``mst_device`` section: the single-sync contract of
    the device-resident MST -> forest pipeline (``core/mst_device.py``) made
    auditable. ``host_syncs``/``sync_bytes`` count and size every
    ``host_sync`` fetch (exactly one per device fit/forest rebuild),
    ``rounds`` the retrospective Borůvka ``mst_round`` events, and
    ``fallbacks`` how many ``tree_build_device`` builds hit the runtime
    eligibility gate and fell back to the host builder. None when the run
    never entered the device path (the section is omitted, not empty)."""
    syncs = [e for e in tracer.events if e.name == "host_sync"]
    rounds = [e for e in tracer.events if e.name == "mst_round"]
    builds = [e for e in tracer.events if e.name == "tree_build_device"]
    if not syncs and not rounds and not builds:
        return None
    return {
        "host_syncs": len(syncs),
        "sync_bytes": int(sum(int(e.fields.get("bytes", 0)) for e in syncs)),
        "sync_wall_s": round(sum(e.wall_s for e in syncs), 6),
        "rounds": len(rounds),
        "forest_builds": len(builds),
        "fallbacks": int(
            sum(1 for e in builds if e.fields.get("fallback"))
        ),
        "build_wall_s": round(sum(e.wall_s for e in builds), 6),
    }


def stream_section(tracer: Tracer) -> dict | None:
    """The run report's ``stream`` section: online-maintenance aggregates
    (``hdbscan_tpu/stream`` + ``serve/server.py``). Totals every
    ``stream_ingest`` event's row routing (``absorb_ratio`` = absorbed /
    rows — how much of the stream the bubble summaries soaked up without
    buffering), counts ``drift_check`` evaluations and how many flagged,
    ``model_refit`` outcomes, and for ``model_swap`` the generation reached
    plus the max in-lock pause (the blue/green "zero pause" claim, made a
    number). None when the run never ingested."""
    ingest = [e for e in tracer.events if e.name == "stream_ingest"]
    if not ingest:
        return None
    rows = sum(int(e.fields.get("rows", 0)) for e in ingest)
    absorbed = sum(int(e.fields.get("absorbed", 0)) for e in ingest)
    checks = [e for e in tracer.events if e.name == "drift_check"]
    refits = [e for e in tracer.events if e.name == "model_refit"]
    swaps = [e for e in tracer.events if e.name == "model_swap"]
    section = {
        "ingest_batches": len(ingest),
        "rows": int(rows),
        "absorbed": int(absorbed),
        "absorb_ratio": round(absorbed / rows, 6) if rows else 0.0,
        "ingest_wall_s": round(sum(e.wall_s for e in ingest), 6),
        "drift_checks": len(checks),
        "drift_flags": int(sum(1 for e in checks if e.fields.get("drifted"))),
        "refits": len(refits),
        "refits_ok": int(sum(1 for e in refits if e.fields.get("ok"))),
    }
    if swaps:
        section["swaps"] = len(swaps)
        section["generation"] = int(
            max(int(e.fields.get("generation", 0)) for e in swaps)
        )
        section["swap_pause_max_s"] = round(
            max(float(e.fields.get("pause_s", e.wall_s)) for e in swaps), 9
        )
    return section


def timeline_section(tracer: Tracer) -> dict | None:
    """The run report's ``timeline`` section reconstructed from the trace's
    ``device_timeline`` events — the fallback when no live
    :class:`~hdbscan_tpu.obs.timeline.TimelineRecorder` table is at hand
    (e.g. rebuilding a report from a trace file). Per phase: per-round
    max-device walls sum into ``wall_s`` (the critical path), segment
    means sum per round, skew is the worst round's max/median, and
    ``straggler_flags`` counts the phase's ``straggler_flag`` events.
    None when the run recorded no timelines (the section is omitted)."""
    rows = [e for e in tracer.events if e.name == "device_timeline"]
    if not rows:
        return None
    flags = [e for e in tracer.events if e.name == "straggler_flag"]
    # Group rows into rounds in emission order: a new (phase, round) pair
    # or a repeated device id closes the open group for that phase.
    groups: list[dict] = []
    open_group: dict[str, dict] = {}
    for e in rows:
        f = e.fields
        phase = str(f.get("phase", "?"))
        rnd = int(f.get("round", 0))
        dev = int(f.get("device", 0))
        g = open_group.get(phase)
        if g is None or g["round"] != rnd or dev in g["devices"]:
            g = {"phase": phase, "round": rnd, "devices": {}, "rows": []}
            open_group[phase] = g
            groups.append(g)
        g["devices"][dev] = True
        g["rows"].append(
            (
                float(e.wall_s),
                float(f.get("compute_s", 0.0)),
                float(f.get("comm_s", 0.0)),
                float(f.get("host_s", 0.0)),
                int(f.get("comm_bytes", 0)),
            )
        )
    table: dict[str, dict] = {}
    for g in groups:
        n_dev = len(g["rows"])
        walls = sorted(r[0] for r in g["rows"])
        median = (
            walls[n_dev // 2]
            if n_dev % 2
            else 0.5 * (walls[n_dev // 2 - 1] + walls[n_dev // 2])
        )
        skew = (walls[-1] / median) if median > 0 else 1.0
        ph = table.setdefault(
            g["phase"],
            {
                "rounds": 0,
                "devices": 0,
                "wall_s": 0.0,
                "compute_s": 0.0,
                "comm_s": 0.0,
                "host_s": 0.0,
                "comm_bytes": 0,
                "max_skew": 1.0,
            },
        )
        ph["rounds"] += 1
        ph["devices"] = max(ph["devices"], n_dev)
        ph["wall_s"] += walls[-1]
        ph["compute_s"] += sum(r[1] for r in g["rows"]) / n_dev
        ph["comm_s"] += sum(r[2] for r in g["rows"]) / n_dev
        ph["host_s"] += sum(r[3] for r in g["rows"]) / n_dev
        ph["comm_bytes"] += sum(r[4] for r in g["rows"])
        ph["max_skew"] = max(ph["max_skew"], skew)
    out: dict[str, dict] = {}
    for name, ph in table.items():
        total = ph["compute_s"] + ph["comm_s"] + ph["host_s"]
        skew = ph.pop("max_skew")
        out[name] = {
            **{
                k: (round(v, 9) if isinstance(v, float) else v)
                for k, v in ph.items()
            },
            "comm_frac": round(ph["comm_s"] / total, 6) if total > 0 else 0.0,
            "skew": round(skew, 6),
            "straggler_flags": sum(
                1 for e in flags if str(e.fields.get("phase")) == name
            ),
        }
    return out


def memory_watermark_section(tracer: Tracer) -> dict | None:
    """The run report's ``memory.watermarks`` table: per-phase device-memory
    peaks over every ``mem_phase_peak`` event the
    :class:`~hdbscan_tpu.obs.audit.MemoryAuditor` emitted. Repeated phases
    max-merge (peaks) and sum (samples, wall) — the same merge the auditor's
    in-memory table applies — so the section reads as "the worst any single
    device ever held during this phase, across the whole run". None when the
    run was not audited (the section is omitted, not empty)."""
    peaks = [e for e in tracer.events if e.name == "mem_phase_peak"]
    if not peaks:
        return None
    table: dict[str, dict] = {}
    for e in peaks:
        f = e.fields
        phase = str(f.get("phase", "?"))
        row = table.setdefault(
            phase,
            {
                "source": f.get("source"),
                "samples": 0,
                "sampled": False,
                "devices": 0,
                "max_device_bytes": 0,
                "total_bytes": 0,
                "wall_s": 0.0,
            },
        )
        row["samples"] += int(f.get("samples", 0))
        # Older traces lack the field: infer from samples so rebuilt
        # reports agree with the auditor's in-memory table.
        row["sampled"] = row["sampled"] or bool(
            f.get("sampled", int(f.get("samples", 0)) > 0)
        )
        row["devices"] = max(row["devices"], int(f.get("devices", 0)))
        row["max_device_bytes"] = max(
            row["max_device_bytes"], int(f.get("max_device_bytes", 0))
        )
        row["total_bytes"] = max(row["total_bytes"], int(f.get("total_bytes", 0)))
        row["wall_s"] = round(row["wall_s"] + float(e.wall_s), 9)
    # Heaviest phases first, matching phase_aggregates' ordering convention.
    return dict(sorted(table.items(), key=lambda kv: -kv[1]["max_device_bytes"]))


def predict_latency_section(tracer: Tracer) -> dict | None:
    """The run report's ``predict_latency`` section: percentiles over every
    ``predict_batch`` event plus total rows served and rows/s; None when the
    run served no predictions (the section is omitted, not empty)."""
    events = [e for e in tracer.events if e.name == "predict_batch"]
    if not events:
        return None
    section = latency_percentiles([e.wall_s for e in events])
    rows = sum(int(e.fields.get("rows", 0)) for e in events)
    wall = sum(e.wall_s for e in events)
    section["rows"] = rows
    if wall > 0:
        section["rows_per_s"] = round(rows / wall, 1)
    return section


def controlplane_section(tracer: Tracer) -> dict | None:
    """The run report's ``controlplane`` section: fleet elasticity and
    fit-as-a-service aggregates over ``scale_event`` / ``fit_job`` /
    ``artifact_map`` events. ``scaling`` counts ups/downs (and failures)
    by reason; ``fit_jobs`` counts terminal outcomes per tenant plus the
    mean queue wait; ``artifacts`` reports load hit rate and the LAST
    event's resident footprint (the store only grows within a process,
    so last == high-water). None when the run had no control plane."""
    scale = [e for e in tracer.events if e.name == "scale_event"]
    jobs = [e for e in tracer.events if e.name == "fit_job"]
    art = [e for e in tracer.events if e.name == "artifact_map"]
    if not (scale or jobs or art):
        return None
    section: dict = {}
    if scale:
        reasons: dict = {}
        for e in scale:
            key = str(e.fields.get("reason", "unknown"))
            reasons[key] = reasons.get(key, 0) + 1
        section["scaling"] = {
            "events": len(scale),
            "up": sum(1 for e in scale if e.fields.get("direction") == "up"),
            "down": sum(
                1 for e in scale if e.fields.get("direction") == "down"
            ),
            "failed": sum(1 for e in scale if not e.fields.get("ok", True)),
            "reasons": reasons,
            "mean_wall_s": round(
                sum(e.wall_s for e in scale) / len(scale), 6
            ),
        }
    if jobs:
        per_tenant: dict = {}
        for e in jobs:
            state = str(e.fields.get("state", ""))
            if state not in ("published", "failed"):
                continue
            tenant = str(e.fields.get("tenant", "?"))
            per_tenant.setdefault(tenant, {"published": 0, "failed": 0})
            per_tenant[tenant][state] += 1
        queued = [
            float(e.fields["queued_s"]) for e in jobs if "queued_s" in e.fields
        ]
        section["fit_jobs"] = {
            "events": len(jobs),
            "published": sum(
                1 for e in jobs if e.fields.get("state") == "published"
            ),
            "failed": sum(
                1 for e in jobs if e.fields.get("state") == "failed"
            ),
            "tenants": per_tenant,
        }
        if queued:
            section["fit_jobs"]["mean_queued_s"] = round(
                sum(queued) / len(queued), 6
            )
    if art:
        hits = sum(1 for e in art if e.fields.get("hit"))
        per_digest = {}  # bytes is per-digest; total = sum over digests
        for e in art:
            per_digest[str(e.fields.get("digest", "?"))] = int(
                e.fields.get("bytes", 0)
            )
        section["artifacts"] = {
            "loads": len(art),
            "hits": hits,
            "misses": len(art) - hits,
            "spooled": sum(1 for e in art if e.fields.get("spooled")),
            "resident": int(art[-1].fields.get("resident", 0)),
            "resident_bytes": sum(per_digest.values()),
        }
    return section


def write_report(path: str, report: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(json_sanitize(report), f, indent=2, sort_keys=False)
        f.write("\n")


# --------------------------------------------------------------------------
# Multi-host: per-process trace files and the coordinator merge
# --------------------------------------------------------------------------


def trace_path_for_process(path: str, process_index: int, process_count: int) -> str:
    """Per-process trace file name: the literal path for single-process runs;
    ``<stem>.<process_index><ext>`` (``trace.3.jsonl``) when several
    processes share the requested base path."""
    if process_count <= 1:
        return path
    stem, ext = os.path.splitext(path)
    return f"{stem}.{process_index}{ext}"


def host_trace_paths(path: str, process_count: int) -> list[str]:
    """Every process's trace path for a given base path (coordinator side)."""
    return [
        trace_path_for_process(path, i, process_count) for i in range(process_count)
    ]


def read_trace(path: str) -> list[dict]:
    """Parse a JSONL trace file into its line dicts (schema-checked softly:
    non-matching lines are kept — the validator is ``scripts/check_trace.py``)."""
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


def merge_host_traces(paths: list[str]) -> dict:
    """Merge per-process JSONL traces into ``{host: {stage: {count, wall_s,
    ...}}}`` — one phase-aggregate table per host, so a straggler's phase
    walls sit next to its peers'. The host key is the trace's ``process``
    field when present, else the file's position in ``paths``. Missing files
    appear as ``{"missing": true}`` (a rank that died before writing is
    itself a finding)."""
    merged: dict[str, dict] = {}
    for i, path in enumerate(paths):
        if not os.path.exists(path):
            merged[str(i)] = {"missing": True}
            continue
        events = read_trace(path)
        host = str(events[0].get("process", i)) if events else str(i)
        merged[host] = phase_aggregates(events)
    return merged
