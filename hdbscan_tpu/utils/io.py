"""Dataset ingest and the five canonical output files.

Ingest re-designs ``mappers/MapperDataset_github.java:12-20`` (whitespace-split
lines -> (rowIndex, double[])); both bundled datasets load with the same
reader (``数据集/dataset.txt`` space-separated, ``数据集/Skin_NonSkin.txt``
tab-separated). Output formats follow the reference's documented contract
(``main/Main.java:534-614``):

- ``<base>_hierarchy.csv``: ``<epsilon>,<label_1>,...,<label_n>`` per level
  (descending); noise = 0. Full hierarchy = every processed edge-weight level;
  compact = only levels where clusters are born or die.
- ``<base>_tree.csv``: ``<label>,<birth>,<death>,<stability>,<gamma>,
  <virtual child gamma>,<character_offset>,<parent>``.
- ``<base>_partition.csv``: one line of flat labels.
- ``<base>_outlier_scores.csv``: ``<score>,<id>`` sorted most-inlier first
  (ties by core distance then id, ``hdbscanstar/OutlierScore.java:36-50``).
- ``<base>_visualization.vis``: auxiliary summary for the visualization module.
"""

from __future__ import annotations

import numpy as np

from hdbscan_tpu.core.tree import CondensedTree


def load_points(path: str, max_rows: int | None = None) -> np.ndarray:
    """Whitespace/comma tolerant float matrix loader (one object per line).

    Any comma in the first line selects CSV mode (np.loadtxt strips spaces
    around comma-separated fields); otherwise whitespace-split, which covers
    both bundled datasets (space- and tab-separated).
    """
    with open(path) as f:
        first = f.readline()
    delim = "," if "," in first else None
    return np.loadtxt(path, delimiter=delim, max_rows=max_rows, dtype=np.float64)


def hierarchy_levels(tree: CondensedTree, compact: bool) -> np.ndarray:
    """Significant epsilon levels, descending."""
    births = tree.birth[1:]
    deaths = tree.death[1:]
    if compact:
        levels = np.concatenate([births, deaths])
    else:
        levels = np.concatenate([births, deaths, tree.point_exit_level])
    levels = levels[np.isfinite(levels) & (levels > 0)]
    return np.unique(levels)[::-1]


def _ancestor_chains(
    tree: CondensedTree, labels: np.ndarray
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Per label: (chain labels root-first, their births — descending along
    the chain). The single chain-walk implementation shared by the matrix
    and streaming hierarchy paths."""
    out = []
    for label in labels:
        labels_c, births_c = [], []
        c = int(label)
        while c > 0:
            labels_c.append(c)
            births_c.append(tree.birth[c])
            c = int(tree.parent[c]) if tree.parent[c] > 0 else 0
        out.append((np.array(labels_c[::-1]), np.array(births_c[::-1])))
    return out


def hierarchy_matrix(tree: CondensedTree, levels: np.ndarray) -> np.ndarray:
    """(L, n) label matrix: row r = labels after processing level ``levels[r]``.

    Label of point p at level w: 0 if p exited at a level >= w, else the
    deepest cluster on p's ancestor chain born at level >= w (clusters that
    "continue" keep their label, mirroring currentClusterLabels semantics in
    ``HdbscanDataBubbles.java:256-374``).
    """
    n = tree.n_points
    out = np.zeros((len(levels), n), np.int64)
    # One chain walk + searchsorted per DISTINCT last-cluster (not per point):
    # points sharing a last cluster share the whole label column except the
    # exit cutoff, which is vectorized below.
    uniq = np.unique(tree.point_last_cluster)
    for label, (labels_c, births_c) in zip(uniq, _ancestor_chains(tree, uniq)):
        # deepest cluster with birth >= w
        pos = np.searchsorted(-births_c, -levels, side="right") - 1
        col = labels_c[np.clip(pos, 0, len(labels_c) - 1)]
        pts = np.nonzero(tree.point_last_cluster == label)[0]
        exits = tree.point_exit_level[pts]
        exited = (exits[None, :] > 0) & (levels[:, None] <= exits[None, :])
        out[:, pts] = np.where(exited, 0, col[:, None])
    return out


def write_hierarchy_file(path: str, tree: CondensedTree, compact: bool, delimiter: str = ",") -> dict[int, int]:
    """Writes the hierarchy file; returns {cluster label: char offset of the
    first row where it appears} (the ``fileOffset`` of ``Cluster.java:165``).

    Streams one level row at a time in O(n) memory — never the (L, n) label
    matrix, which at a 1M-point FULL hierarchy (L ~ distinct edge weights)
    would be tens of GB. Levels descend, so each distinct last-cluster chain
    keeps a monotone pointer to its deepest cluster born at >= the current
    level; rows are byte-identical to the matrix path
    (:func:`hierarchy_matrix`, kept for tests/diagnostics).
    """
    levels = hierarchy_levels(tree, compact)
    offsets: dict[int, int] = {}
    pos = 0
    # One ancestor-chain walk per DISTINCT last cluster (not per point).
    uniq, chain_of_point = np.unique(tree.point_last_cluster, return_inverse=True)
    chains = _ancestor_chains(tree, uniq)
    # Event-driven pointer advance: chain element j becomes current at the
    # first (descending) level row where its birth >= the row's level —
    # precomputed with one searchsorted per chain, so the per-level work is
    # O(events at that row) instead of a Python sweep over every chain.
    cur = np.array([labels_c[0] for labels_c, _ in chains], np.int64)
    ev_row, ev_chain, ev_label = [], [], []
    for ci, (labels_c, births_c) in enumerate(chains):
        if len(labels_c) > 1:
            rows = np.searchsorted(-levels, -births_c[1:], side="left")
            ev_row.append(rows)
            ev_chain.append(np.full(len(rows), ci, np.int64))
            ev_label.append(labels_c[1:])
    if ev_row:
        ev_row = np.concatenate(ev_row)
        ev_chain = np.concatenate(ev_chain)
        ev_label = np.concatenate(ev_label)
        # stable by (row, chain depth order): deeper elements of a chain come
        # later in each chain's slice, so the deepest born-at-this-row wins.
        order = np.argsort(ev_row, kind="stable")
        ev_row, ev_chain, ev_label = ev_row[order], ev_chain[order], ev_label[order]
    else:
        ev_row = np.zeros(0, np.int64)
        ev_chain = ev_label = np.zeros(0, np.int64)
    ev_i = 0
    exits = tree.point_exit_level
    has_exit = exits > 0
    with open(path, "w") as f:
        for r, w in enumerate(levels):
            while ev_i < len(ev_row) and ev_row[ev_i] <= r:
                cur[ev_chain[ev_i]] = ev_label[ev_i]
                ev_i += 1
            row = np.where(has_exit & (w <= exits), 0, cur[chain_of_point])
            line = f"{w:.9g}" + delimiter + delimiter.join(map(str, row)) + "\n"
            for lbl in np.unique(row):
                if lbl > 0 and lbl not in offsets:
                    offsets[int(lbl)] = pos
            f.write(line)
            pos += len(line)
    return offsets


def write_tree_file(
    path: str,
    tree: CondensedTree,
    offsets: dict[int, int] | None = None,
    delimiter: str = ",",
) -> None:
    offsets = offsets or {}
    zeros = np.zeros(tree.n_clusters + 1, np.int64)
    cons = tree.num_constraints_satisfied if tree.num_constraints_satisfied is not None else zeros
    vcons = (
        tree.virtual_child_constraints
        if tree.virtual_child_constraints is not None
        else zeros
    )
    with open(path, "w") as f:
        for c in range(1, tree.n_clusters + 1):
            parent = tree.parent[c] if tree.parent[c] > 0 else 0
            row = [
                str(c),
                f"{tree.birth[c]:.9g}",
                f"{tree.death[c]:.9g}",
                f"{tree.stability[c]:.9g}",
                str(int(cons[c])),
                str(int(vcons[c])),
                str(offsets.get(c, 0)),
                str(int(parent)),
            ]
            f.write(delimiter.join(row) + "\n")


def write_partition_file(path: str, labels: np.ndarray, delimiter: str = ",") -> None:
    with open(path, "w") as f:
        f.write(delimiter.join(map(str, np.asarray(labels, np.int64))) + "\n")


def write_outlier_scores_file(
    path: str, scores: np.ndarray, core_distances: np.ndarray, delimiter: str = ","
) -> None:
    order = np.lexsort((np.arange(len(scores)), core_distances, scores))
    with open(path, "w") as f:
        for i in order:
            f.write(f"{scores[i]:.9g}{delimiter}{i}\n")


def write_visualization_file(path: str, tree: CondensedTree, labels: np.ndarray) -> None:
    """Auxiliary summary (the reference's .vis file is consumed only by an
    external visualization module; we emit a small self-describing version)."""
    import json

    sel = tree.selected if tree.selected is not None else np.zeros(1, bool)
    payload = {
        "n_points": int(tree.n_points),
        "n_clusters": int(tree.n_clusters),
        "selected": [int(c) for c in np.nonzero(sel)[0]],
        "n_noise": int(np.sum(np.asarray(labels) == 0)),
    }
    with open(path, "w") as f:
        json.dump(payload, f)
        f.write("\n")
