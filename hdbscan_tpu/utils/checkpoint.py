"""Per-level checkpoint/resume for the distributed pipeline.

The reference checkpoints implicitly: every stage boundary is a
``saveAsObjectFile`` to HDFS (``_unprocessed_<i>``, ``_local_mst<i>``, ... —
``main/Main.java:101,199,230,238,265,298``; SURVEY.md §5.4), so a crashed
driver can re-run from the last level's files. Here that capability is
explicit and compact: one ``.npz`` per completed level holding the entire
driver state (subset assignment, processed mask, core distances, pooled MST
edges, RNG state), written atomically; ``load_latest`` resumes from the
newest level whose parameter fingerprint matches.
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np

_PREFIX = "mr_level_"


#: Digest scheme version. A checkpoint written under a different scheme is
#: treated as absent (fresh start) rather than raising: the digest exists to
#: catch silent wrong-data resumes, not to brick old checkpoint dirs.
_DIGEST_SCHEME = "v2-"


def _data_digest(data) -> str:
    """Dataset identity: shape + a hash over the full buffer. One sequential
    pass (~6 MB for the 245k north-star set) is cheap next to any fit, and
    unlike a strided row sample it catches edits anywhere in the data, so a
    stale checkpoint can never resume silently. hashlib consumes the array
    via the buffer protocol — no host-RAM copy of multi-GB datasets."""
    import hashlib

    a = np.ascontiguousarray(data)
    h = hashlib.sha1()
    h.update(str(a.shape).encode())
    h.update(str(a.dtype).encode())
    h.update(a)
    return _DIGEST_SCHEME + h.hexdigest()[:16]


def _fingerprint(params, n: int, data_digest: str | None = None) -> dict:
    """The parameters that must match for a checkpoint to be resumable."""
    return {
        "n": int(n),
        "data": data_digest,
        "min_points": params.min_points,
        "min_cluster_size": params.min_cluster_size,
        "processing_units": params.processing_units,
        "k": params.k,
        "dist_function": params.dist_function,
        "variant": params.variant,
        "seed": params.seed,
        "exact_inter_edges": params.exact_inter_edges,
        "global_core_distances": params.global_core_distances,
        "boundary_quality": params.boundary_quality,
    }


def save_level(
    ckpt_dir: str,
    level: int,
    params,
    data_digest: str,
    subset: np.ndarray,
    processed: np.ndarray,
    core: np.ndarray,
    pool_u: np.ndarray,
    pool_v: np.ndarray,
    pool_w: np.ndarray,
    rng_state: dict,
    level_stats: list[dict],
    bmargin: np.ndarray | None = None,
    final_block: np.ndarray | None = None,
) -> str:
    """Write the post-level driver state; atomic via rename."""
    os.makedirs(ckpt_dir, exist_ok=True)
    meta = {
        "level": level,
        "fingerprint": _fingerprint(params, len(subset), data_digest),
        "rng_state": rng_state,
        "level_stats": level_stats,
    }
    path = os.path.join(ckpt_dir, f"{_PREFIX}{level:04d}.npz")
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
                subset=subset,
                processed=processed,
                core=core,
                pool_u=pool_u,
                pool_v=pool_v,
                pool_w=pool_w,
                bmargin=bmargin if bmargin is not None else np.zeros(0),
                final_block=final_block if final_block is not None else np.zeros(0),
            )
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_latest(ckpt_dir: str, params, n: int, data_digest: str | None = None) -> dict | None:
    """Newest matching checkpoint as a dict, or None.

    A checkpoint with a different parameter fingerprint raises — resuming a
    different configuration silently would corrupt results.
    """
    if not os.path.isdir(ckpt_dir):
        return None
    files = sorted(
        f for f in os.listdir(ckpt_dir) if f.startswith(_PREFIX) and f.endswith(".npz")
    )
    if not files:
        return None
    # Newest-to-oldest: files written under an older digest scheme are
    # unverifiable — skip them (rather than abort) so the newest
    # verifiable checkpoint still resumes.
    want = _fingerprint(params, n, data_digest)
    path = meta = None
    for name in reversed(files):
        cand = os.path.join(ckpt_dir, name)
        with np.load(cand) as z:
            m = json.loads(bytes(z["meta"]).decode())
        have = m["fingerprint"]
        if data_digest is not None and (have.get("data") or "").partition("-")[0] != (
            data_digest.partition("-")[0]
        ):
            continue
        path, meta = cand, m
        break
    if path is None:
        return None  # only older-scheme checkpoints present: start fresh
    if meta["fingerprint"] != want:
        raise ValueError(
            f"checkpoint {path} was written for {meta['fingerprint']}, "
            f"current run is {want}; pass a fresh checkpoint_dir"
        )
    with np.load(path) as z:
        return {
            "level": meta["level"],
            "rng_state": meta["rng_state"],
            "level_stats": meta["level_stats"],
            "subset": z["subset"],
            "processed": z["processed"],
            "core": z["core"],
            "pool_u": z["pool_u"],
            "pool_v": z["pool_v"],
            "pool_w": z["pool_w"],
            "bmargin": (
                z["bmargin"]
                if "bmargin" in z.files and len(z["bmargin"])
                else None
            ),
            "final_block": (
                z["final_block"]
                if "final_block" in z.files and len(z["final_block"])
                else None
            ),
        }
