"""Structured per-stage tracing — the observability layer the reference lacks.

The reference's only progress reporting is ``System.out.println`` of iteration
numbers and HDFS file names, partly in Portuguese (``main/Main.java:108,200,
232-233,316,383``; SURVEY.md §5.1). Here every pipeline stage can emit a
structured event (name, wall seconds, counters) through a :class:`Tracer`,
which streams to pluggable sinks: logfmt lines on a text stream for live
progress, or schema-versioned JSON lines on disk (:class:`JsonlSink`) for the
durable per-run artifact the report builder (``utils/telemetry.py``)
aggregates. An optional ``jax.profiler`` context captures full XLA traces for
TensorBoard.

The deep-observability layer (``hdbscan_tpu/obs``) emits through the same
Tracer: ``mem_sample``/``mem_phase_peak`` from the device-memory auditor,
``heartbeat``/``watchdog_stall`` from the progress hub, and ``router_span``
from the fleet router (joinable with replica ``request_span`` events on
``request_id`` — ``scripts/check_trace.py --join``).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

#: Version tag carried by every JSONL trace line. Bump the integer suffix on
#: any backwards-incompatible line-shape change; ``scripts/check_trace.py``
#: validates the prefix.
TRACE_SCHEMA = "hdbscan-tpu-trace/1"


@dataclass
class TraceEvent:
    name: str
    wall_s: float  # 0.0 for instant events
    fields: dict = field(default_factory=dict)

    def format(self) -> str:
        parts = [f"stage={self.name}", f"wall_s={self.wall_s:.3f}"]
        parts += [f"{k}={v}" for k, v in self.fields.items()]
        return " ".join(parts)


class LogfmtSink:
    """Prints events as logfmt lines on a text stream (live progress)."""

    def __init__(self, stream):
        self._stream = stream

    def emit(self, ev: TraceEvent) -> None:
        print(ev.format(), file=self._stream, flush=True)

    def close(self) -> None:  # the stream is owned by the caller
        pass


class JsonlSink:
    """Appends schema-versioned JSON event lines to a file.

    Each line is a self-describing dict ``{"schema": TRACE_SCHEMA, "seq": i,
    "stage": name, "wall_s": float, ...fields}`` plus any ``static`` fields
    given at construction (e.g. ``process`` for multi-host runs). Values are
    sanitized to plain JSON types (numpy scalars appear in trace fields).
    Lines flush as they happen so a killed run keeps its partial trace.

    ``rotate_bytes`` (config knob ``trace_rotate_bytes``, 0 = off) bounds
    the file for long-running servers/maintainers: when the next line
    would push the file past the bound, the current file moves to
    ``<path>.1`` (replacing any previous rotation — at most two files
    ever exist) and a fresh ``<path>`` opens. ``seq`` continues across
    the boundary, so ``scripts/check_trace.py`` can validate a rotated
    set's continuity.
    """

    def __init__(self, path: str, static: dict | None = None,
                 rotate_bytes: int = 0):
        rotate_bytes = int(rotate_bytes)
        if rotate_bytes < 0:
            raise ValueError(
                f"rotate_bytes must be >= 0 (0 = off), got {rotate_bytes!r}"
            )
        self.path = path
        self.rotate_bytes = rotate_bytes
        self.rotations = 0
        self._static = dict(static or {})
        self._seq = 0
        self._bytes = 0
        self._f = open(path, "w", encoding="utf-8")

    def emit(self, ev: TraceEvent) -> None:
        from hdbscan_tpu.utils.telemetry import json_sanitize

        rec = {
            "schema": TRACE_SCHEMA,
            "seq": self._seq,
            **self._static,
            "stage": ev.name,
            "wall_s": float(ev.wall_s),
            **json_sanitize(ev.fields),
        }
        self._seq += 1
        line = json.dumps(rec) + "\n"  # ensure_ascii: len == byte length
        if (
            self.rotate_bytes
            and self._bytes
            and self._bytes + len(line) > self.rotate_bytes
        ):
            self._rotate()
        self._f.write(line)
        self._f.flush()
        self._bytes += len(line)

    def _rotate(self) -> None:
        """Move the full file to ``<path>.1`` and start a fresh one. The
        sink's ``seq`` keeps counting — rotation is invisible to readers
        that follow the continuity rule."""
        self._f.close()
        os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "w", encoding="utf-8")
        self._bytes = 0
        self.rotations += 1

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class Tracer:
    """Collects :class:`TraceEvent` records; optionally streams them to sinks.

    Pass an instance anywhere a ``trace`` hook is accepted
    (``models.exact.fit``, ``models.mr_hdbscan.fit``); calling it records an
    instant event, ``stage()`` wraps a timed block.

    Args:
      stream: file-like; events print as logfmt lines as they happen
        (``sys.stderr`` for live progress). None = collect only. Sugar for
        ``sinks=[LogfmtSink(stream)]``.
      sinks: additional sink objects (``emit(event)`` / ``close()``), e.g.
        :class:`JsonlSink` for the durable artifact.
      counters: ``{field_name: zero-arg callable -> number}``; at every emit
        the DELTA since the previous emit is attached as an event field when
        nonzero. This is how per-phase jit-compile counts ride along
        (``utils/telemetry.compile_counter``): phase events are emitted at
        the END of their phase, so compiles-since-last-event land on the
        phase that triggered them.
      max_events: bound on the in-memory ``events`` list (None or 0 =
        unbounded). A long-running ``serve --ingest`` process emits one
        ``predict_batch`` + one ``stream_ingest`` + one ``request_span``
        per request forever; the bound turns ``events`` into a ring that
        drops the OLDEST events in chunks (``events_dropped`` counts them).
        Sinks are unaffected — every event still streams to every sink, so
        the on-disk JSONL artifact stays complete; only the in-memory view
        (``summary()``, report aggregation) becomes a recent-window view
        once the bound trips.
    """

    def __init__(self, stream=None, sinks=None, counters=None, max_events=None):
        # Serving emits from many threads at once (HTTP handlers, the
        # batcher worker, the background refitter): one lock makes the
        # counter deltas, the in-memory event order, and the sink write
        # order (JsonlSink's per-line seq) mutually consistent.
        self._emit_lock = threading.Lock()
        self.events: list[TraceEvent] = []
        self.max_events = int(max_events) if max_events else None
        if self.max_events is not None and self.max_events < 1:
            raise ValueError(f"max_events must be >= 1 (or 0/None), got {max_events!r}")
        self.events_dropped = 0
        self._sinks = list(sinks or [])
        if stream is not None:
            self._sinks.append(LogfmtSink(stream))
        self._counters = dict(counters or {})
        self._counter_last = {k: fn() for k, fn in self._counters.items()}

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def close(self) -> None:
        """Close all sinks (flushes JSONL files). Idempotent."""
        for s in self._sinks:
            s.close()

    def __call__(self, name: str, /, **fields) -> None:
        # An explicit wall_s field becomes the event's wall (several sites
        # time their own block and emit an instant event with the result) —
        # otherwise the logfmt line would carry two wall_s keys.
        # ``name`` is positional-only so an event FIELD named ``name`` (the
        # circuit_state schema) can't collide with the stage parameter.
        wall = fields.pop("wall_s", 0.0)
        self._emit(TraceEvent(name, float(wall), fields))

    @contextmanager
    def stage(self, name: str, /, **fields):
        t0 = time.monotonic()
        try:
            yield self
        finally:
            self._emit(TraceEvent(name, time.monotonic() - t0, fields))

    def _emit(self, ev: TraceEvent) -> None:
        with self._emit_lock:
            for key, fn in self._counters.items():
                cur = fn()
                delta = cur - self._counter_last[key]
                self._counter_last[key] = cur
                if delta:
                    ev.fields[key] = delta
            self.events.append(ev)
            if self.max_events is not None and len(self.events) > self.max_events:
                # Trim the oldest ~1/8 of the window in one slice so the
                # front-of-list deletion cost amortizes to O(1) per emit
                # instead of O(n) on every event once the ring is full.
                drop = len(self.events) - self.max_events + max(1, self.max_events // 8)
                drop = min(drop, len(self.events) - 1)
                del self.events[:drop]
                self.events_dropped += drop
            for s in self._sinks:
                s.emit(ev)

    def total(self, name: str) -> float:
        """Summed wall seconds of all events with this stage name."""
        return sum(e.wall_s for e in self.events if e.name == name)

    def walls(self, name: str) -> list[float]:
        """Per-event wall seconds of every event with this stage name, in
        emission order (latency-percentile inputs — ``predict_batch``)."""
        return [e.wall_s for e in self.events if e.name == name]

    def summary(self) -> str:
        """One line per distinct stage — count and summed wall — sorted by
        summed wall descending, so the expensive phases lead and new stages
        are never silently dropped (no allowlist)."""
        agg: dict[str, list] = {}
        for e in self.events:
            agg.setdefault(e.name, [0, 0.0])
            agg[e.name][0] += 1
            agg[e.name][1] += e.wall_s
        rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
        lines = [f"{name}: n={n} wall_s={w:.3f}" for name, (n, w) in rows]
        if self.events_dropped:
            lines.append(
                f"(ring buffer: {self.events_dropped} oldest events dropped, "
                f"max_events={self.max_events}; totals cover the retained window)"
            )
        return "\n".join(lines)


def stderr_tracer() -> Tracer:
    """Tracer that live-streams logfmt lines to stderr."""
    return Tracer(stream=sys.stderr)


@contextmanager
def xla_profile(logdir: str):
    """Capture a ``jax.profiler`` trace (TensorBoard format) around a block.

    The TPU-native replacement for the reference's nonexistent profiling
    (SURVEY.md §5.1): wraps ``jax.profiler.trace``; view with TensorBoard's
    profile plugin.
    """
    import jax

    with jax.profiler.trace(logdir):
        yield
