"""Structured per-stage tracing — the observability layer the reference lacks.

The reference's only progress reporting is ``System.out.println`` of iteration
numbers and HDFS file names, partly in Portuguese (``main/Main.java:108,200,
232-233,316,383``; SURVEY.md §5.1). Here every pipeline stage can emit a
structured event (name, wall seconds, counters) through a :class:`Tracer`,
which the CLI/bench can print as logfmt lines or aggregate; an optional
``jax.profiler`` context captures full XLA traces for TensorBoard.
"""

from __future__ import annotations

import sys
import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class TraceEvent:
    name: str
    wall_s: float  # 0.0 for instant events
    fields: dict = field(default_factory=dict)

    def format(self) -> str:
        parts = [f"stage={self.name}", f"wall_s={self.wall_s:.3f}"]
        parts += [f"{k}={v}" for k, v in self.fields.items()]
        return " ".join(parts)


class Tracer:
    """Collects :class:`TraceEvent` records; optionally streams them.

    Pass an instance anywhere a ``trace`` hook is accepted
    (``models.exact.fit``, ``models.mr_hdbscan.fit``); calling it records an
    instant event, ``stage()`` wraps a timed block.

    Args:
      stream: file-like; events print as logfmt lines as they happen
        (``sys.stderr`` for live progress). None = collect only.
    """

    def __init__(self, stream=None):
        self.events: list[TraceEvent] = []
        self._stream = stream

    def __call__(self, name: str, **fields) -> None:
        # An explicit wall_s field becomes the event's wall (several sites
        # time their own block and emit an instant event with the result) —
        # otherwise the logfmt line would carry two wall_s keys.
        wall = fields.pop("wall_s", 0.0)
        self._emit(TraceEvent(name, float(wall), fields))

    @contextmanager
    def stage(self, name: str, **fields):
        t0 = time.monotonic()
        try:
            yield self
        finally:
            self._emit(TraceEvent(name, time.monotonic() - t0, fields))

    def _emit(self, ev: TraceEvent) -> None:
        self.events.append(ev)
        if self._stream is not None:
            print(ev.format(), file=self._stream, flush=True)

    def total(self, name: str) -> float:
        """Summed wall seconds of all events with this stage name."""
        return sum(e.wall_s for e in self.events if e.name == name)

    def summary(self) -> str:
        """One line per distinct stage: count and summed wall."""
        agg: dict[str, list] = {}
        for e in self.events:
            agg.setdefault(e.name, [0, 0.0])
            agg[e.name][0] += 1
            agg[e.name][1] += e.wall_s
        return "\n".join(
            f"{name}: n={n} wall_s={w:.3f}" for name, (n, w) in agg.items()
        )


def stderr_tracer() -> Tracer:
    """Tracer that live-streams logfmt lines to stderr."""
    return Tracer(stream=sys.stderr)


@contextmanager
def xla_profile(logdir: str):
    """Capture a ``jax.profiler`` trace (TensorBoard format) around a block.

    The TPU-native replacement for the reference's nonexistent profiling
    (SURVEY.md §5.1): wraps ``jax.profiler.trace``; view with TensorBoard's
    profile plugin.
    """
    import jax

    with jax.profiler.trace(logdir):
        yield
