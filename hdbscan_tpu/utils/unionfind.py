"""Shared host-side union-find primitives.

The distributed merge layers (tiled Borůvka, glue harvest, pooled-edge MST,
merge forest) all union components between device rounds; these helpers are
the single implementation (SURVEY.md §2.C row P9's host side).
"""

from __future__ import annotations

import numpy as np


def find(parent: np.ndarray, x: int) -> int:
    """Path-halving find."""
    while parent[x] != x:
        parent[x] = parent[parent[x]]
        x = parent[x]
    return x


def flatten_parents(parent: np.ndarray) -> np.ndarray:
    """Vectorized full path compression: pointer jumping to fixpoint.

    Returns an array where every entry points directly at its root — the
    component relabeling fed back to the device between Borůvka rounds.
    """
    p = parent
    while True:
        q = p[p]
        if np.array_equal(q, p):
            return q
        p = q


def contract_min_edges(
    comp: np.ndarray, cand_j: np.ndarray, cand_w: np.ndarray
) -> tuple[np.ndarray, np.ndarray, int]:
    """One fully vectorized Borůvka contraction round (no per-edge Python).

    ``comp``: (n,) component label per vertex (any int labels).
    ``cand_j``/``cand_w``: per-vertex best outgoing candidate (target vertex,
    weight), ``cand_j = -1`` where the vertex has none.

    Per component, the winning candidate is the minimum by the SHARED key
    (w, min(i,j), max(i,j)) — both endpoints of a physical edge compute the
    same key, which makes the selection deterministic across tilings. The
    winners form a functional graph over components; its cycles (usually
    2-cycles, but weight ties can make them longer because per-vertex
    candidates pre-filter by a different tie-break) are resolved by pointer
    doubling: every component lands on its group's cycle, the cycle's minimum
    label becomes the group root, and every non-root component's winning edge
    joins the forest — exactly group_size - 1 edges per contraction group.

    Returns ``(emit, comp_new, n_comp_new)``: the vertex ids whose candidate
    edges join the MST this round (edge = (i, cand_j[i], cand_w[i])), the new
    per-vertex component labels (representative OLD labels, so callers can
    keep feeding them back), and the new component count.
    """
    uc, cidx = np.unique(comp, return_inverse=True)
    c_count = len(uc)
    if c_count <= 1:
        return np.zeros(0, np.int64), comp, c_count

    ids = np.nonzero(cand_j >= 0)[0]
    a = cidx[ids]
    b = cidx[cand_j[ids]]
    cross = a != b
    ids, a, b = ids[cross], a[cross], b[cross]

    t = np.arange(c_count, dtype=np.int64)
    edge_of = np.full(c_count, -1, np.int64)
    if len(ids):
        j = cand_j[ids]
        lo = np.minimum(ids, j)
        hi = np.maximum(ids, j)
        order = np.lexsort((hi, lo, cand_w[ids], a))
        first = np.concatenate([[True], np.diff(a[order]) != 0])
        sel = order[first]  # winning candidate row per component, in ids-space
        t[a[sel]] = b[sel]
        edge_of[a[sel]] = sel

    # Pointer doubling: land every component on its group's cycle while
    # accumulating the minimum label over the forward orbit. After K rounds
    # with 2^K >= c_count, s[c] is on the cycle and mn[x] (for x on the
    # cycle) is the cycle-wide minimum — the canonical group root.
    mn = np.arange(c_count, dtype=np.int64)
    s = t
    for _ in range(max(1, int(c_count).bit_length())):
        mn = np.minimum(mn, mn[s])
        s = s[s]
    rep = mn[s]
    is_root = rep == np.arange(c_count)

    emit_c = np.nonzero(~is_root & (edge_of >= 0))[0]
    emit = ids[edge_of[emit_c]]
    comp_new = uc[rep][cidx]
    return emit, comp_new, int(is_root.sum())
