"""Shared host-side union-find primitives.

The distributed merge layers (tiled Borůvka, glue harvest, pooled-edge MST,
merge forest) all union components between device rounds; these helpers are
the single implementation (SURVEY.md §2.C row P9's host side).
"""

from __future__ import annotations

import numpy as np


def find(parent: np.ndarray, x: int) -> int:
    """Path-halving find."""
    while parent[x] != x:
        parent[x] = parent[parent[x]]
        x = parent[x]
    return x


def flatten_parents(parent: np.ndarray) -> np.ndarray:
    """Vectorized full path compression: pointer jumping to fixpoint.

    Returns an array where every entry points directly at its root — the
    component relabeling fed back to the device between Borůvka rounds.
    """
    p = parent
    while True:
        q = p[p]
        if np.array_equal(q, p):
            return q
        p = q
