"""Evaluation metrics: Adjusted Rand Index with noise-as-singletons.

The reference validates with ARI treating each noise object as its own
singleton cluster (ResearchReport.pdf §5.2); there is no code for it in the
reference repo, so this fills the gap (SURVEY.md §4).
"""

from __future__ import annotations

import numpy as np


def _noise_to_singletons(labels: np.ndarray, noise_label: int = 0) -> np.ndarray:
    labels = np.asarray(labels).copy()
    noise = labels == noise_label
    if noise.any():
        base = labels.max() + 1
        labels[noise] = base + np.arange(noise.sum())
    return labels


def adjusted_rand_index(
    a: np.ndarray,
    b: np.ndarray,
    noise_as_singletons: bool = True,
    noise_label: int = 0,
) -> float:
    """ARI between two labelings; permutation-invariant, 1.0 = identical."""
    a = np.asarray(a).ravel()
    b = np.asarray(b).ravel()
    if a.shape != b.shape:
        raise ValueError("label arrays must have the same shape")
    if noise_as_singletons:
        a = _noise_to_singletons(a, noise_label)
        b = _noise_to_singletons(b, noise_label)
    n = a.size
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    nb = int(bi.max()) + 1

    def comb2(x):
        return x * (x - 1) / 2.0

    # Sparse contingency via paired codes: with noise-as-singletons BOTH
    # labelings can carry ~n distinct labels, so the dense (na, nb) matrix
    # would be O(n²) memory; the pair-count multiset is all ARI needs.
    _, pair_counts = np.unique(ai.astype(np.int64) * nb + bi, return_counts=True)
    sum_ij = comb2(pair_counts).sum()
    sum_a = comb2(np.bincount(ai)).sum()
    sum_b = comb2(np.bincount(bi)).sum()
    total = comb2(n)
    expected = sum_a * sum_b / total if total else 0.0
    max_index = (sum_a + sum_b) / 2.0
    if max_index == expected:
        return 1.0
    return float((sum_ij - expected) / (max_index - expected))
