"""Ring-systolic sharded scans: k-NN/core distances and Borůvka rounds (L0).

The mesh path in ``ops/tiled.py`` scales out by REPLICATING the column set on
every device — each chip scans its row shard against a full copy of the data,
which caps the reachable n at one-device HBM and moves O(n·d) bytes per
device up front. This module is the explicitly sharded alternative, the shape
PANDA and the parallel-EMST literature (PAPERS.md) converge on: every device
owns one contiguous ROW shard, and the COLUMN panels (the row shards
themselves) circulate around a ring

    dev0 ──▶ dev1 ──▶ dev2 ──▶ ... ──▶ dev(D-1)
     ▲                                     │
     └─────────────────────────────────────┘

via ``lax.ppermute``. A full sweep is exactly ``n_dev - 1`` permute steps
(each device sees every panel once); the permute for step ``s+1`` is issued
BEFORE the compute on the held panel, so XLA's async collective-permute
overlaps the neighbor exchange with the distance tiles — on TPU the panel is
in flight on the ICI while the MXU works (guides: ring-collective pattern).
Per-device HBM is O(n/D · d) instead of O(n · d).

Bitwise parity with the host scans is a hard contract (tested on a forced
8-device CPU mesh): the host k-NN scan's ascending tile visit + ``top_k``
lower-index tie preference + stable merge is equivalent to selecting the k
smallest by the LEXICOGRAPHIC key (distance, column id). Panels arrive in a
device-dependent rotation order here, so the cross-panel merge is an EXPLICIT
(distance, id) lexsort (:func:`_lex_merge_k`) — arrival-order independent,
hence bitwise equal to the host path. The Borůvka carry uses the explicit
(weight, column) tie-break for the same reason.

``scan_backend={auto,host,ring}`` (``config.HDBSCANParams.scan_backend``)
threads this engine through ``exact.fit`` and the mr-hdbscan glue/boundary
paths exactly like ``knn_backend`` threads the Pallas kernels.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from hdbscan_tpu import obs
from hdbscan_tpu.core.distances import pairwise_distance
from hdbscan_tpu.ops.tiled import _next_pow2, _pad_rows, _round_up
from hdbscan_tpu.parallel.mesh import (
    BATCH_AXIS,
    device_count,
    get_mesh,
    replicated,
    ring_permutation,
    row_sharding,
)

#: Valid ``scan_backend`` values (``config.HDBSCANParams.scan_backend``).
SCAN_BACKENDS = ("auto", "host", "ring")


def resolve_scan_backend(scan_backend: str, mesh) -> str:
    """Map a ``scan_backend`` knob value to the concrete engine.

    "host" and "ring" are literal. "auto" picks the ring engine only on a
    multi-device TPU mesh — that is where panel circulation beats column
    replication (ICI bandwidth, HBM capacity); a CPU mesh or a single chip
    keeps the host path, so default test/CI behavior is unchanged.
    """
    if scan_backend not in SCAN_BACKENDS:
        raise ValueError(
            f"unknown scan_backend {scan_backend!r}: auto | host | ring"
        )
    if scan_backend != "auto":
        return scan_backend
    if mesh is None:
        return "host"
    if device_count(mesh) > 1 and mesh.devices.flat[0].platform == "tpu":
        return "ring"
    return "host"


def _ring_geometry(
    n: int, n_dev: int, row_tile: int, col_tile: int
) -> tuple[int, int, int, int]:
    """Clamp tiles and size the per-device row shard.

    Returns ``(row_tile, col_tile, shard, n_pad)`` with ``n_pad = shard *
    n_dev``. Both tiles are powers of two; the column tile additionally
    clamps to (the pow2 round-up of) the per-device row count, because a
    panel IS one row shard and the column loop tiles inside it. ``shard`` is
    a multiple of both tiles, so every device runs identical tile shapes —
    the precondition for bitwise distance parity with the host scan (same
    tile shapes select the same kernel form in ``core/distances``).
    """
    row_tile = _next_pow2(max(8, min(row_tile, n)))
    per_dev = -(-n // n_dev)
    col_tile = _next_pow2(max(128, min(col_tile, n)))
    col_tile = min(col_tile, _next_pow2(max(128, per_dev)))
    col_tile = max(col_tile, row_tile)
    shard = _round_up(per_dev, col_tile)
    return row_tile, col_tile, shard, shard * n_dev


def _lex_merge_k(best_d, best_i, tile_d, tile_i, k: int):
    """Merge two (r, k) candidate lists into the k smallest by the explicit
    LEXICOGRAPHIC key (distance, column id).

    The host scan's stable distance-only merge equals this key because it
    visits columns in ascending-id order; ring panels arrive in a rotation
    order that differs per device, so the explicit secondary key is what
    makes the result arrival-order independent (= bitwise host parity).
    """
    cat_d = jnp.concatenate([best_d, tile_d], axis=1)
    cat_i = jnp.concatenate([best_i, tile_i], axis=1)
    order = jnp.lexsort((cat_i, cat_d), axis=-1)[:, :k]
    return (
        jnp.take_along_axis(cat_d, order, axis=1),
        jnp.take_along_axis(cat_i, order, axis=1),
    )


def _per_device_walls(out, t0: float, beat=None) -> list[tuple[int, float]]:
    """Per-device completion walls: block on each addressable output shard
    in turn, timestamping as each lands. Single-controller approximation of
    per-chip timelines — good enough to surface a straggler device or a
    non-overlapped ppermute in the trace (README "Scaling out").
    ``beat(done)`` (an ``obs`` heartbeat) fires as each shard lands, so a
    hung collective is distinguishable from a slow one.

    Fault site: ``phase_stall`` fires at most once per round here and
    sleeps ``delay_s`` before the LAST shard's timestamp — a deterministic
    single-device straggler for exercising the skew detector
    (``obs/timeline.py``) without touching real device timing."""
    from hdbscan_tpu.fault import inject

    walls = []
    shards = sorted(out.addressable_shards, key=lambda s: s.device.id)
    spec = inject.maybe_fire("phase_stall")
    for i, sh in enumerate(shards):
        if spec is not None and i == len(shards) - 1 and spec.delay_s > 0:
            time.sleep(spec.delay_s)
        jax.block_until_ready(sh.data)
        walls.append((int(sh.device.id), time.monotonic() - t0))
        if beat is not None:
            beat(i + 1)
    return walls


def _emit_ring_trace(
    trace, stage: str, wall: float, walls, n_dev: int, rnd: int, *,
    upload_s: float = 0.0, fetch_s: float = 0.0, comm_bytes: int = 0,
    flops: float = 0.0, **fields
) -> None:
    """One summary event (devices + ppermute_steps — the validator contract:
    steps == devices - 1 per round) plus one per-device wall event.

    Also the single seam feeding the installed
    :class:`~hdbscan_tpu.obs.timeline.TimelineRecorder`: the measured
    per-device walls plus the host segments (``upload_s``/``fetch_s``) and
    the round's ring traffic (``comm_bytes`` one device moved) / total
    ``flops`` become per-device ``device_timeline`` events, and the round's
    skew stats ride the summary event. Recording happens even when
    ``trace`` is None — the recorder still feeds the report/healthz."""
    tl = obs.timeline()
    stats = None
    if tl is not None:
        stats = tl.record_round(
            stage, rnd, walls, upload_s=upload_s, fetch_s=fetch_s,
            comm_bytes=comm_bytes, flops=flops, trace=trace,
        )
    if trace is None:
        return
    if stats is not None:
        fields = dict(
            fields,
            skew=stats["skew"],
            max_device_wall_s=stats["max_wall_s"],
            median_device_wall_s=stats["median_wall_s"],
        )
    if comm_bytes:
        fields.setdefault("comm_bytes", int(comm_bytes))
    trace(
        stage,
        wall_s=round(wall, 6),
        devices=n_dev,
        ppermute_steps=n_dev - 1,
        round=rnd,
        **fields,
    )
    for dev_id, w in walls:
        trace(
            "ring_device_wall",
            wall_s=round(w, 6),
            device=dev_id,
            ring_stage=stage,
            round=rnd,
        )


def _emit_modeled_rounds(
    trace, stage: str, wall: float, walls, n_dev: int, rounds: int, *,
    upload_s: float = 0.0, fetch_s: float = 0.0, comm_bytes: int = 0,
    flops: float = 0.0, **fields
) -> None:
    """Trace/timeline emission for an IN-JIT multi-round program.

    The ``while_loop`` Borůvka rounds (``parallel/shard.shard_boruvka_mst``)
    run every round inside one dispatch, so there is one measured wall for
    the whole program and a round-count counter from the single fetch —
    no per-round host walls to feed :func:`_emit_ring_trace` round by
    round. The installed recorder replays the program as ``rounds`` modeled
    per-round rows (:meth:`TimelineRecorder.record_modeled_rounds`: walls
    and traffic split evenly, host segments pinned to the first/last
    round), and ONE summary event lands with the total wall plus a
    ``rounds`` field — its ``ppermute_steps`` stays the per-round
    ``devices - 1`` the validator contract pins."""
    tl = obs.timeline()
    stats = None
    if tl is not None:
        stats = tl.record_modeled_rounds(
            stage, rounds, walls, upload_s=upload_s, fetch_s=fetch_s,
            comm_bytes=comm_bytes, flops=flops, trace=trace,
        )
    if trace is None:
        return
    if stats is not None:
        fields = dict(
            fields,
            skew=stats["skew"],
            max_device_wall_s=stats["max_wall_s"],
            median_device_wall_s=stats["median_wall_s"],
        )
    if comm_bytes:
        fields.setdefault("comm_bytes", int(comm_bytes))
    trace(
        stage,
        wall_s=round(wall, 6),
        devices=n_dev,
        ppermute_steps=n_dev - 1,
        rounds=int(rounds),
        **fields,
    )
    for dev_id, w in walls:
        trace(
            "ring_device_wall",
            wall_s=round(w, 6),
            device=dev_id,
            ring_stage=stage,
            round=0,
        )


# --------------------------------------------------------------------------
# Ring k-NN scan
# --------------------------------------------------------------------------

#: (mesh, k, metric, row_tile, col_tile, fused, interpret) -> compiled fn.
_RING_KNN_CACHE: dict = {}


def _ring_knn_fn(
    mesh, k: int, metric: str, row_tile: int, col_tile: int,
    fused: bool = False, interpret: bool = False,
    kth_only: int | None = None,
):
    """Build (or fetch) the jitted shard_map ring k-NN program.

    The returned fn maps ``(queries P(blocks), panels P(blocks), n P())`` to
    ``(best_d P(blocks), best_i P(blocks))``: each device's query shard ends
    up with its k nearest columns over the WHOLE (unpadded) column set, ids
    global, (distance, id)-lex ascending, (+inf, -1) padded.

    ``kth_only`` (a column index into the k-list) slices the per-device
    result INSIDE the program: the fn returns just that ``(shard,)`` column
    — the only thing core distances need — so the materialized output is
    O(n/D) per device instead of O(n/D * k). Bitwise the same values as
    slicing the full list on the host; the ``--assert-not-replicated``
    fit-path gate budget is what makes the distinction matter.
    """
    key = (mesh, k, metric, row_tile, col_tile, fused, interpret, kth_only)
    fn = _RING_KNN_CACHE.get(key)
    if fn is not None:
        return fn
    n_dev = device_count(mesh)
    perm = ring_permutation(n_dev)

    def per_device(q, panel0, n_arr):
        me = jax.lax.axis_index(BATCH_AXIS)
        q_shard, p_shard = q.shape[0], panel0.shape[0]
        n_row_tiles = q_shard // row_tile
        n_col_tiles = p_shard // col_tile
        inf = jnp.array(jnp.inf, q.dtype)
        n_cols = n_arr.astype(jnp.int32)
        kk = min(k, col_tile)
        # Guard mirrors the host scan: cond-extracted selection only when a
        # tile holds at least k candidates (host: guarded and k <= col_tile).
        guarded = k <= col_tile

        def scan_tile(xr, br, bir, panel, off, c):
            xc = jax.lax.dynamic_slice_in_dim(panel, c * col_tile, col_tile)
            col0 = off + c * col_tile
            ids = col0 + jnp.arange(col_tile, dtype=jnp.int32)
            d = pairwise_distance(xr, xc, metric)
            d = jnp.where(ids[None, :] < n_cols, d, inf)

            def merge(carry):
                br, bir = carry
                nv, ni = jax.lax.top_k(-d, kk)  # kk smallest, (d, id)-lex
                td, ti = -nv, ni + col0
                if kk < k:
                    td = jnp.concatenate(
                        [td, jnp.full((row_tile, k - kk), jnp.inf, d.dtype)],
                        axis=1,
                    )
                    ti = jnp.concatenate(
                        [ti, jnp.full((row_tile, k - kk), -1, jnp.int32)],
                        axis=1,
                    )
                return _lex_merge_k(br, bir, td, ti, k)

            if not guarded:
                return merge((br, bir))
            return jax.lax.cond(
                jnp.any(d < br[:, k - 1][:, None]), merge, lambda t: t,
                (br, bir),
            )

        if fused:  # pragma: no cover - TPU-only (interpret smoke in tests)
            from hdbscan_tpu.ops.pallas_knn import knn_fused_pallas

            def scan_panel(panel, src, best, bidx):
                off = src * p_shard
                xt = panel.T  # (LANES, p_shard) column operand
                colmask = jnp.where(
                    off + jnp.arange(p_shard, dtype=jnp.int32) < n_cols,
                    jnp.float32(0), jnp.float32(jnp.inf),
                )[None, :]
                td, ti = knn_fused_pallas(
                    q, xt, colmask, k, interpret=interpret
                )
                td, ti = td[:, :k], ti[:, :k]
                ti = jnp.where(ti >= 0, ti + off, ti)
                return _lex_merge_k(best, bidx, td, ti, k)

        else:

            def scan_panel(panel, src, best, bidx):
                off = src * p_shard

                def row_step(r, carry):
                    best, bidx = carry
                    xr = jax.lax.dynamic_slice_in_dim(q, r * row_tile, row_tile)
                    br = jax.lax.dynamic_slice_in_dim(
                        best, r * row_tile, row_tile
                    )
                    bir = jax.lax.dynamic_slice_in_dim(
                        bidx, r * row_tile, row_tile
                    )

                    def col_step(c, carry2):
                        return scan_tile(xr, *carry2, panel, off, c)

                    br, bir = jax.lax.fori_loop(
                        0, n_col_tiles, col_step, (br, bir)
                    )
                    best = jax.lax.dynamic_update_slice_in_dim(
                        best, br, r * row_tile, axis=0
                    )
                    bidx = jax.lax.dynamic_update_slice_in_dim(
                        bidx, bir, r * row_tile, axis=0
                    )
                    return best, bidx

                return jax.lax.fori_loop(0, n_row_tiles, row_step, (best, bidx))

        # Carry inits derive from the device-varying query shard so the
        # shard_map varying-axis types match (same idiom as the mesh scan).
        proto = jnp.broadcast_to(q[:, :1], (q_shard, k))
        best0 = jnp.full_like(proto, jnp.inf)
        bidx0 = jnp.full_like(proto, -1).astype(jnp.int32)

        def step(s, carry):
            panel, best, bidx = carry
            # Issue the permute BEFORE computing on the held panel: XLA's
            # async collective-permute overlaps the exchange with the tiles.
            nxt = jax.lax.ppermute(panel, BATCH_AXIS, perm)
            src = (me - s) % n_dev
            best, bidx = scan_panel(panel, src, best, bidx)
            return nxt, best, bidx

        panel, best, bidx = jax.lax.fori_loop(
            0, n_dev - 1, step, (panel0, best0, bidx0)
        )
        # Last panel: compute only — exactly n_dev - 1 ppermutes per sweep.
        best, bidx = scan_panel(panel, (me - (n_dev - 1)) % n_dev, best, bidx)
        return best, bidx

    if kth_only is None:
        body, out_specs = per_device, (P(BATCH_AXIS), P(BATCH_AXIS))
    else:

        def body(q, panel0, n_arr):
            best, _ = per_device(q, panel0, n_arr)
            return best[:, kth_only]

        out_specs = P(BATCH_AXIS)
    fn = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(BATCH_AXIS), P(BATCH_AXIS), P()),
            out_specs=out_specs,
        )
    )
    _RING_KNN_CACHE[key] = fn
    return fn


def _ring_fused_eligible(
    metric: str, k: int, dm: int, dtype, q_shard: int, p_shard: int
) -> bool:
    """Fused Pallas kernel reuse inside the ring step (PR-1 kernel): TPU
    only — off-TPU the guarded-XLA tile scan is the fallback (the
    interpreter replays every grid step through XLA-on-CPU)."""
    from hdbscan_tpu.ops.pallas_knn import COL_TILE, ROW_TILE

    return (
        jax.devices()[0].platform == "tpu"
        and metric == "euclidean"
        and dtype is np.float32
        and k <= 128
        and dm <= 128
        and q_shard % ROW_TILE == 0
        and p_shard % COL_TILE == 0
    )


def ring_knn_core_distances(
    data: np.ndarray,
    min_pts: int,
    metric: str = "euclidean",
    k: int | None = None,
    row_tile: int = 1024,
    col_tile: int = 8192,
    dtype=np.float32,
    return_indices: bool = False,
    fetch_knn: bool = True,
    mesh=None,
    trace=None,
    knn_backend: str = "auto",
    index: str = "exact",
    index_opts: dict | None = None,
):
    """Ring-sharded exact core distances — the ``scan_backend="ring"`` twin
    of :func:`ops.tiled.knn_core_distances`, bitwise identical output.

    Each device holds one row shard; panels circulate (module docstring).
    ``knn_backend`` in ("auto", "fused", "pallas") lets the per-step panel
    scan ride the fused Pallas kernel when eligible on TPU; "xla" forces the
    guarded tile scan everywhere. ``index="rpforest"`` swaps the quadratic
    panel circulation for the rp-forest engine sharded over the same mesh:
    leaf batches and per-point lists row-shard, only candidate-coordinate
    panels cross shards, and no (n, n) scan is formed. Return contract
    matches the host fn: ``(core, knn)`` or ``(core, knn, idx)``;
    ``fetch_knn=False`` fetches only the k-th column — ``(core, None)``.
    """
    n = len(data)
    if index == "rpforest":
        from hdbscan_tpu.ops.rpforest import rpforest_core_distances

        return rpforest_core_distances(
            data, min_pts, metric, k, dtype=dtype,
            return_indices=return_indices, fetch_knn=fetch_knn,
            trace=trace, mesh=mesh if mesh is not None else get_mesh(),
            **(index_opts or {}),
        )
    if index != "exact":
        raise ValueError(f"unknown knn index {index!r}")
    k = max(k or 0, max(min_pts - 1, 1))
    mesh = mesh if mesh is not None else get_mesh()
    n_dev = device_count(mesh)
    row_tile, col_tile, shard, n_pad = _ring_geometry(n, n_dev, row_tile, col_tile)
    data_np = np.asarray(data)
    dm = data_np.shape[1]
    fused = knn_backend in ("auto", "fused", "pallas") and _ring_fused_eligible(
        metric, k, dm, dtype, shard, shard
    )
    data_p = _pad_rows(np.asarray(data_np, dtype), n_pad)
    if fused:  # pragma: no cover - TPU-only
        from hdbscan_tpu.ops.pallas_knn import LANES

        lanes = np.zeros((n_pad, LANES), np.float32)
        lanes[:, :dm] = data_p
        data_p = lanes
    t_up = time.monotonic()
    rows = jax.device_put(data_p, row_sharding(mesh))
    n_arr = jax.device_put(np.asarray(n, np.int32), replicated(mesh))
    upload_s = time.monotonic() - t_up
    # Ring traffic per device per sweep: the circulating panel (one row
    # shard, post-lanes width) crosses each of the n_dev-1 permute steps.
    comm_bytes = (n_dev - 1) * shard * data_p.shape[1] * data_p.dtype.itemsize
    round_flops = 2.0 * n_pad * n_pad * dm
    kth_col = min(max(min_pts - 1, 1), n) - 1
    fetch_knn = fetch_knn or return_indices
    # Core-only callers get the kth-column program: the device output is
    # (shard,) per device, not (shard, k) — the sharded fit path's
    # replication-gate budget has no room for the full lists.
    fn = _ring_knn_fn(
        mesh, k, metric, row_tile, col_tile, fused=fused,
        kth_only=None if fetch_knn else kth_col,
    )

    from hdbscan_tpu.utils.flops import counter as _flops

    _flops.add_scan(n_pad, n_pad, dm, row_tile=row_tile)
    with obs.mem_phase("ring_knn_scan"), obs.task(
        "ring_knn_scan", total=n_dev
    ) as hb:
        t0 = time.monotonic()
        if fetch_knn:
            best_d, best_i = fn(rows, rows, n_arr)
        else:
            best_d, best_i = fn(rows, rows, n_arr), None
        walls = _per_device_walls(best_d, t0, beat=hb.beat)
        wall = time.monotonic() - t0

    from hdbscan_tpu.parallel.mesh import fetch

    if not fetch_knn:
        t_f = time.monotonic()
        kth = np.asarray(fetch(best_d), np.float64)[:n]
        fetch_s = time.monotonic() - t_f
        # Release device state eagerly (not at gc): lingering pieces of the
        # scan otherwise stay resident into the Borůvka phase and charge
        # against the --assert-not-replicated budget there.
        best_d.delete()
        rows.delete()
        _emit_ring_trace(
            trace, "ring_knn_scan", wall, walls, n_dev, 0, rows=n, shard=shard,
            upload_s=upload_s, fetch_s=fetch_s, comm_bytes=comm_bytes,
            flops=round_flops,
        )
        core = np.zeros(n, np.float64) if min_pts <= 1 else kth
        return core, None
    t_f = time.monotonic()
    knn = np.asarray(fetch(best_d), np.float64)[:n]
    idx = np.asarray(fetch(best_i), np.int64)[:n] if return_indices else None
    fetch_s = time.monotonic() - t_f
    best_d.delete()
    best_i.delete()
    rows.delete()
    _emit_ring_trace(
        trace, "ring_knn_scan", wall, walls, n_dev, 0, rows=n, shard=shard,
        upload_s=upload_s, fetch_s=fetch_s, comm_bytes=comm_bytes,
        flops=round_flops,
    )
    if min_pts <= 1:
        core = np.zeros(n, np.float64)
    else:
        core = knn[:, min(min_pts - 1, n) - 1].copy()
    if return_indices:
        return core, knn, idx
    return core, knn


def ring_knn_core_distances_rows(
    data: np.ndarray,
    row_ids: np.ndarray,
    min_pts: int,
    metric: str = "euclidean",
    row_tile: int = 1024,
    col_tile: int = 8192,
    dtype=np.float32,
    mesh=None,
    trace=None,
    index: str = "exact",
    index_opts: dict | None = None,
) -> np.ndarray:
    """Ring-sharded twin of :func:`ops.tiled.knn_core_distances_rows`: core
    distances for SELECTED rows (the mr-hdbscan boundary rescan) — the m
    query rows shard across devices, the full column set circulates as
    panels. Returns (m,) float64 core distances aligned with ``row_ids``.
    ``index="rpforest"`` answers the same rows from a mesh-sharded forest.
    """
    n = len(data)
    m = len(row_ids)
    if m == 0:
        return np.zeros(0, np.float64)
    if index == "rpforest":
        from hdbscan_tpu.ops.rpforest import rpforest_core_distances_rows

        return rpforest_core_distances_rows(
            data, row_ids, min_pts, metric, dtype=dtype, trace=trace,
            mesh=mesh if mesh is not None else get_mesh(),
            **(index_opts or {}),
        )
    if index != "exact":
        raise ValueError(f"unknown knn index {index!r}")
    k = max(min_pts - 1, 1)
    mesh = mesh if mesh is not None else get_mesh()
    n_dev = device_count(mesh)
    row_tile, col_tile, shard, n_pad = _ring_geometry(n, n_dev, row_tile, col_tile)
    # Queries shard independently of the column panels: pad m to a
    # (devices x row_tile) slab.
    q_shard = _round_up(max(-(-m // n_dev), row_tile), row_tile)
    m_pad = q_shard * n_dev
    data_np = np.asarray(data)
    dm = data_np.shape[1]
    t_up = time.monotonic()
    cols = jax.device_put(
        _pad_rows(np.asarray(data_np, dtype), n_pad), row_sharding(mesh)
    )
    q = jax.device_put(
        _pad_rows(np.asarray(data_np[row_ids], dtype), m_pad), row_sharding(mesh)
    )
    n_arr = jax.device_put(np.asarray(n, np.int32), replicated(mesh))
    upload_s = time.monotonic() - t_up
    # The COLUMN panels (full-set row shards) circulate; queries stay put.
    comm_bytes = (n_dev - 1) * shard * dm * np.dtype(dtype).itemsize
    round_flops = 2.0 * m_pad * n_pad * dm
    kth_col = min(max(min_pts - 1, 1), n) - 1
    # Only the kth column ever leaves the device here (boundary rescan):
    # slice it inside the program so the output is O(m/D) per device.
    fn = _ring_knn_fn(mesh, k, metric, row_tile, col_tile, kth_only=kth_col)

    from hdbscan_tpu.utils.flops import counter as _flops

    _flops.add_scan(m_pad, n_pad, dm, row_tile=row_tile)
    with obs.mem_phase("ring_rows_scan"), obs.task(
        "ring_rows_scan", total=n_dev
    ) as hb:
        t0 = time.monotonic()
        best_d = fn(q, cols, n_arr)
        walls = _per_device_walls(best_d, t0, beat=hb.beat)
        wall = time.monotonic() - t0

    from hdbscan_tpu.parallel.mesh import fetch

    t_f = time.monotonic()
    kth = np.asarray(fetch(best_d), np.float64)[:m]
    fetch_s = time.monotonic() - t_f
    best_d.delete()
    q.delete()
    cols.delete()
    _emit_ring_trace(
        trace, "ring_rows_scan", wall, walls, n_dev, 0, rows=m, cols=n,
        shard=shard, upload_s=upload_s, fetch_s=fetch_s,
        comm_bytes=comm_bytes, flops=round_flops,
    )
    if min_pts <= 1:
        return np.zeros(m, np.float64)
    return kth


# --------------------------------------------------------------------------
# Ring Borůvka scan
# --------------------------------------------------------------------------

#: (mesh, metric, row_tile, col_tile, n_comp_pad) -> compiled fn.
_RING_BORUVKA_CACHE: dict = {}

_INT_BIG = np.int32(2**31 - 1)


def _ring_boruvka_fn(
    mesh, metric: str, row_tile: int, col_tile: int, n_comp_pad: int
):
    """Build (or fetch) the jitted shard_map ring Borůvka round.

    Per device: scan the local row shard against every circulating panel
    (data + core circulate as one augmented array — one ppermute per step),
    carrying the per-row min outgoing mutual-reachability edge with the
    EXPLICIT (weight, column) tie-break. Then the glue reduction: a
    ``segment_min``/``pmin`` cascade reduces per-COMPONENT winners by the
    shared key (w, min(i,j), max(i,j)) — the exact key the host contraction
    uses (``utils/unionfind.contract_min_edges``) — and a ``psum`` counts
    candidates for the trace. Outputs are replicated (n_comp_pad,) arrays;
    no O(n) result crosses the mesh.
    """
    key = (mesh, metric, row_tile, col_tile, n_comp_pad)
    fn = _RING_BORUVKA_CACHE.get(key)
    if fn is not None:
        return fn
    n_dev = device_count(mesh)
    perm = ring_permutation(n_dev)

    def per_device(rows_aug, panel0, comp_rep, n_arr):
        me = jax.lax.axis_index(BATCH_AXIS)
        shard = rows_aug.shape[0]
        n_row_tiles = shard // row_tile
        n_col_tiles = shard // col_tile
        dtype = rows_aug.dtype
        inf = jnp.array(jnp.inf, dtype)
        n_pts = n_arr.astype(jnp.int32)
        my_off = (me * shard).astype(jnp.int32)
        kr_all = jax.lax.dynamic_slice_in_dim(comp_rep, my_off, shard)

        def scan_panel(panel, src, bw, bj):
            off = (src * shard).astype(jnp.int32)
            kc_all = jax.lax.dynamic_slice_in_dim(comp_rep, off, shard)

            def row_step(r, carry):
                bw, bj = carry
                xr = jax.lax.dynamic_slice_in_dim(
                    rows_aug, r * row_tile, row_tile
                )[:, :-1]
                cr = jax.lax.dynamic_slice_in_dim(
                    rows_aug, r * row_tile, row_tile
                )[:, -1]
                kr = jax.lax.dynamic_slice_in_dim(kr_all, r * row_tile, row_tile)
                vr = (
                    my_off + r * row_tile
                    + jnp.arange(row_tile, dtype=jnp.int32)
                ) < n_pts
                bw_r = jax.lax.dynamic_slice_in_dim(bw, r * row_tile, row_tile)
                bj_r = jax.lax.dynamic_slice_in_dim(bj, r * row_tile, row_tile)

                def col_step(c, carry2):
                    bw_r, bj_r = carry2
                    xc = jax.lax.dynamic_slice_in_dim(
                        panel, c * col_tile, col_tile
                    )[:, :-1]
                    cc = jax.lax.dynamic_slice_in_dim(
                        panel, c * col_tile, col_tile
                    )[:, -1]
                    kc = jax.lax.dynamic_slice_in_dim(
                        kc_all, c * col_tile, col_tile
                    )
                    col0 = off + c * col_tile
                    vc = (
                        col0 + jnp.arange(col_tile, dtype=jnp.int32)
                    ) < n_pts
                    d = pairwise_distance(xr, xc, metric)
                    w = jnp.maximum(d, jnp.maximum(cr[:, None], cc[None, :]))
                    out = (kr[:, None] != kc[None, :]) & vc[None, :] & vr[:, None]
                    w = jnp.where(out, w, inf)
                    tw = jnp.min(w, axis=1)
                    tj = jnp.argmin(w, axis=1).astype(jnp.int32) + col0
                    # Explicit (w, j) lex — panels arrive in rotated order,
                    # so "first tile wins" (the host rule) must become
                    # "lowest column id wins" to stay order-independent.
                    upd = (tw < bw_r) | ((tw == bw_r) & (tj < bj_r))
                    return (
                        jnp.where(upd, tw, bw_r),
                        jnp.where(upd, tj, bj_r),
                    )

                bw_r, bj_r = jax.lax.fori_loop(
                    0, n_col_tiles, col_step, (bw_r, bj_r)
                )
                bw = jax.lax.dynamic_update_slice_in_dim(
                    bw, bw_r, r * row_tile, axis=0
                )
                bj = jax.lax.dynamic_update_slice_in_dim(
                    bj, bj_r, r * row_tile, axis=0
                )
                return bw, bj

            return jax.lax.fori_loop(0, n_row_tiles, row_step, (bw, bj))

        bw0 = jnp.full_like(rows_aug[:, -1], jnp.inf)
        bj0 = jnp.full_like(kr_all, -1)

        def step(s, carry):
            panel, bw, bj = carry
            nxt = jax.lax.ppermute(panel, BATCH_AXIS, perm)  # overlap: issue first
            bw, bj = scan_panel(panel, (me - s) % n_dev, bw, bj)
            return nxt, bw, bj

        panel, bw, bj = jax.lax.fori_loop(0, n_dev - 1, step, (panel0, bw0, bj0))
        bw, bj = scan_panel(panel, (me - (n_dev - 1)) % n_dev, bw, bj)

        # Glue reduction: per-component winner by the host contraction's
        # shared key (w, lo=min(i,j), hi=max(i,j)), as a segment_min + pmin
        # cascade — w first, then lo among w-ties, then hi among (w, lo)-ties.
        gid = my_off + jnp.arange(shard, dtype=jnp.int32)
        finite = bj >= 0
        big = jnp.int32(_INT_BIG)
        lo = jnp.where(finite, jnp.minimum(gid, bj), big)
        hi = jnp.where(finite, jnp.maximum(gid, bj), big)
        wkey = jnp.where(finite, bw, inf)
        seg = jnp.clip(kr_all, 0, n_comp_pad - 1)
        w_c = jax.ops.segment_min(wkey, seg, num_segments=n_comp_pad)
        w_all = jax.lax.pmin(w_c, BATCH_AXIS)
        on_w = wkey == w_all[seg]
        lo_c = jax.ops.segment_min(
            jnp.where(on_w, lo, big), seg, num_segments=n_comp_pad
        )
        lo_all = jax.lax.pmin(lo_c, BATCH_AXIS)
        on_lo = on_w & (lo == lo_all[seg])
        hi_c = jax.ops.segment_min(
            jnp.where(on_lo, hi, big), seg, num_segments=n_comp_pad
        )
        hi_all = jax.lax.pmin(hi_c, BATCH_AXIS)
        n_cand = jax.lax.psum(jnp.sum(finite.astype(jnp.int32)), BATCH_AXIS)
        return w_all, lo_all, hi_all, n_cand

    fn = jax.jit(
        shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(BATCH_AXIS), P(BATCH_AXIS), P(), P()),
            out_specs=(P(), P(), P(), P()),
        )
    )
    _RING_BORUVKA_CACHE[key] = fn
    return fn


class RingBoruvkaScanner:
    """Ring-sharded drop-in for :class:`ops.tiled.BoruvkaScanner`.

    Same ``min_outgoing(comp) -> (best_w, best_j)`` contract, same final
    edges bitwise (see module docstring); but the point matrix shards over
    the mesh (O(n/D·d) HBM per device) and only (n_comp,) reduced winners
    cross back to host per round — the candidate arrays the host scanner
    ships home stay on-device, reduced by the segment_min/pmin/psum glue.

    The returned per-point arrays carry ONE candidate per component (the
    component's winning edge, scattered onto its in-component endpoint);
    ``contract_min_edges`` selects winners by exactly the key this reduction
    minimizes, so the host contraction — and hence the emitted MST edges —
    are identical to the host scanner's round for round.
    """

    def __init__(
        self,
        data: np.ndarray,
        core: np.ndarray,
        metric: str = "euclidean",
        row_tile: int = 1024,
        col_tile: int = 8192,
        dtype=np.float32,
        mesh=None,
        pad_pow2: bool = False,
        trace=None,
    ):
        n = len(data)
        self.n = n
        self.d = np.asarray(data).shape[1]
        self.metric = metric
        self.mesh = mesh if mesh is not None else get_mesh()
        self.n_dev = device_count(self.mesh)
        self.trace = trace
        self.row_tile, self.col_tile, self.shard, n_pad = _ring_geometry(
            n, self.n_dev, row_tile, col_tile
        )
        if pad_pow2:
            # Shrinking per-level calls reuse compiled shapes (host scanner
            # rationale); pow2 per-device shards keep tiles dividing evenly.
            self.shard = _next_pow2(self.shard)
            n_pad = self.shard * self.n_dev
        self.n_pad = n_pad
        aug = np.concatenate(
            [np.asarray(data, dtype), np.asarray(core, dtype)[:, None]], axis=1
        )
        self._rows = jax.device_put(
            _pad_rows(aug, n_pad), row_sharding(self.mesh)
        )
        self._n_arr = jax.device_put(
            np.asarray(n, np.int32), replicated(self.mesh)
        )
        self._round = 0

    def min_outgoing(self, comp: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(best_w, best_j) per point — inf/-1 except each component's
        winning outgoing edge, scattered onto its in-component endpoint."""
        from hdbscan_tpu.utils.flops import counter as _flops

        _flops.add_scan(self.n_pad, self.n_pad, self.d, row_tile=self.row_tile)
        comp = np.asarray(comp)
        uniq, dense = np.unique(comp, return_inverse=True)
        n_comp = len(uniq)
        n_comp_pad = _next_pow2(max(8, n_comp))
        t_up = time.monotonic()
        comp_rep = jax.device_put(
            _pad_rows(dense.astype(np.int32), self.n_pad),
            replicated(self.mesh),
        )
        upload_s = time.monotonic() - t_up
        fn = _ring_boruvka_fn(
            self.mesh, self.metric, self.row_tile, self.col_tile, n_comp_pad
        )
        with obs.mem_phase("ring_boruvka_scan"), obs.task(
            "ring_boruvka_scan", total=self.n_dev
        ) as hb:
            t0 = time.monotonic()
            w_all, lo_all, hi_all, n_cand = fn(
                self._rows, self._rows, comp_rep, self._n_arr
            )
            walls = _per_device_walls(w_all, t0, beat=hb.beat)
            wall = time.monotonic() - t0

        from hdbscan_tpu.parallel.mesh import fetch

        t_f = time.monotonic()
        w, lo, hi, cand = fetch((w_all, lo_all, hi_all, n_cand))
        fetch_s = time.monotonic() - t_f
        w = np.asarray(w, np.float64)[:n_comp]
        lo = np.asarray(lo, np.int64)[:n_comp]
        hi = np.asarray(hi, np.int64)[:n_comp]
        # The augmented (d+1-wide) row-shard panel circulates each round.
        comm_bytes = (
            (self.n_dev - 1) * self.shard * (self.d + 1)
            * self._rows.dtype.itemsize
        )
        _emit_ring_trace(
            self.trace, "ring_boruvka_scan", wall, walls, self.n_dev,
            self._round, n_comp=n_comp, candidates=int(cand),
            upload_s=upload_s, fetch_s=fetch_s, comm_bytes=comm_bytes,
            flops=2.0 * self.n_pad * self.n_pad * self.d,
        )
        self._round += 1
        bw = np.full(self.n, np.inf, np.float64)
        bj = np.full(self.n, -1, np.int64)
        fin = np.isfinite(w)
        if fin.any():
            lo_f, hi_f = lo[fin], hi[fin]
            cids = np.flatnonzero(fin)
            # The winner edge's in-component endpoint is the emitting vertex
            # (host semantics: the vertex whose candidate won the component).
            u = np.where(dense[lo_f] == cids, lo_f, hi_f)
            v = lo_f + hi_f - u
            bw[u] = w[fin]
            bj[u] = v
        return bw, bj
