"""One sharded program: the end-to-end partitioned fit layer (ROADMAP item 1).

Every earlier scale-out tier still REPLICATES the point set somewhere:
``ops/tiled`` broadcasts the column panel, the rp-forest build walks a full
``data_dev`` copy per tree, and the ring Borůvka glue returns replicated
(n_comp,) winner arrays that are O(n) in the first rounds. This module is the
composition layer that removes the last copies — the paper's partitioned
premise (MapReduce recursive sampling) restated in JAX sharding vocabulary:

* an explicit PARTITION-RULE table (``PARTITION_RULES``): a regex ->
  ``PartitionSpec`` map over the fit's logical pytree (points / neighbors /
  edges / forest / comp / scalars), applied with
  :func:`match_partition_rules` and pinned at phase boundaries with
  ``with_sharding_constraint`` (:func:`constrain`) so XLA cannot silently
  replicate an intermediate between phases;
* a row-sharded rp-forest build (:func:`shard_forest_core_distances`): each
  device builds T rank-split trees over ITS OWN row shard (shared replicated
  hyperplane normals are O(T · 2^depth · d) — the only broadcast), then a
  PANDA-style bounded k-NN exchange circulates (panel points, per-shard
  thresholds, per-shard leaf members) around the ring — every query routes
  down each visiting shard's trees and lex-merges that leaf's candidates, so
  the per-device working set stays O(n/D · d) and n is no longer capped by
  one chip's HBM;
* a fully row-sharded Borůvka round (:class:`ShardBoruvkaScanner`): the
  component labels shard WITH the rows and circulate as a second panel
  (where the ring scanner replicated them), and the per-row (weight, column)
  winners come back row-sharded — the only O(n) hop is the per-round fetch
  to the host contraction (``utils/unionfind.contract_min_edges``), the
  Wang-et-al EMST shape of "all-gather edges only at contraction".

``fit_sharding={auto,replicated,sharded}`` (``config.HDBSCANParams``)
threads the layer through ``models/exact.fit`` — "auto" turns it on only on
multi-device TPU meshes (CPU/test defaults unchanged), and the sharded
program is the first end-to-end fit that runs green under the
``--assert-not-replicated`` device-memory gate on a forced-8-device mesh.

Parity contract: with ``knn_index="exact"`` the sharded fit is BITWISE
identical to the single-device path (ring k-NN parity + per-row (w, j)-lex
Borůvka winners match the host scanner's first-tile-wins rule, so the host
contraction sees identical inputs). The sharded rp-forest tier is
approximate by construction (per-shard trees differ from global trees) and
is gated by recall/ARI like the replicated rp-forest tier.
"""

from __future__ import annotations

import re
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from hdbscan_tpu import obs
from hdbscan_tpu.core.distances import METRICS, pairwise_distance
from hdbscan_tpu.ops.tiled import _next_pow2, _pad_rows
from hdbscan_tpu.parallel.mesh import (
    BATCH_AXIS,
    device_count,
    fetch,
    get_mesh,
    replicated,
    ring_permutation,
    row_sharding,
)
from hdbscan_tpu.parallel.ring import (
    _emit_ring_trace,
    _per_device_walls,
    _ring_geometry,
)

#: Valid ``fit_sharding`` values (``config.HDBSCANParams.fit_sharding``).
FIT_SHARDINGS = ("auto", "replicated", "sharded")


def resolve_fit_sharding(fit_sharding: str, mesh) -> str:
    """Map the ``fit_sharding`` knob to the concrete program.

    "replicated" and "sharded" are literal. "auto" picks the sharded
    program only on a multi-device TPU mesh — the same policy as
    ``ring.resolve_scan_backend`` — so CPU meshes and single chips keep the
    replicated default and test/CI behavior is unchanged unless a test
    forces "sharded" (the forced-8-device parity/gate suites do).
    """
    if fit_sharding not in FIT_SHARDINGS:
        raise ValueError(
            f"unknown fit_sharding {fit_sharding!r}: auto | replicated | sharded"
        )
    if fit_sharding != "auto":
        return fit_sharding
    if mesh is None:
        return "replicated"
    if device_count(mesh) > 1 and mesh.devices.flat[0].platform == "tpu":
        return "sharded"
    return "replicated"


# ---------------------------------------------------------------------------
# Partition-rule table. The fit's device state is named as a slash-joined
# pytree path; the FIRST matching regex supplies the PartitionSpec (the
# match_partition_rules idiom of the big-model trainers, SNIPPETS.md [2]).

#: (regex over pytree paths) -> PartitionSpec. Row-major O(n) state shards
#: along the batch axis; O(1)/O(log n) broadcast state (hyperplane normals,
#: scalars) replicates. Order matters: first match wins.
PARTITION_RULES: tuple[tuple[str, P], ...] = (
    (r"^points/", P(BATCH_AXIS)),       # (n_pad, d) rows + circulating panels
    (r"^neighbors/", P(BATCH_AXIS)),    # (n_pad, k) per-point candidate lists
    (r"^edges/", P(BATCH_AXIS)),        # (n_pad,) per-row Borůvka winners
    (r"^comp/", P(BATCH_AXIS)),         # (n_pad,) component labels
    (r"^forest/normals", P()),          # (T, 2^depth - 1, d): the only broadcast
    (r"^forest/", P(BATCH_AXIS)),       # per-shard thresholds + leaf members
    (r"^scalars/", P()),                # 0-d bookkeeping
)


def _tree_paths(tree):
    """Slash-joined string path per leaf, leaf order = tree_flatten order."""
    paths = []
    for kp, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
        parts = []
        for entry in kp:
            if hasattr(entry, "key"):
                parts.append(str(entry.key))
            elif hasattr(entry, "idx"):
                parts.append(str(entry.idx))
            else:  # pragma: no cover - defensive
                parts.append(str(entry))
        paths.append("/".join(parts))
    return paths


def match_partition_rules(rules, tree):
    """PartitionSpec pytree for ``tree``: first rule whose regex searches the
    leaf's slash-joined path wins. Unmatched leaves raise — an unnamed fit
    buffer is exactly the silent replication this layer exists to prevent."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    specs = []
    for path in _tree_paths(tree):
        for pat, spec in rules:
            if re.search(pat, path):
                specs.append(spec)
                break
        else:
            raise ValueError(f"no partition rule matches pytree path {path!r}")
    return jax.tree_util.tree_unflatten(treedef, specs)


def partition_rule_table() -> list[dict]:
    """JSON-serializable rule table for the run manifest
    (``utils/telemetry.run_manifest``): the reviewable record of which fit
    state shards and which replicates."""
    return [
        {"path": pat, "spec": str(spec)} for pat, spec in PARTITION_RULES
    ]


def constrain(tree, mesh):
    """Pin ``tree`` to its matched partition specs with
    ``with_sharding_constraint`` — called at phase boundaries INSIDE the
    jitted programs so XLA's layout search cannot replicate an O(n)
    intermediate across a phase seam."""
    specs = match_partition_rules(PARTITION_RULES, tree)
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, s)
        ),
        tree,
        specs,
    )


# ---------------------------------------------------------------------------
# Sharded rp-forest: per-shard tree builds + ring-circulated candidate panels.

#: (mesh, shard, d, trees, depth, dtype, is_build) -> compiled program.
_SHARD_FOREST_CACHE: dict = {}


def _shard_geometry(n: int, n_dev: int) -> tuple[int, int]:
    """Per-device row count and padded total: ``shard = ceil(n / n_dev)``.
    No tile rounding — the forest programs gather, they don't tile — so the
    padding is < n_dev rows, all on the last device."""
    shard = -(-n // n_dev)
    return shard, shard * n_dev


def _forest_build_sweep_fn(
    mesh,
    n: int,
    shard: int,
    trees: int,
    depth: int,
    k: int,
    metric: str,
    leaf_mask: np.ndarray,
    lmax: int,
    dtype,
    precision: str = "f32",
):
    """Jitted shard_map program fusing the per-shard tree BUILD with the
    PANDA-style bounded k-NN panel exchange, double-buffered end to end.

    Every device builds T rank-split trees over its own row shard, then the
    circulating panel triple (panel rows, panel leaf members, panel
    thresholds) makes n_dev - 1 ``ppermute`` steps; per step each device
    routes its resident queries down the VISITING shard's T trees and
    lex-merges the visited leaves' members into its k-best — a bounded
    exchange: O(T · Lmax) candidate rows per query per shard, never a full
    panel scan.

    The ring overlap contract applies across the build seam too: the step-1
    ROWS panel goes in flight BEFORE the local tree build (pure local
    compute — the ICI transfer hides under it), the members/thresholds
    panels go in flight under the own-panel visit (their first chance: the
    build produces them), and every later step issues its three permutes
    before visiting the resident panel. The previous two-dispatch version
    synchronized on the fully built forest before the first byte of the
    exchange could move.

    The candidate distance tile is the SHARED fused-forest kernel body
    (``ops/pallas_forest.rows_dist``): at ``precision="f32"`` it is
    literally the same vmapped ``pairwise_distance`` row this function
    always computed (bitwise unchanged); ``"bf16"`` swaps in the bf16
    MXU dot with f32 accumulation/norms. The sharded tier has no global
    refine pass (an arbitrary cross-shard gather would replicate — same
    reason it has no rescan), so bf16 core distances carry the bf16-dot
    value error directly, quality-gated by the sampled ``recall_at_k``
    counter like every other approximation on this tier.
    """
    from hdbscan_tpu.ops.pallas_forest import rows_dist
    from hdbscan_tpu.ops.rpforest import (
        _build_geom,
        _build_one_tree,
        _dedup_lex_merge,
        _level_segments,
        route_queries,
    )

    key = (
        mesh, n, shard, trees, depth, k, metric,
        leaf_mask.tobytes(), lmax, np.dtype(dtype).str, precision,
        "build_sweep",
    )
    fn = _SHARD_FOREST_CACHE.get(key)
    if fn is not None:
        return fn
    n_dev = device_count(mesh)
    perm = ring_permutation(n_dev)
    sentinel = n
    mask_j = jnp.asarray(leaf_mask)
    geom = _build_geom(shard, depth)
    leaves = _level_segments(shard, depth)[depth]
    pos_idx = np.zeros((len(leaves), lmax), np.int64)
    for j, (s, e) in enumerate(leaves):
        width = e - s
        pos_idx[j, :width] = np.arange(s, e)
        pos_idx[j, width:] = e - 1  # pad by repeating the last position
    pos_idx_j = jnp.asarray(pos_idx)

    def per_device(rows, normals):
        me = jax.lax.axis_index(BATCH_AXIS)
        # Double-buffer across the build seam: the step-1 rows panel is
        # already moving while this device builds its trees.
        if n_dev > 1:
            next_rows = jax.lax.ppermute(rows, BATCH_AXIS, perm)
        perms, thrs = jax.vmap(
            lambda nrm: _build_one_tree(rows, nrm, geom)
        )(normals)
        members = jnp.take(perms, pos_idx_j, axis=1).astype(jnp.int32)

        my_gid = (me * shard + jnp.arange(shard)).astype(jnp.int32)
        valid_q = my_gid < n
        inf = jnp.asarray(jnp.inf, rows.dtype)
        # Seed with self at distance 0 — guaranteed even if threshold
        # routing sends a boundary point to a sibling of its build leaf.
        best_d = jnp.full((shard, k), jnp.inf, rows.dtype)
        best_i = jnp.full((shard, k), sentinel, jnp.int32)
        best_d = best_d.at[:, 0].set(jnp.where(valid_q, 0.0, jnp.inf))
        best_i = best_i.at[:, 0].set(jnp.where(valid_q, my_gid, sentinel))

        def visit(p_rows, p_mem, p_thr, src, bd, bi):
            off = (src * shard).astype(jnp.int32)
            for t in range(trees):
                node = route_queries(rows, normals[t], p_thr[t], depth)
                mem = p_mem[t][node]            # (shard, Lmax) panel-local
                gid = off + mem
                cpts = p_rows[mem]              # (shard, Lmax, d)
                cd = rows_dist(
                    rows, cpts, metric,
                    d_real=rows.shape[1], precision=precision,
                )
                ok = mask_j[node] & (gid < n) & valid_q[:, None]
                cd = jnp.where(ok, cd, inf)
                ci = jnp.where(ok, gid, sentinel)
                bd, bi = _dedup_lex_merge(
                    jnp.concatenate([bd, cd], axis=1),
                    jnp.concatenate([bi, ci], axis=1),
                    k,
                    sentinel,
                )
            return bd, bi

        if n_dev == 1:
            return visit(rows, members, thrs, me, best_d, best_i)

        # Members/thresholds for step 1 go in flight under the own-panel
        # visit — their first chance, the build just produced them.
        next_mem = jax.lax.ppermute(members, BATCH_AXIS, perm)
        next_thr = jax.lax.ppermute(thrs, BATCH_AXIS, perm)
        best_d, best_i = visit(rows, members, thrs, me, best_d, best_i)

        def step(s, carry):
            p_rows, p_mem, p_thr, bd, bi = carry
            # Overlap: issue the three panel permutes before the visit.
            nr = jax.lax.ppermute(p_rows, BATCH_AXIS, perm)
            nm = jax.lax.ppermute(p_mem, BATCH_AXIS, perm)
            nt = jax.lax.ppermute(p_thr, BATCH_AXIS, perm)
            bd, bi = visit(p_rows, p_mem, p_thr, (me - s) % n_dev, bd, bi)
            return nr, nm, nt, bd, bi

        p_rows, p_mem, p_thr, best_d, best_i = jax.lax.fori_loop(
            1, n_dev - 1, step,
            (next_rows, next_mem, next_thr, best_d, best_i),
        )
        # Last panel: visit only — exactly n_dev - 1 ppermutes per array.
        best_d, best_i = visit(
            p_rows, p_mem, p_thr, (me - (n_dev - 1)) % n_dev, best_d, best_i
        )
        return best_d, best_i

    shmapped = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS), P()),
        out_specs=(P(BATCH_AXIS), P(BATCH_AXIS)),
    )

    def program(rows, normals):
        out = constrain(
            {"points": {"rows": rows}, "forest": {"normals": normals}}, mesh
        )
        bd, bi = shmapped(out["points"]["rows"], out["forest"]["normals"])
        pinned = constrain({"neighbors": {"dist": bd, "ids": bi}}, mesh)
        return pinned["neighbors"]["dist"], pinned["neighbors"]["ids"]

    fn = jax.jit(program)
    _SHARD_FOREST_CACHE[key] = fn
    return fn


def _host_recall(data: np.ndarray, best_i: np.ndarray, k: int, sample: int):
    """Sampled recall@k against a host numpy brute-force scan (euclidean
    only). The replicated tier samples recall on device against the full
    data copy it already holds; here a device-side oracle would be the very
    O(n) replication the gate forbids, so the oracle runs on host."""
    n = len(data)
    rows = np.unique(np.linspace(0, n - 1, num=min(sample, n), dtype=np.int64))
    hits = 0
    for r in rows:
        d = np.linalg.norm(data - data[r], axis=1)
        exact = np.lexsort((np.arange(n), d))[:k]
        hits += len(np.intersect1d(exact, best_i[r][best_i[r] < n]))
    return float(hits) / float(len(rows) * k), int(len(rows))


def shard_forest_core_distances(
    data: np.ndarray,
    min_pts: int,
    metric: str = "euclidean",
    k: int | None = None,
    *,
    trees: int = 4,
    leaf_size: int = 1024,
    seed: int = 0,
    dtype=np.float32,
    mesh=None,
    trace=None,
    recall_sample: int = 256,
    knn_precision: str = "f32",
    **_ignored,
):
    """Row-sharded rp-forest core distances: per-shard tree builds + the
    ring-circulated candidate-panel exchange (module docstring).

    Returns (n,) float64 core distances (min_pts-th smallest with self
    included, zeros at ``min_pts <= 1``) — the ``fetch_knn=False`` contract
    of the other core-distance engines. Unlike the replicated rp-forest
    tier there is no global neighbor-of-neighbor rescan (it would gather
    arbitrary rows across shards, i.e. replicate); the cross-shard panel
    visits are the recall repair, quality-gated by the sampled
    ``recall_at_k`` counter and the e2e ARI tests. ``**_ignored`` swallows
    replicated-tier-only index_opts (``rescan_rounds``, ``knn_backend``)
    so call sites can pass one opts dict to either engine.

    ``knn_precision="bf16"`` runs the per-visit candidate distance tile —
    the shared fused-forest kernel body, ``ops/pallas_forest.rows_dist`` —
    as bf16 MXU dots with f32 accumulation (euclidean only; no refine pass
    exists on this tier, see ``_forest_build_sweep_fn``).
    """
    from hdbscan_tpu.ops.rpforest import (
        _heap_base,
        _level_segments,
        forest_depth,
    )

    if metric not in METRICS:
        raise ValueError(f"unknown metric {metric!r}; expected one of {METRICS}")
    if knn_precision not in ("f32", "bf16"):
        raise ValueError(
            f"unknown knn_precision {knn_precision!r}: f32 | bf16"
        )
    if knn_precision == "bf16" and metric != "euclidean":
        raise ValueError(
            "knn_precision='bf16' supports euclidean only "
            f"(got metric={metric!r})"
        )
    data = np.asarray(data)
    n, d = data.shape
    mesh = mesh if mesh is not None else get_mesh()
    n_dev = device_count(mesh)
    k_eff = max(k or 0, max(min_pts - 1, 1))
    k_eff = min(k_eff, n)
    shard, n_pad = _shard_geometry(n, n_dev)
    # Same clamp as the replicated tier, applied at SHARD scale: every
    # per-shard leaf must be able to supply a full candidate list.
    leaf_size = min(max(leaf_size, 2 * k_eff + 2, 8), max(shard, 2))
    depth = forest_depth(shard, leaf_size)
    leaves = _level_segments(shard, depth)[depth]
    lmax = max(e - s for s, e in leaves)
    leaf_mask = np.zeros((len(leaves), lmax), bool)
    for j, (s, e) in enumerate(leaves):
        leaf_mask[j, : e - s] = True
    num_nodes = _heap_base(depth)
    rng = np.random.default_rng(np.random.SeedSequence([seed, shard, depth]))
    normals = rng.standard_normal((trees, max(num_nodes, 1), d))
    normals /= np.maximum(np.linalg.norm(normals, axis=-1, keepdims=True), 1e-12)

    t_up = time.monotonic()
    rows = jax.device_put(
        _pad_rows(np.asarray(data, dtype), n_pad), row_sharding(mesh)
    )
    normals_dev = jax.device_put(normals.astype(dtype), replicated(mesh))
    upload_s = time.monotonic() - t_up

    # The build fuses into the sweep dispatch (the step-1 rows panel is in
    # flight while the trees build — _forest_build_sweep_fn), so the build
    # event is a geometry record: its wall hides under the exchange.
    if trace is not None:
        trace(
            "shard_knn_build",
            wall_s=0.0,
            fused=True,
            devices=n_dev,
            trees=trees,
            depth=depth,
            leaf_size=leaf_size,
            max_leaf=lmax,
            n=n,
            d=d,
        )

    from hdbscan_tpu.utils.flops import counter as _flops

    # Each query visits T leaves in each of D shards: T·D·Lmax candidates.
    _flops.add_scan(n_pad * trees * n_dev, lmax, d)
    sweep = _forest_build_sweep_fn(
        mesh, n, shard, trees, depth, k_eff, metric, leaf_mask, lmax, dtype,
        precision=knn_precision,
    )
    with obs.mem_phase("shard_knn_scan"), obs.task(
        "shard_knn_scan", total=n_dev
    ) as hb:
        t0 = time.monotonic()
        best_d, best_i = sweep(rows, normals_dev)
        walls = _per_device_walls(best_d, t0, beat=hb.beat)
        wall = time.monotonic() - t0
    # One visiting panel per permute step: the shard's points plus its
    # trees' leaf members and heap thresholds.
    itemsize = np.dtype(dtype).itemsize
    panel_bytes = (
        shard * d * itemsize
        + trees * len(leaves) * lmax * 4
        + trees * num_nodes * itemsize
    )
    _emit_ring_trace(
        trace, "shard_panel_sweep", wall, walls, n_dev, 0,
        rows=n, trees=trees, shard=shard,
        upload_s=upload_s, comm_bytes=(n_dev - 1) * panel_bytes,
        flops=2.0 * n_pad * trees * n_dev * lmax * d,
    )

    kth_col = min(max(min_pts - 1, 1), n) - 1
    t0 = time.monotonic()
    kth = np.asarray(fetch(best_d[:, kth_col]), np.float64)[:n]
    if trace is not None:
        fields = dict(
            n=n,
            k=k_eff,
            trees=trees,
            devices=n_dev,
            candidates=trees * n_dev * lmax,
        )
        if recall_sample and metric == "euclidean":
            ids = np.asarray(fetch(best_i), np.int64)[:n]
            recall, rows_used = _host_recall(data, ids, k_eff, recall_sample)
            fields["recall_at_k"] = recall
            fields["recall_rows"] = rows_used
        trace(
            "shard_knn_exchange",
            wall_s=round(time.monotonic() - t0, 6),
            **fields,
        )
    # Free every device buffer of the forest pass eagerly — deferred
    # deletion would otherwise keep the (shard, k) lists and row panels
    # resident into the Borůvka phase, charging its replication budget.
    # (Leaf members/thresholds are in-jit transients of the fused program.)
    for arr in (best_d, best_i, rows, normals_dev):
        arr.delete()
    if min_pts <= 1:
        return np.zeros(n, np.float64)
    return kth


def shard_core_distances(
    data: np.ndarray,
    min_pts: int,
    metric: str = "euclidean",
    *,
    row_tile: int = 1024,
    col_tile: int = 8192,
    dtype=np.float32,
    mesh=None,
    trace=None,
    knn_backend: str = "auto",
    index: str = "exact",
    index_opts: dict | None = None,
) -> np.ndarray:
    """Core distances under the sharded program: (n,) float64.

    ``index="exact"`` delegates to the ring k-NN scan — already fully
    row-sharded (queries, panels and per-point lists all P(blocks); only
    the scalar n replicates), bitwise identical to the host scan.
    ``index="rpforest"`` runs the row-sharded forest build + panel
    exchange (:func:`shard_forest_core_distances`) instead of the
    replicated forest.
    """
    mesh = mesh if mesh is not None else get_mesh()
    if index == "rpforest":
        return shard_forest_core_distances(
            data, min_pts, metric, dtype=dtype, mesh=mesh, trace=trace,
            **(index_opts or {}),
        )
    if index != "exact":
        raise ValueError(f"unknown knn index {index!r}")
    from hdbscan_tpu.parallel.ring import ring_knn_core_distances

    core, _ = ring_knn_core_distances(
        data, min_pts, metric, row_tile=row_tile, col_tile=col_tile,
        dtype=dtype, fetch_knn=False, mesh=mesh, trace=trace,
        knn_backend=knn_backend,
    )
    return core


def shard_core_distances_rows(
    data: np.ndarray,
    row_ids: np.ndarray,
    min_pts: int,
    metric: str = "euclidean",
    *,
    dtype=np.float32,
    mesh=None,
    trace=None,
    index: str = "exact",
    index_opts: dict | None = None,
) -> np.ndarray:
    """Core distances for SELECTED rows under the sharded program — the
    mr-hdbscan boundary-rescan contract ((m,) float64 aligned with
    ``row_ids``). Exact rows ride the ring rows-scan (queries row-shard,
    panels circulate); the forest tier answers from a full sharded pass and
    slices, same as the replicated rp-forest rows path."""
    mesh = mesh if mesh is not None else get_mesh()
    row_ids = np.asarray(row_ids)
    if index == "rpforest":
        core = shard_forest_core_distances(
            data, min_pts, metric, dtype=dtype, mesh=mesh, trace=trace,
            recall_sample=0, **(index_opts or {}),
        )
        return core[row_ids]
    if index != "exact":
        raise ValueError(f"unknown knn index {index!r}")
    from hdbscan_tpu.parallel.ring import ring_knn_core_distances_rows

    return ring_knn_core_distances_rows(
        data, row_ids, min_pts, metric, dtype=dtype, mesh=mesh, trace=trace,
    )


# ---------------------------------------------------------------------------
# Fully row-sharded Borůvka rounds.

#: (mesh, metric, row_tile, col_tile) -> compiled per-round program.
_SHARD_BORUVKA_CACHE: dict = {}


def _shard_boruvka_fn(mesh, metric: str, row_tile: int, col_tile: int):
    """Jitted shard_map Borůvka round with ROW-SHARDED component labels.

    The ring scanner replicates the dense component vector ((n,) int32 on
    every device — O(n) replicated, which trips the gate in the early
    rounds where n_comp ≈ n). Here the labels shard with their rows and
    circulate as a second panel next to the augmented data panel (two
    ``ppermute``s per step, both issued before the tile scan). Outputs are
    the per-ROW best outgoing (weight, column) under the explicit (w, j)
    lex tie-break, row-sharded — bitwise the host scanner's per-point
    arrays (its ascending-column first-tile-wins rule IS the (w, j)-lex
    min), so the host contraction and the emitted MST edges are identical.
    """
    key = (mesh, metric, row_tile, col_tile)
    fn = _SHARD_BORUVKA_CACHE.get(key)
    if fn is not None:
        return fn
    n_dev = device_count(mesh)
    perm = ring_permutation(n_dev)

    def per_device(rows_aug, comp_rows, n_arr):
        me = jax.lax.axis_index(BATCH_AXIS)
        shard = rows_aug.shape[0]
        n_row_tiles = shard // row_tile
        n_col_tiles = shard // col_tile
        dtype = rows_aug.dtype
        inf = jnp.array(jnp.inf, dtype)
        n_pts = n_arr.astype(jnp.int32)
        my_off = (me * shard).astype(jnp.int32)

        def scan_panel(p_aug, p_comp, src, bw, bj):
            off = (src * shard).astype(jnp.int32)

            def row_step(r, carry):
                bw, bj = carry
                xr = jax.lax.dynamic_slice_in_dim(
                    rows_aug, r * row_tile, row_tile
                )[:, :-1]
                cr = jax.lax.dynamic_slice_in_dim(
                    rows_aug, r * row_tile, row_tile
                )[:, -1]
                kr = jax.lax.dynamic_slice_in_dim(
                    comp_rows, r * row_tile, row_tile
                )
                vr = (
                    my_off + r * row_tile
                    + jnp.arange(row_tile, dtype=jnp.int32)
                ) < n_pts
                bw_r = jax.lax.dynamic_slice_in_dim(bw, r * row_tile, row_tile)
                bj_r = jax.lax.dynamic_slice_in_dim(bj, r * row_tile, row_tile)

                def col_step(c, carry2):
                    bw_r, bj_r = carry2
                    xc = jax.lax.dynamic_slice_in_dim(
                        p_aug, c * col_tile, col_tile
                    )[:, :-1]
                    cc = jax.lax.dynamic_slice_in_dim(
                        p_aug, c * col_tile, col_tile
                    )[:, -1]
                    kc = jax.lax.dynamic_slice_in_dim(
                        p_comp, c * col_tile, col_tile
                    )
                    col0 = off + c * col_tile
                    vc = (
                        col0 + jnp.arange(col_tile, dtype=jnp.int32)
                    ) < n_pts
                    d = pairwise_distance(xr, xc, metric)
                    w = jnp.maximum(d, jnp.maximum(cr[:, None], cc[None, :]))
                    out = (kr[:, None] != kc[None, :]) & vc[None, :] & vr[:, None]
                    w = jnp.where(out, w, inf)
                    tw = jnp.min(w, axis=1)
                    tj = jnp.argmin(w, axis=1).astype(jnp.int32) + col0
                    # Explicit (w, j) lex: rotated panel arrival order must
                    # not change the winner (= host ascending-column rule).
                    upd = (tw < bw_r) | ((tw == bw_r) & (tj < bj_r))
                    return (
                        jnp.where(upd, tw, bw_r),
                        jnp.where(upd, tj, bj_r),
                    )

                bw_r, bj_r = jax.lax.fori_loop(
                    0, n_col_tiles, col_step, (bw_r, bj_r)
                )
                bw = jax.lax.dynamic_update_slice_in_dim(
                    bw, bw_r, r * row_tile, axis=0
                )
                bj = jax.lax.dynamic_update_slice_in_dim(
                    bj, bj_r, r * row_tile, axis=0
                )
                return bw, bj

            return jax.lax.fori_loop(0, n_row_tiles, row_step, (bw, bj))

        bw0 = jnp.full_like(rows_aug[:, -1], jnp.inf)
        bj0 = jnp.full_like(comp_rows, -1)

        def step(s, carry):
            p_aug, p_comp, bw, bj = carry
            # Overlap: both panel permutes issued before the tile scan.
            na = jax.lax.ppermute(p_aug, BATCH_AXIS, perm)
            nc = jax.lax.ppermute(p_comp, BATCH_AXIS, perm)
            bw, bj = scan_panel(p_aug, p_comp, (me - s) % n_dev, bw, bj)
            return na, nc, bw, bj

        p_aug, p_comp, bw, bj = jax.lax.fori_loop(
            0, n_dev - 1, step, (rows_aug, comp_rows, bw0, bj0)
        )
        bw, bj = scan_panel(p_aug, p_comp, (me - (n_dev - 1)) % n_dev, bw, bj)
        return bw, bj

    shmapped = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS), P(BATCH_AXIS), P()),
        out_specs=(P(BATCH_AXIS), P(BATCH_AXIS)),
    )

    def program(rows_aug, comp_rows, n_arr):
        pinned = constrain(
            {"points": {"aug": rows_aug}, "comp": {"rows": comp_rows}}, mesh
        )
        bw, bj = shmapped(
            pinned["points"]["aug"], pinned["comp"]["rows"], n_arr
        )
        out = constrain({"edges": {"weight": bw, "src": bj}}, mesh)
        return out["edges"]["weight"], out["edges"]["src"]

    # The component panel is rewritten every round — donate it so the round
    # reuses the buffer instead of holding both generations live. The
    # caller MUST pass a runtime-owned panel (see ``_owned_row_panel``):
    # donating a zero-copy ``device_put`` view of host memory is undefined
    # behavior.
    fn = jax.jit(program, donate_argnums=(1,))
    _SHARD_BORUVKA_CACHE[key] = fn
    return fn


# Jitted materializing copy: the output buffer is allocated and owned by
# the runtime, unlike the possibly zero-copy host view device_put returns.
_OWNED_COPY = jax.jit(jnp.copy)


def _owned_row_panel(host_rows: np.ndarray, mesh):
    """Upload a host panel into a runtime-OWNED row-sharded buffer.

    ``jax.device_put`` of an aligned numpy array on CPU backends is
    zero-copy: the returned jax.Array borrows numpy's memory. Donating
    that borrowed buffer to a round program is undefined behavior — the
    donation hands XLA memory the Python allocator still owns and may
    recycle while the round is in flight. On the forced-8-device CPU mesh
    this corrupted roughly one run in three (garbage MST edge weights,
    timing-dependent: any concurrent thread shifted the allocator enough
    to expose it). The jitted copy materializes a buffer the runtime owns
    outright, which is the precondition for donating it.
    """
    return _OWNED_COPY(jax.device_put(host_rows, row_sharding(mesh)))


class ShardBoruvkaScanner:
    """Fully row-sharded drop-in for :class:`ops.tiled.BoruvkaScanner`.

    Same ``min_outgoing(comp) -> (best_w, best_j)`` contract and bitwise
    the same per-point arrays as the host scanner (see
    :func:`_shard_boruvka_fn`), but every O(n) buffer — points, cores,
    component labels, per-row winners — lives row-sharded: per-device HBM
    is O(n/D · d) in every round. The per-round fetch of the (n,) winner
    arrays to the host contraction is the "all-gather edges only at
    contraction" step of the parallel-EMST shape: host memory, where O(n)
    is fine; the ``--assert-not-replicated`` gate measures device memory.
    """

    def __init__(
        self,
        data: np.ndarray,
        core: np.ndarray,
        metric: str = "euclidean",
        row_tile: int = 1024,
        col_tile: int = 8192,
        dtype=np.float32,
        mesh=None,
        trace=None,
    ):
        n = len(data)
        self.n = n
        self.d = np.asarray(data).shape[1]
        self.metric = metric
        self.mesh = mesh if mesh is not None else get_mesh()
        self.n_dev = device_count(self.mesh)
        self.trace = trace
        self.row_tile, self.col_tile, self.shard, n_pad = _ring_geometry(
            n, self.n_dev, row_tile, col_tile
        )
        self.n_pad = n_pad
        aug = np.concatenate(
            [np.asarray(data, dtype), np.asarray(core, dtype)[:, None]], axis=1
        )
        self._rows = jax.device_put(
            _pad_rows(aug, n_pad), row_sharding(self.mesh)
        )
        self._n_arr = jax.device_put(
            np.asarray(n, np.int32), replicated(self.mesh)
        )
        self._round = 0

    def close(self) -> None:
        """Delete the scanner's device buffers NOW. Dropping the Python
        references alone leaves the row shards to the runtime's deferred
        deletion, which keeps them resident through a successor program's
        first rounds — phantom bytes that read as replication to the
        fit-path memory gate when two scanners run back to back."""
        for arr in (self._rows, self._n_arr):
            try:
                arr.delete()
            except Exception:
                pass

    def min_outgoing(self, comp: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Per-point (best_w, best_j): minimum outgoing mutual-reachability
        edge of every point's component seen from that point, (w, j)-lex."""
        from hdbscan_tpu.utils.flops import counter as _flops

        _flops.add_scan(self.n_pad, self.n_pad, self.d, row_tile=self.row_tile)
        comp = np.asarray(comp)
        fn = _shard_boruvka_fn(
            self.mesh, self.metric, self.row_tile, self.col_tile
        )
        with obs.mem_phase("shard_boruvka_scan"), obs.task(
            "shard_boruvka_scan", total=self.n_dev
        ) as hb:
            # The component panel is donated to the round program: it must
            # be runtime-owned (``_owned_row_panel``), and the live-arrays
            # sampler stays out of the window between its creation and the
            # round's outputs being ready (obs.donation_guard).
            with obs.donation_guard():
                # Component labels are vertex ids (< n): int32 panel.
                t_up = time.monotonic()
                comp_dev = _owned_row_panel(
                    _pad_rows(comp.astype(np.int32), self.n_pad), self.mesh
                )
                t0 = time.monotonic()
                upload_s = t0 - t_up
                bw_dev, bj_dev = fn(self._rows, comp_dev, self._n_arr)
                walls = _per_device_walls(bw_dev, t0, beat=hb.beat)
            wall = time.monotonic() - t0

        t_f = time.monotonic()
        bw = np.asarray(fetch(bw_dev), np.float64)[: self.n]
        bj = np.asarray(fetch(bj_dev), np.int64)[: self.n]
        fetch_s = time.monotonic() - t_f
        # Free the round's device outputs NOW: the runtime's deferred
        # deletion otherwise keeps every round's (shard,) pieces resident
        # through the next round's scan, and the accumulated O(n·rounds/D)
        # bytes read as replication to the fit-path memory gate.
        bw_dev.delete()
        bj_dev.delete()
        # Two circulating panels per step: the augmented row shard and the
        # matching int32 component-label shard.
        comm_bytes = (self.n_dev - 1) * self.shard * (
            (self.d + 1) * self._rows.dtype.itemsize + 4
        )
        _emit_ring_trace(
            self.trace, "shard_boruvka_scan", wall, walls, self.n_dev,
            self._round,
            n_comp=int(len(np.unique(comp))),
            candidates=int(np.sum(bj >= 0)),
            upload_s=upload_s, fetch_s=fetch_s, comm_bytes=comm_bytes,
            flops=2.0 * self.n_pad * self.n_pad * self.d,
        )
        self._round += 1
        return bw, bj


# ---------------------------------------------------------------------------
# In-jit sharded Borůvka: every round — scan, cross-device winner reduction,
# contraction — inside ONE device program (mst_backend=device under sharding).

#: (mesh, metric, n, row_tile, col_tile, max_rounds, dtype) -> compiled fn.
_SHARD_MST_CACHE: dict = {}


def _shard_mst_fn(
    mesh, metric: str, n: int, row_tile: int, col_tile: int,
    max_rounds: int, dtype_str: str,
):
    """Jitted shard_map program running ALL sharded Borůvka rounds in-jit.

    Fuses :func:`_shard_boruvka_fn`'s row-sharded ring scan with
    ``core/mst_device._contract_round``'s scatter-min tie-break cascade:

    * scan — the augmented row panel circulates (``ppermute`` issued before
      each panel's tile scan, the overlap contract), per-row winners carry
      the explicit (w, j) lex tie-break, labels are sliced per panel from
      the round's component vector;
    * reduction — the per-shard scatter-mins over the (n,) label space
      reduce across the mesh with a ``lax.pmin`` cascade in the host
      contraction's key order (w, then lo, then hi, then row, then the
      winner's target column) — five (n,)-sized all-reduces per round
      replace the per-round O(n) host fetch;
    * contraction — the pointer-doubling collapse
      (``mst_device._collapse_labels``, the SAME code the replicated device
      engine runs) executes identically on every device over the reduced
      (replicated-in-jit) winner arrays, so labels stay consistent with no
      host relabel. The replicated component carry lives only inside the
      program — per-device HBM, invisible to Python, bounded by one int32
      (n_pad,) vector; every Python-held O(n) output stays row-sharded.

    Emission replays ``_boruvka_rounds_device``'s slot scatter bit for bit
    (ascending-label order per round, (n_pad,)-sized buffers padded with
    +inf self-loops so ``forest_events_device`` consumes them directly).
    Outputs: row-sharded (n_pad,) u/v/w edge buffers plus replicated
    count/rounds/per-round stats. One ``while_loop`` over rounds — the fit
    performs ZERO host syncs between the core scan and the final fetch.
    """
    from hdbscan_tpu.core.mst_device import (
        _collapse_labels,
        _doubling_rounds,  # noqa: F401  (collapse dependency, keep imported)
    )
    from hdbscan_tpu.ops.pallas_segmin import (
        min_outgoing_panel,
        panel_eligible,
    )

    key = (mesh, metric, n, row_tile, col_tile, max_rounds, dtype_str)
    fn = _SHARD_MST_CACHE.get(key)
    if fn is not None:
        return fn
    n_dev = device_count(mesh)
    perm = ring_permutation(n_dev)
    use_pallas = panel_eligible(
        mesh.devices.flat[0].platform, np.dtype(dtype_str)
    )
    sentinel = jnp.iinfo(jnp.int32).max

    def per_device(rows_aug):
        shard = rows_aug.shape[0]
        n_pad = shard * n_dev
        n_row_tiles = shard // row_tile
        n_col_tiles = shard // col_tile
        dtype = rows_aug.dtype
        inf = jnp.array(jnp.inf, dtype)
        me = jax.lax.axis_index(BATCH_AXIS)
        my_off = (me * shard).astype(jnp.int32)
        gid = my_off + jnp.arange(shard, dtype=jnp.int32)
        valid_l = gid < n
        valid_full = jnp.arange(n_pad, dtype=jnp.int32) < n
        buf = n_pad

        def scan_panel(p_aug, src, bw, bj, kr_all, comp):
            off = (src * shard).astype(jnp.int32)
            kc_all = jax.lax.dynamic_slice_in_dim(comp, off, shard)
            vc_all = (off + jnp.arange(shard, dtype=jnp.int32)) < n
            if use_pallas:
                pw, pj = min_outgoing_panel(
                    rows_aug[:, :-1], rows_aug[:, -1], kr_all, valid_l,
                    p_aug[:, :-1], p_aug[:, -1], kc_all, vc_all,
                    metric, row_tile, col_tile,
                )
                # Panel-local winner -> global column id; inf rows carry a
                # harmless 0 (the lex merge can't pick them: bw=inf pairs
                # with bj=-1 only at init, and 0 < -1 is false).
                tj = jnp.where(pj >= 0, pj + off, 0)
                upd = (pw < bw) | ((pw == bw) & (tj < bj))
                return jnp.where(upd, pw, bw), jnp.where(upd, tj, bj)

            def row_step(r, carry):
                bw, bj = carry
                xr = jax.lax.dynamic_slice_in_dim(
                    rows_aug, r * row_tile, row_tile
                )[:, :-1]
                cr = jax.lax.dynamic_slice_in_dim(
                    rows_aug, r * row_tile, row_tile
                )[:, -1]
                kr = jax.lax.dynamic_slice_in_dim(kr_all, r * row_tile, row_tile)
                vr = jax.lax.dynamic_slice_in_dim(valid_l, r * row_tile, row_tile)
                bw_r = jax.lax.dynamic_slice_in_dim(bw, r * row_tile, row_tile)
                bj_r = jax.lax.dynamic_slice_in_dim(bj, r * row_tile, row_tile)

                def col_step(c, carry2):
                    bw_r, bj_r = carry2
                    xc = jax.lax.dynamic_slice_in_dim(
                        p_aug, c * col_tile, col_tile
                    )[:, :-1]
                    cc = jax.lax.dynamic_slice_in_dim(
                        p_aug, c * col_tile, col_tile
                    )[:, -1]
                    kc = jax.lax.dynamic_slice_in_dim(
                        kc_all, c * col_tile, col_tile
                    )
                    vc = jax.lax.dynamic_slice_in_dim(
                        vc_all, c * col_tile, col_tile
                    )
                    col0 = off + c * col_tile
                    d = pairwise_distance(xr, xc, metric)
                    w = jnp.maximum(d, jnp.maximum(cr[:, None], cc[None, :]))
                    out = (kr[:, None] != kc[None, :]) & vc[None, :] & vr[:, None]
                    w = jnp.where(out, w, inf)
                    tw = jnp.min(w, axis=1)
                    tj = jnp.argmin(w, axis=1).astype(jnp.int32) + col0
                    # Explicit (w, j) lex — rotated panel arrival order must
                    # not change the winner (= host ascending-column rule).
                    upd = (tw < bw_r) | ((tw == bw_r) & (tj < bj_r))
                    return (
                        jnp.where(upd, tw, bw_r),
                        jnp.where(upd, tj, bj_r),
                    )

                bw_r, bj_r = jax.lax.fori_loop(
                    0, n_col_tiles, col_step, (bw_r, bj_r)
                )
                bw = jax.lax.dynamic_update_slice_in_dim(
                    bw, bw_r, r * row_tile, axis=0
                )
                bj = jax.lax.dynamic_update_slice_in_dim(
                    bj, bj_r, r * row_tile, axis=0
                )
                return bw, bj

            return jax.lax.fori_loop(0, n_row_tiles, row_step, (bw, bj))

        def cond(st):
            return (
                (st["rnd"] < max_rounds) & (st["n_comp"] > 1) & st["progress"]
            )

        def body(st):
            comp = st["comp"]
            kr_all = jax.lax.dynamic_slice_in_dim(comp, my_off, shard)
            bw0 = jnp.full((shard,), jnp.inf, dtype)
            bj0 = jnp.full((shard,), -1, jnp.int32)

            def step(s, carry):
                p_aug, bw, bj = carry
                # Overlap: issue the panel permute before the tile scan.
                nxt = jax.lax.ppermute(p_aug, BATCH_AXIS, perm)
                bw, bj = scan_panel(
                    p_aug, (me - s) % n_dev, bw, bj, kr_all, comp
                )
                return nxt, bw, bj

            p_aug, bw, bj = jax.lax.fori_loop(
                0, n_dev - 1, step, (rows_aug, bw0, bj0)
            )
            bw, bj = scan_panel(
                p_aug, (me - (n_dev - 1)) % n_dev, bw, bj, kr_all, comp
            )

            # Cross-device winner reduction: per-shard scatter-min partials
            # over the (n,) label space, pmin-reduced in the shared key
            # order (w, lo, hi, row) of _contract_round — then one extra
            # pmin lands the unique winner row's target column, the value
            # _contract_round reads locally as bj[win_row].
            bj_c = jnp.clip(bj, 0, n_pad - 1)
            cross = valid_l & (bj >= 0) & (kr_all != comp[bj_c])
            lab = jnp.where(cross, kr_all, n)
            wpart = (
                jnp.full((n,), jnp.inf, bw.dtype)
                .at[lab]
                .min(bw, mode="drop")
            )
            wmin = jax.lax.pmin(wpart, BATCH_AXIS)
            comp_c = jnp.clip(kr_all, 0, n - 1)
            tied = cross & (bw == wmin[comp_c])

            def seg_min(mask, val):
                part = (
                    jnp.full((n,), sentinel, jnp.int32)
                    .at[jnp.where(mask, lab, n)]
                    .min(val, mode="drop")
                )
                return jax.lax.pmin(part, BATCH_AXIS)

            lo = jnp.minimum(gid, bj_c)
            hi = jnp.maximum(gid, bj_c)
            lo_min = seg_min(tied, lo)
            tied = tied & (lo == lo_min[comp_c])
            hi_min = seg_min(tied, hi)
            tied = tied & (hi == hi_min[comp_c])
            row_min = seg_min(tied, gid)
            has_edge = row_min < sentinel
            win_row = jnp.where(has_edge, row_min, 0)
            bj_win = seg_min(tied & (gid == row_min[comp_c]), bj_c)
            bjw_c = jnp.clip(bj_win, 0, n_pad - 1)

            # Identical on every device from here: the reduced arrays are
            # replicated, so the collapse + emission need no host relabel.
            emit_mask, rep, n_comp, added = _collapse_labels(
                comp, valid_full, has_edge, comp[bjw_c], n
            )
            pos = st["count"] + jnp.cumsum(emit_mask.astype(jnp.int32)) - 1
            slot = jnp.where(emit_mask, pos, buf)
            wr = jnp.clip(win_row, 0, n_pad - 1)
            eu = st["eu"].at[slot].set(wr, mode="drop")
            ev = st["ev"].at[slot].set(bjw_c.astype(jnp.int32), mode="drop")
            ew = st["ew"].at[slot].set(wmin, mode="drop")
            rnd = st["rnd"]
            return dict(
                comp=rep[comp],
                eu=eu,
                ev=ev,
                ew=ew,
                count=st["count"] + added.astype(jnp.int32),
                rnd=rnd + 1,
                n_comp=n_comp.astype(jnp.int32),
                progress=added > 0,
                stat_comp=st["stat_comp"].at[rnd].set(
                    n_comp.astype(jnp.int32)
                ),
                stat_edges=st["stat_edges"].at[rnd].set(
                    added.astype(jnp.int32)
                ),
            )

        state = dict(
            comp=jnp.arange(n_pad, dtype=jnp.int32),
            eu=jnp.zeros((buf,), jnp.int32),
            ev=jnp.zeros((buf,), jnp.int32),
            ew=jnp.full((buf,), jnp.inf, dtype),
            count=jnp.int32(0),
            rnd=jnp.int32(0),
            n_comp=jnp.int32(n),
            progress=jnp.asarray(True),
            stat_comp=jnp.zeros((max_rounds,), jnp.int32),
            stat_edges=jnp.zeros((max_rounds,), jnp.int32),
        )
        st = jax.lax.while_loop(cond, body, state)
        # Edge buffers leave the program ROW-SHARDED (each device keeps its
        # slice of the replicated in-jit buffer) — the Python-visible
        # footprint stays O(n/D) per device, which is what the
        # --assert-not-replicated gate measures.
        eu_l = jax.lax.dynamic_slice_in_dim(st["eu"], my_off, shard)
        ev_l = jax.lax.dynamic_slice_in_dim(st["ev"], my_off, shard)
        ew_l = jax.lax.dynamic_slice_in_dim(st["ew"], my_off, shard)
        return (
            eu_l, ev_l, ew_l,
            st["count"], st["rnd"], st["stat_comp"], st["stat_edges"],
        )

    shmapped = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS),),
        out_specs=(
            P(BATCH_AXIS), P(BATCH_AXIS), P(BATCH_AXIS),
            P(), P(), P(), P(),
        ),
        # The round while_loop has no replication rule in the checker; the
        # P() outputs ARE replicated by construction — every carried value
        # derives from lax.pmin reductions executed identically per device.
        check_rep=False,
    )

    def program(rows_aug):
        pinned = constrain({"points": {"aug": rows_aug}}, mesh)
        eu, ev, ew, count, rounds, stat_comp, stat_edges = shmapped(
            pinned["points"]["aug"]
        )
        out = constrain(
            {"edges": {"u": eu, "v": ev, "weight": ew}}, mesh
        )
        return {
            "u": out["edges"]["u"],
            "v": out["edges"]["v"],
            "w": out["edges"]["weight"],
            "count": count,
            "rounds": rounds,
            "stat_comp": stat_comp,
            "stat_edges": stat_edges,
        }

    # The augmented row panel is consumed by the first round's scan and
    # never needed again — donate it so it drops out of the Python-visible
    # per-device footprint for the rest of the (single-dispatch) program.
    # Same precondition as the round program: the caller must pass a
    # runtime-owned panel (``_owned_row_panel``).
    fn = jax.jit(program, donate_argnums=(0,))
    _SHARD_MST_CACHE[key] = fn
    return fn


def shard_boruvka_mst(
    data: np.ndarray,
    core: np.ndarray,
    metric: str = "euclidean",
    row_tile: int = 1024,
    col_tile: int = 8192,
    dtype=np.float32,
    mesh=None,
    max_rounds: int = 64,
):
    """Run every sharded Borůvka round in ONE device program.

    Returns ``(res, holds)``: ``res`` is the device result dict (row-sharded
    (n_pad,) ``u``/``v``/``w`` edge buffers padded with +inf self-loops,
    replicated ``count``/``rounds``/``stat_comp``/``stat_edges``) shaped for
    ``core/mst_device.forest_events_device``. ``holds`` is empty: the input
    panel is DONATED to the program (runtime-owned upload, the
    ``_owned_row_panel`` precondition), so the per-device Python-visible
    footprint during the fit is the row-sharded outputs alone — which is
    what keeps the ``boruvka_mst_device`` phase under the replication
    gate's ``0.5*n*itemsize`` budget at the certified n=8192 geometry.

    Bitwise contract: the emitted edges equal the host-contraction sharded
    path (:class:`ShardBoruvkaScanner` + ``contract_min_edges``) edge for
    edge — same scan tie-break, same contraction key, same emission order —
    pinned by the randomized sweep in ``tests/unit/test_shard_mst.py``.
    """
    n = len(data)
    mesh = mesh if mesh is not None else get_mesh()
    n_dev = device_count(mesh)
    row_tile, col_tile, shard, n_pad = _ring_geometry(
        n, n_dev, row_tile, col_tile
    )
    aug = np.concatenate(
        [np.asarray(data, dtype), np.asarray(core, dtype)[:, None]], axis=1
    )
    fn = _shard_mst_fn(
        mesh, metric, n, row_tile, col_tile, max_rounds, np.dtype(dtype).str
    )
    # Donated input: must be runtime-owned, and the live-arrays sampler
    # stays out of the upload-to-dispatch window (obs.donation_guard).
    with obs.donation_guard():
        rows = _owned_row_panel(_pad_rows(aug, n_pad), mesh)
        res = fn(rows)
    return res, ()
