"""Device mesh helpers — the TPU-native replacement for the Spark runtime (L0).

The reference scales by handing each worker a whole subset
(``mapPartitionsToPair``, ``main/Main.java:166-169``; one worker ≈ one
"processing unit"). Here the analog is a 1-D ``jax.sharding.Mesh`` over all
local devices with per-partition blocks sharded along the batch axis: one TPU
core processes a stream of padded blocks, XLA/ICI handle the data movement
(SURVEY.md §2.C rows P1/P4/P6). Multi-host extends the same mesh over DCN via
``jax.distributed`` without code changes — the mesh axis is the only
parallelism vocabulary.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "blocks"


def get_mesh(devices: list | None = None) -> Mesh:
    """1-D data-parallel mesh over the given (default: all) devices."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def device_count(mesh: Mesh) -> int:
    """Number of devices on the (1-D) mesh."""
    return int(np.prod(mesh.devices.shape))


def ring_permutation(n_dev: int) -> list[tuple[int, int]]:
    """``lax.ppermute`` pairs for one unidirectional ring rotation step:
    device i hands its held panel to i+1 (mod n_dev), so after s steps
    device i holds the panel that originated at (i - s) mod n_dev. The
    ring-systolic scans (``parallel/ring.py``) take exactly n_dev - 1 such
    steps per sweep."""
    return [(i, (i + 1) % n_dev) for i in range(n_dev)]


def block_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-axis sharding for (B, ...) block stacks."""
    return NamedSharding(mesh, P(BATCH_AXIS))


#: Leading-axis sharding for (n, ...) row sets (tiled-scan row shards) —
#: identical placement to block_sharding; the alias names the intent.
row_sharding = block_sharding


def replicated(mesh: Mesh) -> NamedSharding:
    """Replicated sharding — broadcast arrays (sample matrices, models),
    the ``Broadcast``/driver-closure analog (SURVEY.md §2.C row P4)."""
    return NamedSharding(mesh, P())


def pad_batch(batch_size: int, num_devices: int) -> int:
    """Blocks are padded so the batch axis divides the mesh evenly."""
    return -(-batch_size // num_devices) * num_devices


def fetch(tree):
    """Device->host fetch that works across process boundaries.

    Single-controller arrays (fully addressable) take the plain
    ``device_get`` path. Arrays sharded over a multi-process mesh are not
    fully addressable — each controller holds only its shards — so they
    gather over DCN first (``process_allgather(tiled=True)``: shard axes
    concatenate back to the global shape, the multi-host analog of the
    shuffle-read half of a Spark stage boundary, SURVEY.md §2.C). Every
    process returns the same full numpy tree.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if all(getattr(x, "is_fully_addressable", True) for x in leaves):
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(tree, tiled=True)
