"""Multi-host (multi-controller) runtime — the DCN half of the L0 story.

The reference's Spark cluster is inherently multi-host: the driver talks to
executors over the network and every stage boundary round-trips HDFS
(``main/Main.java:89-95``; SURVEY.md §2.C "communication backend"). The
TPU-native equivalent is JAX's multi-controller model: one Python process
per host, ``jax.distributed.initialize`` wiring them into a single logical
device set, a ``Mesh`` spanning every chip, ICI collectives within a slice
and DCN between hosts — all emitted by XLA from sharding annotations, never
hand-written sends.

This module carries the three pieces a multi-host run needs on top of the
single-host code (which is multi-controller-clean already: everything device
side is mesh-sharded, everything host-side orchestrates through numpy):

- :func:`initialize_from_cluster_name` — process wiring, mapped onto the
  reference's ``clusterName=`` flag (``local`` = single process, the
  reference's ``local`` Spark master; ``auto`` = TPU-pod env autodetection;
  explicit ``coordinator:port,process_id,num_processes`` otherwise).
- :func:`host_row_slab` — per-host dataset ingest: each host loads only its
  contiguous row slab (the analog of HDFS blocks feeding Spark partitions).
- :func:`global_rows_from_local` — assembly of per-host slabs into one
  globally-sharded device array over a mesh, via
  ``jax.make_array_from_process_local_data`` (DCN touches data only when a
  later resharding demands it).

Single-process behavior is the identity (slab = whole set, assembly = plain
``device_put``), which is what the tests pin; real multi-host runs need a
TPU pod (ROADMAP "Misc" tracks that this is scaffolded, not yet demonstrated
on hardware we don't have).
"""

from __future__ import annotations

import jax
import numpy as np

__all__ = [
    "already_initialized",
    "communicate_all",
    "free_local_port",
    "hermetic_child_env",
    "initialize_from_cluster_name",
    "host_row_slab",
    "global_rows_from_local",
    "process_count",
    "process_index",
]


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def already_initialized() -> bool:
    """True when ``jax.distributed.initialize`` has already run in-process.

    JAX exposes no public predicate; the stable observable is the client
    handle on the global distributed state (None until initialize, reset by
    shutdown). Falls back to False if the private module moves — the worst
    case is the original double-init error, never a wrong no-op.
    """
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def _backend_already_touched() -> bool:
    """True when some XLA backend initialized before distributed wiring.

    ``jax.distributed.initialize`` only takes effect when it runs BEFORE the
    first backend touch; afterwards it is a silent no-op and every process
    believes it is the single controller (they then race on outputs). A
    sitecustomize that imports jax AND asks for devices at interpreter start
    is the observed trigger. Best-effort probe of the bridge's backend cache;
    False on private-API drift (the explicit path still has the
    ``process_count`` post-check as a backstop).
    """
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False


def initialize_from_cluster_name(cluster_name: str) -> bool:
    """Wire this process into a multi-controller run per ``clusterName=``.

    - ``"local"`` (the reference default, ``main/Main.java:71``): no-op.
    - ``"auto"``: ``jax.distributed.initialize()`` with environment
      autodetection (TPU pods publish coordinator/process info in the
      runtime environment).
    - ``"<coordinator_host:port>,<process_id>,<num_processes>"``: explicit
      wiring for CPU/GPU clusters or manual pod bring-up.

    Returns True if distributed init ran (or had already run — the call is
    idempotent: an already-initialized runtime is detected and left as-is
    rather than tripping JAX's double-initialize error, ADVICE r2; a prior
    init whose process count contradicts the requested wiring raises).
    Raises RuntimeError when a JAX backend initialized before this call
    (which would make ``initialize`` a silent no-op) or when the resulting
    process count does not match the requested one.
    """
    if cluster_name in ("", "local"):
        return False
    nproc = None
    if cluster_name != "auto":
        try:
            coordinator, pid, nproc = cluster_name.rsplit(",", 2)
            pid, nproc = int(pid), int(nproc)
        except ValueError as e:
            raise ValueError(
                f"clusterName must be 'local', 'auto', or "
                f"'<host:port>,<process_id>,<num_processes>'; got {cluster_name!r}"
            ) from e
    if already_initialized():
        if nproc is not None and jax.process_count() != nproc:
            raise RuntimeError(
                f"jax.distributed was already initialized with "
                f"{jax.process_count()} processes, but clusterName="
                f"{cluster_name!r} requests {nproc} — conflicting wiring"
            )
        return True
    if _backend_already_touched():
        raise RuntimeError(
            "a JAX backend was initialized before distributed wiring "
            "(e.g. by a sitecustomize that imports jax and touches devices "
            "at interpreter start); jax.distributed.initialize would be a "
            "silent no-op. Initialize distributed before any jax device use."
        )
    if cluster_name == "auto":
        jax.distributed.initialize()
        if not already_initialized():
            # The auto path has no requested process count to post-check
            # against, so the only backstop is the client probe itself:
            # immediately after a successful initialize it MUST see the
            # client. If it doesn't, either initialize silently no-opped
            # (backend touched first) or the private-API probe drifted on a
            # JAX upgrade — both deserve a loud stop, not a single-process
            # run racing its peers (ADVICE r3; the probe symbols are pinned
            # by tests/unit/test_distributed.py against the vendored JAX).
            raise RuntimeError(
                "jax.distributed.initialize() returned but the distributed "
                "client is not observable: either a JAX backend initialized "
                "before distributed wiring (silent no-op) or the "
                "already_initialized() probe no longer matches this JAX "
                "version. Refusing to continue as an unwired process."
            )
        return True
    # Init's own errors (bad ranks, unreachable coordinator) surface as
    # themselves, not as a format complaint.
    jax.distributed.initialize(
        coordinator_address=coordinator, process_id=pid, num_processes=nproc
    )
    if jax.process_count() != nproc:
        # Backstop for the silent-no-op case the probe above missed.
        raise RuntimeError(
            f"jax.distributed.initialize ran but process_count() == "
            f"{jax.process_count()} != {nproc}: a JAX backend was "
            "initialized before distributed wiring. Initialize distributed "
            "before any jax device use."
        )
    return True


def hermetic_child_env(
    n_local_devices: int, repo_root: str | None = None
) -> dict:
    """Environment for spawning a hermetic CPU-JAX child process.

    Used by every harness that launches real OS processes for
    multi-controller runs (2-process tests, ``dryrun_multichip``): forces the
    CPU platform with ``n_local_devices`` virtual devices and strips
    ``PYTHONPATH`` entries that carry a ``sitecustomize.py`` — those hooks
    import jax and touch a backend at interpreter start, which would turn
    the child's ``jax.distributed.initialize`` into a silent no-op (see
    :func:`_backend_already_touched`). One copy of these rules; the callers
    must not re-implement them.
    """
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_local_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    keep = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not os.path.isfile(os.path.join(p, "sitecustomize.py"))
    ]
    paths = ([repo_root] if repo_root else []) + keep
    env["PYTHONPATH"] = os.pathsep.join(paths)
    return env


def free_local_port(attempts: int = 5, backoff_s: float = 0.05) -> int:
    """An OS-assigned free TCP port for a local coordinator.

    Retries with exponential backoff: under parallel test runs the kernel's
    ephemeral range can be transiently exhausted (EADDRINUSE/EAGAIN on a
    port-0 bind), and one losing bind should not fail a whole multi-rank
    test. TOCTOU caveat stands regardless: the port is released before the
    coordinator binds it — callers pair this with
    :func:`communicate_all`'s kill-the-set timeout handling so a lost race
    cannot leak ranks blocked on a dead port.
    """
    import socket
    import time

    last_err = None
    for attempt in range(attempts):
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]
        except OSError as e:  # pragma: no cover - needs ephemeral exhaustion
            last_err = e
            time.sleep(backoff_s * (2**attempt))
    raise OSError(
        f"free_local_port: no ephemeral port after {attempts} attempts"
    ) from last_err  # pragma: no cover


def communicate_all(procs, timeout: int = 300):
    """``communicate()`` every subprocess; kill the whole set on any timeout.

    A hung rank (e.g. coordinator-port race) must not leak its peers blocked
    at a distributed barrier holding the port. Returns [(stdout, stderr)]
    in order. On timeout the whole set is killed and a ``TimeoutError``
    names the dead ranks (the indices still running when the deadline hit)
    — "rank 2 of 4 hung" debugs a coordinator race; a bare TimeoutExpired
    does not. The original ``subprocess.TimeoutExpired`` rides as
    ``__cause__``.
    """
    import subprocess

    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout))
    except subprocess.TimeoutExpired as e:
        dead = [i for i, p in enumerate(procs) if p.poll() is None]
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.communicate()
        raise TimeoutError(
            f"communicate_all: rank(s) {dead} of {len(procs)} still running "
            f"after {timeout}s — killed the whole set (coordinator-port race "
            "or a rank lost mid-collective leaves peers blocked at a "
            "barrier)"
        ) from e
    return outs


def host_row_slab(n_rows: int, index: int | None = None, count: int | None = None):
    """This host's contiguous row range [start, stop) of an n-row dataset.

    Slabs are balanced to within one row (first ``n % count`` hosts get the
    extra), covering all rows exactly once across processes — each host
    loads only its slab (the HDFS-block analog; SURVEY.md §2.C P6).
    """
    index = process_index() if index is None else index
    count = process_count() if count is None else count
    base, extra = divmod(n_rows, count)
    start = index * base + min(index, extra)
    stop = start + base + (1 if index < extra else 0)
    return start, stop


def global_rows_from_local(
    local_rows: np.ndarray, mesh, n_global: int
) -> jax.Array:
    """Assemble per-host row slabs into one row-sharded global device array.

    ``mesh`` must span all processes' devices with its (single) axis over
    rows; ``n_global`` is the full dataset length (the slabs' sum). With one
    process this degenerates to a sharded ``device_put`` of the whole set.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))
    global_shape = (n_global, *local_rows.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_rows), global_shape
    )
