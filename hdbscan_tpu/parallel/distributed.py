"""Multi-host (multi-controller) runtime — the DCN half of the L0 story.

The reference's Spark cluster is inherently multi-host: the driver talks to
executors over the network and every stage boundary round-trips HDFS
(``main/Main.java:89-95``; SURVEY.md §2.C "communication backend"). The
TPU-native equivalent is JAX's multi-controller model: one Python process
per host, ``jax.distributed.initialize`` wiring them into a single logical
device set, a ``Mesh`` spanning every chip, ICI collectives within a slice
and DCN between hosts — all emitted by XLA from sharding annotations, never
hand-written sends.

This module carries the three pieces a multi-host run needs on top of the
single-host code (which is multi-controller-clean already: everything device
side is mesh-sharded, everything host-side orchestrates through numpy):

- :func:`initialize_from_cluster_name` — process wiring, mapped onto the
  reference's ``clusterName=`` flag (``local`` = single process, the
  reference's ``local`` Spark master; ``auto`` = TPU-pod env autodetection;
  explicit ``coordinator:port,process_id,num_processes`` otherwise).
- :func:`host_row_slab` — per-host dataset ingest: each host loads only its
  contiguous row slab (the analog of HDFS blocks feeding Spark partitions).
- :func:`global_rows_from_local` — assembly of per-host slabs into one
  globally-sharded device array over a mesh, via
  ``jax.make_array_from_process_local_data`` (DCN touches data only when a
  later resharding demands it).

Single-process behavior is the identity (slab = whole set, assembly = plain
``device_put``), which is what the tests pin; real multi-host runs need a
TPU pod (ROADMAP "Misc" tracks that this is scaffolded, not yet demonstrated
on hardware we don't have).
"""

from __future__ import annotations

import jax
import numpy as np

__all__ = [
    "already_initialized",
    "initialize_from_cluster_name",
    "host_row_slab",
    "global_rows_from_local",
    "process_count",
    "process_index",
]


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def already_initialized() -> bool:
    """True when ``jax.distributed.initialize`` has already run in-process.

    JAX exposes no public predicate; the stable observable is the client
    handle on the global distributed state (None until initialize, reset by
    shutdown). Falls back to False if the private module moves — the worst
    case is the original double-init error, never a wrong no-op.
    """
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def initialize_from_cluster_name(cluster_name: str) -> bool:
    """Wire this process into a multi-controller run per ``clusterName=``.

    - ``"local"`` (the reference default, ``main/Main.java:71``): no-op.
    - ``"auto"``: ``jax.distributed.initialize()`` with environment
      autodetection (TPU pods publish coordinator/process info in the
      runtime environment).
    - ``"<coordinator_host:port>,<process_id>,<num_processes>"``: explicit
      wiring for CPU/GPU clusters or manual pod bring-up.

    Returns True if distributed init ran (or had already run — the call is
    idempotent: an already-initialized runtime is detected and left as-is
    rather than tripping JAX's double-initialize error, ADVICE r2).
    """
    if cluster_name in ("", "local"):
        return False
    if already_initialized():
        return True
    if cluster_name == "auto":
        jax.distributed.initialize()
        return True
    try:
        coordinator, pid, nproc = cluster_name.rsplit(",", 2)
        pid, nproc = int(pid), int(nproc)
    except ValueError as e:
        raise ValueError(
            f"clusterName must be 'local', 'auto', or "
            f"'<host:port>,<process_id>,<num_processes>'; got {cluster_name!r}"
        ) from e
    # Outside the except: init's own errors (bad ranks, unreachable
    # coordinator) must surface as themselves, not as a format complaint.
    jax.distributed.initialize(
        coordinator_address=coordinator, process_id=pid, num_processes=nproc
    )
    return True


def host_row_slab(n_rows: int, index: int | None = None, count: int | None = None):
    """This host's contiguous row range [start, stop) of an n-row dataset.

    Slabs are balanced to within one row (first ``n % count`` hosts get the
    extra), covering all rows exactly once across processes — each host
    loads only its slab (the HDFS-block analog; SURVEY.md §2.C P6).
    """
    index = process_index() if index is None else index
    count = process_count() if count is None else count
    base, extra = divmod(n_rows, count)
    start = index * base + min(index, extra)
    stop = start + base + (1 if index < extra else 0)
    return start, stop


def global_rows_from_local(
    local_rows: np.ndarray, mesh, n_global: int
) -> jax.Array:
    """Assemble per-host row slabs into one row-sharded global device array.

    ``mesh`` must span all processes' devices with its (single) axis over
    rows; ``n_global`` is the full dataset length (the slabs' sum). With one
    process this degenerates to a sharded ``device_put`` of the whole set.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))
    global_shape = (n_global, *local_rows.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_rows), global_shape
    )
