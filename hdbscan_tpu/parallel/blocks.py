"""Batched per-partition kernels over padded blocks (L5 distributed ops).

The reference's per-partition operators (``mappers/FirstStep.java:44-120``)
run one subset per Spark task. The TPU-native form: stack many subsets into a
(B, capacity, d) padded block tensor, ``vmap`` the fused exact-HDBSCAN* device
program over the batch axis, and shard that axis over the device mesh — B
subset-MSTs per launch instead of B JVM tasks (SURVEY.md §2.C row P1).

Also here: the nearest-sample assignment kernel (``FirstStep.java:74-102``'s
O(n·|S|·d) loop as tiled matmul argmin) and host-side block packing (the
``HashPartitioner`` re-binning analog, row P6).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from hdbscan_tpu.core.distances import pairwise_distance, self_distance_matrix
from hdbscan_tpu.core.knn import core_distances_from_matrix, mutual_reachability
from hdbscan_tpu.core.mst import boruvka_mst


@partial(jax.jit, static_argnames=("min_pts", "metric"))
def block_mst_batch(x: jax.Array, num_valid: jax.Array, min_pts: int, metric: str):
    """Fused exact pipeline per padded block, vmapped over the batch axis.

    Args:
      x: (B, cap, d) point blocks, rows >= num_valid[b] are padding.
      num_valid: (B,) int32 valid-point counts.

    Returns:
      (u, v, w, mask, core): per-block MST edge arrays (B, cap-1) in local
      indices, validity mask, and (B, cap) core distances (+inf on padding).
    """

    def one(xb, nv):
        cap = xb.shape[0]
        valid = jnp.arange(cap, dtype=jnp.int32) < nv
        dist = self_distance_matrix(xb, metric)
        core = core_distances_from_matrix(dist, min_pts, valid)
        mrd = mutual_reachability(dist, core)
        u, v, w, mask, _ = boruvka_mst(mrd, nv)
        return u, v, w, mask, core

    return jax.vmap(one)(x, num_valid)


@partial(jax.jit, static_argnames=("min_pts", "metric"))
def block_mst_batch_packed(x: jax.Array, num_valid: jax.Array, min_pts: int, metric: str):
    """:func:`block_mst_batch` with outputs packed into ONE (B, 5*cap-4) array.

    The tunnel between host and TPU pays a full round trip per fetched array
    leaf, so the five result arrays are concatenated on device (in the weight
    dtype; int32 ids are exact in f32 up to 2^24 >> any block capacity) and
    split again on host — see :func:`unpack_block_mst`.
    """
    u, v, w, mask, core = block_mst_batch(x, num_valid, min_pts, metric)
    dt = w.dtype
    return jnp.concatenate(
        [u.astype(dt), v.astype(dt), w, mask.astype(dt), core], axis=1
    )


def unpack_block_mst(packed: np.ndarray, cap: int):
    """Host-side split of :func:`block_mst_batch_packed` output."""
    u, v, w, mask = unpack_block_mst_edges(packed, cap)
    core = packed[:, 4 * (cap - 1) :].astype(np.float64)
    return u, v, w, mask, core


def unpack_block_mst_edges(packed: np.ndarray, cap: int):
    """Host-side split of the [u, v, w, mask] packed edge columns."""
    e = cap - 1
    u = packed[:, :e].astype(np.int64)
    v = packed[:, e : 2 * e].astype(np.int64)
    w = packed[:, 2 * e : 3 * e].astype(np.float64)
    mask = packed[:, 3 * e : 4 * e] != 0
    return u, v, w, mask


@partial(jax.jit, static_argnames=("metric",))
def block_mst_batch_with_core(
    x: jax.Array, core: jax.Array, num_valid: jax.Array, metric: str
):
    """Per-block Borůvka MST under PRE-COMPUTED (global) core distances.

    The random-blocks merge path (``partition/reducers/UnionFindReducer.java``
    capability; ``mappers/CoreDistanceMapper.java`` broadcasts the whole
    dataset for exactly this reason): blocks see only their own points, but
    mutual reachability uses core distances computed over the whole dataset
    (one tiled pass, ``ops.tiled.knn_core_distances``), so pooled block edges
    are globally meaningful — per-block local core distances inflate at block
    boundaries, which distorts the merged hierarchy and makes quality depend
    on where the partitioner happened to cut.
    Returns (u, v, w, mask) per block in local indices.
    """

    def one(xb, cb, nv):
        cap = xb.shape[0]
        valid = jnp.arange(cap, dtype=jnp.int32) < nv
        dist = self_distance_matrix(xb, metric)
        dist = jnp.where(valid[None, :] & valid[:, None], dist, jnp.inf)
        mrd = mutual_reachability(dist, cb)
        u, v, w, mask, _ = boruvka_mst(mrd, nv)
        return u, v, w, mask

    return jax.vmap(one)(x, core, num_valid)


@partial(jax.jit, static_argnames=("metric",))
def block_mst_batch_with_core_packed(
    x: jax.Array, core: jax.Array, num_valid: jax.Array, metric: str
):
    """:func:`block_mst_batch_with_core`, outputs packed into ONE (B, 4*(cap-1))
    array ([u, v, w, mask] in w's dtype) — single-leaf fetch over the tunnel."""
    u, v, w, mask = block_mst_batch_with_core(x, core, num_valid, metric)
    dt = w.dtype
    return jnp.concatenate([u.astype(dt), v.astype(dt), w, mask.astype(dt)], axis=1)


@partial(jax.jit, static_argnames=("metric",))
def nearest_sample_tile(points: jax.Array, samples: jax.Array, sample_valid: jax.Array, metric: str):
    """Per-point nearest sample over one tile: returns (argmin idx, min dist).

    The device form of the reference's per-point scan over the collected
    sample list (``FirstStep.java:77-85``) — one (T, S) distance matrix per
    tile, masked argmin over padded sample slots.
    """
    d = pairwise_distance(points, samples, metric)
    d = jnp.where(sample_valid[None, :], d, jnp.inf)
    idx = jnp.argmin(d, axis=1).astype(jnp.int32)
    return idx, jnp.take_along_axis(d, idx[:, None], axis=1)[:, 0]


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


@partial(jax.jit, static_argnames=("metric", "tile"))
def _nearest_sample_scan(points, samples, sample_valid, metric: str, tile: int):
    """Whole-dataset nearest-sample argmin as ONE device program.

    Tiles the point axis with ``lax.map`` so the (tile, s_pad) distance matrix
    stays VMEM-sized; a single dispatch + single fetch instead of one
    host round trip per tile (the tunnel round trip dominates at ~100ms).
    """
    n_pad, d = points.shape
    tiles = points.reshape(n_pad // tile, tile, d)

    def one(pts):
        dd = pairwise_distance(pts, samples, metric)
        dd = jnp.where(sample_valid[None, :], dd, jnp.inf)
        return jnp.argmin(dd, axis=1).astype(jnp.int32)

    return jax.lax.map(one, tiles).reshape(n_pad)


def nearest_sample_assign(
    points: np.ndarray,
    samples: np.ndarray,
    metric: str = "euclidean",
    tile: int = 8192,
) -> np.ndarray:
    """Nearest-sample assignment, one device call (padding-stable compiles).

    Sample count and point count are padded to powers of two so
    level-to-level calls of similar size reuse the compiled kernel.
    """
    n = len(points)
    s = len(samples)
    s_pad = _next_pow2(max(s, 1))
    samples_p = np.zeros((s_pad, samples.shape[1]), samples.dtype)
    samples_p[:s] = samples
    # Both tile and n_pad are powers of two, so tile | n_pad always holds.
    tile = min(_next_pow2(tile), _next_pow2(max(n, 8)))
    n_pad = _next_pow2(max(n, tile))
    points_p = np.zeros((n_pad, points.shape[1]), points.dtype)
    points_p[:n] = points
    pts_j, smp_j, val_j = jax.device_put((points_p, samples_p, np.arange(s_pad) < s))
    idx = _nearest_sample_scan(pts_j, smp_j, val_j, metric, tile)
    return np.asarray(idx, np.int32)[:n].copy()


@partial(jax.jit, static_argnames=("metric", "tile"))
def _seam_margin_scan(points, samples, groups, sample_valid, metric: str, tile: int):
    """Per-point distance margin to the nearest OTHER-group sample.

    For each point: d1 = distance to its nearest sample (group g1), d2 =
    distance to the nearest sample whose group differs from g1. The margin
    d2 - d1 approximates twice the point's distance to the partition seam —
    small margin = the point sits where two induced subsets meet. One device
    program, point axis tiled like :func:`_nearest_sample_scan`; outputs are
    packed into one (n_pad, 2) leaf (single tunnel fetch).
    """
    n_pad, d = points.shape
    tiles = points.reshape(n_pad // tile, tile, d)
    inf = jnp.array(jnp.inf, points.dtype)

    def one(pts):
        dd = pairwise_distance(pts, samples, metric)
        dd = jnp.where(sample_valid[None, :], dd, inf)
        i1 = jnp.argmin(dd, axis=1)
        d1 = jnp.take_along_axis(dd, i1[:, None], axis=1)[:, 0]
        g1 = groups[i1]
        other = groups[None, :] != g1[:, None]
        d2 = jnp.min(jnp.where(other, dd, inf), axis=1)
        return jnp.stack([d1, d2], axis=1)

    return jax.lax.map(one, tiles).reshape(n_pad, 2)


def seam_margins(
    points: np.ndarray,
    samples: np.ndarray,
    sample_groups: np.ndarray,
    metric: str = "euclidean",
    tile: int = 8192,
) -> np.ndarray:
    """(n,) seam margins d_other_group - d_own for the boundary-quality mode.

    ``sample_groups``: per-sample induced-subset id (the model's flat groups).
    A point whose margin is small lies near the seam between its subset and a
    neighboring one — exactly where per-block core distances inflate and
    where the true inter-subset MST edges live (``config.boundary_quality``).
    Points of a subset with no other group anywhere get +inf margins.
    """
    n = len(points)
    s = len(samples)
    s_pad = _next_pow2(max(s, 1))
    # float32 throughout: margins are a selection heuristic, and f64 compute
    # is emulated (slow) on TPU while doubling the tunnel transfer.
    samples_p = np.zeros((s_pad, samples.shape[1]), np.float32)
    samples_p[:s] = samples
    groups_p = np.full(s_pad, -1, np.int32)
    groups_p[:s] = sample_groups
    # Shrink the point tile when the sample axis is wide so the per-step
    # (tile, s_pad) distance matrix stays HBM-friendly.
    tile = min(_next_pow2(tile), max(128, _next_pow2((1 << 25) // s_pad)))
    tile = min(tile, _next_pow2(max(n, 8)))
    n_pad = _next_pow2(max(n, tile))
    points_p = np.zeros((n_pad, points.shape[1]), np.float32)
    points_p[:n] = points
    pts_j, smp_j, grp_j, val_j = jax.device_put(
        (points_p, samples_p, groups_p, np.arange(s_pad) < s)
    )
    out = np.asarray(
        _seam_margin_scan(pts_j, smp_j, grp_j, val_j, metric, tile), np.float64
    )[:n]
    return out[:, 1] - out[:, 0]


@dataclass
class PackedBlocks:
    """Subsets packed into a padded (B, cap, d) tensor plus index maps."""

    x: np.ndarray  # (B, cap, d)
    num_valid: np.ndarray  # (B,) int32
    point_index: np.ndarray  # (B, cap) global point id per slot (-1 padding)
    subset_ids: np.ndarray  # (B,) the subset each block came from
    core: np.ndarray | None = None  # (B, cap) precomputed global core distances


def pack_blocks(
    data: np.ndarray,
    point_ids_per_subset: list[np.ndarray],
    capacity: int,
    core: np.ndarray | None = None,
) -> PackedBlocks:
    """Pack per-subset point-id lists into padded device blocks.

    Every subset must fit ``capacity`` (the driver routes only small subsets
    here — ``processing_units`` semantics, ``mappers/FirstStep.java:68``).
    ``core``: optional per-point (global) core distances to pack alongside.
    """
    b = len(point_ids_per_subset)
    d = data.shape[1]
    x = np.zeros((b, capacity, d), data.dtype)
    num_valid = np.zeros(b, np.int32)
    point_index = np.full((b, capacity), -1, np.int64)
    core_b = None
    if core is not None:
        core_b = np.full((b, capacity), np.inf, np.float64)
    for i, ids in enumerate(point_ids_per_subset):
        k = len(ids)
        if k > capacity:
            raise ValueError(f"subset {i} has {k} points > capacity {capacity}")
        x[i, :k] = data[ids]
        num_valid[i] = k
        point_index[i, :k] = ids
        if core_b is not None:
            core_b[i, :k] = core[ids]
    return PackedBlocks(
        x=x,
        num_valid=num_valid,
        point_index=point_index,
        subset_ids=np.arange(b),
        core=core_b,
    )


#: Rough per-block working-set multiplier for the fused MST kernel: the
#: Borůvka loop holds the weight matrix plus the per-round component mask and
#: XLA temporaries — ~8 copies of the (cap, cap) matrix in practice.
_BLOCK_TEMPS = 8


def run_packed_blocks(
    packed: PackedBlocks,
    min_pts: int,
    metric: str = "euclidean",
    mesh=None,
    batch_pad: int = 1,
    hbm_budget_bytes: int = 2 << 30,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Execute the batched MST kernel; returns global-id edges + core distances.

    ``mesh``: optional device mesh — block batch axis is sharded across it
    (each device computes its shard of blocks; results gather to host).
    ``batch_pad``: round each launch's batch up to a multiple (mesh size)
    with empty blocks so the shard axis divides evenly.
    ``hbm_budget_bytes``: cap on the per-launch working set; large batches
    split into fixed-size launches (all identical shape -> one compile).

    Returns:
      (u, v, w) concatenated global-id MST edges over all blocks and a
      (B, cap) core-distance array aligned with ``packed.point_index``.
    """
    b = len(packed.x)
    cap = packed.x.shape[1]
    itemsize = 8 if jax.config.jax_enable_x64 else 4
    from hdbscan_tpu.parallel.mesh import pad_batch

    per_block = cap * cap * itemsize * _BLOCK_TEMPS
    # All chunk sizes are powers of two: launches for 2, 3, or 4 blocks of one
    # capacity share a single compiled shape instead of compiling per count.
    chunk = max(1, hbm_budget_bytes // per_block)
    chunk = 1 << (chunk.bit_length() - 1)  # pow2 floor of the budget chunk
    chunk = min(max(batch_pad, chunk), _next_pow2(pad_batch(b, batch_pad)))
    chunk = pad_batch(chunk, batch_pad)  # keep the mesh axis dividing evenly

    sh = None
    if mesh is not None:
        from hdbscan_tpu.parallel.mesh import block_sharding

        sh = block_sharding(mesh)

    core = np.empty((b, cap), np.float64)
    gu, gv, gw = [], [], []

    # Analytic accounting (utils/flops.py): the fused block program's
    # dominant arithmetic is one (cap, cap, d) distance matrix per block
    # (the in-matrix Borůvka rounds re-read, not recompute).
    from hdbscan_tpu.utils.flops import counter as _flops

    _flops.add(
        2.0 * b * cap * cap * packed.x.shape[2],
        float(b * cap * cap * itemsize),
    )

    with_core = packed.core is not None
    if with_core:
        core[:] = packed.core

    def drain_one(start, real, out):
        # One batched fetch of one packed leaf per launch (each fetched leaf
        # pays a full host<->device round trip over the tunnel). fetch()
        # allgathers across controllers when the mesh spans processes.
        from hdbscan_tpu.parallel.mesh import fetch

        pk = fetch(out)
        if with_core:
            u, v, w, mask = unpack_block_mst_edges(pk, cap)
        else:
            u, v, w, mask, core_c = unpack_block_mst(pk, cap)
            core[start : start + real] = core_c[:real]
        for i in range(real):
            m = mask[i]
            ids = packed.point_index[start + i]
            gu.append(ids[u[i][m]])
            gv.append(ids[v[i][m]])
            gw.append(w[i][m])

    # Dispatch launches (JAX async) ahead of fetching so the device pipelines
    # while the host feeds — draining the OLDEST launch as soon as the window
    # fills (rolling window, not drain-all): one launch computes while one
    # drains, which is all the overlap the pipeline can use, and resident
    # inputs+outputs stay within ~2x the per-launch HBM budget.
    max_inflight = 2
    pending = []
    for start in range(0, b, chunk):
        x = packed.x[start : start + chunk]
        nv = packed.num_valid[start : start + chunk]
        real = len(x)
        if real != chunk:  # pad every launch to the same shape: one compile
            x = np.concatenate([x, np.zeros((chunk - real, *x.shape[1:]), x.dtype)])
            nv = np.concatenate([nv, np.zeros(chunk - real, nv.dtype)])
        if with_core:
            cb = packed.core[start : start + chunk]
            if len(cb) != chunk:
                cb = np.concatenate([cb, np.full((chunk - len(cb), cap), np.inf)])
            if sh is not None:
                xj, cj, nvj = jax.device_put(
                    (x, cb.astype(x.dtype), nv), (sh, sh, sh)
                )
            else:
                xj, cj, nvj = jax.device_put((x, cb.astype(x.dtype), nv))
            out = block_mst_batch_with_core_packed(xj, cj, nvj, metric)
        else:
            if sh is not None:
                xj, nvj = jax.device_put((x, nv), (sh, sh))
            else:
                xj, nvj = jax.device_put((x, nv))
            out = block_mst_batch_packed(xj, nvj, min_pts, metric)
        pending.append((start, real, out))
        if len(pending) >= max_inflight:
            drain_one(*pending.pop(0))
    for p in pending:
        drain_one(*p)
    return (
        np.concatenate(gu) if gu else np.zeros(0, np.int64),
        np.concatenate(gv) if gv else np.zeros(0, np.int64),
        np.concatenate(gw) if gw else np.zeros(0, np.float64),
        core,
    )
