"""hdbscan-tpu: TPU-native MR-HDBSCAN* (JAX / XLA / pjit / shard_map).

A brand-new framework with the capabilities of the reference Spark/Java
MR-HDBSCAN* reproduction (see SURVEY.md): exact single-block HDBSCAN*, the
distributed recursive-sampling + data-bubble approximation, pluggable distance
metrics, constraints, GLOSH outlier scores, and the canonical output files —
re-architected for TPU hardware.
"""

__version__ = "0.1.0"

import os as _os


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache (set HDBSCAN_TPU_CACHE_DIR to move it,
    or to "" to disable). First TPU compiles are tens of seconds over remote
    compile; the cache makes every later process start warm."""
    cache = _os.environ.get("HDBSCAN_TPU_CACHE_DIR")
    if cache == "":
        return
    if cache is None and _os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # the user configured JAX's cache themselves; don't override
    if cache is None:
        # Repo checkout: keep the cache next to the package so every process
        # (tests, bench, driver) shares it. Unwritable parent (installed
        # package): fall back to the user cache dir.
        cache = _os.path.join(_os.path.dirname(_os.path.dirname(__file__)), ".jax_cache")
        if not _os.access(_os.path.dirname(cache), _os.W_OK):
            cache = _os.path.join(
                _os.path.expanduser("~"), ".cache", "hdbscan_tpu", "jax_cache"
            )
    try:
        import jax

        if jax.config.jax_compilation_cache_dir is not None:
            return  # already configured in-process; preserve user intent
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - cache is an optimization only
        pass


_enable_compile_cache()

from hdbscan_tpu.config import HDBSCANParams  # noqa: F401
