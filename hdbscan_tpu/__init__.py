"""hdbscan-tpu: TPU-native MR-HDBSCAN* (JAX / XLA / pjit / shard_map).

A brand-new framework with the capabilities of the reference Spark/Java
MR-HDBSCAN* reproduction (see SURVEY.md): exact single-block HDBSCAN*, the
distributed recursive-sampling + data-bubble approximation, pluggable distance
metrics, constraints, GLOSH outlier scores, and the canonical output files —
re-architected for TPU hardware.
"""

__version__ = "0.1.0"

from hdbscan_tpu.config import HDBSCANParams  # noqa: F401
