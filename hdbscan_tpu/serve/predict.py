"""Jitted batched ``approximate_predict`` against a fitted ClusterModel.

Semantics (README "Serving"): classification of an unseen point q is
approximate and nearest-exemplar, the hdbscan ``approximate_predict``
formulation rendered in this repo's eps-level representation:

1. **k-NN**: q's k nearest training points (k = minPts - 1) via the same
   tiled exact scan the fit used (``ops/tiled._knn_core_scan``, or the fused
   Pallas kernel under ``predict_backend=fused``; under
   ``predict_backend=rpforest`` the artifact's stored rp-forest routes q to
   T leaves and only their members are scanned — sub-quadratic, approximate.
   On a real TPU that candidate scan runs the fused forest rescan program
   (``ops/pallas_forest.forest_rescan_topk``) so the (B, T·Lmax) candidate
   distance matrix stays in VMEM; bitwise-equal to the XLA line at f32).
2. **Core distance**: ``core_q`` = the (minPts - 1)-th smallest training
   distance — identical to the fit's self-included semantics for training
   rows (their own row sits in the train set at distance 0).
3. **Attachment level**: ``eps_q = min_i max(d_i, core_q, core_i)`` over the
   k-NN list — the mutual-reachability level at which q would join the
   hierarchy; the argmin neighbor is q's exemplar.
4. **Cluster**: starting from the exemplar's deepest cluster, climb to the
   deepest ancestor whose birth level covers ``eps_q`` (cluster births
   strictly increase toward the root, so the climb is a monotone predicate —
   binary lifting over a precomputed ancestor table, O(log C) per query,
   fully jitted). A query that is an exact duplicate of a training row skips
   the climb and attaches at that row's fitted cluster, which makes
   ``approximate_predict`` on the training set reproduce the fit labels
   bitwise (the artifact round-trip guarantee the tier-1 tests pin).
5. **Label** = the attachment cluster's nearest selected ancestor
   (``core/tree_vec.selected_ancestors`` jump table; 0 = noise).
   **Probability** = ``min(1, eps_min[label] / eps_q)`` (per-cluster max
   lambda). **Outlier score** = GLOSH with ``eps_q`` as the exit level,
   clipped at 0.

Batching: queries pad into power-of-two buckets (floor 8 — smaller requests
share the 8-row compile), so steady-state serving triggers zero recompiles
once :meth:`Predictor.warmup` has run every bucket (verified via
``utils/telemetry.compile_counter`` in the tier-1 tests). The query buffer
is donated to the device program, and multi-chunk batches double-buffer the
host-to-device staging against compute (the ``ops/blockscan`` prestage
pattern).
"""

from __future__ import annotations

import itertools
import threading
import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from hdbscan_tpu.fault import inject
from hdbscan_tpu.ops.tiled import (
    _knn_core_scan,
    _next_pow2,
    _pad_rows,
    _tile_sizes,
)

#: Smallest device bucket: requests of 1..8 rows share one compiled shape
#: (the scan's minimum row tile is 8 sublanes anyway, so a 1-row program
#: would compute 8 rows regardless).
_MIN_BUCKET = 8

#: Process-unique predictor ids for predict_batch trace attribution.
_PRED_IDS = itertools.count(1)

#: Largest query row tile; buckets above it loop row tiles inside the scan.
_MAX_ROW_TILE = 128


def _resolve_backend(backend: str, model, dtype) -> tuple[str, bool]:
    """('xla'|'fused'|'rpforest', interpret) with ``knn_backend``-style
    fallback rules: 'fused' silently falls back to the XLA scan when the
    kernel cannot run (non-euclidean, d > 128, k > 128, non-f32, or off-TPU
    at large n, where only the slow interpreter exists). 'rpforest' is
    opt-in only — never picked by 'auto', because it answers from the
    artifact's stored index (approximate) instead of the exact train scan —
    and requires a ``/2`` artifact that carries one."""
    if backend not in ("auto", "xla", "fused", "rpforest"):
        raise ValueError(
            f"predict backend must be 'auto', 'xla', 'fused' or 'rpforest', "
            f"got {backend!r}"
        )
    if backend == "rpforest":
        if getattr(model, "rpf", None) is None:
            raise ValueError(
                "predict_backend='rpforest' needs a model artifact that "
                "carries an rp-forest index (hdbscan-tpu-model/2, fitted "
                "with knn_index=rpforest or saved with forest=...)"
            )
        return "rpforest", False
    on_tpu = jax.devices()[0].platform == "tpu"
    k = max(model.min_points - 1, 1)
    fusable = (
        model.metric == "euclidean"
        and k <= 128
        and model.data.shape[1] <= 128
        and dtype is np.float32
        and (on_tpu or model.n_train <= (1 << 14))
    )
    if backend == "fused" and fusable:
        return "fused", not on_tpu
    if backend == "auto" and fusable and on_tpu:
        return "fused", False
    return "xla", False


def _climb(anc, birth, cluster, eps):
    """Deepest ancestor-or-self of ``cluster`` whose birth >= ``eps``.

    Births strictly increase toward the root (ties contract into one
    multi-way merge node, so a child is always born strictly below its
    parent) and the root's birth is +inf, so the predicate is monotone along
    every ancestor chain and the chain always ends in a pass. Binary lifting
    finds the last failing node greedily; its parent is the answer.
    """
    cur = cluster.astype(jnp.int32)
    for level in range(anc.shape[0] - 1, -1, -1):
        cand = anc[level][cur]
        cur = jnp.where(birth[cand] < eps, cand, cur)
    return jnp.where(birth[cur] >= eps, cur, anc[0][cur]).astype(jnp.int32)


def _attach(
    knn_d, knn_i, xq, train, core_t, labels_t, last_t, anc, birth,
    sel_anc, eps_min, eps_max, sel_ids, kth_col: int, with_membership: bool,
):
    """Shared post-k-NN logic: attachment level, climb, labels, prob, GLOSH
    (and optionally the per-selected-cluster membership matrix)."""
    if kth_col < 0:  # minPts <= 1: every core distance is zero (fit parity)
        core_q = jnp.zeros(knn_d.shape[0], knn_d.dtype)
    else:
        core_q = knn_d[:, kth_col]
    mrd = jnp.maximum(jnp.maximum(knn_d, core_q[:, None]), core_t[knn_i])
    j = jnp.argmin(mrd, axis=1)  # first minimum = lowest-distance exemplar
    eps_q = jnp.take_along_axis(mrd, j[:, None], axis=1)[:, 0]
    nbr = jnp.take_along_axis(knn_i, j[:, None], axis=1)[:, 0]
    # Exact-duplicate shortcut: a query identical to a training row attaches
    # at that row's fitted cluster with no climb — float-rounding in the
    # rebuilt distances can otherwise nudge eps_q past a birth level shared
    # with the point's exit and flip the label by one tree level.
    nbr0 = knn_i[:, 0]
    is_dup = jnp.all(xq == train[nbr0], axis=1) & (nbr0 >= 0)
    cluster = jnp.where(
        is_dup, last_t[nbr0], _climb(anc, birth, last_t[nbr], eps_q)
    )
    label = sel_anc[cluster]
    em = eps_min[label]
    prob = jnp.where(
        label > 0, jnp.where(eps_q <= em, 1.0, em / eps_q), 0.0
    )
    emax = eps_max[cluster]
    score = jnp.where(
        eps_q > 0, jnp.clip(1.0 - emax / eps_q, 0.0, 1.0), 0.0
    )
    if not with_membership:
        return label, prob, score
    # Soft clustering: per selected cluster, the minimum mutual-reachability
    # distance to a k-NN neighbor fitted to that cluster; inverse-normalized.
    labn = labels_t[knn_i]  # (B, k) fitted flat labels of the neighbors
    inf = jnp.array(jnp.inf, mrd.dtype)
    md = jnp.min(
        jnp.where(labn[:, :, None] == sel_ids[None, None, :], mrd[:, :, None], inf),
        axis=1,
    )  # (B, S)
    inv = jnp.where(md > 0, 1.0 / jnp.maximum(md, 1e-30), 1e30)
    tot = jnp.sum(jnp.where(jnp.isfinite(md), inv, 0.0), axis=1, keepdims=True)
    mvec = jnp.where(
        jnp.isfinite(md) & (tot > 0), inv / jnp.maximum(tot, 1e-30), 0.0
    )
    return label, prob, score, mvec


def _predict_kernel_xla(
    xq, train, valid, core_t, labels_t, last_t, anc, birth, sel_anc,
    eps_min, eps_max, sel_ids,
    k: int, kth_col: int, metric: str, row_tile: int, col_tile: int,
    with_membership: bool,
):
    knn_d, knn_i = _knn_core_scan(
        xq, train, valid, k, metric, row_tile, col_tile, with_indices=True
    )
    return _attach(
        knn_d, knn_i, xq, train, core_t, labels_t, last_t, anc, birth,
        sel_anc, eps_min, eps_max, sel_ids, kth_col, with_membership,
    )


def _predict_kernel_rpf(
    xq, normals, thresholds, members, train, core_t, labels_t, last_t, anc,
    birth, sel_anc, eps_min, eps_max, sel_ids,
    k: int, kth_col: int, metric: str, depth: int, sentinel: int,
    with_membership: bool, fused: bool = False, interpret: bool = False,
):
    """Sub-quadratic k-NN: route each query down the stored forest planes
    (``ops/rpforest.route_queries``, ``depth`` gather+dot steps per tree),
    scan only the T visited leaves' members (T * Lmax candidates instead of
    all n train rows), and keep everything downstream of the k-NN list —
    attachment, climb, labels — identical to the exact kernels. Candidate
    count is fixed by the stored forest geometry, so every bucket still
    compiles exactly once (the zero-steady-state-recompile property).

    ``fused`` routes the candidate scan through the fused forest rescan
    program (``ops/pallas_forest.forest_rescan_topk``): a predict query
    has no running k-best, so one tile reduction IS the dedup lex-merge —
    the (B, T·Lmax) candidate distance matrix never leaves VMEM. Bitwise
    equal to the XLA line at f32 (pinned by the tier-1 parity test); the
    CPU default stays the XLA scan.
    """
    from hdbscan_tpu.core.distances import pairwise_distance
    from hdbscan_tpu.ops.rpforest import _dedup_lex_merge, route_queries

    xqf = xq.astype(normals.dtype)
    # (T, B) leaf per tree; members[t, leaf] -> (T, B, Lmax) candidate ids.
    leaves = jax.vmap(
        lambda nrm, thr: route_queries(xqf, nrm, thr, depth)
    )(normals, thresholds)
    cand = jax.vmap(lambda mem, lv: mem[lv])(members, leaves)
    cand = jnp.moveaxis(cand, 0, 1).reshape(xq.shape[0], -1).astype(jnp.int32)
    if fused:
        from hdbscan_tpu.ops.pallas_forest import forest_rescan_topk

        knn_d, knn_i = forest_rescan_topk(
            xqf, train[cand], cand, k, metric, "f32", sentinel,
            interpret=interpret,
        )
        knn_d = knn_d.astype(train.dtype)
    else:
        dm = jax.vmap(
            lambda q, pts: pairwise_distance(q[None, :], pts, metric)[0]
        )(xqf, train[cand])
        knn_d, knn_i = _dedup_lex_merge(
            dm.astype(train.dtype), cand, k, sentinel
        )
    return _attach(
        knn_d, knn_i, xq, train, core_t, labels_t, last_t, anc, birth,
        sel_anc, eps_min, eps_max, sel_ids, kth_col, with_membership,
    )


def _predict_kernel_fused(
    xq, train_rows, train_t, colmask, core_t, labels_t, last_t, anc, birth,
    sel_anc, eps_min, eps_max, sel_ids,
    k: int, kth_col: int, with_membership: bool, interpret: bool,
):
    from hdbscan_tpu.ops.pallas_knn import knn_fused_pallas

    d_all, i_all = knn_fused_pallas(xq, train_t, colmask, k, interpret=interpret)
    return _attach(
        d_all[:, :k], i_all[:, :k], xq, train_rows, core_t, labels_t, last_t,
        anc, birth, sel_anc, eps_min, eps_max, sel_ids, kth_col,
        with_membership,
    )


@lru_cache(maxsize=None)
def _jitted_kernel(which: str):
    """Module-level jit wrappers (stable jit cache across Predictor
    instances). Query buffers are donated only where the backend supports
    donation — donating on CPU just warns and copies."""
    donate = (0,) if jax.default_backend() != "cpu" else ()
    if which == "xla":
        return jax.jit(
            _predict_kernel_xla,
            static_argnames=(
                "k", "kth_col", "metric", "row_tile", "col_tile",
                "with_membership",
            ),
            donate_argnums=donate,
        )
    if which == "rpforest":
        return jax.jit(
            _predict_kernel_rpf,
            static_argnames=(
                "k", "kth_col", "metric", "depth", "sentinel",
                "with_membership", "fused", "interpret",
            ),
            donate_argnums=donate,
        )
    return jax.jit(
        _predict_kernel_fused,
        static_argnames=("k", "kth_col", "with_membership", "interpret"),
        donate_argnums=donate,
    )


def _ancestor_table(parent: np.ndarray) -> np.ndarray:
    """Binary-lifting ancestor table over the cluster labels: ``anc[l][c]``
    is c's 2^l-th ancestor, saturating at the root (and at the unused label
    0), as one (L, C+1) int32 array."""
    c1 = len(parent)
    anc0 = np.where(parent > 0, parent, np.arange(c1)).astype(np.int32)
    levels = max(1, int(np.ceil(np.log2(max(c1, 2)))))
    anc = [anc0]
    for _ in range(levels - 1):
        anc.append(anc[-1][anc[-1]])
    return np.stack(anc)


class Predictor:
    """Device-resident serving state for one :class:`ClusterModel`.

    The training points, core distances and every tree table are placed on
    device once at construction (the cuSLINK stance: keep the hierarchy
    resident on-accelerator between queries); each :meth:`predict` call
    ships only the padded query bucket.

    Args:
      model: a loaded ``serve/artifact.ClusterModel``.
      backend: 'auto' | 'xla' | 'fused' (``HDBSCANParams.predict_backend``).
      max_batch: bucket ceiling; requests above it chunk. Rounded up to a
        power of two, floor ``_MIN_BUCKET``.
      dtype: device scan dtype (f32 default, matching the fit scans).
      tracer: optional ``utils/tracing.Tracer`` — every dispatched bucket
        emits a ``predict_batch`` event (bucket, rows, batch_seq, wall_s).
      metrics: optional ``utils/metrics.MetricsRegistry`` — every dispatched
        bucket observes the batch-size and device-wall histograms served by
        ``GET /metrics`` (warmup dispatches are excluded: they go through
        ``_dispatch`` directly, not this path).
    """

    def __init__(
        self, model, backend: str = "auto", max_batch: int = 256,
        dtype=np.float32, tracer=None, metrics=None,
    ):
        self.model = model
        self.tracer = tracer
        self._m_batch_rows = self._m_device_s = None
        if metrics is not None:
            from hdbscan_tpu.utils.metrics import DEFAULT_SIZE_BUCKETS

            self._m_batch_rows = metrics.histogram(
                "hdbscan_tpu_predict_batch_rows",
                "Rows per dispatched device batch (post-coalescing).",
                buckets=DEFAULT_SIZE_BUCKETS,
            )
            self._m_device_s = metrics.histogram(
                "hdbscan_tpu_predict_device_seconds",
                "Device wall per dispatched batch (H2D + compute + D2H).",
            )
        self.dtype = dtype
        self.backend, self._interpret = _resolve_backend(backend, model, dtype)
        n = model.n_train
        self.k = max(model.min_points - 1, 1)
        self.kth_col = (
            min(max(model.min_points - 1, 1), n) - 1 if model.min_points > 1 else -1
        )
        self.max_bucket = max(_MIN_BUCKET, _next_pow2(max(1, int(max_batch))))
        # Serializes dispatch: donated query buffers and batch_seq ordering
        # both assume one predict() in flight (the HTTP server can call in
        # from handler threads as well as the batcher worker).
        self._lock = threading.RLock()
        self.buckets = [
            1 << p
            for p in range(_MIN_BUCKET.bit_length() - 1, self.max_bucket.bit_length())
        ]
        self._batch_seq = 0
        # Distinguishes predictors sharing one trace file (blue/green swaps
        # build a fresh Predictor per model generation): check_trace
        # enforces monotonic batch_seq per (process, predictor). A counter,
        # not id(self) — the allocator reuses a freed predictor's address
        # under swap/eviction churn, which would alias two generations'
        # batch_seq streams into one false regression.
        self._pred_id = f"{next(_PRED_IDS):06x}"

        c1 = len(model.parent)
        anc = _ancestor_table(model.parent)
        if self.backend == "fused":
            from hdbscan_tpu.ops.pallas_knn import COL_TILE, LANES

            self._row_mult = 256  # pallas ROW_TILE: fused buckets pad to it
            n_pad = -(-max(n, COL_TILE) // COL_TILE) * COL_TILE
            x = np.zeros((n_pad, LANES), np.float32)
            x[:n, : model.data.shape[1]] = model.data
            colmask = np.full((1, n_pad), np.inf, np.float32)
            colmask[0, :n] = 0.0
            self._train_rows = jax.device_put(x)
            self._train_t = jax.device_put(np.ascontiguousarray(x.T))
            self._colmask = jax.device_put(colmask)
            self._lanes = LANES
        elif self.backend == "rpforest":
            # One pad row past the sentinel id (= n_train), so a short
            # candidate list's sentinel entries gather a zero row whose inf
            # distance keeps them out of every argmin.
            self._row_mult = 1
            n_pad = n + 1
            # On a real TPU the stored-plane candidate scan rides the fused
            # forest rescan program (bitwise-equal at f32). CPU keeps the
            # XLA line — same values, no interpreter latency; tests flip
            # ``_rpf_fused``/``_interpret`` to pin the interpret-mode
            # parity explicitly.
            from hdbscan_tpu.ops.pallas_forest import fused_forest_eligible

            self._rpf_fused = (
                jax.devices()[0].platform == "tpu"
                and fused_forest_eligible(
                    n, model.data.shape[1], self.k, model.metric, dtype
                )
            )
            rpf = model.rpf
            self._train = jax.device_put(
                jnp.asarray(_pad_rows(np.asarray(model.data, dtype), n_pad))
            )
            self._rpf_normals = jax.device_put(jnp.asarray(rpf["normals"]))
            self._rpf_thresholds = jax.device_put(
                jnp.asarray(rpf["thresholds"])
            )
            self._rpf_members = jax.device_put(jnp.asarray(rpf["members"]))
            self._rpf_depth = int(rpf["depth"])
        else:
            self._row_mult = 1
            self.row_tile_cap = _MAX_ROW_TILE
            _, self.col_tile, n_pad = _tile_sizes(n, _MAX_ROW_TILE, 8192)
            self._train = jax.device_put(
                jnp.asarray(_pad_rows(np.asarray(model.data, dtype), n_pad))
            )
            self._valid = jax.device_put(jnp.asarray(np.arange(n_pad) < n))
        self._core_t = jax.device_put(
            jnp.asarray(_pad_rows(np.asarray(model.core, dtype), n_pad))
        )
        self._labels_t = jax.device_put(
            jnp.asarray(_pad_rows(np.asarray(model.labels, np.int32), n_pad))
        )
        self._last_t = jax.device_put(
            jnp.asarray(_pad_rows(np.asarray(model.last_cluster, np.int32), n_pad))
        )
        self._anc = jax.device_put(jnp.asarray(anc))
        self._birth = jax.device_put(jnp.asarray(np.asarray(model.birth, dtype)))
        self._sel_anc = jax.device_put(
            jnp.asarray(np.asarray(model.sel_anc, np.int32))
        )
        self._eps_min = jax.device_put(
            jnp.asarray(np.asarray(model.eps_min, dtype))
        )
        self._eps_max = jax.device_put(
            jnp.asarray(np.asarray(model.eps_max, dtype))
        )
        self._sel_ids = jax.device_put(
            jnp.asarray(model.selected_ids.astype(np.int32))
        )
        assert c1 == len(model.sel_anc)

    # -- bucket plumbing ---------------------------------------------------

    def bucket_for(self, rows: int) -> int:
        """Smallest configured power-of-two bucket holding ``rows`` (the
        ceiling bucket for oversized requests, which chunk)."""
        for b in self.buckets:
            if rows <= b:
                return b
        return self.max_bucket

    def _stage(self, chunk: np.ndarray, bucket: int):
        """Pad one chunk to its device bucket and start the async H2D copy."""
        dev_rows = max(bucket, self._row_mult)
        if self.backend == "fused":
            xq = np.zeros((dev_rows, self._lanes), np.float32)
            xq[: len(chunk), : chunk.shape[1]] = chunk
        else:
            xq = np.zeros((dev_rows, chunk.shape[1]), self.dtype)
            xq[: len(chunk)] = chunk
        return jax.device_put(xq)

    def _dispatch(self, staged, bucket: int, with_membership: bool):
        if self.backend == "fused":
            return _jitted_kernel("fused")(
                staged, self._train_rows, self._train_t, self._colmask,
                self._core_t, self._labels_t, self._last_t, self._anc,
                self._birth, self._sel_anc, self._eps_min, self._eps_max,
                self._sel_ids, k=self.k, kth_col=self.kth_col,
                with_membership=with_membership, interpret=self._interpret,
            )
        if self.backend == "rpforest":
            return _jitted_kernel("rpforest")(
                staged, self._rpf_normals, self._rpf_thresholds,
                self._rpf_members, self._train, self._core_t,
                self._labels_t, self._last_t, self._anc, self._birth,
                self._sel_anc, self._eps_min, self._eps_max, self._sel_ids,
                k=self.k, kth_col=self.kth_col, metric=self.model.metric,
                depth=self._rpf_depth, sentinel=self.model.n_train,
                with_membership=with_membership, fused=self._rpf_fused,
                interpret=self._interpret,
            )
        dev_rows = max(bucket, self._row_mult)
        row_tile = min(_next_pow2(max(dev_rows, 8)), self.row_tile_cap)
        return _jitted_kernel("xla")(
            staged, self._train, self._valid, self._core_t, self._labels_t,
            self._last_t, self._anc, self._birth, self._sel_anc,
            self._eps_min, self._eps_max, self._sel_ids, k=self.k,
            kth_col=self.kth_col, metric=self.model.metric,
            row_tile=row_tile, col_tile=self.col_tile,
            with_membership=with_membership,
        )

    # -- public API --------------------------------------------------------

    def predict(self, X, with_membership: bool = False):
        """Batched prediction: returns ``(labels, probabilities,
        outlier_scores)`` int64/float64 arrays aligned with ``X`` rows
        (plus the (n, S) membership matrix when ``with_membership``).

        Requests above ``max_bucket`` chunk; chunk i+1's host-to-device copy
        is staged while chunk i computes (the ``ops/blockscan`` prestage
        pattern), so the device never idles on transfer.
        """
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if X.shape[1] != self.model.data.shape[1]:
            raise ValueError(
                f"query dims {X.shape[1]} != model dims {self.model.data.shape[1]}"
            )
        with self._lock:
            return self._predict_locked(X, with_membership)

    def _predict_locked(self, X: np.ndarray, with_membership: bool):
        if inject.maybe_fire("predict_dispatch") is not None:
            raise inject.InjectedFault("injected predict_dispatch fault")
        n = len(X)
        chunks = []
        a = 0
        while a < n:
            b = min(n - a, self.max_bucket)
            chunks.append((a, b, self.bucket_for(b)))
            a += b
        outs = []
        staged = self._stage(X[chunks[0][0] : chunks[0][0] + chunks[0][1]],
                             chunks[0][2])
        for ci, (a, b, bucket) in enumerate(chunks):
            t0 = time.perf_counter()
            out = self._dispatch(staged, bucket, with_membership)
            if ci + 1 < len(chunks):  # overlap next H2D with this compute
                na, nb, nbucket = chunks[ci + 1]
                staged = self._stage(X[na : na + nb], nbucket)
            fetched = jax.device_get(out)
            wall = time.perf_counter() - t0
            if self.tracer is not None:
                self.tracer(
                    "predict_batch",
                    bucket=int(bucket),
                    rows=int(b),
                    batch_seq=self._batch_seq,
                    backend=self.backend,
                    pred=self._pred_id,
                    wall_s=round(wall, 6),
                )
            self._batch_seq += 1
            if self._m_batch_rows is not None:
                self._m_batch_rows.observe(b)
                self._m_device_s.observe(wall)
            outs.append(tuple(np.asarray(f)[:b] for f in fetched))
        label = np.concatenate([o[0] for o in outs]).astype(np.int64)
        prob = np.concatenate([o[1] for o in outs]).astype(np.float64)
        score = np.concatenate([o[2] for o in outs]).astype(np.float64)
        if with_membership:
            mvec = np.concatenate([o[3] for o in outs]).astype(np.float64)
            return label, prob, score, mvec
        return label, prob, score

    def warmup(self, with_membership: bool = False) -> dict:
        """AOT-compile every bucket (zeros through each shape, blocking), so
        steady-state serving never compiles. Returns ``{"buckets": [...],
        "wall_s": float, "jit_compiles": int, "cache_hits": int}``.

        ``jit_compiles`` counts compiles this warmup actually PAID:
        backend-compile events (``utils/telemetry.compile_counter``) minus
        persistent-compile-cache hits (``cache_hit_counter``) — jax still
        fires a backend-compile duration event when it deserializes a
        cached executable, so the raw delta alone would make a warm spawn
        look cold. A replica spawned by the fleet router with its siblings'
        ``JAX_COMPILATION_CACHE_DIR`` reports ``jit_compiles == 0`` and
        ``cache_hits > 0`` here (the scale-up warm-standby contract).
        """
        from hdbscan_tpu.utils.telemetry import cache_hit_counter, compile_counter

        counter = compile_counter()
        hits = cache_hit_counter()
        before = counter()
        hits_before = hits()
        t0 = time.perf_counter()
        d = self.model.data.shape[1]
        with self._lock:
            for bucket in self.buckets:
                staged = self._stage(np.zeros((1, d)), bucket)
                jax.block_until_ready(self._dispatch(staged, bucket, False))
                if with_membership:
                    staged = self._stage(np.zeros((1, d)), bucket)
                    jax.block_until_ready(self._dispatch(staged, bucket, True))
        wall = time.perf_counter() - t0
        cache_hits = hits() - hits_before
        info = {
            "buckets": list(self.buckets),
            "wall_s": round(wall, 6),
            "jit_compiles": max(0, counter() - before - cache_hits),
            "cache_hits": cache_hits,
        }
        if self.tracer is not None:
            self.tracer("predict_warmup", **{**info, "wall_s": info["wall_s"]})
        return info


def _predictor_for(model, backend, max_batch, tracer) -> Predictor:
    """Per-model predictor cache so the functional API reuses device state
    (and jit caches) across calls instead of re-staging per call."""
    cache = model.__dict__.setdefault("_predictor_cache", {})
    key = (backend, max_batch)
    if key not in cache:
        cache[key] = Predictor(
            model, backend=backend, max_batch=max_batch, tracer=tracer
        )
    pred = cache[key]
    if tracer is not None:
        pred.tracer = tracer
    return pred


def approximate_predict(
    model, X, backend: str = "auto", max_batch: int = 256, tracer=None
):
    """hdbscan-style ``(labels, probabilities)`` for unseen points ``X``
    against a fitted :class:`~hdbscan_tpu.serve.artifact.ClusterModel`."""
    labels, prob, _ = _predictor_for(model, backend, max_batch, tracer).predict(X)
    return labels, prob


def outlier_scores(
    model, X, backend: str = "auto", max_batch: int = 256, tracer=None
):
    """GLOSH outlier scores for unseen points (score of the level at which
    each query attaches to the fitted hierarchy; clipped at 0)."""
    return _predictor_for(model, backend, max_batch, tracer).predict(X)[2]


def membership_vectors(
    model, X, backend: str = "auto", max_batch: int = 256, tracer=None
):
    """Soft clustering: an (n, S) matrix over ``model.selected_ids`` —
    inverse-mutual-reachability weights to each selected cluster's nearest
    fitted exemplar in the query's k-NN list, normalized per row (zero rows
    for queries whose neighborhood touches no selected cluster)."""
    return _predictor_for(model, backend, max_batch, tracer).predict(
        X, with_membership=True
    )[3]
