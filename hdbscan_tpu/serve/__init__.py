"""Serving subsystem: persist a fitted model, classify new points, serve HTTP.

Four layers (README "Serving"):

- ``serve/artifact.py`` — schema-versioned :class:`ClusterModel` saved as one
  atomic ``.npz`` (condensed-tree arrays, selected clusters, per-cluster
  max-lambda, training points + core distances, params fingerprint);
- ``serve/predict.py`` — jitted batched :func:`approximate_predict` (query
  k-NN against the training set, mutual-reachability attachment level,
  nearest-selected-ancestor labels), plus :func:`membership_vectors` and
  GLOSH :func:`outlier_scores` for unseen points;
- ``serve/batcher.py`` — :class:`MicroBatcher` coalescing concurrent
  requests into padded power-of-two buckets (zero steady-state recompiles
  after AOT warmup);
- ``serve/server.py`` — stdlib HTTP ``/predict`` + ``/healthz`` (plus
  ``/ingest`` + ``/swap`` in streaming mode — ``hdbscan_tpu/stream``,
  README "Streaming") with blue/green model-handle swaps, ``predict_batch``
  trace events and latency percentiles in the run report.
"""

from hdbscan_tpu.serve.artifact import MODEL_SCHEMA, ClusterModel  # noqa: F401
from hdbscan_tpu.serve.batcher import MicroBatcher  # noqa: F401
from hdbscan_tpu.serve.predict import (  # noqa: F401
    Predictor,
    approximate_predict,
    membership_vectors,
    outlier_scores,
)
from hdbscan_tpu.serve.server import ClusterServer  # noqa: F401
