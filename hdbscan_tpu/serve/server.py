"""Stdlib HTTP inference server over a fitted ClusterModel.

Endpoints:

- ``POST /predict`` — body ``{"points": [[...], ...]}`` (optionally
  ``"membership": true``); responds ``{"labels", "probabilities",
  "outlier_scores", "generation"}`` (plus ``"membership"`` +
  ``"selected_ids"`` when requested). Plain predicts route through the
  :class:`~hdbscan_tpu.serve.batcher.MicroBatcher`, so concurrent clients
  coalesce into shared bucket dispatches.
- ``POST /ingest`` — streaming mode only: predicts the points, absorbs
  duplicates/near-duplicates into bubble summaries, updates the drift
  sketches, and (on a drift flag or point budget) kicks off a background
  re-fit. See ``hdbscan_tpu/stream/``.
- ``POST /swap`` — apply a staged re-fit artifact (``stream_reload=manual``)
  or an explicit ``{"path": ...}`` artifact: the blue/green hot swap.
- ``GET /healthz`` — model summary, backend, warmed buckets, batcher
  coalescing stats, stream/swap state, uptime, per-route request/error
  counts and the current in-flight count (snapshotted from the metrics
  registry).
- ``GET /metrics`` — Prometheus text exposition (``utils/metrics.py``):
  request totals by route/status, in-flight gauge, request-latency and
  batch-size histograms, swap/refit/drift counters, ingest absorb
  counters. ``scripts/check_metrics.py`` validates the output.

Per-request spans: every successful ``/predict``/``/ingest`` request gets
a process-unique request id (echoed as ``X-Request-Id``) and, when a
tracer is attached, a ``request_span`` trace event decomposing its wall
into parse / queue-wait / batch-assembly / device-predict / respond
segments, with rows, pow2 bucket, coalesced-peer count and model
generation attributed. The segment timestamps are contiguous
``perf_counter`` marks threaded through the batcher via a per-request
``meta`` dict (filled by the worker before the Future resolves), so the
five segments telescope exactly to the span wall —
``scripts/check_trace.py`` enforces the sum within 1e-6.

Blue/green serving: every model lives in an immutable ``_ModelHandle``
(model + warmed predictor + its own MicroBatcher + generation number).
A request pins the handle it started with — ``self._handle`` is read once
— and a swap is a single reference assignment under a lock, so in-flight
requests finish on the model they started on and new requests see the new
one; nothing is dropped and no request mixes models. The old handle's
batcher is then drain-closed (every accepted future completes — the
graceful-shutdown guarantee in batcher.py). Swaps are guarded by the
artifact digest check (``ClusterModel.load``) plus a fingerprint-field
match against the served model, and emit ``model_swap`` trace events with
a per-server monotonic generation (validated by scripts/check_trace.py).

``http.server.ThreadingHTTPServer`` only — no new dependencies; the device
is still single-dispatcher because every handler thread funnels into the
handle's batcher worker (or the predictor's internal lock for membership
calls). ``SIGTERM``/``close()`` drains in-flight work before exiting.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from hdbscan_tpu.serve.artifact import _FINGERPRINT_FIELDS, ClusterModel
from hdbscan_tpu.serve.batcher import MicroBatcher
from hdbscan_tpu.serve.predict import Predictor
from hdbscan_tpu.utils.metrics import MetricsRegistry

#: Refuse request bodies above this size (64 MiB ~ a 1M x 8-dim f64 batch);
#: a streaming client should chunk instead of shipping one giant body.
MAX_BODY_BYTES = 64 << 20

#: Bounded retries for the swap race: a request that pinned a handle whose
#: batcher closed before its submit landed just re-pins the current handle.
_PIN_RETRIES = 8

#: Process-wide request-id sequence: ids stay unique even when several
#: servers share one process and one trace file (check_trace enforces
#: per-process request_span id uniqueness).
_REQUEST_IDS = itertools.count(1)


class _ModelHandle:
    """One served model generation: artifact + warmed predictor + batcher.

    Immutable once built — a swap builds a fresh handle and replaces the
    server's reference; it never mutates a live one.
    """

    __slots__ = ("model", "predictor", "batcher", "generation", "warmup_info")

    def __init__(self, model, predictor, batcher, generation, warmup_info):
        self.model = model
        self.predictor = predictor
        self.batcher = batcher
        self.generation = generation
        self.warmup_info = warmup_info

    @property
    def digest(self) -> str | None:
        return self.model.fingerprint.get("data")


class _Handler(BaseHTTPRequestHandler):
    server_version = "hdbscan-tpu-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs away from stderr
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _json(self, code: int, obj: dict, headers: dict | None = None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _text(self, code: int, text: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - http.server API
        route = self.path.split("?")[0]
        srv = self.server.cluster_server
        known = route in ("/healthz", "/metrics")
        t0 = time.perf_counter()
        srv._m_in_flight.inc()
        code = 500
        try:
            if route == "/healthz":
                code = 200
                self._json(code, srv.health())
            elif route == "/metrics":
                code = 200
                self._text(code, srv.render_metrics())
            else:
                code = 404
                self._json(code, {"error": f"unknown path {self.path!r}"})
        finally:
            srv._m_in_flight.dec()
            srv._observe_request(
                route if known else "other", code, time.perf_counter() - t0
            )

    def _read_payload(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_BODY_BYTES:
            raise ValueError(f"body exceeds {MAX_BODY_BYTES} bytes")
        return json.loads(self.rfile.read(length).decode()) if length else {}

    def do_POST(self):  # noqa: N802 - http.server API
        path = self.path.split("?")[0]
        srv = self.server.cluster_server
        known = path in ("/predict", "/ingest", "/swap")
        t0 = time.perf_counter()
        srv._m_in_flight.inc()
        code = 500
        span = None
        try:
            try:
                payload = self._read_payload()
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                code = 400
                self._json(code, {"error": f"bad request: {e}"})
                return
            # meta is filled across threads (batcher worker) with the span
            # timestamps; the Future resolution inside predict/ingest is the
            # happens-before edge that publishes it back to this thread.
            meta: dict = {}
            rid = srv.next_request_id()
            try:
                if path == "/predict":
                    points = np.asarray(payload["points"], np.float64)
                    meta["t_parse"] = time.perf_counter()
                    out = srv.predict(
                        points, bool(payload.get("membership", False)), meta=meta
                    )
                    rows = len(out["labels"])
                elif path == "/ingest":
                    points = np.asarray(payload["points"], np.float64)
                    meta["t_parse"] = time.perf_counter()
                    out = srv.ingest(points, meta=meta)
                    rows = out["rows"]
                elif path == "/swap":
                    out = srv.swap(payload.get("path"))
                    rows = 0
                else:
                    code = 404
                    self._json(code, {"error": f"unknown path {self.path!r}"})
                    return
            except KeyError as e:
                code = 400
                self._json(code, {"error": f"bad request: missing {e}"})
                return
            except ValueError as e:  # shape/dim/guard mismatches: client errors
                code = 400
                self._json(code, {"error": str(e)})
                return
            except RuntimeError as e:  # mode errors (ingest off, nothing staged)
                code = 409
                self._json(code, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001 - surface, don't crash
                code = 500
                self._json(code, {"error": f"{type(e).__name__}: {e}"})
                return
            code = 200
            self._json(code, out, headers={"X-Request-Id": rid})
            if path in ("/predict", "/ingest"):
                span = (path, rid, rows, int(out.get("generation", 0)), meta)
        finally:
            t_end = time.perf_counter()
            srv._m_in_flight.dec()
            srv._observe_request(path if known else "other", code, t_end - t0)
            if span is not None:
                srv._emit_request_span(*span, t0=t0, t_end=t_end)


class ClusterServer:
    """Predictor + batcher + HTTP front, as one closeable unit.

    Construction warms every bucket (AOT), so the first real request already
    hits a compiled program; ``port=0`` binds an ephemeral port (tests).

    ``ingest=True`` turns on the streaming subsystem: ``/ingest`` routes
    arriving points through the predict path into an
    :class:`~hdbscan_tpu.stream.IngestBuffer`, a
    :class:`~hdbscan_tpu.stream.DriftDetector` watches the GLOSH-score and
    assignment-rate distributions, and a :class:`~hdbscan_tpu.stream.Refitter`
    re-fits in the background on drift or point budget, publishing
    generation-numbered artifacts under ``model_dir`` that hot-swap in
    (``stream_reload="auto"``) or stage for ``POST /swap``
    (``"manual"``). Stream knobs come from ``params``
    (:class:`~hdbscan_tpu.config.HDBSCANParams` ``stream_*`` fields).
    """

    def __init__(
        self,
        model,
        backend: str = "auto",
        max_batch: int = 256,
        linger_s: float = 0.002,
        host: str = "127.0.0.1",
        port: int = 8799,
        tracer=None,
        warmup: bool = True,
        verbose: bool = False,
        ingest: bool = False,
        params=None,
        model_dir: str | None = None,
    ):
        self.tracer = tracer
        self._backend_req = backend
        self._max_batch = max_batch
        self._linger_s = linger_s
        self._warmup = warmup
        self._swap_lock = threading.Lock()
        self._closed = False
        self._swap_count = 0
        self.last_swap: dict | None = None
        self.pending: dict | None = None  # staged artifact (manual reload)
        # Distinguishes servers sharing one trace file: check_trace enforces
        # monotonic swap generations per (process, server).
        self._server_id = f"{os.getpid():x}.{id(self) & 0xFFFFFF:06x}"

        # Metrics registry must exist before the first handle: the predictor
        # observes its batch histograms through it.
        self.metrics = MetricsRegistry()
        self._m_requests = self.metrics.counter(
            "hdbscan_tpu_requests_total",
            "HTTP requests by route and status code.",
            labelnames=("route", "status"),
        )
        self._m_in_flight = self.metrics.gauge(
            "hdbscan_tpu_requests_in_flight",
            "HTTP requests currently being handled.",
        )
        self._m_latency = self.metrics.histogram(
            "hdbscan_tpu_request_latency_seconds",
            "End-to-end HTTP request wall by route.",
            labelnames=("route",),
        )
        self._m_swaps = self.metrics.counter(
            "hdbscan_tpu_model_swaps_total",
            "Blue/green model swaps applied.",
        )
        self._m_generation = self.metrics.gauge(
            "hdbscan_tpu_model_generation",
            "Generation number of the served model handle.",
        )
        self._m_uptime = self.metrics.gauge(
            "hdbscan_tpu_uptime_seconds",
            "Seconds since server construction.",
        )

        self._handle = self._build_handle(model, generation=1)
        self._m_generation.set(1.0)

        self.ingest_enabled = bool(ingest)
        self._params = params
        if self.ingest_enabled:
            self._init_stream(params, model_dir)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.cluster_server = self
        self._httpd.verbose = verbose
        self.host, self.port = self._httpd.server_address[:2]
        self._t0 = time.monotonic()
        self._thread: threading.Thread | None = None
        self._serving = False  # a serve_forever loop is (or was) running

    # -- stream wiring -----------------------------------------------------

    def _init_stream(self, params, model_dir) -> None:
        from hdbscan_tpu.stream import DriftDetector, IngestBuffer, Refitter

        def knob(name, default):
            return getattr(params, name, default) if params is not None else default

        self.reload_mode = knob("stream_reload", "auto")
        self._refit_budget = int(knob("stream_refit_budget", 2048))
        self._absorb_frac = float(knob("stream_absorb_eps_frac", 0.25))
        self._drift_stat = knob("stream_drift_stat", "psi")
        self._drift_threshold = float(knob("stream_drift_threshold", 2.0))
        self.model_dir = model_dir or "stream_models"
        self._ingest_lock = threading.Lock()
        self._m_drift_checks = self.metrics.counter(
            "hdbscan_tpu_drift_checks_total", "Drift detector checks run."
        )
        self._m_drift_flags = self.metrics.counter(
            "hdbscan_tpu_drift_flags_total", "Drift checks that flagged shift."
        )
        self._m_refit_kicks = self.metrics.counter(
            "hdbscan_tpu_refit_kicks_total",
            "Background re-fits kicked from the ingest path, by trigger.",
            labelnames=("trigger",),
        )
        self.buffer = IngestBuffer(
            self.model, absorb_eps_frac=self._absorb_frac, metrics=self.metrics
        )
        self.drift = DriftDetector(
            *DriftDetector.baseline_from_model(self.model, self._handle.predictor),
            stat=self._drift_stat,
            threshold=self._drift_threshold,
            tracer=self.tracer,
        )
        refit_params = self._refit_params(params)
        self.refitter = Refitter(
            refit_params,
            self.model_dir,
            tracer=self.tracer,
            on_publish=self._on_publish,
            metrics=self.metrics,
        )

    def _refit_params(self, params):
        """Re-fit params: caller's knobs where given, but the fingerprint
        fields pinned to the served model's so the swap guard passes."""
        from hdbscan_tpu.config import HDBSCANParams

        base = params if params is not None else HDBSCANParams()
        return base.replace(**dict(self.model.params))

    # -- handles -----------------------------------------------------------

    def _build_handle(self, model, generation: int) -> _ModelHandle:
        backend = self._backend_req
        if backend == "rpforest" and model.rpf is None:
            backend = "auto"  # re-fit artifacts ship without a forest
        predictor = Predictor(
            model, backend=backend, max_batch=self._max_batch,
            tracer=self.tracer, metrics=self.metrics,
        )
        warmup_info = predictor.warmup() if self._warmup else None
        batcher = MicroBatcher(predictor, linger_s=self._linger_s)
        return _ModelHandle(model, predictor, batcher, generation, warmup_info)

    @property
    def model(self):
        return self._handle.model

    @property
    def predictor(self):
        return self._handle.predictor

    @property
    def batcher(self):
        return self._handle.batcher

    @property
    def generation(self) -> int:
        return self._handle.generation

    @property
    def warmup_info(self):
        return self._handle.warmup_info

    # -- request paths -----------------------------------------------------

    def next_request_id(self) -> str:
        """Process-unique request id (pid + process-wide sequence)."""
        return f"{os.getpid()}-{next(_REQUEST_IDS)}"

    def _observe_request(self, route: str, status: int, wall: float) -> None:
        self._m_requests.inc(route=route, status=str(status))
        self._m_latency.observe(wall, route=route)

    def _emit_request_span(
        self, route, rid, rows, generation, meta, t0, t_end
    ) -> None:
        """Emit one ``request_span`` trace event for a successful
        ``/predict``/``/ingest`` request. The five segments are contiguous
        perf_counter diffs (clamped monotone into [t0, t_end]) so they
        telescope exactly to the span wall; 9-decimal rounding keeps the
        telescoped sum inside check_trace's 1e-6 tolerance, which 6
        decimals would not."""
        if self.tracer is None:
            return
        t_parse = min(max(t0, meta.get("t_parse", t0)), t_end)
        t_asm = min(max(t_parse, meta.get("t_assembled", t_parse)), t_end)
        t_disp = min(max(t_asm, meta.get("t_dispatch", t_asm)), t_end)
        t_done = min(max(t_disp, meta.get("t_done", t_disp)), t_end)
        bucket = meta.get("bucket")
        if not bucket:  # defensive: never emit a non-pow2 bucket
            pred = self._handle.predictor
            bucket = pred.bucket_for(min(max(int(rows), 1), pred.max_bucket))
        self.tracer(
            "request_span",
            request_id=rid,
            route=route,
            rows=int(rows),
            bucket=int(bucket),
            coalesced=int(meta.get("coalesced", 1)),
            generation=int(generation),
            parse_s=round(t_parse - t0, 9),
            queue_s=round(t_asm - t_parse, 9),
            assemble_s=round(t_disp - t_asm, 9),
            predict_s=round(t_done - t_disp, 9),
            respond_s=round(t_end - t_done, 9),
            wall_s=round(t_end - t0, 9),
        )

    def predict(
        self, points: np.ndarray, membership: bool = False,
        meta: dict | None = None,
    ) -> dict:
        for _ in range(_PIN_RETRIES):
            handle = self._handle  # pin: this request never mixes models
            try:
                return self._predict_on(handle, points, membership, meta)
            except RuntimeError as e:
                # The pinned handle's batcher closed under us (swap landed
                # between the pin and the submit) — re-pin and retry; no
                # request is dropped across a swap. (The retry's dispatch
                # overwrites the meta timestamps, so a span still describes
                # the attempt that actually served the rows.)
                if "closed" not in str(e) or self._closed:
                    raise
        raise RuntimeError("predict retries exhausted during model swaps")

    def _predict_on(
        self, handle: _ModelHandle, points, membership: bool,
        meta: dict | None = None,
    ) -> dict:
        if membership:
            # Membership needs the 4-output kernel variant; it bypasses the
            # batcher and relies on the predictor's internal dispatch lock —
            # no queue wait and no coalescing, so the span meta collapses
            # queue/assemble to zero-width here.
            if meta is not None:
                t = time.perf_counter()
                meta["t_assembled"] = meta["t_dispatch"] = t
            labels, prob, score, mvec = handle.predictor.predict(
                points, with_membership=True
            )
            if meta is not None:
                meta["t_done"] = time.perf_counter()
                meta["coalesced"] = 1
                meta["bucket"] = handle.predictor.bucket_for(
                    min(len(labels), handle.predictor.max_bucket)
                )
            return {
                "labels": labels.tolist(),
                "probabilities": [round(p, 6) for p in prob.tolist()],
                "outlier_scores": [round(s, 6) for s in score.tolist()],
                "membership": np.round(mvec, 6).tolist(),
                "selected_ids": handle.model.selected_ids.tolist(),
                "generation": handle.generation,
            }
        labels, prob, score = handle.batcher.predict(points, meta=meta)
        return {
            "labels": labels.tolist(),
            "probabilities": [round(p, 6) for p in prob.tolist()],
            "outlier_scores": [round(s, 6) for s in score.tolist()],
            "generation": handle.generation,
        }

    def ingest(self, points: np.ndarray, meta: dict | None = None) -> dict:
        """Streaming entry: predict → absorb/buffer → drift check → maybe
        kick a background re-fit. Returns per-batch routing + drift info."""
        if not self.ingest_enabled:
            raise RuntimeError("server started without ingest mode")
        t0 = time.perf_counter()
        points = np.asarray(points, np.float64)
        if points.ndim == 1:
            points = points[None, :]
        scored = False
        for _ in range(_PIN_RETRIES):
            handle = self._handle
            try:
                labels, prob, score = handle.batcher.predict(points, meta=meta)
            except RuntimeError as e:
                if "closed" not in str(e) or self._closed:
                    raise
                continue
            scored = True
            if handle is self._handle:
                break
            # A swap landed mid-predict: the buffer/drift state now keys to
            # the new model, so this batch's scores are stale — redo on the
            # current handle rather than polluting the fresh sketches.
        if not scored:
            raise RuntimeError("ingest retries exhausted during model swaps")
        with self._ingest_lock:
            absorbed, buffered = self.buffer.absorb(points, labels, prob)
            self.drift.update(labels, score)
            check = self.drift.check(generation=handle.generation)
            self._m_drift_checks.inc()
            if check["drifted"]:
                self._m_drift_flags.inc()
            trigger = None
            if check["drifted"]:
                trigger = "drift"
            elif self.buffer.buffered_rows >= self._refit_budget:
                trigger = "budget"
            refit_started = False
            if trigger and self.pending is None and not self.refitter.busy:
                pool = self.buffer.refit_points(
                    originals=min(self.model.n_train, 8192)
                )
                refit_started = self.refitter.request(pool, trigger)
                if refit_started:
                    self._m_refit_kicks.inc(trigger=trigger)
        if self.tracer is not None:
            self.tracer(
                "stream_ingest",
                rows=int(len(points)),
                absorbed=int(absorbed),
                buffered=int(buffered),
                generation=int(handle.generation),
                wall_s=round(time.perf_counter() - t0, 6),
            )
        return {
            "rows": int(len(points)),
            "absorbed": int(absorbed),
            "buffered": int(buffered),
            "generation": int(handle.generation),
            "drift": check,
            "refit_started": bool(refit_started),
        }

    # -- blue/green swap ---------------------------------------------------

    def _on_publish(self, path: str, model, reason: str) -> None:
        """Refitter callback (worker thread): hot-swap, or stage for
        ``POST /swap`` in manual reload mode."""
        staged = {"path": path, "reason": reason, "n_train": int(model.n_train)}
        if getattr(self, "reload_mode", "auto") == "manual":
            self.pending = staged
            return
        try:
            self.swap_model(model, reason=reason, path=path)
        except Exception as exc:  # guard failure: keep serving the old model
            self.last_swap = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}

    def swap(self, path: str | None = None) -> dict:
        """HTTP-facing swap: explicit artifact ``path``, else the staged
        re-fit publication."""
        if path is None:
            if self.pending is None:
                raise RuntimeError("no staged artifact to swap in")
            path = self.pending["path"]
        return self.swap_model(path, reason="manual")

    def swap_model(self, model_or_path, reason: str = "manual",
                   path: str | None = None) -> dict:
        """Atomically replace the served model (blue/green).

        Accepts a :class:`ClusterModel` or an artifact path. Path loads run
        the artifact's schema + sha256 digest checks (``ClusterModel.load``
        refuses corrupt or mismatched files); either way the fingerprint
        fields must match the served model — a swap may change the data, not
        the clustering contract. The expensive part (predictor build +
        warmup) happens on the old model's watch; the swap itself is one
        reference assignment under the lock, and in-flight requests finish
        on the handle they pinned. Old batcher drains afterwards.
        """
        if isinstance(model_or_path, (str, os.PathLike)):
            path = str(model_or_path)
            new_model = ClusterModel.load(path)  # schema + digest guard
        else:
            new_model = model_or_path
        old_model = self._handle.model
        for f in _FINGERPRINT_FIELDS:
            if new_model.params.get(f) != old_model.params.get(f):
                raise ValueError(
                    f"swap fingerprint mismatch on {f!r}: incoming "
                    f"{new_model.params.get(f)!r} != served "
                    f"{old_model.params.get(f)!r} — refusing to swap"
                )
        new_handle = self._build_handle(new_model, generation=0)  # warm first
        with self._swap_lock:
            if self._closed:
                raise RuntimeError("server is closed")
            old = self._handle
            new_handle.generation = old.generation + 1
            t0 = time.perf_counter()
            self._handle = new_handle  # the swap: one reference assignment
            pause_s = time.perf_counter() - t0
            self._swap_count += 1
        self._m_swaps.inc()
        self._m_generation.set(float(new_handle.generation))
        if self.tracer is not None:
            self.tracer(
                "model_swap",
                generation=int(new_handle.generation),
                digest=str(new_handle.digest),
                n_train=int(new_model.n_train),
                reason=str(reason),
                server=self._server_id,
                pause_s=round(pause_s, 9),
                wall_s=round(pause_s, 9),
            )
        old.batcher.close()  # graceful: every in-flight future completes
        if self.ingest_enabled:
            with self._ingest_lock:
                self.buffer.reset(new_model)
                self.drift.rebaseline(
                    *type(self.drift).baseline_from_model(
                        new_model, new_handle.predictor
                    )
                )
                self.pending = None
        info = {
            "ok": True,
            "generation": int(new_handle.generation),
            "n_train": int(new_model.n_train),
            "digest": str(new_handle.digest),
            "reason": str(reason),
            "path": path,
            "pause_s": round(pause_s, 9),
        }
        self.last_swap = info
        return info

    # -- health / metrics --------------------------------------------------

    def render_metrics(self) -> str:
        """Prometheus text exposition for ``GET /metrics``. Live-state
        gauges (uptime, served generation) refresh at scrape time; all
        counters and histograms accumulate at their event sites."""
        self._m_uptime.set(round(time.monotonic() - self._t0, 3))
        self._m_generation.set(float(self._handle.generation))
        return self.metrics.render()

    def health(self) -> dict:
        handle = self._handle
        # Per-route request/error counts + current in-flight, snapshotted
        # from the metrics registry (the /metrics counters, folded over
        # status: >= 400 counts as an error).
        requests: dict = {}
        for labels, value in self._m_requests.samples():
            row = requests.setdefault(
                labels["route"], {"requests": 0, "errors": 0}
            )
            row["requests"] += int(value)
            if int(labels["status"]) >= 400:
                row["errors"] += int(value)
        out = {
            "status": "ok",
            "model": handle.model.summary(),
            "backend": handle.predictor.backend,
            "buckets": list(handle.predictor.buckets),
            "warmup": handle.warmup_info,
            "batcher": handle.batcher.stats,
            "generation": handle.generation,
            "swaps": self._swap_count,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "requests": requests,
            "in_flight": int(self._m_in_flight.value()),
        }
        if self.last_swap is not None:
            out["last_swap"] = self.last_swap
        if self.ingest_enabled:
            stats = self.buffer.stats()
            out["stream"] = {
                "rows_seen": stats["rows_seen"],
                "absorbed_exact": stats["absorbed_exact"],
                "absorbed_near": stats["absorbed_near"],
                "buffered": stats["buffered"],
                "bubbles": len(stats["bubbles"]),
                "drift_rows": self.drift.rows,
                "drift_checks": self.drift.checks,
                "refits_ok": self.refitter.refits_ok,
                "refits_failed": self.refitter.refits_failed,
                "refit_busy": self.refitter.busy,
                "reload": self.reload_mode,
                "pending": self.pending,
            }
        return out

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ClusterServer":
        """Serve on a daemon thread (tests / embedding); returns self."""
        self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="predict-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Serve on the calling thread until interrupted (CLI path).
        ``SIGTERM`` triggers the same graceful drain as ``close()``."""
        try:
            signal.signal(
                signal.SIGTERM,
                lambda *_: threading.Thread(
                    target=self.close, name="sigterm-close"
                ).start(),
            )
        except ValueError:
            pass  # not the main thread (embedded) — close() still works
        try:
            self._serving = True
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.close()

    def close(self) -> None:
        """Graceful shutdown: stop accepting, finish in-flight requests
        (batcher drain resolves every accepted future), then release."""
        with self._swap_lock:
            if self._closed:
                return
            self._closed = True
        if self._serving:  # shutdown() blocks unless a serve loop is live
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._handle.batcher.close()
        if self.ingest_enabled:
            self.refitter.join(timeout=0.5)  # daemon thread; don't block long

    def __enter__(self) -> "ClusterServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
