"""Stdlib HTTP inference server over a fitted ClusterModel.

Endpoints:

- ``POST /predict`` — body ``{"points": [[...], ...]}`` (optionally
  ``"membership": true``); responds ``{"labels", "probabilities",
  "outlier_scores", "generation"}`` (plus ``"membership"`` +
  ``"selected_ids"`` when requested). Plain predicts route through the
  :class:`~hdbscan_tpu.serve.batcher.MicroBatcher`, so concurrent clients
  coalesce into shared bucket dispatches.
- ``POST /ingest`` — streaming mode only: predicts the points, absorbs
  duplicates/near-duplicates into bubble summaries, updates the drift
  sketches, and (on a drift flag or point budget) kicks off a background
  re-fit. See ``hdbscan_tpu/stream/``.
- ``POST /swap`` — apply a staged re-fit artifact (``stream_reload=manual``)
  or an explicit ``{"path": ...}`` artifact: the blue/green hot swap.
- ``GET /healthz`` — model summary, backend, warmed buckets, batcher
  coalescing stats, stream/swap state, uptime, per-route request/error
  counts and the current in-flight count (snapshotted from the metrics
  registry).
- ``GET /metrics`` — Prometheus text exposition (``utils/metrics.py``):
  request totals by route/status, in-flight gauge, request-latency and
  batch-size histograms, swap/refit/drift counters, ingest absorb
  counters. ``scripts/check_metrics.py`` validates the output.

Per-request spans: every terminated ``/predict``/``/ingest`` request —
success or error — gets a process-unique request id (echoed as
``X-Request-Id``) and, when a tracer is attached, exactly one trace event:
a ``request_shed`` (when the bounded batcher queue refused it with
429/503 + Retry-After) or a ``request_span`` carrying the HTTP ``status``
and decomposing its wall into parse / queue-wait / batch-assembly /
device-predict / respond segments, with rows, pow2 bucket, coalesced-peer
count and model generation attributed. The segment timestamps are
contiguous ``perf_counter`` marks threaded through the batcher via a
per-request ``meta`` dict (filled by the worker before the Future
resolves), so the five segments telescope exactly to the span wall —
``scripts/check_trace.py`` enforces the sum within 1e-6 and that
shed + served + failed accounts for every offered request.

Fault tolerance (README "Fault tolerance"): per-request deadlines
(``X-Deadline-Ms`` header / ``serve_deadline_ms`` knob → 504 fail-fast
before a batch slot is spent), bounded-queue load shedding
(``serve_queue_bound``), a refit circuit breaker that degrades to the
pinned generation after repeated refit/swap failures, optional crash-safe
ingest durability (``stream_wal_dir`` → ``stream/wal.StreamJournal``),
and the ``HDBSCAN_TPU_FAULTS`` injection harness
(``hdbscan_tpu/fault/``) for chaos testing all of the above.

Blue/green serving: every model lives in an immutable ``_ModelHandle``
(model + warmed predictor + its own MicroBatcher + generation number).
A request pins the handle it started with — ``self._handle`` is read once
— and a swap is a single reference assignment under a lock, so in-flight
requests finish on the model they started on and new requests see the new
one; nothing is dropped and no request mixes models. The old handle's
batcher is then drain-closed (every accepted future completes — the
graceful-shutdown guarantee in batcher.py). Swaps are guarded by the
artifact digest check (``ClusterModel.load``) plus a fingerprint-field
match against the served model, and emit ``model_swap`` trace events with
a per-server monotonic generation (validated by scripts/check_trace.py).

``http.server.ThreadingHTTPServer`` only — no new dependencies; the device
is still single-dispatcher because every handler thread funnels into the
handle's batcher worker (or the predictor's internal lock for membership
calls). ``SIGTERM``/``close()`` drains in-flight work before exiting.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from hdbscan_tpu import obs
from hdbscan_tpu.fault import inject
from hdbscan_tpu.obs import heartbeat as obs_heartbeat
from hdbscan_tpu.fault.policy import (
    CIRCUIT_STATE_VALUES,
    CircuitBreaker,
    DeadlineExceeded,
    ShedRequest,
    retry_call,
)
from hdbscan_tpu.serve.artifact import _FINGERPRINT_FIELDS, ClusterModel
from hdbscan_tpu.serve.batcher import MicroBatcher
from hdbscan_tpu.serve.predict import Predictor
from hdbscan_tpu.utils.metrics import MetricsRegistry

#: Refuse request bodies above this size (64 MiB ~ a 1M x 8-dim f64 batch);
#: a streaming client should chunk instead of shipping one giant body.
MAX_BODY_BYTES = 64 << 20

#: Bounded retries for the swap race: a request that pinned a handle whose
#: batcher closed before its submit landed just re-pins the current handle.
_PIN_RETRIES = 8

#: Process-wide request-id sequence: ids stay unique even when several
#: servers share one process and one trace file (check_trace enforces
#: per-process request_span id uniqueness).
_REQUEST_IDS = itertools.count(1)

#: Inert-row sentinel for maintained models: padded rows sit at this
#: coordinate with this core distance, so they can never be a query's
#: nearest neighbor and never attach. Fits float32 comfortably (squared
#: distances stay below f32 max), which the predict kernels rely on.
_INERT_FILL = 1e18


class _ModelHandle:
    """One served model generation: artifact + warmed predictor + batcher.

    Immutable once built — a swap builds a fresh handle and replaces the
    server's reference; it never mutates a live one.
    """

    __slots__ = ("model", "predictor", "batcher", "generation", "warmup_info")

    def __init__(self, model, predictor, batcher, generation, warmup_info):
        self.model = model
        self.predictor = predictor
        self.batcher = batcher
        self.generation = generation
        self.warmup_info = warmup_info

    @property
    def digest(self) -> str | None:
        return self.model.fingerprint.get("data")


class _Handler(BaseHTTPRequestHandler):
    server_version = "hdbscan-tpu-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs away from stderr
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _json(self, code: int, obj: dict, headers: dict | None = None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _text(self, code: int, text: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - http.server API
        route = self.path.split("?")[0]
        srv = self.server.cluster_server
        known = route in ("/healthz", "/metrics")
        t0 = time.perf_counter()
        srv._m_in_flight.inc()
        code = 500
        try:
            if route == "/healthz":
                code = 200
                self._json(code, srv.health())
            elif route == "/metrics":
                code = 200
                self._text(code, srv.render_metrics())
            else:
                code = 404
                self._json(code, {"error": f"unknown path {self.path!r}"})
        finally:
            srv._m_in_flight.dec()
            srv._observe_request(
                route if known else "other", code, time.perf_counter() - t0
            )

    def _read_payload(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length > MAX_BODY_BYTES:
            raise ValueError(f"body exceeds {MAX_BODY_BYTES} bytes")
        return json.loads(self.rfile.read(length).decode()) if length else {}

    def do_POST(self):  # noqa: N802 - http.server API
        path = self.path.split("?")[0]
        srv = self.server.cluster_server
        known = path in ("/predict", "/ingest", "/swap")
        t0 = time.perf_counter()
        srv._m_in_flight.inc()
        code = 500
        # A fleet router (or any upstream) that stamped X-Request-Id wins:
        # the replica's request_span then joins the router_span bitwise on
        # the shared id (obs/correlate.py).
        rid = self.headers.get("X-Request-Id") or srv.next_request_id()
        # meta is filled across threads (batcher worker) with the span
        # timestamps; the Future resolution inside predict/ingest is the
        # happens-before edge that publishes it back to this thread.
        meta: dict = {}
        rows = 0
        generation = int(srv.generation)
        shed_reason = None  # set when the request was load-shed (429/503)
        try:
            act = inject.maybe_fire("slow_request")
            if act is not None:
                time.sleep(act.delay_s)
            if inject.maybe_fire("http_reset") is not None:
                # Simulated socket reset: drop the connection without a
                # response. 499 (client-saw-reset) keeps the status label
                # numeric — health() folds int(status) >= 400 into errors.
                code = 499
                self.close_connection = True
                return
            try:
                payload = self._read_payload()
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                code = 400
                self._json(code, {"error": f"bad request: {e}"})
                return
            try:
                deadline = srv.request_deadline(self.headers, t0)
                if deadline is not None:
                    meta["deadline"] = deadline
                if path == "/predict":
                    points = np.asarray(payload["points"], np.float64)
                    meta["t_parse"] = time.perf_counter()
                    tenant = payload.get("tenant")
                    if tenant is not None:
                        out = srv.tenant_predict(
                            str(tenant), points,
                            bool(payload.get("membership", False)), meta=meta,
                        )
                    else:
                        out = srv.predict(
                            points, bool(payload.get("membership", False)),
                            meta=meta,
                        )
                    rows = len(out["labels"])
                elif path == "/ingest":
                    points = np.asarray(payload["points"], np.float64)
                    meta["t_parse"] = time.perf_counter()
                    out = srv.ingest(points, meta=meta)
                    rows = out["rows"]
                elif path == "/swap":
                    out = srv.swap(payload.get("path"))
                else:
                    code = 404
                    self._json(code, {"error": f"unknown path {self.path!r}"})
                    return
            except ShedRequest as e:  # bounded-queue load shedding
                code = e.status
                shed_reason = e.reason
                self._json(
                    code,
                    {"error": str(e), "reason": e.reason},
                    headers={
                        "Retry-After": f"{max(e.retry_after_s, 0.001):.3f}",
                        "X-Request-Id": rid,
                    },
                )
                return
            except DeadlineExceeded as e:  # fail fast, no batch slot spent
                code = 504
                self._json(code, {"error": str(e)}, headers={"X-Request-Id": rid})
                return
            except KeyError as e:
                code = 400
                self._json(code, {"error": f"bad request: missing {e}"})
                return
            except ValueError as e:  # shape/dim/guard mismatches: client errors
                code = 400
                self._json(code, {"error": str(e)})
                return
            except RuntimeError as e:  # mode errors (ingest off, nothing staged)
                code = 409
                self._json(code, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001 - surface, don't crash
                code = 500
                self._json(code, {"error": f"{type(e).__name__}: {e}"})
                return
            code = 200
            generation = int(out.get("generation", generation))
            self._json(code, out, headers={"X-Request-Id": rid})
        finally:
            t_end = time.perf_counter()
            srv._m_in_flight.dec()
            srv._observe_request(path if known else "other", code, t_end - t0)
            # Accounting contract (check_trace): every /predict and /ingest
            # request terminates in exactly one request_shed (load shed) or
            # one request_span (any other outcome, success or error) —
            # shed + served + failed == offered.
            if path in ("/predict", "/ingest"):
                if shed_reason is not None:
                    srv._emit_request_shed(path, rid, code, shed_reason)
                else:
                    srv._emit_request_span(
                        path, rid, rows, generation, meta,
                        t0=t0, t_end=t_end, status=code,
                    )


class ClusterServer:
    """Predictor + batcher + HTTP front, as one closeable unit.

    Construction warms every bucket (AOT), so the first real request already
    hits a compiled program; ``port=0`` binds an ephemeral port (tests).

    ``ingest=True`` turns on the streaming subsystem: ``/ingest`` routes
    arriving points through the predict path into an
    :class:`~hdbscan_tpu.stream.IngestBuffer`, a
    :class:`~hdbscan_tpu.stream.DriftDetector` watches the GLOSH-score and
    assignment-rate distributions, and a :class:`~hdbscan_tpu.stream.Refitter`
    re-fits in the background on drift or point budget, publishing
    generation-numbered artifacts under ``model_dir`` that hot-swap in
    (``stream_reload="auto"``) or stage for ``POST /swap``
    (``"manual"``). Stream knobs come from ``params``
    (:class:`~hdbscan_tpu.config.HDBSCANParams` ``stream_*`` fields).
    """

    def __init__(
        self,
        model,
        backend: str = "auto",
        max_batch: int = 256,
        linger_s: float = 0.002,
        host: str = "127.0.0.1",
        port: int = 8799,
        tracer=None,
        warmup: bool = True,
        verbose: bool = False,
        ingest: bool = False,
        params=None,
        model_dir: str | None = None,
        queue_bound: int | None = None,
        deadline_ms: float | None = None,
        wal_dir: str | None = None,
        fault_spec: str | None = None,
        tenants=None,
    ):
        self.tracer = tracer
        self._backend_req = backend
        self._max_batch = max_batch
        self._linger_s = linger_s
        self._warmup = warmup
        self._swap_lock = threading.Lock()
        self._closed = False
        self._swap_count = 0
        self.last_swap: dict | None = None
        self.pending: dict | None = None  # staged artifact (manual reload)
        # Distinguishes servers sharing one trace file: check_trace enforces
        # monotonic swap generations per (process, server).
        self._server_id = f"{os.getpid():x}.{id(self) & 0xFFFFFF:06x}"

        def knob(name, default):
            return getattr(params, name, default) if params is not None else default

        # Resilience knobs: explicit ctor args win, then params, then the
        # permissive defaults (unbounded queue, no deadline) that keep
        # embedded/test servers at the historical behavior.
        self._queue_bound = int(
            queue_bound if queue_bound is not None else knob("serve_queue_bound", 0)
        )
        self._deadline_ms = float(
            deadline_ms if deadline_ms is not None else knob("serve_deadline_ms", 0.0)
        )
        self._wal_dir = wal_dir or str(knob("stream_wal_dir", "") or "")

        # Fault harness: an explicit/config spec installs the process plan;
        # either way an already-installed plan (e.g. a chaos test's) gets
        # this server's tracer and fault counter attached.
        spec = fault_spec if fault_spec is not None else str(knob("fault_spec", "") or "")
        if not spec:
            spec = os.environ.get(inject.ENV_VAR, "").strip()
        if spec:
            inject.install(spec, tracer=tracer)

        # Metrics registry must exist before the first handle: the predictor
        # observes its batch histograms through it.
        self.metrics = MetricsRegistry()
        self._m_requests = self.metrics.counter(
            "hdbscan_tpu_requests_total",
            "HTTP requests by route and status code.",
            labelnames=("route", "status"),
        )
        self._m_in_flight = self.metrics.gauge(
            "hdbscan_tpu_requests_in_flight",
            "HTTP requests currently being handled.",
        )
        self._m_latency = self.metrics.histogram(
            "hdbscan_tpu_request_latency_seconds",
            "End-to-end HTTP request wall by route.",
            labelnames=("route",),
        )
        self._m_swaps = self.metrics.counter(
            "hdbscan_tpu_model_swaps_total",
            "Blue/green model swaps applied.",
        )
        self._m_generation = self.metrics.gauge(
            "hdbscan_tpu_model_generation",
            "Generation number of the served model handle.",
        )
        self._m_uptime = self.metrics.gauge(
            "hdbscan_tpu_uptime_seconds",
            "Seconds since server construction.",
        )
        self._m_shed = self.metrics.counter(
            "hdbscan_tpu_requests_shed_total",
            "HTTP requests refused to shed load, by route and reason.",
            labelnames=("route", "reason"),
        )
        self._m_faults = self.metrics.counter(
            "hdbscan_tpu_faults_injected_total",
            "Injected faults fired (fault harness), by site.",
            labelnames=("site",),
        )
        self._m_watchdog = self.metrics.counter(
            "hdbscan_tpu_watchdog_stalls_total",
            "Watchdog stack dumps fired (no heartbeat within watchdog_s).",
        )
        self._m_device_peak = self.metrics.gauge(
            "hdbscan_tpu_device_peak_bytes",
            "Per-device peak resident bytes across audited fit phases.",
            labelnames=("device",),
        )
        self._m_straggler = self.metrics.counter(
            "hdbscan_tpu_straggler_flags_total",
            "Straggler flags fired (device >= skew_threshold x round-median "
            "wall for straggler_rounds consecutive rounds), by device.",
            labelnames=("device",),
        )
        # Timeline/straggler layer: an installed TimelineRecorder (CLI- or
        # test-built) feeds this server's straggler counter so /metrics sees
        # slow devices; none is created here — refit/ingest paths install
        # their own when telemetry asks for it.
        tl = obs.timeline()
        if tl is not None and tl.straggler_counter is None:
            tl.straggler_counter = self._m_straggler
        # Progress/watchdog layer (``hdbscan_tpu/obs``): arm the hub when
        # config asks for a watchdog and none is installed yet (a CLI-built
        # hub keeps priority); either way the installed hub feeds this
        # server's stall counter so /metrics sees refit/fit hangs.
        hub = obs.heartbeats()
        if hub is None and float(knob("watchdog_s", 0.0)) > 0:
            hub = obs_heartbeat.Heartbeats(
                tracer=tracer,
                heartbeat_s=float(knob("heartbeat_s", 1.0)),
                watchdog_s=float(knob("watchdog_s", 0.0)),
                stall_counter=self._m_watchdog,
            )
            obs.install(heartbeats=hub)
        elif hub is not None and hub._stall_counter is None:
            hub._stall_counter = self._m_watchdog
        plan = inject.plan()
        if plan is not None:
            if plan.tracer is None and tracer is not None:
                plan.tracer = tracer
            plan.add_on_fire(self._on_fault_fire)

        self._handle = self._build_handle(model, generation=1)
        self._m_generation.set(1.0)

        # Multi-tenant registry (``fleet/tenants.py``): a directory path
        # builds one over its artifacts with this server's metrics/tracer
        # attached; a prebuilt TenantRegistry is used as-is. None keeps the
        # single-model behavior (a request with a tenant field gets 409).
        self.tenants = None
        if tenants is not None:
            if isinstance(tenants, str):
                from hdbscan_tpu.fleet.tenants import TenantRegistry

                # Per-host zero-copy artifact store: with the knob on,
                # tenant artifacts map through the digest-keyed spool
                # (fleet/artifacts.py) shared by every replica on the host.
                store = None
                if str(knob("fleet_artifact_store", "off")) == "shared":
                    from hdbscan_tpu.fleet.artifacts import default_store

                    store = default_store(tracer=tracer, metrics=self.metrics)
                self.tenants = TenantRegistry.from_dir(
                    tenants,
                    backend=self._backend_req,
                    max_batch=self._max_batch,
                    lru_size=int(knob("tenant_lru_size", 8)),
                    quota_rps=float(knob("tenant_quota_rps", 0.0)),
                    metrics=self.metrics,
                    tracer=tracer,
                    artifact_store=store,
                )
            else:
                self.tenants = tenants

        self.ingest_enabled = bool(ingest)
        self._params = params
        if self.ingest_enabled:
            self._init_stream(params, model_dir)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.cluster_server = self
        self._httpd.verbose = verbose
        self.host, self.port = self._httpd.server_address[:2]
        self._t0 = time.monotonic()
        self._thread: threading.Thread | None = None
        self._serving = False  # a serve_forever loop is (or was) running

    # -- fault wiring ------------------------------------------------------

    def _on_fault_fire(self, site: str, spec, nth: int) -> None:
        """Fault-plan hook: count every injected fault so /metrics accounts
        for each one the harness fires."""
        self._m_faults.inc(site=site)

    def _on_circuit_state(self, name: str, state: str) -> None:
        self._m_circuit.set(float(CIRCUIT_STATE_VALUES[state]), name=name)

    def _on_refit_result(self, ok: bool, error: str | None) -> None:
        """Refitter outcome hook → the refit circuit breaker."""
        if ok:
            self._refit_circuit.record_success()
        else:
            self._refit_circuit.record_failure()

    # -- stream wiring -----------------------------------------------------

    def _init_stream(self, params, model_dir) -> None:
        from hdbscan_tpu.stream import (
            DriftDetector,
            IngestBuffer,
            Refitter,
            StreamJournal,
        )

        def knob(name, default):
            return getattr(params, name, default) if params is not None else default

        self.reload_mode = knob("stream_reload", "auto")
        self._refit_budget = int(knob("stream_refit_budget", 2048))
        self._absorb_frac = float(knob("stream_absorb_eps_frac", 0.25))
        self._drift_stat = knob("stream_drift_stat", "psi")
        self._drift_threshold = float(knob("stream_drift_threshold", 2.0))
        self.model_dir = model_dir or "stream_models"
        self._ingest_lock = threading.Lock()
        self._m_drift_checks = self.metrics.counter(
            "hdbscan_tpu_drift_checks_total", "Drift detector checks run."
        )
        self._m_drift_flags = self.metrics.counter(
            "hdbscan_tpu_drift_flags_total", "Drift checks that flagged shift."
        )
        self._m_refit_kicks = self.metrics.counter(
            "hdbscan_tpu_refit_kicks_total",
            "Background re-fits kicked from the ingest path, by trigger.",
            labelnames=("trigger",),
        )
        self.buffer = IngestBuffer(
            self.model, absorb_eps_frac=self._absorb_frac, metrics=self.metrics
        )
        self.drift = DriftDetector(
            *DriftDetector.baseline_from_model(self.model, self._handle.predictor),
            stat=self._drift_stat,
            threshold=self._drift_threshold,
            tracer=self.tracer,
        )
        # Refit circuit breaker: repeated fit/swap failures trip it open and
        # the server degrades to serving the pinned generation — no refit
        # kicks until reset_s has elapsed (state in /healthz + /metrics).
        self._m_circuit = self.metrics.gauge(
            "hdbscan_tpu_circuit_state",
            "Circuit breaker state (0 closed, 1 half-open, 2 open).",
            labelnames=("name",),
        )
        self._m_circuit.set(0.0, name="refit")
        self._refit_circuit = CircuitBreaker(
            "refit",
            failures=int(knob("circuit_failures", 3)),
            reset_s=float(knob("circuit_reset_s", 30.0)),
            tracer=self.tracer,
            on_state=self._on_circuit_state,
        )
        refit_params = self._refit_params(params)
        self.refitter = Refitter(
            refit_params,
            self.model_dir,
            tracer=self.tracer,
            on_publish=self._on_publish,
            metrics=self.metrics,
            on_result=self._on_refit_result,
        )
        # Crash-safe durability: recover buffer/drift state from the WAL
        # directory (if it belongs to this model's digest), then keep
        # journaling every accepted ingest batch.
        self.journal = None
        wal_info = None
        if self._wal_dir:
            self.journal = StreamJournal(
                self._wal_dir,
                snapshot_every=int(knob("stream_snapshot_every", 64)),
                tracer=self.tracer,
                metrics=self.metrics,
            )
            wal_info = self.journal.open(
                str(self.model.fingerprint.get("data") or ""),
                self.buffer,
                self.drift,
            )
        # Incremental hierarchy maintenance (``stream_maintain=incremental``):
        # novel rows fold into an online MST + dirty-subtree finalize instead
        # of waiting for a full re-fit; the re-fit path demotes to the
        # fallback ladder (drift / maintainer failure / circuit breaker).
        self.maintain_mode = str(knob("stream_maintain", "off"))
        self._maintain_budget_ms = float(knob("maintain_budget_ms", 0.0))
        self._maintain_dirty_frac = float(knob("maintain_dirty_max_frac", 1.0))
        self._maintain_refresh = int(knob("maintain_refresh_every", 64))
        self.maintainer = None
        self._finalizer = None
        self.maintain_refreshes = 0
        self.maintain_fallbacks = 0
        self.maintain_last_error: str | None = None
        if self.maintain_mode == "incremental":
            self._init_maintainer(wal_info)

    def _refit_params(self, params):
        """Re-fit params: caller's knobs where given, but the fingerprint
        fields pinned to the served model's so the swap guard passes."""
        from hdbscan_tpu.config import HDBSCANParams

        base = params if params is not None else HDBSCANParams()
        return base.replace(**dict(self.model.params))

    # -- incremental maintenance -------------------------------------------

    def _init_maintainer(self, wal_info=None) -> None:
        """Bootstrap the online hierarchy maintainer from the served model
        (O(n² d) host k-NN + Prim: artifacts store no MST — documented
        residual of ROADMAP item 3), then replay any WAL-recovered novel
        rows through the deterministic maintenance fold and verify the
        persisted watermark digests. Any failure demotes to the re-fit
        ladder instead of raising into server construction."""
        from hdbscan_tpu.incremental import (
            DirtySubtreeFinalizer,
            HierarchyMaintainer,
            MaintainFallback,
        )

        model = self._handle.model
        try:
            self.maintainer = HierarchyMaintainer(
                model.data,
                min_pts=int(model.params.get("min_points", 2)),
                metric=str(model.params.get("dist_function", "euclidean")),
                rpf=model.rpf,
                budget_ms=self._maintain_budget_ms,
                dirty_max_frac=self._maintain_dirty_frac,
                refresh_every=self._maintain_refresh,
                tracer=self.tracer,
                metrics=self.metrics,
                name=self._server_id,
            )
            self._finalizer = DirtySubtreeFinalizer(
                self._refit_params(self._params),
                dirty_max_frac=self._maintain_dirty_frac,
                tracer=self.tracer,
                name=self._server_id,
            )
        except Exception as exc:
            self._maintain_disable(f"bootstrap: {type(exc).__name__}: {exc}")
            return
        # WAL recovery: maintainer state is never journaled as events — it
        # is a deterministic fold over the buffer's novel chunks, which
        # ``journal.open()`` above just replayed (stream/wal.py docstring).
        # Re-run the fold; the snapshot's "maintain" watermark (journal +
        # MST sha256) must reproduce bitwise at the recorded insert count,
        # else the maintainer stands down rather than serve a silently
        # diverged hierarchy.
        watermark = (wal_info or {}).get("maintain") or None
        verify = None
        if watermark and int(watermark.get("inserts", 0)) > 0:
            verify = (int(watermark["inserts"]), watermark)
        try:
            for chunk in self.buffer.novel_chunks():
                self.maintainer.rebuild(chunk, verify_at=verify)
        except (MaintainFallback, Exception) as exc:
            self._maintain_disable(f"recovery: {type(exc).__name__}: {exc}")

    def _maintain_disable(self, error) -> None:
        """Demote the stream to the re-fit ladder: drop the maintainer (the
        ``budget`` trigger un-suppresses on the next ingest), record and
        trace the demotion. Caller decides whether to kick a re-fit."""
        m, self.maintainer, self._finalizer = self.maintainer, None, None
        self.maintain_fallbacks += 1
        self.maintain_last_error = str(error)
        if m is not None:
            m._count("fallback")
        if self.tracer is not None:
            self.tracer(
                "maintain_fallback",
                maintainer=self._server_id,
                generation=int(self._handle.generation),
                n=int(m.n) if m is not None else 0,
                inserts=int(m.inserts) if m is not None else 0,
                error=str(error),
            )

    def _maintain_batch(self, chunk_start: int):
        """Fold this batch's novel rows into the maintainer (caller holds
        ``_ingest_lock``). Per row: bounded insert; when the splice cadence
        fires, MST splice + dirty-subtree finalize + maintained-model build
        — but NOT the handle swap, which needs ``_swap_lock`` and is done
        by the caller after releasing the ingest lock (lock order:
        ``swap_model`` takes swap → ingest, so never the reverse).

        The per-row cadence check mirrors ``HierarchyMaintainer.rebuild``
        exactly — live fold and WAL recovery fold are the same function of
        the novel-row sequence, which is what makes the snapshot watermark
        verifiable bitwise.

        Returns ``(stats_dict, maintained_model_or_None)``; a failure
        demotes via :meth:`_maintain_disable` and reports ``fallback`` in
        the stats — ingest itself never fails on maintenance."""
        from hdbscan_tpu.incremental import MaintainFallback

        m = self.maintainer
        inserted = spliced = 0
        over_budget = False
        new_model = None
        try:
            for idx in range(chunk_start, self.buffer.novel_chunk_count):
                for row in self.buffer.novel_chunk(idx):
                    info = m.insert(row)
                    inserted += 1
                    over_budget = over_budget or info["over_budget"]
                    if m._since_splice >= m.refresh_every:
                        m.splice()
                        spliced += 1
            if spliced:
                with obs.task("stream_maintain", total=1) as t:
                    lo, hi, w = m.mst_arrays()
                    tree, labels, _scores, _inf = self._finalizer.finalize(
                        m.n, lo, hi, w, m.core[: m.n]
                    )
                    new_model = self._maintained_model(tree, labels)
                    t.beat(1)
        except (MaintainFallback, Exception) as exc:
            self._maintain_disable(f"{type(exc).__name__}: {exc}")
            return (
                {"inserted": inserted, "spliced": spliced, "fallback": True},
                None,
            )
        return (
            {
                "inserted": inserted,
                "spliced": spliced,
                "over_budget": over_budget,
                "fallback": False,
            },
            new_model,
        )

    def _maintained_model(self, tree, labels):
        """Serving artifact for the maintained hierarchy, shape-padded.

        Rows pad to the maintainer's power-of-two capacity with inert
        sentinels (coordinates and core at 1e18 — never the nearest
        neighbor, never attach) so the predictor's train-side shapes stay
        CONSTANT across maintenance refreshes: the module-level jit cache
        hits and the handle rebuild costs no AOT re-warm until the
        capacity actually doubles. ``rpf=None``: the stored planes only
        index the bootstrap rows, so the padded model serves through the
        exhaustive backend (plane refresh is a ROADMAP 3 residual)."""
        from hdbscan_tpu.models._finalize import serving_tables
        from hdbscan_tpu.utils.checkpoint import _data_digest

        m = self.maintainer
        base = self._handle.model
        n, cap = m.n, m._cap
        labels = np.asarray(labels, np.int64)
        tables = serving_tables(tree, labels)
        data = np.full((cap, m.dims), _INERT_FILL, np.float64)
        data[:n] = m.data[:n]
        core = np.full(cap, _INERT_FILL, np.float64)
        core[:n] = m.core[:n]
        lab = np.zeros(cap, np.int64)
        lab[:n] = labels
        last = np.zeros(cap, np.int64)
        last[:n] = np.asarray(tree.point_last_cluster, np.int64)
        fingerprint = dict(base.fingerprint)
        fingerprint["n"] = int(cap)
        fingerprint["data"] = _data_digest(data)
        return ClusterModel(
            mode=base.mode,
            params=dict(base.params),
            fingerprint=fingerprint,
            data=data,
            core=core,
            labels=lab,
            last_cluster=last,
            parent=np.asarray(tree.parent, np.int64),
            birth=np.asarray(tree.birth, np.float64),
            selected=np.asarray(tree.selected, bool),
            sel_anc=np.asarray(tables["sel_anc"], np.int64),
            eps_min=np.asarray(tables["eps_min"], np.float64),
            eps_max=np.asarray(tables["eps_max"], np.float64),
            rpf=None,
        )

    def maintain_stats(self) -> dict:
        """Maintenance block of ``/healthz``'s stream dict."""
        out = {
            "mode": self.maintain_mode,
            "active": self.maintainer is not None,
            "refreshes": int(self.maintain_refreshes),
            "fallbacks": int(self.maintain_fallbacks),
            "last_error": self.maintain_last_error,
        }
        if self.maintainer is not None:
            m = self.maintainer
            out.update(
                n=int(m.n),
                inserts=int(m.inserts),
                splices=int(m.splices),
                pending_edges=int(m.pending_edges),
                over_budget=int(m.over_budget),
            )
        return out

    # -- handles -----------------------------------------------------------

    def _build_handle(self, model, generation: int) -> _ModelHandle:
        backend = self._backend_req
        if backend == "rpforest" and model.rpf is None:
            backend = "auto"  # re-fit artifacts ship without a forest
        predictor = Predictor(
            model, backend=backend, max_batch=self._max_batch,
            tracer=self.tracer, metrics=self.metrics,
        )
        warmup_info = predictor.warmup() if self._warmup else None
        batcher = MicroBatcher(
            predictor, linger_s=self._linger_s, max_queue=self._queue_bound
        )
        return _ModelHandle(model, predictor, batcher, generation, warmup_info)

    def _install_handle(self, new_model, reason: str) -> tuple:
        """Blue/green core shared by :meth:`swap_model` and the maintained
        handle refresh: build + warm the new handle on the old model's
        watch, swap under ``_swap_lock`` (one reference assignment),
        account the swap, emit the ``model_swap`` trace, drain-close the
        old batcher. Returns ``(new_handle, pause_s)``."""
        new_handle = self._build_handle(new_model, generation=0)  # warm first
        with self._swap_lock:
            if self._closed:
                raise RuntimeError("server is closed")
            old = self._handle
            new_handle.generation = old.generation + 1
            t0 = time.perf_counter()
            self._handle = new_handle  # the swap: one reference assignment
            pause_s = time.perf_counter() - t0
            self._swap_count += 1
        self._m_swaps.inc()
        self._m_generation.set(float(new_handle.generation))
        if self.tracer is not None:
            self.tracer(
                "model_swap",
                generation=int(new_handle.generation),
                digest=str(new_handle.digest),
                n_train=int(new_model.n_train),
                reason=str(reason),
                server=self._server_id,
                pause_s=round(pause_s, 9),
                wall_s=round(pause_s, 9),
            )
        old.batcher.close()  # graceful: every in-flight future completes
        return new_handle, pause_s

    def _publish_maintained(self, new_model) -> None:
        """Handle refresh for a maintained model — NOT a swap: buffer,
        drift sketches and journal keep their state, because the WAL
        replay base is the bootstrap model plus the grow-only novel-chunk
        log. Called with no locks held; the padded shapes make the
        predictor rebuild hit the warm jit cache (no AOT re-warm)."""
        try:
            self._install_handle(new_model, reason="maintain")
        except Exception as exc:
            self._maintain_disable(f"publish: {type(exc).__name__}: {exc}")
        else:
            self.maintain_refreshes += 1
            if self.maintainer is not None:
                self.maintainer._count("refresh")

    @property
    def model(self):
        return self._handle.model

    @property
    def predictor(self):
        return self._handle.predictor

    @property
    def batcher(self):
        return self._handle.batcher

    @property
    def generation(self) -> int:
        return self._handle.generation

    @property
    def warmup_info(self):
        return self._handle.warmup_info

    # -- request paths -----------------------------------------------------

    def next_request_id(self) -> str:
        """Process-unique request id (pid + process-wide sequence)."""
        return f"{os.getpid()}-{next(_REQUEST_IDS)}"

    def _observe_request(self, route: str, status: int, wall: float) -> None:
        self._m_requests.inc(route=route, status=str(status))
        self._m_latency.observe(wall, route=route)

    def request_deadline(self, headers, t0: float) -> float | None:
        """Resolve the request's deadline (a perf_counter instant) from the
        ``X-Deadline-Ms`` header, falling back to the server-wide
        ``serve_deadline_ms`` default; None when neither applies."""
        raw = headers.get("X-Deadline-Ms")
        if raw is not None:
            try:
                ms = float(raw)
            except ValueError:
                raise ValueError(f"bad X-Deadline-Ms header: {raw!r}") from None
            if ms <= 0:
                raise ValueError(f"X-Deadline-Ms must be > 0, got {raw!r}")
            return t0 + ms / 1000.0
        if self._deadline_ms > 0:
            return t0 + self._deadline_ms / 1000.0
        return None

    def _emit_request_shed(self, route, rid, status, reason) -> None:
        """Account one load-shed request: counter always, trace when a
        tracer is attached (``request_shed`` — check_trace counts these
        against request_span ids so shed+served+failed == offered)."""
        self._m_shed.inc(route=route, reason=str(reason))
        if self.tracer is not None:
            self.tracer(
                "request_shed",
                request_id=rid,
                route=route,
                status=int(status),
                reason=str(reason),
            )

    def _emit_request_span(
        self, route, rid, rows, generation, meta, t0, t_end, status=200
    ) -> None:
        """Emit one ``request_span`` trace event for a terminated
        ``/predict``/``/ingest`` request — successes and errors alike
        (``status`` carries the HTTP code, so error latency is visible in
        the trace). The five segments are contiguous perf_counter diffs
        (clamped monotone into [t0, t_end]) so they telescope exactly to
        the span wall; 9-decimal rounding keeps the telescoped sum inside
        check_trace's 1e-6 tolerance, which 6 decimals would not."""
        if self.tracer is None:
            return
        t_parse = min(max(t0, meta.get("t_parse", t0)), t_end)
        t_asm = min(max(t_parse, meta.get("t_assembled", t_parse)), t_end)
        t_disp = min(max(t_asm, meta.get("t_dispatch", t_asm)), t_end)
        t_done = min(max(t_disp, meta.get("t_done", t_disp)), t_end)
        bucket = meta.get("bucket")
        if not bucket:  # defensive: never emit a non-pow2 bucket
            pred = self._handle.predictor
            bucket = pred.bucket_for(min(max(int(rows), 1), pred.max_bucket))
        self.tracer(
            "request_span",
            request_id=rid,
            route=route,
            status=int(status),
            rows=int(rows),
            bucket=int(bucket),
            coalesced=int(meta.get("coalesced", 1)),
            generation=int(generation),
            parse_s=round(t_parse - t0, 9),
            queue_s=round(t_asm - t_parse, 9),
            assemble_s=round(t_disp - t_asm, 9),
            predict_s=round(t_done - t_disp, 9),
            respond_s=round(t_end - t_done, 9),
            wall_s=round(t_end - t0, 9),
        )

    def predict(
        self, points: np.ndarray, membership: bool = False,
        meta: dict | None = None,
    ) -> dict:
        for _ in range(_PIN_RETRIES):
            handle = self._handle  # pin: this request never mixes models
            try:
                return self._predict_on(handle, points, membership, meta)
            except RuntimeError as e:
                # The pinned handle's batcher closed under us (swap landed
                # between the pin and the submit) — re-pin and retry; no
                # request is dropped across a swap. (The retry's dispatch
                # overwrites the meta timestamps, so a span still describes
                # the attempt that actually served the rows.)
                if "closed" not in str(e) or self._closed:
                    raise
        raise RuntimeError("predict retries exhausted during model swaps")

    def _predict_on(
        self, handle: _ModelHandle, points, membership: bool,
        meta: dict | None = None,
    ) -> dict:
        if membership:
            # Membership needs the 4-output kernel variant; it bypasses the
            # batcher and relies on the predictor's internal dispatch lock —
            # no queue wait and no coalescing, so the span meta collapses
            # queue/assemble to zero-width here.
            if meta is not None:
                t = time.perf_counter()
                meta["t_assembled"] = meta["t_dispatch"] = t
            labels, prob, score, mvec = handle.predictor.predict(
                points, with_membership=True
            )
            if meta is not None:
                meta["t_done"] = time.perf_counter()
                meta["coalesced"] = 1
                meta["bucket"] = handle.predictor.bucket_for(
                    min(len(labels), handle.predictor.max_bucket)
                )
            return {
                "labels": labels.tolist(),
                "probabilities": [round(p, 6) for p in prob.tolist()],
                "outlier_scores": [round(s, 6) for s in score.tolist()],
                "membership": np.round(mvec, 6).tolist(),
                "selected_ids": handle.model.selected_ids.tolist(),
                "generation": handle.generation,
            }
        labels, prob, score = handle.batcher.predict(points, meta=meta)
        return {
            "labels": labels.tolist(),
            "probabilities": [round(p, 6) for p in prob.tolist()],
            "outlier_scores": [round(s, 6) for s in score.tolist()],
            "generation": handle.generation,
        }

    def tenant_predict(
        self, tenant: str, points, membership: bool = False,
        meta: dict | None = None,
    ) -> dict:
        """Predict against one tenant's model via the registry: quota check
        (429 ShedRequest on exceed), LRU touch, load + AOT warmup on a cold
        tenant. Bypasses the micro-batcher like the membership path — the
        tenant predictor's internal dispatch lock serializes, so the span
        meta collapses queue/assemble to zero-width."""
        if self.tenants is None:
            raise RuntimeError(
                "server started without a tenant registry (--tenants-dir)"
            )
        if meta is not None:
            t = time.perf_counter()
            meta["t_assembled"] = meta["t_dispatch"] = t
        out, info = self.tenants.predict(
            tenant, points, with_membership=membership
        )
        if meta is not None:
            meta["t_done"] = time.perf_counter()
            meta["coalesced"] = 1
            meta["bucket"] = info["bucket"]
        labels, prob, score = out[:3]
        resp = {
            "labels": labels.tolist(),
            "probabilities": [round(p, 6) for p in prob.tolist()],
            "outlier_scores": [round(s, 6) for s in score.tolist()],
            "tenant": info["tenant"],
            "generation": info["generation"],
        }
        if membership:
            resp["membership"] = np.round(out[3], 6).tolist()
            resp["selected_ids"] = info["selected_ids"]
        return resp

    def ingest(self, points: np.ndarray, meta: dict | None = None) -> dict:
        """Streaming entry: predict → absorb/buffer → drift check → maybe
        kick a background re-fit. Returns per-batch routing + drift info."""
        if not self.ingest_enabled:
            raise RuntimeError("server started without ingest mode")
        t0 = time.perf_counter()
        points = np.asarray(points, np.float64)
        if points.ndim == 1:
            points = points[None, :]
        scored = False
        for _ in range(_PIN_RETRIES):
            handle = self._handle
            try:
                labels, prob, score = handle.batcher.predict(points, meta=meta)
            except RuntimeError as e:
                if "closed" not in str(e) or self._closed:
                    raise
                continue
            scored = True
            if handle is self._handle:
                break
            # A swap landed mid-predict: the buffer/drift state now keys to
            # the new model, so this batch's scores are stale — redo on the
            # current handle rather than polluting the fresh sketches.
        if not scored:
            raise RuntimeError("ingest retries exhausted during model swaps")
        maintained = None
        new_model = None
        with self._ingest_lock:
            chunk_start = (
                self.buffer.novel_chunk_count
                if self.maintainer is not None else 0
            )
            absorbed, buffered = self.buffer.absorb(points, labels, prob)
            self.drift.update(labels, score)
            if self.maintainer is not None:
                maintained, new_model = self._maintain_batch(chunk_start)
            if self.journal is not None:
                # Write-ahead relative to the HTTP ack: the batch (with its
                # predicted labels/prob/scores, so replay never re-predicts)
                # is fsync'd before the 200 goes out. The maintain watermark
                # captures the state AFTER this batch's fold, so recovery
                # verifies its replay at exactly this insert count.
                self.journal.append_ingest(points, labels, prob, score)
                self.journal.maybe_snapshot(
                    self.buffer,
                    self.drift,
                    maintain=(
                        self.maintainer.state_dict()
                        if self.maintainer is not None else None
                    ),
                )
            check = self.drift.check(generation=handle.generation)
            self._m_drift_checks.inc()
            if check["drifted"]:
                self._m_drift_flags.inc()
            trigger = None
            if maintained is not None and maintained["fallback"]:
                trigger = "maintain_fallback"
            elif check["drifted"]:
                trigger = "drift"
            elif (
                self.maintainer is None
                and self.buffer.buffered_rows >= self._refit_budget
            ):
                # An active maintainer suppresses the point-budget trigger:
                # novel rows are already folded into the served hierarchy,
                # so the full re-fit is reserved for drift and the fallback
                # ladder (maintain_fallback / circuit breaker).
                trigger = "budget"
            refit_started = False
            # Circuit gate: after repeated refit/swap failures the breaker
            # is open and triggers are suppressed — the server degrades to
            # serving the pinned generation instead of burning fit cycles.
            if (
                trigger
                and self.pending is None
                and not self.refitter.busy
                and self._refit_circuit.allow()
            ):
                pool = self.buffer.refit_points(
                    originals=min(self.model.n_train, 8192)
                )
                refit_started = self.refitter.request(pool, trigger)
                if refit_started:
                    self._m_refit_kicks.inc(trigger=trigger)
        if new_model is not None:
            # Outside the ingest lock by necessity: the handle refresh takes
            # _swap_lock, and swap_model's order is swap → ingest.
            self._publish_maintained(new_model)
        if self.tracer is not None:
            self.tracer(
                "stream_ingest",
                rows=int(len(points)),
                absorbed=int(absorbed),
                buffered=int(buffered),
                generation=int(handle.generation),
                wall_s=round(time.perf_counter() - t0, 6),
            )
        out = {
            "rows": int(len(points)),
            "absorbed": int(absorbed),
            "buffered": int(buffered),
            "generation": int(self._handle.generation),
            "drift": check,
            "refit_started": bool(refit_started),
        }
        if maintained is not None:
            out["maintained"] = maintained
        return out

    # -- blue/green swap ---------------------------------------------------

    def _on_publish(self, path: str, model, reason: str) -> None:
        """Refitter callback (worker thread): hot-swap, or stage for
        ``POST /swap`` in manual reload mode."""
        staged = {"path": path, "reason": reason, "n_train": int(model.n_train)}
        if getattr(self, "reload_mode", "auto") == "manual":
            self.pending = staged
            return
        try:
            self.swap_model(model, reason=reason, path=path)
        except Exception as exc:  # guard failure: keep serving the old model
            self.last_swap = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            self._refit_circuit.record_failure()

    def swap(self, path: str | None = None) -> dict:
        """HTTP-facing swap: explicit artifact ``path``, else the staged
        re-fit publication."""
        if path is None:
            if self.pending is None:
                raise RuntimeError("no staged artifact to swap in")
            path = self.pending["path"]
        return self.swap_model(path, reason="manual")

    def swap_model(self, model_or_path, reason: str = "manual",
                   path: str | None = None) -> dict:
        """Atomically replace the served model (blue/green).

        Accepts a :class:`ClusterModel` or an artifact path. Path loads run
        the artifact's schema + sha256 digest checks (``ClusterModel.load``
        refuses corrupt or mismatched files); either way the fingerprint
        fields must match the served model — a swap may change the data, not
        the clustering contract. The expensive part (predictor build +
        warmup) happens on the old model's watch; the swap itself is one
        reference assignment under the lock, and in-flight requests finish
        on the handle they pinned. Old batcher drains afterwards.
        """
        if isinstance(model_or_path, (str, os.PathLike)):
            path = str(model_or_path)
            # Schema + digest guard; transient IO faults retry with backoff
            # (permanent refusals — corrupt digest, fingerprint mismatch —
            # raise ValueError and are not retried).
            new_model = retry_call(
                lambda: ClusterModel.load(path),
                attempts=3, base_s=0.05, cap_s=0.5, seed=0,
                retry_on=(OSError, inject.InjectedFault),
                tracer=self.tracer, name="artifact_load",
            )
        else:
            new_model = model_or_path
        old_model = self._handle.model
        for f in _FINGERPRINT_FIELDS:
            if new_model.params.get(f) != old_model.params.get(f):
                raise ValueError(
                    f"swap fingerprint mismatch on {f!r}: incoming "
                    f"{new_model.params.get(f)!r} != served "
                    f"{old_model.params.get(f)!r} — refusing to swap"
                )
        new_handle, pause_s = self._install_handle(new_model, reason)
        if self.ingest_enabled:
            with self._ingest_lock:
                self.buffer.reset(new_model)
                self.drift.rebaseline(
                    *type(self.drift).baseline_from_model(
                        new_model, new_handle.predictor
                    )
                )
                self.pending = None
                if self.journal is not None:
                    # The old generation's stream state was consumed by the
                    # refit; re-key the journal to the new digest.
                    self.journal.restart(str(new_handle.digest or ""))
                if self.maintain_mode == "incremental":
                    # A real swap resets the maintenance fold's base: the
                    # old maintainer's bootstrap model and novel log were
                    # consumed by the re-fit. Re-bootstrap over the new fit
                    # (O(n² d) host pass) under the ingest lock so no batch
                    # folds into a stale maintainer meanwhile.
                    self.maintainer = self._finalizer = None
                    self._init_maintainer()
        info = {
            "ok": True,
            "generation": int(new_handle.generation),
            "n_train": int(new_model.n_train),
            "digest": str(new_handle.digest),
            "reason": str(reason),
            "path": path,
            "pause_s": round(pause_s, 9),
        }
        self.last_swap = info
        return info

    # -- health / metrics --------------------------------------------------

    def render_metrics(self) -> str:
        """Prometheus text exposition for ``GET /metrics``. Live-state
        gauges (uptime, served generation) refresh at scrape time; all
        counters and histograms accumulate at their event sites."""
        self._m_uptime.set(round(time.monotonic() - self._t0, 3))
        self._m_generation.set(float(self._handle.generation))
        aud = obs.auditor()
        if aud is not None:
            for dev, peak in aud.device_peaks().items():
                self._m_device_peak.set(float(peak), device=dev)
        return self.metrics.render()

    def health(self) -> dict:
        handle = self._handle
        # Per-route request/error counts + current in-flight, snapshotted
        # from the metrics registry (the /metrics counters, folded over
        # status: >= 400 counts as an error).
        requests: dict = {}
        for labels, value in self._m_requests.samples():
            row = requests.setdefault(
                labels["route"], {"requests": 0, "errors": 0}
            )
            row["requests"] += int(value)
            if int(labels["status"]) >= 400:
                row["errors"] += int(value)
        out = {
            "status": "ok",
            "model": handle.model.summary(),
            "backend": handle.predictor.backend,
            "buckets": list(handle.predictor.buckets),
            "warmup": handle.warmup_info,
            "batcher": handle.batcher.stats,
            "generation": handle.generation,
            "swaps": self._swap_count,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "requests": requests,
            "in_flight": int(self._m_in_flight.value()),
        }
        if self.last_swap is not None:
            out["last_swap"] = self.last_swap
        wd = obs.watchdog_state()
        if wd is not None:
            out["watchdog"] = wd
        sg = obs.straggler_state()
        if sg is not None:
            out["straggler"] = sg
        if self.ingest_enabled:
            stats = self.buffer.stats()
            out["stream"] = {
                "rows_seen": stats["rows_seen"],
                "absorbed_exact": stats["absorbed_exact"],
                "absorbed_near": stats["absorbed_near"],
                "buffered": stats["buffered"],
                "bubbles": len(stats["bubbles"]),
                "drift_rows": self.drift.rows,
                "drift_checks": self.drift.checks,
                "refits_ok": self.refitter.refits_ok,
                "refits_failed": self.refitter.refits_failed,
                "refit_busy": self.refitter.busy,
                "refit_last_error": self.refitter.last_error,
                "refit_last_error_at": self.refitter.last_error_at,
                "refit_backoff_s": round(self.refitter.backoff_remaining_s(), 3),
                "circuit": self._refit_circuit.state_info(),
                "reload": self.reload_mode,
                "pending": self.pending,
                "maintain": self.maintain_stats(),
            }
            if self.journal is not None:
                out["stream"]["wal"] = self.journal.stats()
        return out

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ClusterServer":
        """Serve on a daemon thread (tests / embedding); returns self."""
        self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="predict-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Serve on the calling thread until interrupted (CLI path).
        ``SIGTERM`` triggers the same graceful drain as ``close()``."""
        try:
            signal.signal(
                signal.SIGTERM,
                lambda *_: threading.Thread(
                    target=self.close, name="sigterm-close"
                ).start(),
            )
        except ValueError:
            pass  # not the main thread (embedded) — close() still works
        try:
            self._serving = True
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.close()

    def close(self) -> None:
        """Graceful shutdown: stop accepting, finish in-flight requests
        (batcher drain resolves every accepted future), then release."""
        with self._swap_lock:
            if self._closed:
                return
            self._closed = True
        if self._serving:  # shutdown() blocks unless a serve loop is live
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._handle.batcher.close()
        if self.ingest_enabled:
            self.refitter.join(timeout=0.5)  # daemon thread; don't block long
            if self.journal is not None:
                self.journal.close()

    def __enter__(self) -> "ClusterServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
