"""Stdlib HTTP inference server over a fitted ClusterModel.

Endpoints:

- ``POST /predict`` — body ``{"points": [[...], ...]}`` (optionally
  ``"membership": true``); responds ``{"labels", "probabilities",
  "outlier_scores"}`` (plus ``"membership"`` + ``"selected_ids"`` when
  requested). Plain predicts route through the
  :class:`~hdbscan_tpu.serve.batcher.MicroBatcher`, so concurrent clients
  coalesce into shared bucket dispatches.
- ``GET /healthz`` — model summary, backend, warmed buckets, batcher
  coalescing stats, uptime.

``http.server.ThreadingHTTPServer`` only — no new dependencies; the device
is still single-dispatcher because every handler thread funnels into the
batcher's worker (or the predictor's internal lock for membership calls).
Latency observability comes from the ``predict_batch`` trace events the
predictor emits; the CLI ``serve`` command turns those into p50/p95/p99 in
the run report (``utils/telemetry.predict_latency_section``).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from hdbscan_tpu.serve.batcher import MicroBatcher
from hdbscan_tpu.serve.predict import Predictor

#: Refuse request bodies above this size (64 MiB ~ a 1M x 8-dim f64 batch);
#: a streaming client should chunk instead of shipping one giant body.
MAX_BODY_BYTES = 64 << 20


class _Handler(BaseHTTPRequestHandler):
    server_version = "hdbscan-tpu-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs away from stderr
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.split("?")[0] != "/healthz":
            self._json(404, {"error": f"unknown path {self.path!r}"})
            return
        self._json(200, self.server.cluster_server.health())

    def do_POST(self):  # noqa: N802 - http.server API
        if self.path.split("?")[0] != "/predict":
            self._json(404, {"error": f"unknown path {self.path!r}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length > MAX_BODY_BYTES:
                self._json(413, {"error": f"body exceeds {MAX_BODY_BYTES} bytes"})
                return
            payload = json.loads(self.rfile.read(length).decode())
            points = np.asarray(payload["points"], np.float64)
            membership = bool(payload.get("membership", False))
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            self._json(400, {"error": f"bad request: {e}"})
            return
        try:
            out = self.server.cluster_server.predict(points, membership)
        except ValueError as e:  # shape/dim mismatches are client errors
            self._json(400, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 - surface, don't crash the server
            self._json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._json(200, out)


class ClusterServer:
    """Predictor + batcher + HTTP front, as one closeable unit.

    Construction warms every bucket (AOT), so the first real request already
    hits a compiled program; ``port=0`` binds an ephemeral port (tests).
    """

    def __init__(
        self,
        model,
        backend: str = "auto",
        max_batch: int = 256,
        linger_s: float = 0.002,
        host: str = "127.0.0.1",
        port: int = 8799,
        tracer=None,
        warmup: bool = True,
        verbose: bool = False,
    ):
        self.model = model
        self.predictor = Predictor(
            model, backend=backend, max_batch=max_batch, tracer=tracer
        )
        self.warmup_info = self.predictor.warmup() if warmup else None
        self.batcher = MicroBatcher(self.predictor, linger_s=linger_s)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.cluster_server = self
        self._httpd.verbose = verbose
        self.host, self.port = self._httpd.server_address[:2]
        self._t0 = time.monotonic()
        self._thread: threading.Thread | None = None
        self._closed = False

    # -- request paths -----------------------------------------------------

    def predict(self, points: np.ndarray, membership: bool = False) -> dict:
        if membership:
            # Membership needs the 4-output kernel variant; it bypasses the
            # batcher and relies on the predictor's internal dispatch lock.
            labels, prob, score, mvec = self.predictor.predict(
                points, with_membership=True
            )
            return {
                "labels": labels.tolist(),
                "probabilities": [round(p, 6) for p in prob.tolist()],
                "outlier_scores": [round(s, 6) for s in score.tolist()],
                "membership": np.round(mvec, 6).tolist(),
                "selected_ids": self.model.selected_ids.tolist(),
            }
        labels, prob, score = self.batcher.predict(points)
        return {
            "labels": labels.tolist(),
            "probabilities": [round(p, 6) for p in prob.tolist()],
            "outlier_scores": [round(s, 6) for s in score.tolist()],
        }

    def health(self) -> dict:
        return {
            "status": "ok",
            "model": self.model.summary(),
            "backend": self.predictor.backend,
            "buckets": list(self.predictor.buckets),
            "warmup": self.warmup_info,
            "batcher": self.batcher.stats,
            "uptime_s": round(time.monotonic() - self._t0, 3),
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ClusterServer":
        """Serve on a daemon thread (tests / embedding); returns self."""
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="predict-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Serve on the calling thread until interrupted (CLI path)."""
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.batcher.close()

    def __enter__(self) -> "ClusterServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
