"""Schema-versioned model artifact: one atomic ``.npz`` per fitted model.

The fit pipelines end at five CSV output files and the model evaporates
(``main/Main.java:534-614`` — the reference has no inference path at all).
:class:`ClusterModel` is the persistent form: everything
``serve/predict.approximate_predict`` needs to classify new points against
the fitted hierarchy — training points + per-row core distances (the k-NN
reference set), the condensed-tree arrays (parent/birth chains for the
attachment climb), the selected-cluster set with its flat-label jump table,
per-selected-cluster max-lambda (membership probabilities) and per-cluster
GLOSH ``eps_max`` — plus a params fingerprint reusing ``utils/checkpoint``'s
digest scheme so a model can never silently serve the wrong dataset or
parameterization.

Deduplicated fits are stored expanded to ROW space (labels/cores already are;
the tree's per-point arrays translate through ``dedup_inverse``), so the
artifact is self-contained: predict never needs the fit-time vertex maps.
MR/data-bubble fits store the full training rows under the global/hybrid
core vector — the pooled mutual-reachability weights are re-weighted to that
same core vector during fit, so query attachment levels are commensurable
with the tree's levels.

Save is atomic (tempfile + ``os.replace``); load refuses a mismatched schema
version, a corrupt payload (stored-data digest != stored fingerprint), and —
when the caller supplies ``params``/``data`` — a mismatched fingerprint.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass

import numpy as np

from hdbscan_tpu.fault import inject
from hdbscan_tpu.utils.checkpoint import _data_digest

#: Version tag carried by every model artifact. Bump the integer suffix on
#: any backwards-incompatible array-layout change; ``load`` refuses other
#: versions outright (a served prediction from misread arrays is silent
#: corruption, unlike a checkpoint, which can just start fresh).
#: ``/2`` adds the OPTIONAL rp-forest index arrays (``rpf_*``) so servers
#: can answer approximate_predict sub-quadratically; every ``/1`` array is
#: unchanged, so ``/1`` artifacts still load (they simply carry no index).
MODEL_SCHEMA = "hdbscan-tpu-model/2"

#: Schemas :meth:`ClusterModel.load` accepts. ``/1`` is the pre-rpforest
#: layout — a strict subset of ``/2`` — and loads with ``rpf=None``.
_COMPAT_SCHEMAS = ("hdbscan-tpu-model/1", MODEL_SCHEMA)

#: The arrays of a stored rp-forest index (``ops/rpforest.RPForest`` field
#: order); the artifact stores each under an ``rpf_`` key prefix.
_RPF_ARRAYS = ("normals", "thresholds", "members", "leaf_mask")

#: The parameter fields that must match for a model to serve a dataset —
#: the serve-relevant subset of ``utils/checkpoint._fingerprint`` (fit-only
#: knobs like ``k`` or ``refine_iterations`` are baked into the stored tree
#: and need not match at load time).
_FINGERPRINT_FIELDS = ("min_points", "min_cluster_size", "dist_function")


def _rpf_pack(forest) -> dict:
    """Host-side dict form of an ``ops/rpforest.RPForest`` for storage."""
    return {
        "trees": int(forest.trees),
        "depth": int(forest.depth),
        "leaf_size": int(forest.leaf_size),
        "normals": np.asarray(forest.normals, np.float32),
        "thresholds": np.asarray(forest.thresholds, np.float32),
        "members": np.asarray(forest.members, np.int32),
        "leaf_mask": np.asarray(forest.leaf_mask, bool),
    }


def _fingerprint(params, n: int, data_digest: str | None) -> dict:
    fp = {"n": int(n), "data": data_digest}
    for f in _FINGERPRINT_FIELDS:
        fp[f] = getattr(params, f)
    return fp


@dataclass
class ClusterModel:
    """A fitted clustering, ready to classify unseen points.

    Per-row arrays (length n, ROW space even for deduplicated fits):
    ``data``/``core``/``labels``/``last_cluster``. Per-cluster arrays
    (length C+1, 1-indexed labels, 0 unused — ``core/tree.CondensedTree``
    layout): ``parent``/``birth``/``selected``/``sel_anc``/``eps_min``/
    ``eps_max``.
    """

    mode: str  # "exact" | "mr"
    params: dict  # the _FINGERPRINT_FIELDS subset, as plain values
    fingerprint: dict
    data: np.ndarray  # (n, d) float64 training points
    core: np.ndarray  # (n,) float64 core distances
    labels: np.ndarray  # (n,) int64 fitted flat labels (0 = noise)
    last_cluster: np.ndarray  # (n,) int64 deepest cluster per point
    parent: np.ndarray  # (C+1,) int64 cluster parent (-1 root, 0 unused)
    birth: np.ndarray  # (C+1,) float64 cluster birth eps (inf at root)
    selected: np.ndarray  # (C+1,) bool EOM solution set
    sel_anc: np.ndarray  # (C+1,) int64 nearest selected ancestor-or-self
    eps_min: np.ndarray  # (C+1,) float64 per-selected-cluster min exit eps
    eps_max: np.ndarray  # (C+1,) float64 lowest descendant death (GLOSH)
    schema: str = MODEL_SCHEMA
    #: Optional rp-forest index (schema /2): ``{"trees", "depth",
    #: "leaf_size"}`` ints plus the ``ops/rpforest.RPForest`` arrays —
    #: ``normals`` (T, 2^depth - 1, d) f32, ``thresholds`` (T, 2^depth - 1)
    #: f32, ``members`` (T, L, Lmax) i32, ``leaf_mask`` (L, Lmax) bool.
    #: ``serve/predict`` routes queries down the stored planes instead of
    #: scanning all n train rows when ``predict_backend="rpforest"``.
    rpf: dict | None = None

    @property
    def n_train(self) -> int:
        return len(self.data)

    @property
    def min_points(self) -> int:
        return int(self.params["min_points"])

    @property
    def metric(self) -> str:
        return str(self.params["dist_function"])

    @property
    def selected_ids(self) -> np.ndarray:
        """The selected cluster labels, ascending — the column order of
        :func:`serve.predict.membership_vectors`."""
        return np.flatnonzero(self.selected).astype(np.int64)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_fit_result(
        cls, result, data: np.ndarray, params, forest=None
    ) -> "ClusterModel":
        """Build the artifact from a fit result (``models/hdbscan.
        HDBSCANResult`` or ``models/mr_hdbscan.MRHDBSCANResult``) plus the
        training data and params it was fitted with.

        Consensus results are stored as their REPRESENTATIVE draw's tree
        with the consensus flat labels — the same mixed provenance the
        five-file output set documents (``write_outputs`` sidecar).

        ``forest``: an ``ops/rpforest.RPForest`` to embed as the artifact's
        serving index. When omitted and ``params.knn_index`` resolves to
        rpforest for this n, a forest is built here (same knobs and seed the
        fit's scans used), so an approximate fit round-trips into an
        approximate-serving artifact with no extra caller step.
        """
        from hdbscan_tpu.models._finalize import serving_tables

        data = np.asarray(data, np.float64)
        if data.ndim == 1:
            data = data[:, None]
        n = len(data)
        tree = result.tree
        labels = np.asarray(result.labels, np.int64)
        core = np.asarray(result.core_distances, np.float64)
        if len(labels) != n or len(core) != n:
            raise ValueError(
                f"result arrays (n={len(labels)}) do not match data (n={n})"
            )
        inv = getattr(result, "dedup_inverse", None)
        last = np.asarray(tree.point_last_cluster, np.int64)
        if inv is not None:
            last = last[inv]
        tables = serving_tables(tree)
        mode = "mr" if hasattr(result, "n_levels") else "exact"
        rpf = None
        if forest is not None:
            rpf = _rpf_pack(forest)
        elif getattr(params, "knn_index", "exact") != "exact":
            from hdbscan_tpu.ops.rpforest import build_forest, resolve_knn_index

            index = resolve_knn_index(
                params.knn_index, n,
                getattr(params, "knn_index_threshold", 1),
            )
            if index == "rpforest":
                k = max(getattr(params, "min_points", 2) - 1, 1)
                leaf_size = max(
                    getattr(params, "rpf_leaf_size", 1024), 2 * k + 2, 8
                )
                rpf = _rpf_pack(
                    build_forest(
                        data,
                        trees=getattr(params, "rpf_trees", 4),
                        leaf_size=min(leaf_size, max(n, 2)),
                        seed=getattr(params, "seed", 0),
                    )
                )
        return cls(
            mode=mode,
            params={f: getattr(params, f) for f in _FINGERPRINT_FIELDS},
            fingerprint=_fingerprint(params, n, _data_digest(data)),
            data=data,
            core=core,
            labels=labels,
            last_cluster=last,
            parent=np.asarray(tree.parent, np.int64),
            birth=np.asarray(tree.birth, np.float64),
            selected=np.asarray(tree.selected, bool),
            sel_anc=np.asarray(tables["sel_anc"], np.int64),
            eps_min=np.asarray(tables["eps_min"], np.float64),
            eps_max=np.asarray(tables["eps_max"], np.float64),
            rpf=rpf,
        )

    # -- persistence -------------------------------------------------------

    def save(self, path: str, *, compress: bool = True) -> str:
        """Write the artifact atomically (tempfile + ``os.replace``, the
        ``utils/checkpoint`` pattern: a crashed save never leaves a
        half-written model where a server could load it).

        ``compress=False`` stores members uncompressed (``np.savez``):
        larger on disk, but the per-host ``fleet.artifacts.ArtifactStore``
        can then spool and memory-map the arrays without a decompression
        copy, so many replicas on one host share the OS page cache."""
        out_dir = os.path.dirname(os.path.abspath(path))
        os.makedirs(out_dir, exist_ok=True)
        meta = {
            "schema": self.schema,
            "mode": self.mode,
            "params": self.params,
            "fingerprint": self.fingerprint,
        }
        extra = {}
        if self.rpf is not None:
            meta["rpf"] = {
                k: int(self.rpf[k]) for k in ("trees", "depth", "leaf_size")
            }
            extra = {f"rpf_{k}": self.rpf[k] for k in _RPF_ARRAYS}
        fd, tmp = tempfile.mkstemp(dir=out_dir, suffix=".tmp")
        os.close(fd)
        savez = np.savez_compressed if compress else np.savez
        try:
            with open(tmp, "wb") as f:
                savez(
                    f,
                    meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
                    data=self.data,
                    core=self.core,
                    labels=self.labels,
                    last_cluster=self.last_cluster,
                    parent=self.parent,
                    birth=self.birth,
                    selected=self.selected,
                    sel_anc=self.sel_anc,
                    eps_min=self.eps_min,
                    eps_max=self.eps_max,
                    **extra,
                )
            # Fault sites for the chaos suite: a "torn" save crashes between
            # the tempfile write and the atomic rename — proving a crashed
            # publish leaves no partial artifact where a server could load
            # it; "digest" corrupts the published bytes so load's stored-
            # digest check must catch them.
            act = inject.maybe_fire("artifact_save")
            if act is not None and act.mode != "digest":
                raise inject.InjectedFault(
                    "injected artifact_save crash before publish rename"
                )
            os.replace(tmp, path)
            if act is not None and act.mode == "digest":
                with open(path, "r+b") as f:
                    f.seek(-1, os.SEEK_END)
                    last = f.read(1)[0]
                    f.seek(-1, os.SEEK_END)
                    f.write(bytes([last ^ 0xFF]))
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return path

    @classmethod
    def load(cls, path: str, params=None, data=None) -> "ClusterModel":
        """Load and verify an artifact.

        Raises ``ValueError`` on (1) a schema version this build cannot read
        (``/1`` loads compatibly with no index; ``/2`` is current) — arrays
        of another layout must not be misread;
        (2) a corrupt payload — the stored training data's digest must equal
        the stored fingerprint's; (3) a fingerprint mismatch against the
        caller's ``params`` and/or ``data`` when supplied (a server asked to
        serve config X with a model fitted under config Y must refuse, the
        ``utils/checkpoint.load_latest`` stance).
        """
        if inject.maybe_fire("artifact_load") is not None:
            raise inject.InjectedFault("injected transient artifact_load fault")
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            schema = meta.get("schema")
            if schema not in _COMPAT_SCHEMAS:
                raise ValueError(
                    f"model {path} has schema {schema!r}; this build reads "
                    f"{' / '.join(map(repr, _COMPAT_SCHEMAS))} only"
                )
            rpf = None
            if meta.get("rpf") is not None:
                rpf = dict(meta["rpf"])
                for key in _RPF_ARRAYS:
                    rpf[key] = z[f"rpf_{key}"]
            model = cls(
                mode=meta["mode"],
                params=meta["params"],
                fingerprint=meta["fingerprint"],
                data=z["data"],
                core=z["core"],
                labels=z["labels"],
                last_cluster=z["last_cluster"],
                parent=z["parent"],
                birth=z["birth"],
                selected=z["selected"],
                sel_anc=z["sel_anc"],
                eps_min=z["eps_min"],
                eps_max=z["eps_max"],
                schema=schema,
                rpf=rpf,
            )
        stored_digest = model.fingerprint.get("data")
        if stored_digest is not None and _data_digest(model.data) != stored_digest:
            raise ValueError(
                f"model {path} is corrupt: stored training data digest does "
                f"not match its fingerprint ({stored_digest})"
            )
        if params is not None or data is not None:
            want = dict(model.fingerprint)
            if params is not None:
                for f in _FINGERPRINT_FIELDS:
                    want[f] = getattr(params, f)
            if data is not None:
                arr = np.asarray(data, np.float64)
                if arr.ndim == 1:
                    arr = arr[:, None]
                want["n"] = len(arr)
                want["data"] = _data_digest(arr)
            if want != model.fingerprint:
                raise ValueError(
                    f"model {path} was fitted for {model.fingerprint}, "
                    f"caller expects {want}; refusing to serve"
                )
        return model

    def summary(self) -> dict:
        """Small JSON-safe description (the ``/healthz`` payload core)."""
        out = {
            "schema": self.schema,
            "mode": self.mode,
            "n_train": int(self.n_train),
            "dims": int(self.data.shape[1]),
            "n_clusters": int(len(self.parent) - 1),
            "n_selected": int(self.selected.sum()),
            "params": dict(self.params),
        }
        if self.rpf is not None:
            out["rpf"] = {
                k: int(self.rpf[k]) for k in ("trees", "depth", "leaf_size")
            }
        return out
