"""Request coalescing for the inference server: the micro-batching layer.

Concurrent HTTP requests land here one at a time; the batcher drains them
into a single padded bucket dispatch so the device runs one program per
linger window instead of one per request. Shapes stay inside the
:class:`~hdbscan_tpu.serve.predict.Predictor`'s warmed power-of-two bucket
set, so coalescing never triggers a recompile — the zero-steady-state-
recompile guarantee holds under any request mix.

Stdlib only (``threading`` + ``queue`` + ``concurrent.futures.Future``), one
worker thread owning the device — JAX dispatch is not thread-safe across
donated buffers, and a single dispatcher keeps ``predict_batch`` trace
events (``batch_seq``) strictly ordered.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from hdbscan_tpu.fault import inject
from hdbscan_tpu.fault.policy import DeadlineExceeded, ShedRequest


class MicroBatcher:
    """Coalesce concurrent predict requests into bucket-sized batches.

    Args:
      predictor: a warmed :class:`~hdbscan_tpu.serve.predict.Predictor`.
      linger_s: how long the worker waits for more requests after the first
        one arrives before dispatching (the latency the smallest request
        pays to let a batch form; 0 disables coalescing).
      max_rows: dispatch ceiling per coalesced batch — defaults to the
        predictor's largest bucket, so a coalesced batch is exactly one
        device program.
      max_queue: bound on queued (undispatched) requests; a submit over the
        bound raises :class:`ShedRequest` (HTTP 503 + Retry-After) instead
        of queueing unboundedly. 0 = unbounded (the historical behavior).
    """

    def __init__(self, predictor, linger_s: float = 0.002,
                 max_rows: int | None = None, max_queue: int = 0):
        self.predictor = predictor
        self.linger_s = float(linger_s)
        self.max_rows = int(max_rows or predictor.max_bucket)
        self.max_queue = int(max_queue or 0)
        self._q: queue.Queue = queue.Queue()
        self._closed = False
        self._close_lock = threading.Lock()  # orders submit() vs close()
        self._batches = 0
        self._rows = 0
        self._shed = 0
        self._deadline_drops = 0
        self._worker = threading.Thread(
            target=self._run, name="predict-batcher", daemon=True
        )
        self._worker.start()

    # -- client side -------------------------------------------------------

    def submit(self, X, meta: dict | None = None) -> Future:
        """Enqueue one request; the Future resolves to this request's
        ``(labels, probabilities, outlier_scores)`` slice of the coalesced
        dispatch.

        ``meta``, when given, is filled by the worker before the Future
        resolves (the resolution is the happens-before edge) with the span
        attribution the server's ``request_span`` event needs: perf_counter
        marks ``t_assembled``/``t_dispatch``/``t_done``, the dispatched
        ``bucket``, the ``coalesced`` peer count, and ``batch_rows``.

        ``meta['deadline']`` (a ``time.perf_counter`` instant) makes the
        request deadline-aware: an already-expired deadline raises
        :class:`DeadlineExceeded` here, and one that expires while queued
        fails the future the same way before dispatch — an expired request
        never occupies a batch slot.
        """
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if inject.maybe_fire("batcher_submit") is not None:
            raise inject.InjectedFault("injected batcher_submit fault")
        deadline = meta.get("deadline") if meta else None
        if deadline is not None and time.perf_counter() > deadline:
            raise DeadlineExceeded("request deadline passed before enqueue")
        fut: Future = Future()
        # The close lock orders this put against close()'s sentinel: every
        # accepted future lands ahead of the sentinel in the FIFO queue, so
        # the worker's drain-until-sentinel loop resolves all of them —
        # close() never abandons an in-flight request.
        with self._close_lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if self.max_queue and self._q.qsize() >= self.max_queue:
                self._shed += 1
                raise ShedRequest(
                    f"batcher queue at bound ({self.max_queue})",
                    status=503,
                    retry_after_s=max(0.01, self.linger_s * 2),
                    reason="queue_full",
                )
            self._q.put((X, fut, meta))
        return fut

    def predict(self, X, meta: dict | None = None):
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(X, meta).result()

    @property
    def stats(self) -> dict:
        """Dispatch counters — the coalescing ratio is rows/batches; shed
        and deadline_drops count load-shedding outcomes."""
        return {
            "batches": self._batches,
            "rows": self._rows,
            "shed": self._shed,
            "deadline_drops": self._deadline_drops,
        }

    # -- worker side -------------------------------------------------------

    def _collect(self, first) -> tuple[list, bool]:
        """Drain the queue into one batch: start from ``first``, keep
        accepting until the linger window closes or the batch would exceed
        ``max_rows``. Returns (batch, saw_close_sentinel)."""
        batch = [first]
        rows = len(first[0])
        deadline = time.monotonic() + self.linger_s
        while rows < self.max_rows:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if item is None:
                return batch, True
            batch.append(item)
            rows += len(item[0])
        return batch, False

    def _dispatch(self, batch) -> None:
        t_assembled = time.perf_counter()  # linger window closed; batch fixed
        # Deadline fail-fast: a request whose deadline already passed gets a
        # DeadlineExceeded (the server maps it to 504) instead of a batch
        # slot — under overload, expired work must not displace live work.
        live = []
        for item in batch:
            m = item[2]
            dl = m.get("deadline") if m else None
            if dl is not None and t_assembled > dl:
                self._deadline_drops += 1
                item[1].set_exception(
                    DeadlineExceeded("request deadline passed before dispatch")
                )
            else:
                live.append(item)
        if not live:
            return
        batch = live
        xs = [x for x, _, _ in batch]
        futs = [f for _, f, _ in batch]
        try:
            x_all = np.concatenate(xs)
        except ValueError as e:  # mixed dims inside one window
            for f in futs:
                f.set_exception(ValueError(f"incompatible request shapes: {e}"))
            return
        t_dispatch = time.perf_counter()
        try:
            labels, prob, score = self.predictor.predict(x_all)
        except Exception as e:  # noqa: BLE001 - fan the failure out
            for f in futs:
                f.set_exception(e)
            return
        t_done = time.perf_counter()
        self._batches += 1
        self._rows += len(x_all)
        bucket = self.predictor.bucket_for(
            min(len(x_all), self.predictor.max_bucket)
        )
        # Fill every caller's meta BEFORE resolving any future: the waiting
        # handler thread reads its meta only after .result() returns, so
        # resolution order is the publication barrier.
        for _, _, m in batch:
            if m is not None:
                m.update(
                    t_assembled=t_assembled,
                    t_dispatch=t_dispatch,
                    t_done=t_done,
                    bucket=bucket,
                    coalesced=len(batch),
                    batch_rows=len(x_all),
                )
        a = 0
        for x, f in zip(xs, futs):
            b = a + len(x)
            f.set_result((labels[a:b], prob[a:b], score[a:b]))
            a = b

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                break
            batch, stop = self._collect(item)
            self._dispatch(batch)
            if stop:
                break
        self._drain()

    def _drain(self) -> None:
        """Dispatch everything still queued ahead of the close sentinel (the
        linger window in :meth:`_collect` can expire with items left), in
        ``max_rows``-sized batches, so shutdown completes every accepted
        future instead of abandoning it."""
        pending = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                pending.append(item)
        while pending:
            batch, rows = [], 0
            while pending and rows < self.max_rows:
                batch.append(pending.pop(0))
                rows += len(batch[-1][0])
            self._dispatch(batch)

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout: float | None = 5.0) -> None:
        """Stop accepting requests, flush what's queued, join the worker.
        Every future accepted before close is resolved (graceful drain); if
        the worker cannot finish within ``timeout`` the leftovers fail with
        a ``RuntimeError`` rather than hanging their callers forever."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(None)
        self._worker.join(timeout=timeout)
        if not self._worker.is_alive():
            return
        # Worker wedged (device fault mid-dispatch): fail what's left so no
        # caller blocks forever on an unresolvable future.
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item[1].set_exception(
                    RuntimeError("MicroBatcher closed before dispatch")
                )

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
