"""Command-line driver — the ``Main.main`` capability (L6) without its bugs.

Usage mirrors the reference's documented contract (``main/Main.java:534-614``)::

    python -m hdbscan_tpu file=<input> minPts=4 minClSize=4 \
        [processing_units=N] [k=0.2] [constraints=<csv>] [compact={true,false}] \
        [dist_function={euclidean,cosine,pearson,manhattan,supremum}] \
        [out_dir=DIR] [seed=N] [variant={db,rs}] [dedup={true,false}] \
        [exact_inter_edges={true,false}] [global_cores={true,false}] [refine=N] \
        [boundary=F] [boundary_alpha=F] [boundary_max_frac=F] [glue_alpha=F] \
        [glue_factor=N] [glue_rows=N] [block_pruning={true,false}] \
        [knn_backend={auto,xla,pallas,fused}] \
        [knn_index={auto,exact,rpforest}] [knn_index_threshold=N] \
        [rpf_trees=N] [rpf_leaf_size=N] [rpf_rescan=N] \
        [scan_backend={auto,host,ring}] \
        [fit_sharding={auto,replicated,sharded}] \
        [tree_backend={auto,reference,vectorized}] \
        [mst_backend={auto,host,device}] \
        [consensus=N] [compat_cf={true,false}] \
        [clusterName={local,auto,<host:port>,<pid>,<np>}] \
        [heartbeat=F] [watchdog=F] [skew_threshold=F] [straggler_rounds=N] \
        [trace_rotate_bytes=N] [--assert-not-replicated] [--flight-dir DIR] \
        [--trace-out PATH] [--report PATH] [--compile-cache {auto,off,DIR}]

Telemetry (README "Observability"): ``--trace-out PATH`` appends every
pipeline stage event as a schema-versioned JSON line (multi-host runs write
one ``PATH``-derived file per process: ``trace.<process_index>.jsonl``);
``--report PATH`` writes a run-report JSON — manifest (config, backends,
device topology, env overrides), per-phase wall/GFLOP/MFU/compile aggregates,
per-phase device-memory watermarks, and per-host phase walls when several
processes ran. With both flags absent no telemetry file I/O happens.

Deep observability (README "Observability", ``hdbscan_tpu/obs/``): with
either telemetry flag, a per-phase device-memory auditor samples every
device around each traced fit phase (``mem_sample``/``mem_phase_peak``
events + a ``memory.watermarks`` report table), and long-running phases
emit periodic ``heartbeat`` events with monotone progress fractions and an
ETA. ``heartbeat=F`` sets the emission cadence (seconds, default 1.0);
``watchdog=F`` arms a hang watchdog that dumps every Python thread's stack
to the trace and stderr when no phase beats within F seconds (0 = off).
``--assert-not-replicated`` checks the audited watermarks after the fit and
exits nonzero if any single device's memory grew by ~n*itemsize during a
sharded phase — i.e. an O(n) buffer was replicated instead of sharded.

Mesh timelines + flight recorder (README "Deep observability"): with either
telemetry flag, every sharded/ring round also decomposes into per-device
``device_timeline`` events (telescoping compute/comm/host segments,
``attribution: model``) and the report gains ``timeline`` + ``roofline``
sections (``hdbscan-tpu-report/3``). ``skew_threshold=F`` (default 2.0) and
``straggler_rounds=N`` (default 3) tune the straggler detector: a device at
>= F x the round-median wall for N consecutive rounds emits
``straggler_flag`` events. ``trace_rotate_bytes=N`` (default 256 MiB, 0 =
off) rotates ``--trace-out`` files to ``<path>.1`` at the bound.
``--flight-dir DIR`` arms the flight recorder: a bounded in-memory ring of
recent trace events that writes a self-contained post-mortem bundle
(``flight-<pid>-<seq>-<reason>.json`` — event tail, heartbeats, thread
stacks, watermarks, manifest; validate with ``scripts/check_flight.py``)
on watchdog stall, replication-gate trip, unhandled fit exception, or
SIGTERM. A healthy run writes nothing.

``knn_index`` picks the neighbor-graph TIER (README "Approximate
neighbors"): ``exact`` (default) keeps the O(n²) scans bitwise-unchanged,
``rpforest`` runs the sub-quadratic random-projection-forest engine
(``ops/rpforest.py`` — ``rpf_trees`` trees of ≤ ``rpf_leaf_size``-point
leaves with ``rpf_rescan`` neighbor-of-neighbor repair rounds), and
``auto`` flips to rpforest at ``knn_index_threshold`` points.
``scan_backend`` picks the device scan engine for the k-NN/core and
Borůvka sweeps (README "Scaling out"): ``host`` keeps the single-program
tiled scans, ``ring`` shards rows over the mesh and circulates column
panels via ``ppermute``, and ``auto`` selects ring only on a multi-device
TPU mesh. ``fit_sharding`` picks the end-to-end partition tier (README "One
sharded program", ``parallel/shard.py``): ``replicated`` keeps the existing
engines, ``sharded`` routes the fit through ONE partitioned program —
row-sharded core scans plus fully row-sharded Borůvka rounds (with
``mst_backend=device`` the contraction cascade runs in-jit and the fit makes
exactly one host sync), the path the ``--assert-not-replicated`` gate
certifies end to end — and ``auto`` picks sharded only on a multi-device TPU
mesh. The MR pipeline honors the tier too (sharded global cores, boundary
rescan and glue harvests); it no longer forces the exact program. The run manifest records the
partition-rule table. ``tree_backend`` picks the host finalize engine for the condensed
tree (README "Finalize pipeline"): ``reference`` is the per-node Python
walk, ``vectorized`` the array-level engine with bitwise-identical outputs,
and ``auto`` uses vectorized with a reference fallback on unsupported
inputs. ``mst_backend`` picks the MST -> merge-forest engine upstream of
that (README "Device-resident finalize"): ``host`` keeps the per-round
host contraction plus the sequential host forest builder, ``device`` runs
every Borůvka round and the union-find forest scan in-jit with exactly one
host sync per fit (trace event ``host_sync``), and ``auto`` uses device on
big eligible edge pools with a host fallback (bitwise-identical outputs
either way). ``--compile-cache`` controls jax's persistent XLA compile cache:
``auto`` (default) resolves JAX_COMPILATION_CACHE_DIR then the per-user
default dir, ``off`` disables it, anything else is the cache directory.
Reports record per-phase ``cache_hits`` next to ``jit_compiles`` so warmed
vs cold compile bills are visible.

Unlike the reference, argv is actually honored (the reference shadows it with
hard-coded args, ``main/Main.java:71`` — treated as a bug, SURVEY.md §7), and
the dataset is routed automatically: inputs that fit ``processing_units`` run
the exact single-block path; larger inputs run the full recursive-sampling +
data-bubble pipeline. Outputs are the five canonical files either way.

Serving (README "Serving") — three subcommands; a bare ``key=value``
invocation still means ``fit`` (the reference-compatible form above)::

    python -m hdbscan_tpu fit file=<input> ... [--model-out MODEL.npz]
    python -m hdbscan_tpu predict --model MODEL.npz --points <input> \
        [--out PRED.csv] [predict_backend={auto,xla,fused,rpforest}] \
        [predict_batch=N] \
        [--trace-out PATH] [--report PATH]
    python -m hdbscan_tpu serve --model MODEL.npz [--host H] [--port P] \
        [predict_backend=...] [predict_batch=N] [--trace-out PATH] \
        [--report PATH] [--ingest] [--model-dir DIR] \
        [--tenants-dir DIR] [--port-file PATH] \
        [absorb_eps=F] [drift_stat={psi,ks}] [drift_threshold=F] \
        [refit_budget=N] [stream_reload={auto,manual}] [trace_max_events=N] \
        [queue_bound=N] [deadline_ms=F] [faults=SPEC] [circuit_failures=N] \
        [circuit_reset=F] [wal_dir=DIR] [snapshot_every=N] \
        [maintain={off,incremental}] [maintain_budget=F] \
        [maintain_dirty_frac=F] [maintain_refresh=N] \
        [tenant_lru=N] [tenant_quota=F]
    python -m hdbscan_tpu fleet --model MODEL.npz [--host H] [--port P] \
        [--model-dir DIR] [--tenants-dir DIR] [--ingest] [--wal-root DIR] \
        [--trace-out PATH] [--report PATH] [--replica-trace-dir DIR] \
        [fleet_replicas=N] \
        [fleet_policy={consistent_hash,least_loaded}] \
        [fleet_health_interval=F] [fleet_drain=F] \
        [autoscale={true,false}] [fleet_min=N] [fleet_max=N] \
        [scale_high_load=F] [scale_low_load=F] [scale_p99=F] \
        [scale_cooldown=F] [artifact_store={shared,off}] \
        [<replica serve knobs, forwarded verbatim>]

``fit --model-out`` persists the fitted clustering as one atomic
schema-versioned ``.npz`` (``serve/artifact.ClusterModel``); ``predict``
classifies new points against it (labels, membership probabilities, GLOSH
outlier scores — ``serve/predict.approximate_predict``); ``serve`` starts a
stdlib HTTP server (``POST /predict``, ``GET /healthz``, ``GET /metrics``)
with micro-batched dispatch. Both serving commands AOT-warm every
power-of-two batch bucket so steady state recompiles nothing, emit
per-batch ``predict_batch`` trace events, and report p50/p95/p99/p999
latency in the run report (``predict_latency``). The server additionally
exposes a Prometheus text exposition at ``GET /metrics``
(``utils/metrics.py``; validate with ``scripts/check_metrics.py``) and,
when tracing, emits one ``request_span`` event per successful
``/predict``/``/ingest`` request decomposing its wall into parse /
queue-wait / batch-assembly / device-predict / respond segments
(``request_spans`` report section; ``scripts/check_trace.py`` validates
the schema). ``trace_max_events=N`` bounds the tracer's in-memory event
list for long-running serves (0 = unbounded; the JSONL trace file always
gets every event).

``serve --ingest`` (README "Streaming") additionally opens ``POST /ingest``:
arriving points route through the predict path, duplicates/near-duplicates
(within ``absorb_eps`` of their cluster's density level) fold into
per-cluster bubble summaries, a GLOSH-score drift detector
(``drift_stat``/``drift_threshold``) watches for distribution shift, and on
drift or ``refit_budget`` buffered novel rows a background re-fit publishes
a new artifact under ``--model-dir`` that hot-swaps in atomically
(``stream_reload=auto``; ``manual`` stages it for ``POST /swap``). SIGTERM
drains in-flight requests before exiting.

Fault tolerance (README "Fault tolerance"): ``queue_bound=N`` bounds the
micro-batcher queue (excess requests are shed with 429/503 + Retry-After;
0 = unbounded), ``deadline_ms=F`` gives every request a default deadline
(clients override per-request via the ``X-Deadline-Ms`` header; expired
requests fail fast with 504 instead of occupying a batch slot),
``circuit_failures``/``circuit_reset`` tune the breaker that pins the
served generation after repeated re-fit failures, and ``wal_dir=DIR``
makes ``/ingest`` crash-safe: every accepted chunk is fsync'd to a JSONL
write-ahead log (snapshotted every ``snapshot_every`` appends) and
replayed bit-identically on restart. ``faults=SPEC`` (or the
``HDBSCAN_TPU_FAULTS`` env var) installs the deterministic fault-injection
harness — see ``hdbscan_tpu/fault/inject.py`` for the spec grammar.

``maintain=incremental`` (README "Incremental maintenance") absorbs novel
rows ONLINE instead of waiting for a re-fit: each buffered point updates a
maintained mutual-reachability MST (``hdbscan_tpu/incremental``), and
every ``maintain_refresh`` inserts the hierarchy re-finalizes and the
served model hot-refreshes blue/green (no full fit, no AOT re-warm).
``maintain_budget=F`` counts per-insert wall overruns (ms, 0 = unbounded),
``maintain_dirty_frac=F`` caps the splice/finalize dirty share before the
maintainer demotes to the circuit-gated re-fit ladder. With ``wal_dir``
the snapshot carries a maintenance watermark that recovery re-verifies
bitwise.

Fleet (README "Fleet"): ``fleet`` spawns ``fleet_replicas`` independent
``serve`` subprocesses sharing the same ``--model`` (and ``--model-dir``
artifacts) and fronts them on ONE asyncio accept loop — ``/predict`` and
``/ingest`` route by ``fleet_policy`` (``consistent_hash`` pins a tenant's
requests to a replica via an md5 ring; ``least_loaded`` picks the replica
with the fewest in-flight requests), ``/metrics`` scrapes every replica and
serves one aggregated exposition with a ``replica`` label, and ``/swap``
broadcasts to all replicas. A health loop probes ``/healthz`` every
``fleet_health_interval`` seconds; a dead replica is routed around within
one interval and restarted (each replica keeps its own ``--wal-root``/r<id>
write-ahead log, so acked ingest survives a SIGKILL). SIGTERM forwards to
every replica and waits up to ``fleet_drain`` seconds for drain — exit
status is nonzero if any replica had to be killed. ``serve --tenants-dir
DIR`` (also forwarded by ``fleet``) serves every ``<tenant>.npz`` in DIR
behind an LRU of ``tenant_lru`` AOT-warmed predictors with per-tenant
generations and a ``tenant_quota`` req/s token bucket (exceed = 429 +
Retry-After); ``POST /predict`` bodies gain an optional ``"tenant"`` field.
``serve --port-file PATH`` writes the bound port to PATH after the socket
binds (how the fleet router discovers each replica's ephemeral port).
Control plane (README "Fleet" / control-plane subsections):
``autoscale=true`` runs the hysteresis autoscaler
(``fleet/controlplane.py``) over the router's queue-depth/p99 signals,
scaling between ``fleet_min`` and ``fleet_max`` replicas — scale-up spawns
a standby, warms it against the shared persistent XLA compile cache
(every replica env carries the same ``JAX_COMPILATION_CACHE_DIR``, per the
``compile_cache`` knob), and admits it to the ring only when healthy;
scale-down drains the victim before the WAL-safe SIGTERM. Thresholds:
``scale_high_load``/``scale_low_load`` (in-flight per up replica),
``scale_p99`` (seconds, 0 = off), ``scale_cooldown`` (hold after a scale
op). Every operation traces as ``scale_event`` and counts in
``hdbscan_tpu_scale_events_total``. ``artifact_store=shared`` loads tenant
artifacts through the per-host digest-keyed mmap spool
(``fleet/artifacts.py``) so T tenants cost one resident copy per HOST
instead of per replica; fit-as-a-service jobs (``fleet/jobs.py``,
``fit_workers``/``fit_queue_bound``/``fit_quota`` knobs) publish new
generations through the per-tenant blue/green swap.
``fleet --replica-trace-dir DIR`` gives every replica its own
``--trace-out`` file under DIR; the router stamps ``X-Request-Id`` on every
proxied request and emits a ``router_span`` per request, so
``scripts/check_trace.py --join ROUTER.jsonl DIR/replica_*.jsonl`` (or
``hdbscan_tpu.obs.correlate.merge_fleet_traces``) reconstructs every
router -> replica causal chain by request id.
"""

from __future__ import annotations

import sys
import time

from hdbscan_tpu.config import HDBSCANParams

HELP = __doc__


def _pop_path_flag(argv: list[str], flag: str) -> str | None:
    """Extract ``--flag PATH`` or ``--flag=PATH`` from argv (in place).

    The telemetry flags are run-artifact concerns, not clustering parameters,
    so they stay out of the reference's ``key=value`` vocabulary
    (``HDBSCANParams.from_args`` would reject them as unknown flags).
    """
    value = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == flag:
            if i + 1 >= len(argv):
                raise ValueError(f"{flag} requires a PATH argument")
            value = argv[i + 1]
            del argv[i : i + 2]
        elif a.startswith(flag + "="):
            value = a[len(flag) + 1 :]
            del argv[i]
        else:
            i += 1
    return value


def _pop_bool_flag(argv: list[str], flag: str) -> bool:
    """Extract a bare ``--flag`` switch from argv (in place)."""
    present = False
    while flag in argv:
        argv.remove(flag)
        present = True
    return present


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or any(a in ("-h", "--help", "help") for a in argv):
        print(HELP)
        return 0
    # Subcommand dispatch; a bare key=value invocation (the reference's
    # documented contract) still means fit.
    if argv[0] == "predict":
        return _main_predict(argv[1:], list(argv))
    if argv[0] == "serve":
        return _main_serve(argv[1:], list(argv))
    if argv[0] == "fleet":
        return _main_fleet(argv[1:], list(argv))
    if argv[0] == "fit":
        argv = argv[1:]
    return _main_fit(argv)


def _main_fit(argv: list[str]) -> int:
    argv_full = list(argv)  # manifest records argv as given, flags included
    try:
        trace_out = _pop_path_flag(argv, "--trace-out")
        report_out = _pop_path_flag(argv, "--report")
        compile_cache_flag = _pop_path_flag(argv, "--compile-cache")
        model_out = _pop_path_flag(argv, "--model-out")
        flight_dir = _pop_path_flag(argv, "--flight-dir")
        assert_not_replicated = _pop_bool_flag(argv, "--assert-not-replicated")
        params = HDBSCANParams.from_args(argv)
        if compile_cache_flag is not None:
            import dataclasses

            # replace() re-runs __post_init__ validation on the new value.
            params = dataclasses.replace(
                params, compile_cache=compile_cache_flag
            )
    except ValueError as e:
        print(f"error: {e}\n{HELP}", file=sys.stderr)
        return 2
    if not params.input_file:
        print("error: file=<input> is required", file=sys.stderr)
        return 2

    # Multi-controller wiring BEFORE any device use (the reference's Spark
    # master flag, re-mapped: clusterName=local|auto|<host:port>,<pid>,<np>).
    from hdbscan_tpu.parallel.distributed import (
        initialize_from_cluster_name,
        process_count,
    )

    try:
        initialize_from_cluster_name(params.cluster_name)
    except (ValueError, RuntimeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    import jax
    import numpy as np

    from hdbscan_tpu.models import hdbscan, mr_hdbscan
    from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache
    from hdbscan_tpu.utils.io import load_points

    cache_dir = enable_persistent_compilation_cache(params.compile_cache)

    # Multi-controller SPMD driving (the reference's Spark master+executors,
    # main/Main.java:89-95, re-mapped): every process runs the SAME
    # deterministic driver loop — host decisions replicate (same seed, same
    # data), device scans shard over the GLOBAL mesh so each process computes
    # only its row/block shard, and sharded results allgather over DCN
    # (parallel/mesh.fetch). Process 0 alone writes outputs and prints.
    n_proc = process_count()
    is_main = n_proc == 1 or jax.process_index() == 0
    mesh = None
    if len(jax.devices()) > 1:
        from hdbscan_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
        if is_main and n_proc > 1:
            print(
                f"hdbscan-tpu: {n_proc} processes, "
                f"{len(jax.devices())} devices (global mesh)",
                file=sys.stderr,
            )

    # Per-stage tracing: always collected so the end-of-run summary can show
    # phase walls, selected fractions, and FLOP rates (the reference's only
    # progress output is println of filenames — SURVEY.md §5.1). Set
    # HDBSCAN_TPU_TRACE=1 to also live-stream logfmt lines to stderr;
    # --trace-out/--report persist the run as JSONL events + a report JSON
    # (utils/telemetry.py). With both flags absent the tracer is the same
    # collect-only object as before — zero telemetry file I/O.
    import os

    from hdbscan_tpu.utils.tracing import JsonlSink, Tracer

    telemetry_on = trace_out is not None or report_out is not None
    sinks = []
    counters = None
    trace_path = None
    if telemetry_on:
        from hdbscan_tpu.utils import telemetry

        # Per-phase jit-compile + cache-hit attribution rides the tracer's
        # counter hook (cache_hits ~= jit_compiles on a warmed machine).
        counters = {
            "jit_compiles": telemetry.compile_counter(),
            "cache_hits": telemetry.cache_hit_counter(),
        }
        if trace_out is not None:
            trace_path = telemetry.trace_path_for_process(
                trace_out, jax.process_index(), n_proc
            )
            sinks.append(JsonlSink(
                trace_path,
                static={"process": jax.process_index()},
                rotate_bytes=params.trace_rotate_bytes,
            ))
    tracer = Tracer(
        stream=sys.stderr if os.environ.get("HDBSCAN_TPU_TRACE") else None,
        sinks=sinks,
        counters=counters,
        max_events=params.trace_max_events,
    )
    # Deep observability (hdbscan_tpu/obs): the per-phase memory auditor and
    # heartbeat/watchdog hub install once per fit when telemetry is on (or
    # the replication gate was requested — it needs audited watermarks).
    # Uninstalled, every fit-path obs call is a no-op attribute check.
    from hdbscan_tpu import obs

    installed_obs = False
    tl_rec = None
    if (telemetry_on or assert_not_replicated) and obs.auditor() is None:
        from hdbscan_tpu.obs.audit import MemoryAuditor
        from hdbscan_tpu.obs.heartbeat import Heartbeats
        from hdbscan_tpu.obs.timeline import TimelineRecorder

        tl_rec = TimelineRecorder(
            skew_threshold=params.obs_skew_threshold,
            straggler_rounds=params.obs_straggler_rounds,
            trace=tracer,
        )
        obs.install(
            auditor=MemoryAuditor(tracer=tracer),
            heartbeats=Heartbeats(
                tracer,
                heartbeat_s=params.heartbeat_s,
                watchdog_s=params.watchdog_s,
            ),
            timeline=tl_rec,
        )
        installed_obs = True

    # Flight recorder (README "Deep observability"): always-on bounded ring
    # over the trace stream; dumps a post-mortem bundle to --flight-dir on
    # watchdog stall (sniffed from the stream), replication-gate trip,
    # unhandled fit exception, or SIGTERM. A healthy run writes no files.
    flight = None
    if flight_dir is not None:
        from hdbscan_tpu.obs.flightrec import FlightRecorder
        from hdbscan_tpu.utils import telemetry as _tm

        flight = FlightRecorder(
            flight_dir,
            manifest=_tm.run_manifest(params, argv=argv_full),
            tracer=tracer,
        )
        tracer.add_sink(flight)
        obs.install(flight=flight)
        installed_obs = True
        import signal
        import threading as _threading

        if _threading.current_thread() is _threading.main_thread():
            prev_term = signal.getsignal(signal.SIGTERM)

            def _on_sigterm(signum, frame):
                try:
                    flight.dump("sigterm")
                finally:
                    signal.signal(
                        signal.SIGTERM,
                        prev_term if callable(prev_term) else signal.SIG_DFL,
                    )
                    signal.raise_signal(signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_sigterm)

    mem_start = None
    if report_out is not None:
        from hdbscan_tpu.utils import telemetry

        mem_start = telemetry.sample_device_memory()

    fit_done = False
    try:
        t0 = time.monotonic()
        data = load_points(params.input_file)
        if data.ndim == 1:
            data = data[:, None]
        n = len(data)
        tracer(
            "load_points",
            rows=n,
            dims=int(data.shape[1]),
            wall_s=round(time.monotonic() - t0, 6),
        )
        t0 = time.monotonic()
        from hdbscan_tpu.parallel.shard import resolve_fit_sharding

        if (
            resolve_fit_sharding(params.fit_sharding, mesh) == "sharded"
            and n <= params.processing_units
        ):
            # The ONE partitioned program (``parallel/shard.py``): the
            # whole exact fit runs row-sharded — the end-to-end path the
            # ``--assert-not-replicated`` gate certifies. Above
            # processing_units the MR pipeline keeps the sharded scanners
            # (global cores, boundary rescan, glue harvests all route
            # through ``parallel/shard.py``) instead of forcing the exact
            # program — see the mr branch below.
            from hdbscan_tpu.models import exact

            result = exact.fit(data, params, mesh=mesh, trace=tracer)
            mode = "exact-sharded"
        elif n <= params.processing_units:
            # Single-block exact path: dense local compute (no mesh to shard).
            result = hdbscan.fit(data, params, trace=tracer)
            mode = "exact"
        else:
            # consensus_draws > 1 dispatches to consensus.fit inside.
            # Under fit_sharding=sharded the per-level/boundary scans run
            # the sharded engines (mr_hdbscan routes them internally).
            result = mr_hdbscan.fit(data, params, mesh=mesh, trace=tracer)
            sharded_tag = (
                "-sharded"
                if resolve_fit_sharding(params.fit_sharding, mesh) == "sharded"
                else ""
            )
            mode = (
                f"mr-consensus{sharded_tag} ({params.consensus_draws} draws)"
                if params.consensus_draws > 1
                else f"mr{sharded_tag} ({result.n_levels} levels)"
            )
        wall = time.monotonic() - t0
        tracer("fit", mode=mode.split(" ")[0], rows=n, wall_s=round(wall, 6))
        fit_done = True

        if assert_not_replicated:
            from hdbscan_tpu.obs.audit import ReplicatedBufferError

            try:
                gate = obs.assert_not_replicated(n, data.dtype.itemsize)
            except ReplicatedBufferError as e:
                if flight is not None:
                    try:
                        flight.dump("replication_gate", extra={"error": str(e)})
                    except Exception:
                        pass
                print(f"error: replicated device buffer: {e}", file=sys.stderr)
                return 3
            except RuntimeError as e:
                # No audited phases (e.g. a path the auditor doesn't cover
                # yet): the gate must fail loudly, not pass vacuously.
                print(f"error: {e}", file=sys.stderr)
                return 3
            tracer(
                "replication_gate",
                ok=True,
                threshold_bytes=int(gate["threshold_bytes"]),
                worst_fraction=gate["worst_fraction"],
                phases=len(gate["phases"]),
            )

        if is_main:
            t0 = time.monotonic()
            paths = hdbscan.write_outputs(result, params)
            tracer("write_outputs", wall_s=round(time.monotonic() - t0, 6))
            if model_out is not None:
                t0 = time.monotonic()
                result.to_cluster_model(data, params).save(model_out)
                tracer("model_save", wall_s=round(time.monotonic() - t0, 6))
                paths = dict(paths, model=model_out)
            n_clusters = len(set(result.labels[result.labels > 0].tolist()))
            n_noise = int(np.sum(result.labels == 0))
            print(
                f"hdbscan-tpu: {n} points, {mode}, {n_clusters} clusters, "
                f"{n_noise} noise, {wall:.2f}s"
            )
            if result.infinite_stability:
                # Reference's canonical warning (HDBSCANStar.java:40-47 intent).
                print(
                    "WARNING: some clusters have infinite stability (duplicate "
                    "points denser than minPts); results may be unreliable at "
                    "those clusters.",
                    file=sys.stderr,
                )
            for kind, path in paths.items():
                print(f"  {kind}: {path}")
            if getattr(result, "consensus_info", None) is not None:
                print(
                    "note: consensus run — partition.csv and outlier scores "
                    "describe the stabilized ensemble reading; hierarchy/tree "
                    "files describe the representative draw (see the "
                    "consensus provenance sidecar).",
                    file=sys.stderr,
                )
            # Phase summary (VERDICT r3 item 9): every traced stage's count
            # and summed wall, expensive first — no allowlist, so new stages
            # are never silently dropped.
            summary = tracer.summary()
            if summary:
                print("phases:", file=sys.stderr)
                for line in summary.splitlines():
                    print(f"  {line}", file=sys.stderr)
    except BaseException as e:
        # The black box's whole point: an unhandled fit crash leaves a
        # bundle behind even though the process is about to die.
        if flight is not None and not isinstance(e, SystemExit):
            try:
                flight.dump("exception", extra={"error": repr(e)})
            except Exception:
                pass
        raise
    finally:
        # Uninstall the fit's auditor/heartbeats (stops the watchdog thread)
        # before the tracer flushes — nothing may emit after close.
        if installed_obs:
            obs.clear()
        # Flush/close trace sinks BEFORE the exit barrier: the coordinator
        # reads every rank's trace file right after the barrier releases.
        tracer.close()
        if n_proc > 1 and fit_done:
            # Barrier before exit — in a finally so a rank that fails AFTER
            # the pipeline (e.g. unwritable out_dir on process 0) still
            # joins before teardown. Gated on fit completion: a rank that
            # failed BEFORE/INSIDE fit must NOT issue the barrier while
            # healthy peers are still inside fit's collectives (mismatched
            # collective order deadlocks both) — it exits loudly instead and
            # peers surface the loss via the coordinator's liveness error.
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("hdbscan_tpu_cli_done")

    if report_out is not None and is_main:
        # After the barrier: every rank's trace file is closed, so the
        # coordinator can merge per-host phase walls into one report.
        from hdbscan_tpu.utils import telemetry

        per_host = None
        if n_proc > 1 and trace_out is not None:
            per_host = telemetry.merge_host_traces(
                telemetry.host_trace_paths(trace_out, n_proc)
            )
        telemetry.write_report(
            report_out,
            telemetry.build_report(
                tracer,
                manifest=telemetry.run_manifest(
                    params,
                    argv=argv_full,
                    extra={
                        "compile_cache": {
                            "dir": cache_dir,
                            "jit_compiles": telemetry.compile_counter()(),
                            "cache_hits": telemetry.cache_hit_counter()(),
                        }
                    },
                ),
                memory={
                    "start": mem_start,
                    "end": telemetry.sample_device_memory(),
                },
                per_host=per_host,
                timeline=(
                    tl_rec.phase_table() if tl_rec is not None else None
                ),
            ),
        )
    return 0


def _serving_tracer(
    trace_out: str | None, report_out: str | None, max_events: int | None = None
):
    """Telemetry wiring for the single-process serving commands — same
    sinks/counters contract as the fit driver (predict_batch events carry
    per-phase jit_compiles deltas, so the zero-steady-state-recompile claim
    is checkable from the trace alone). ``max_events``
    (``params.trace_max_events``) bounds the in-memory event list so a
    long-running serve process cannot grow without limit — the JSONL sink
    still streams every event to disk."""
    import os

    from hdbscan_tpu.utils.tracing import JsonlSink, Tracer

    sinks = []
    counters = None
    if trace_out is not None or report_out is not None:
        from hdbscan_tpu.utils import telemetry

        counters = {
            "jit_compiles": telemetry.compile_counter(),
            "cache_hits": telemetry.cache_hit_counter(),
        }
        if trace_out is not None:
            sinks.append(JsonlSink(trace_out, static={"process": 0}))
    return Tracer(
        stream=sys.stderr if os.environ.get("HDBSCAN_TPU_TRACE") else None,
        sinks=sinks,
        counters=counters,
        max_events=max_events,
    )


def _write_serving_report(report_out: str, tracer, params, argv_full) -> None:
    from hdbscan_tpu.utils import telemetry

    report = telemetry.build_report(
        tracer, manifest=telemetry.run_manifest(params, argv=argv_full)
    )
    latency = telemetry.predict_latency_section(tracer)
    if latency is not None:
        report["predict_latency"] = latency
    telemetry.write_report(report_out, report)


def _main_predict(argv: list[str], argv_full: list[str]) -> int:
    try:
        model_path = _pop_path_flag(argv, "--model")
        points_path = _pop_path_flag(argv, "--points")
        out_path = _pop_path_flag(argv, "--out")
        trace_out = _pop_path_flag(argv, "--trace-out")
        report_out = _pop_path_flag(argv, "--report")
        params = HDBSCANParams.from_args(argv)
    except ValueError as e:
        print(f"error: {e}\n{HELP}", file=sys.stderr)
        return 2
    if not model_path or not points_path:
        print(
            "error: predict requires --model MODEL.npz and --points <input>",
            file=sys.stderr,
        )
        return 2

    import numpy as np

    from hdbscan_tpu.serve.artifact import ClusterModel
    from hdbscan_tpu.serve.predict import Predictor
    from hdbscan_tpu.utils.io import load_points

    tracer = _serving_tracer(trace_out, report_out, params.trace_max_events)
    try:
        t0 = time.monotonic()
        try:
            model = ClusterModel.load(model_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"error: cannot load model: {e}", file=sys.stderr)
            return 2
        tracer(
            "model_load",
            n_train=model.n_train,
            mode=model.mode,
            wall_s=round(time.monotonic() - t0, 6),
        )
        t0 = time.monotonic()
        points = load_points(points_path)
        if points.ndim == 1:
            points = points[:, None]
        tracer(
            "load_points",
            rows=len(points),
            dims=int(points.shape[1]),
            wall_s=round(time.monotonic() - t0, 6),
        )
        predictor = Predictor(
            model,
            backend=params.predict_backend,
            max_batch=params.predict_max_batch,
            tracer=tracer,
        )
        predictor.warmup()
        try:
            labels, prob, score = predictor.predict(points)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if out_path is not None:
            with open(out_path, "w", encoding="utf-8") as f:
                f.write("label,probability,outlier_score\n")
                for row in zip(labels, prob, score):
                    f.write(f"{row[0]},{row[1]:.6f},{row[2]:.6f}\n")
        n_clusters = len(set(labels[labels > 0].tolist()))
        n_noise = int(np.sum(labels == 0))
        print(
            f"hdbscan-tpu predict: {len(points)} points, {n_clusters} "
            f"clusters, {n_noise} noise ({predictor.backend} backend)"
        )
        if out_path is not None:
            print(f"  predictions: {out_path}")
    finally:
        tracer.close()
    if report_out is not None:
        _write_serving_report(report_out, tracer, params, argv_full)
    return 0


def _main_serve(argv: list[str], argv_full: list[str]) -> int:
    try:
        model_path = _pop_path_flag(argv, "--model")
        host = _pop_path_flag(argv, "--host") or "127.0.0.1"
        port = _pop_path_flag(argv, "--port")
        trace_out = _pop_path_flag(argv, "--trace-out")
        report_out = _pop_path_flag(argv, "--report")
        model_dir = _pop_path_flag(argv, "--model-dir")
        tenants_dir = _pop_path_flag(argv, "--tenants-dir")
        port_file = _pop_path_flag(argv, "--port-file")
        ingest = _pop_bool_flag(argv, "--ingest")
        params = HDBSCANParams.from_args(argv)
        port = int(port) if port is not None else 8799
    except ValueError as e:
        print(f"error: {e}\n{HELP}", file=sys.stderr)
        return 2
    if not model_path:
        print("error: serve requires --model MODEL.npz", file=sys.stderr)
        return 2

    from hdbscan_tpu.serve.artifact import ClusterModel
    from hdbscan_tpu.serve.server import ClusterServer
    from hdbscan_tpu.utils.cache import enable_persistent_compilation_cache

    # Same persistent-cache policy as ``fit``: honor the ``compile_cache``
    # knob and drop jax's min-compile-time floor to zero, else the fleet
    # router's injected JAX_COMPILATION_CACHE_DIR looks enabled but never
    # persists sub-second (CPU-sized) warmup compiles — and a scaled-up
    # standby could not report the warm-spawn ``jit_compiles == 0`` the
    # control plane asserts.
    enable_persistent_compilation_cache(params.compile_cache)

    tracer = _serving_tracer(trace_out, report_out, params.trace_max_events)
    try:
        try:
            model = ClusterModel.load(model_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"error: cannot load model: {e}", file=sys.stderr)
            return 2
        server = ClusterServer(
            model,
            backend=params.predict_backend,
            max_batch=params.predict_max_batch,
            host=host,
            port=port,
            tracer=tracer,
            ingest=ingest,
            params=params,
            model_dir=model_dir,
            tenants=tenants_dir,
        )
        if port_file is not None:
            # The fleet router polls this file to discover the replica's
            # ephemeral port (serve --port 0), so write it only after the
            # socket is bound.
            with open(port_file, "w", encoding="utf-8") as f:
                f.write(f"{server.port}\n")
        mode = ""
        if ingest:
            mode = (
                f", ingest on ({params.stream_drift_stat} drift @ "
                f"{params.stream_drift_threshold}, {params.stream_reload} "
                f"reload)"
            )
        if tenants_dir is not None:
            mode += (
                f", tenants dir {tenants_dir} "
                f"(lru {params.tenant_lru_size})"
            )
        print(
            f"hdbscan-tpu serve: http://{server.host}:{server.port} "
            f"(model {model_path}, {model.n_train} train points, "
            f"{server.predictor.backend} backend, buckets "
            f"{server.predictor.buckets}{mode})",
            file=sys.stderr,
        )
        server.serve_forever()
    finally:
        tracer.close()
    if report_out is not None:
        _write_serving_report(report_out, tracer, params, argv_full)
    return 0


def _main_fleet(argv: list[str], argv_full: list[str]) -> int:
    try:
        model_path = _pop_path_flag(argv, "--model")
        host = _pop_path_flag(argv, "--host") or "127.0.0.1"
        port = _pop_path_flag(argv, "--port")
        trace_out = _pop_path_flag(argv, "--trace-out")
        report_out = _pop_path_flag(argv, "--report")
        model_dir = _pop_path_flag(argv, "--model-dir")
        tenants_dir = _pop_path_flag(argv, "--tenants-dir")
        wal_root = _pop_path_flag(argv, "--wal-root")
        replica_trace_dir = _pop_path_flag(argv, "--replica-trace-dir")
        ingest = _pop_bool_flag(argv, "--ingest")
        params = HDBSCANParams.from_args(argv)
        port = int(port) if port is not None else 0
    except ValueError as e:
        print(f"error: {e}\n{HELP}", file=sys.stderr)
        return 2
    if not model_path:
        print("error: fleet requires --model MODEL.npz", file=sys.stderr)
        return 2

    from hdbscan_tpu.fleet.router import FleetRouter

    tracer = _serving_tracer(trace_out, report_out, params.trace_max_events)
    rc = 1
    try:
        # Remaining key=value argv forwards to every replica verbatim, so
        # predict_batch / queue_bound / wal knobs tune the whole fleet from
        # one command line (fleet_* keys are valid serve config too — inert
        # in a replica).
        router = FleetRouter(
            model_path,
            replicas=params.fleet_replicas,
            policy=params.fleet_policy,
            health_interval_s=params.fleet_health_interval_s,
            drain_s=params.fleet_drain_s,
            host=host,
            port=port,
            replica_args=argv,
            tenants_dir=tenants_dir,
            model_dir=model_dir,
            ingest=ingest,
            wal_root=wal_root,
            tracer=tracer,
            replica_trace_dir=replica_trace_dir,
            verbose=True,
            compile_cache=params.compile_cache,
        )
        try:
            router.start()
        except RuntimeError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(
            f"hdbscan-tpu fleet: http://{router.host}:{router.port} "
            f"({params.fleet_replicas} replicas, {params.fleet_policy} "
            f"routing, model {model_path})",
            file=sys.stderr,
        )
        scaler = None
        if params.fleet_autoscale:
            from hdbscan_tpu.fleet.controlplane import Autoscaler

            scaler = Autoscaler(
                router,
                min_replicas=params.fleet_min_replicas,
                max_replicas=params.fleet_max_replicas,
                high_load=params.fleet_scale_high_load,
                low_load=params.fleet_scale_low_load,
                high_p99_s=params.fleet_scale_p99_s,
                cooldown_s=params.fleet_scale_cooldown_s,
            ).start()
            print(
                f"hdbscan-tpu fleet: autoscaler on "
                f"[{params.fleet_min_replicas}, "
                f"{params.fleet_max_replicas}] replicas",
                file=sys.stderr,
            )
        try:
            rc = router.serve_forever()
        finally:
            if scaler is not None:
                scaler.stop()
    finally:
        tracer.close()
    if report_out is not None:
        _write_serving_report(report_out, tracer, params, argv_full)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
