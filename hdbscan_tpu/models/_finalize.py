"""Shared post-processing tail of every fit(): edge pool -> clustering.

All three models (single-block exact ``hdbscan``, blocked exact ``exact``,
distributed ``mr_hdbscan``) end in the same host-side sequence — merge forest,
condensed tree, constraint counting, EOM propagation, flat labels, GLOSH —
mirroring the reference's canonical per-node pipeline tail
(SURVEY.md §3.4; ``HDBSCANStar.propagateTree``/``findProminentClusters``/
``calculateOutlierScores``, ``hdbscanstar/HDBSCANStar.java:505,567,653``).
Kept in one place so constraint/propagation fixes apply to every path.

``params.tree_backend`` selects the condense/propagate/labels engine:
``reference`` is the per-node Python walk in ``core/tree.py``, ``vectorized``
the array-level engine in ``core/tree_vec.py`` (bitwise-identical outputs),
and ``auto`` (default) picks vectorized whenever
``tree_vec.supports_inputs`` accepts the inputs, falling back to reference
otherwise (non-integral point weights). Every ``tree_*`` trace event carries
the backend that actually ran (``native``/``python``/``device`` for the
merge forest).

``params.mst_backend`` selects the merge-forest builder upstream of that:
``device`` (or ``auto`` on big eligible pools) builds the forest from one
device union-find scan (``core/mst_device.py`` — trace events ``host_sync``
and ``tree_build_device``), falling back to the host builder when the pool
fails the runtime eligibility gate. Callers that already hold a forest
(the device-resident exact fit) pass it in and skip the rebuild.
"""

from __future__ import annotations

import numpy as np

from hdbscan_tpu.config import HDBSCANParams
from hdbscan_tpu.core import tree as tree_mod
from hdbscan_tpu.core import tree_vec


def resolve_tree_backend(
    params: HDBSCANParams, point_weights: np.ndarray | None
) -> str:
    """The condense/extract engine finalize will actually use."""
    backend = getattr(params, "tree_backend", "auto")
    if backend in ("reference", "vectorized"):
        return backend
    return (
        "vectorized" if tree_vec.supports_inputs(point_weights) else "reference"
    )


def serving_tables(
    tree: tree_mod.CondensedTree, labels: np.ndarray | None = None
) -> dict:
    """Prediction-time views of a propagated condensed tree — the arrays
    ``serve/artifact.ClusterModel`` persists beyond the raw tree fields:

    - ``sel_anc``: per-label nearest selected ancestor-or-self (the flat-label
      jump table, ``core/tree_vec.selected_ancestors``), indexed at serve time
      with the *query's* attachment cluster;
    - ``eps_min``: per-selected-cluster minimum member exit eps ("max
      lambda", ``core/tree.cluster_eps_min``) backing membership
      probabilities;
    - ``eps_max``: per-cluster lowest descendant death (GLOSH numerator,
      ``propagate_tree``'s ``lowest_child_death``).

    ``labels``: the fit's flat labels in the tree's point space (vertex
    space for deduplicated fits); recomputed when omitted.
    """
    if tree.selected is None:
        raise ValueError("propagate_tree() must run before serving_tables()")
    return {
        "sel_anc": tree_vec.selected_ancestors(tree),
        "eps_min": tree_mod.cluster_eps_min(tree, labels),
        "eps_max": np.asarray(tree.lowest_child_death, np.float64),
    }


def finalize_clustering(
    n: int,
    u: np.ndarray,
    v: np.ndarray,
    w: np.ndarray,
    core: np.ndarray,
    params: HDBSCANParams,
    num_constraints_satisfied: np.ndarray | None = None,
    point_weights: np.ndarray | None = None,
    constraint_index_map: np.ndarray | None = None,
    trace=None,
    forest: tree_mod.MergeForest | None = None,
) -> tuple[tree_mod.CondensedTree, np.ndarray, np.ndarray, bool]:
    """Edge pool + core distances -> (tree, labels, outlier_scores, infinite).

    Constraint counts load from ``params.constraints_file`` when not supplied
    (both gamma and virtual-child vGamma credits feed propagation).
    ``point_weights``: member count per vertex (deduplicated pipelines).
    ``constraint_index_map``: row id -> vertex id translation for constraint
    files when vertices are deduplicated points.
    ``trace``: optional per-stage event callable — isolates the host tree
    layers (merge forest / condense / propagate / labels / GLOSH) so the
    multi-M-row runs can tell scan wall from tree wall.
    ``forest``: pre-built merge forest (the device-resident exact fit builds
    it before its single host sync); when omitted, ``params.mst_backend``
    picks the builder here.
    """
    import time as _time

    from hdbscan_tpu.native import merge_forest_lib

    backend = resolve_tree_backend(params, point_weights)
    eng = tree_vec if backend == "vectorized" else tree_mod

    if forest is None:
        from hdbscan_tpu.core import mst_device

        if mst_device.resolve_mst_backend(
            params, n
        ) == "device" and mst_device.supports_inputs(w, point_weights):
            # Reference condense walks Python children lists; the vectorized
            # engine consumes kids_csr directly, so skip the list cut there.
            forest = mst_device.build_merge_forest_device(
                n,
                u,
                v,
                w,
                point_weights=point_weights,
                trace=trace,
                build_children=(backend == "reference"),
            )
    if forest is None:
        t0 = _time.monotonic()
        forest = tree_mod.build_merge_forest(
            n, u, v, w, point_weights=point_weights
        )
        if trace is not None:
            trace(
                "tree_merge_forest",
                n=n,
                edges=len(u),
                backend="native" if merge_forest_lib() is not None else "python",
                wall_s=round(_time.monotonic() - t0, 6),
            )
    t0 = _time.monotonic()
    tree = eng.condense_forest(
        forest,
        params.min_cluster_size,
        point_weights=point_weights,
        self_levels=core if params.self_edges else None,
    )
    if trace is not None:
        trace(
            "tree_condense",
            clusters=len(tree.parent) - 1,
            backend=backend,
            wall_s=round(_time.monotonic() - t0, 6),
        )
    virtual_child_constraints = None
    if params.constraints_file and num_constraints_satisfied is None:
        from hdbscan_tpu.core.constraints import (
            Constraint,
            count_constraints_satisfied,
            load_constraints,
        )

        cons = load_constraints(params.constraints_file)
        if constraint_index_map is not None:
            cons = [
                Constraint(
                    int(constraint_index_map[c.point_a]),
                    int(constraint_index_map[c.point_b]),
                    c.kind,
                )
                for c in cons
            ]
        num_constraints_satisfied, virtual_child_constraints = (
            count_constraints_satisfied(tree, cons)
        )
    t0 = _time.monotonic()
    infinite = eng.propagate_tree(
        tree, num_constraints_satisfied, virtual_child_constraints
    )
    if trace is not None:
        trace(
            "tree_propagate",
            backend=backend,
            wall_s=round(_time.monotonic() - t0, 6),
        )
    t0 = _time.monotonic()
    labels = eng.flat_labels(tree)
    if trace is not None:
        trace(
            "tree_labels",
            backend=backend,
            wall_s=round(_time.monotonic() - t0, 6),
        )
    t0 = _time.monotonic()
    scores = tree_mod.outlier_scores(tree, core)
    if trace is not None:
        trace(
            "tree_glosh",
            backend=backend,
            wall_s=round(_time.monotonic() - t0, 6),
        )
    return tree, labels, scores, infinite
