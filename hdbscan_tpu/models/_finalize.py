"""Shared post-processing tail of every fit(): edge pool -> clustering.

All three models (single-block exact ``hdbscan``, blocked exact ``exact``,
distributed ``mr_hdbscan``) end in the same host-side sequence — merge forest,
condensed tree, constraint counting, EOM propagation, flat labels, GLOSH —
mirroring the reference's canonical per-node pipeline tail
(SURVEY.md §3.4; ``HDBSCANStar.propagateTree``/``findProminentClusters``/
``calculateOutlierScores``, ``hdbscanstar/HDBSCANStar.java:505,567,653``).
Kept in one place so constraint/propagation fixes apply to every path.
"""

from __future__ import annotations

import numpy as np

from hdbscan_tpu.config import HDBSCANParams
from hdbscan_tpu.core import tree as tree_mod


def finalize_clustering(
    n: int,
    u: np.ndarray,
    v: np.ndarray,
    w: np.ndarray,
    core: np.ndarray,
    params: HDBSCANParams,
    num_constraints_satisfied: np.ndarray | None = None,
    point_weights: np.ndarray | None = None,
    constraint_index_map: np.ndarray | None = None,
    trace=None,
) -> tuple[tree_mod.CondensedTree, np.ndarray, np.ndarray, bool]:
    """Edge pool + core distances -> (tree, labels, outlier_scores, infinite).

    Constraint counts load from ``params.constraints_file`` when not supplied
    (both gamma and virtual-child vGamma credits feed propagation).
    ``point_weights``: member count per vertex (deduplicated pipelines).
    ``constraint_index_map``: row id -> vertex id translation for constraint
    files when vertices are deduplicated points.
    ``trace``: optional per-stage event callable — isolates the host tree
    layers (merge forest / condense / propagate+labels/GLOSH) so the
    multi-M-row runs can tell scan wall from tree wall.
    """
    import time as _time

    t0 = _time.monotonic()
    forest = tree_mod.build_merge_forest(n, u, v, w, point_weights=point_weights)
    if trace is not None:
        trace(
            "tree_merge_forest",
            n=n,
            edges=len(u),
            wall_s=round(_time.monotonic() - t0, 3),
        )
    t0 = _time.monotonic()
    tree = tree_mod.condense_forest(
        forest,
        params.min_cluster_size,
        point_weights=point_weights,
        self_levels=core if params.self_edges else None,
    )
    if trace is not None:
        trace(
            "tree_condense",
            clusters=len(tree.parent) - 1,
            wall_s=round(_time.monotonic() - t0, 3),
        )
    virtual_child_constraints = None
    if params.constraints_file and num_constraints_satisfied is None:
        from hdbscan_tpu.core.constraints import (
            Constraint,
            count_constraints_satisfied,
            load_constraints,
        )

        cons = load_constraints(params.constraints_file)
        if constraint_index_map is not None:
            cons = [
                Constraint(
                    int(constraint_index_map[c.point_a]),
                    int(constraint_index_map[c.point_b]),
                    c.kind,
                )
                for c in cons
            ]
        num_constraints_satisfied, virtual_child_constraints = (
            count_constraints_satisfied(tree, cons)
        )
    t0 = _time.monotonic()
    infinite = tree_mod.propagate_tree(
        tree, num_constraints_satisfied, virtual_child_constraints
    )
    labels = tree_mod.flat_labels(tree)
    scores = tree_mod.outlier_scores(tree, core)
    if trace is not None:
        trace("tree_extract", wall_s=round(_time.monotonic() - t0, 3))
    return tree, labels, scores, infinite
