"""Bubble-level HDBSCAN* — the local-model step of the MR pipeline.

Re-design of ``main/LocalModelReduceByKey.call``
(``main/LocalModelReduceByKey.java:29-108``), which per oversized subset runs:
bubble core distances -> bubble MST -> edge sort -> simplified cluster tree ->
prominent clusters + noise reassignment -> inter-cluster edges. Here the dense
math (corrected distances, core distances, MRD, Borůvka MST) is one jitted XLA
program; the condensed tree + excess-of-mass extraction reuse the L3 host code
with member weights (``countMembers += nB[v]``, ``HdbscanDataBubbles.java:330-338``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from hdbscan_tpu.core import tree as tree_mod
from hdbscan_tpu.core.bubbles import (
    bubble_core_distances,
    bubble_distance_matrix,
    bubble_mutual_reachability,
    inter_cluster_edge_mask,
    reassign_noise_bubbles,
)
from hdbscan_tpu.core.mst import boruvka_mst


@dataclass
class BubbleModel:
    """Result of clustering one subset's bubbles.

    ``labels``: flat cluster per bubble (0 only if the whole subset is noise —
    noise bubbles are reassigned to their nearest cluster, mirroring
    ``HdbscanDataBubbles.java:485-502``).
    ``inter_edges``: (u, v, w) bubble-index MST edges crossing flat clusters —
    the candidate inter-partition MST edges (``findInterClusterEdges``).
    """

    labels: np.ndarray
    tree: tree_mod.CondensedTree
    core: np.ndarray
    mst: tuple[np.ndarray, np.ndarray, np.ndarray]
    inter_edges: tuple[np.ndarray, np.ndarray, np.ndarray]


@partial(jax.jit, static_argnames=("min_pts", "dims", "metric"))
def _bubble_device_block(rep, extent, nn_dist, n_b, num_valid, min_pts: int, dims: int, metric: str):
    """Fused device program: corrected distances -> core -> MRD -> Borůvka.

    ``num_valid``: leading count of real bubbles (rest is shape padding so
    level-to-level calls of similar size reuse the compiled program).
    """
    m = rep.shape[0]
    valid = jnp.arange(m, dtype=jnp.int32) < num_valid
    dist = bubble_distance_matrix(rep, extent, nn_dist, metric)
    core = bubble_core_distances(dist, n_b, extent, min_pts, dims, valid=valid)
    mrd = bubble_mutual_reachability(dist, core)
    u, v, w, mask, _ = boruvka_mst(mrd, num_valid)
    return dist, core, u, v, w, mask


def fit_bubbles(
    rep: np.ndarray,
    extent: np.ndarray,
    nn_dist: np.ndarray,
    n_b: np.ndarray,
    min_pts: int,
    min_cluster_size: int,
    metric: str = "euclidean",
    num_valid: int | None = None,
) -> BubbleModel:
    """Cluster one subset's bubbles; returns flat labels + inter-cluster edges.

    ``num_valid``: real bubble count when the inputs are shape-padded; all
    returned arrays are sliced back to it.
    """
    rep = jnp.asarray(rep)
    m_pad, dims = rep.shape
    m = m_pad if num_valid is None else int(num_valid)
    if m == 0:
        raise ValueError("empty bubble set")
    if m == 1:
        # Degenerate subset: single bubble, trivially one (root) cluster —
        # built through the standard tree path so the contract holds.
        empty = np.zeros(0, np.int64)
        w1 = np.asarray(n_b, np.float64)[:1]
        forest = tree_mod.build_merge_forest(
            1, empty, empty, np.zeros(0), point_weights=w1
        )
        tree = tree_mod.condense_forest(forest, min_cluster_size, point_weights=w1)
        tree_mod.propagate_tree(tree)
        return BubbleModel(
            labels=np.ones(1, np.int64),
            tree=tree,
            core=np.zeros(1),
            mst=(empty, empty, np.zeros(0)),
            inter_edges=(empty, empty, np.zeros(0)),
        )
    dist, core, u, v, w, mask = _bubble_device_block(
        rep,
        jnp.asarray(extent),
        jnp.asarray(nn_dist),
        jnp.asarray(n_b, rep.dtype),
        jnp.int32(m),
        min_pts,
        dims,
        metric,
    )
    mask = np.asarray(mask)
    u = np.asarray(u)[mask]
    v = np.asarray(v)[mask]
    w = np.asarray(w, np.float64)[mask]
    core_h = np.asarray(core, np.float64)[:m]
    dist = dist[:m, :m]
    weights = np.asarray(n_b, np.float64)[:m]

    tree, labels = tree_mod.extract_clusters(
        m, u, v, w, min_cluster_size, point_weights=weights, self_levels=core_h
    )

    labels = np.asarray(
        reassign_noise_bubbles(dist, jnp.asarray(labels)), np.int64
    )
    cross = np.asarray(inter_cluster_edge_mask(jnp.asarray(u), jnp.asarray(v), jnp.asarray(labels)))
    return BubbleModel(
        labels=labels,
        tree=tree,
        core=core_h,
        mst=(u, v, w),
        inter_edges=(u[cross], v[cross], w[cross]),
    )
