"""Bubble-level HDBSCAN* — the local-model step of the MR pipeline.

Re-design of ``main/LocalModelReduceByKey.call``
(``main/LocalModelReduceByKey.java:29-108``), which per oversized subset runs:
bubble core distances -> bubble MST -> edge sort -> simplified cluster tree ->
prominent clusters + noise reassignment -> inter-cluster edges. Here the dense
math (corrected distances, core distances, MRD, Borůvka MST) is one jitted XLA
program; the condensed tree + excess-of-mass extraction reuse the L3 host code
with member weights (``countMembers += nB[v]``, ``HdbscanDataBubbles.java:330-338``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from hdbscan_tpu.core import tree as tree_mod
from hdbscan_tpu.core.bubbles import (
    bubble_core_distances,
    bubble_distance_matrix,
    bubble_mutual_reachability,
    inter_cluster_edge_mask,
    reassign_noise_bubbles,
)
from hdbscan_tpu.core.mst import boruvka_mst


@dataclass
class BubbleModel:
    """Result of clustering one subset's bubbles.

    ``labels``: flat cluster per bubble (0 only if the whole subset is noise —
    noise bubbles are reassigned to their nearest cluster, mirroring
    ``HdbscanDataBubbles.java:485-502``).
    ``inter_edges``: (u, v, w) bubble-index MST edges crossing flat clusters —
    the candidate inter-partition MST edges (``findInterClusterEdges``).
    ``weights``: member count per bubble (already fetched to host).
    """

    labels: np.ndarray
    tree: tree_mod.CondensedTree
    core: np.ndarray
    mst: tuple[np.ndarray, np.ndarray, np.ndarray]
    inter_edges: tuple[np.ndarray, np.ndarray, np.ndarray]
    weights: np.ndarray | None = None


@partial(jax.jit, static_argnames=("min_pts", "dims", "metric"))
def _bubble_device_block(rep, extent, nn_dist, n_b, num_valid, min_pts: int, dims: int, metric: str):
    """Fused device program: corrected distances -> core -> MRD -> Borůvka.

    ``num_valid``: leading count of real bubbles (rest is shape padding so
    level-to-level calls of similar size reuse the compiled program).
    """
    m = rep.shape[0]
    valid = jnp.arange(m, dtype=jnp.int32) < num_valid
    dist = bubble_distance_matrix(rep, extent, nn_dist, metric)
    core = bubble_core_distances(dist, n_b, extent, min_pts, dims, valid=valid)
    return _bubble_device_block_given_core(dist, core, n_b, num_valid)


@jax.jit
def _bubble_device_block_given_core(dist, core, n_b, num_valid):
    """MRD + Borůvka over a corrected-distance matrix and core vector — the
    shared tail of :func:`_bubble_device_block`, also entered directly by the
    compat path (``core/compat.py`` computes cores host-side with the
    reference's buggy walk, then rejoins the device pipeline here).

    Packs everything the host fetches into ONE leaf (each fetched array pays
    a full tunnel round trip): [u, v, w, mask | core, n_b], in w's dtype —
    the layout :func:`unpack_edge_leaf` decodes. u/v/mask are ALSO returned
    as device arrays so the follow-up reassign call reuses them without a
    host->device upload.
    """
    mrd = bubble_mutual_reachability(dist, core)
    u, v, w, mask, _ = boruvka_mst(mrd, num_valid)
    dt = w.dtype
    packed = jnp.concatenate(
        [u.astype(dt), v.astype(dt), w, mask.astype(dt), core.astype(dt), n_b.astype(dt)]
    )
    return dist, u, v, mask, packed


def unpack_edge_leaf(packed: np.ndarray, m_pad: int, with_n_b: bool):
    """Split a packed [u | v | w | mask | core (| n_b)] device leaf.

    One copy of the offset arithmetic for every fused block program that
    packs its outputs into a single fetched leaf (`_bubble_device_block`,
    `mr_hdbscan._rs_device_block`).
    """
    e = m_pad - 1
    u = packed[:e].astype(np.int64)
    v = packed[e : 2 * e].astype(np.int64)
    w = packed[2 * e : 3 * e].astype(np.float64)
    mask = packed[3 * e : 4 * e] != 0
    core = packed[4 * e : 4 * e + m_pad].astype(np.float64)
    if not with_n_b:
        return u, v, w, mask, core
    n_b = packed[4 * e + m_pad :].astype(np.float64)
    return u, v, w, mask, core, n_b


def _unpack_bubble_block(packed: np.ndarray, m_pad: int):
    return unpack_edge_leaf(packed, m_pad, with_n_b=True)


@jax.jit
def _bubble_reassign_block(dist, labels, u, v, mask, num_valid):
    """Noise reassignment + inter-cluster edge mask as ONE padded device call.

    ``labels`` is (m_pad,) with zeros on padding; padding bubbles are excluded
    as donors via ``valid``. ``u``/``v``/``mask`` are the padded MST edge
    arrays rebuilt on host from the packed fetch. Output is one packed leaf:
    [labels | cross] in float.
    """
    m = dist.shape[0]
    valid = jnp.arange(m, dtype=jnp.int32) < num_valid
    new = reassign_noise_bubbles(dist, labels, valid=valid)
    cross = mask & inter_cluster_edge_mask(u, v, new)
    dt = dist.dtype
    return jnp.concatenate([new.astype(dt), cross.astype(dt)])


def fit_bubbles(
    rep: np.ndarray,
    extent: np.ndarray,
    nn_dist: np.ndarray,
    n_b: np.ndarray,
    min_pts: int,
    min_cluster_size: int,
    metric: str = "euclidean",
    num_valid: int | None = None,
    compat_cf_int_math: bool = False,
) -> BubbleModel:
    """Cluster one subset's bubbles; returns flat labels + inter-cluster edges.

    ``num_valid``: real bubble count when the inputs are shape-padded; all
    returned arrays are sliced back to it. ``compat_cf_int_math`` swaps the
    core-distance step for the reference's faithful buggy walk
    (``core/compat.reference_bubble_core_distances``).
    """
    rep = jnp.asarray(rep)
    m_pad, dims = rep.shape
    m = m_pad if num_valid is None else int(num_valid)
    if m == 0:
        raise ValueError("empty bubble set")
    if m == 1:
        # Degenerate subset: single bubble, trivially one (root) cluster —
        # built through the standard tree path so the contract holds.
        empty = np.zeros(0, np.int64)
        w1 = np.asarray(n_b, np.float64)[:1]
        forest = tree_mod.build_merge_forest(
            1, empty, empty, np.zeros(0), point_weights=w1
        )
        tree = tree_mod.condense_forest(forest, min_cluster_size, point_weights=w1)
        tree_mod.propagate_tree(tree)
        return BubbleModel(
            labels=np.ones(1, np.int64),
            tree=tree,
            core=np.zeros(1),
            mst=(empty, empty, np.zeros(0)),
            inter_edges=(empty, empty, np.zeros(0)),
            weights=w1,
        )
    if compat_cf_int_math:
        dist = bubble_distance_matrix(
            rep, jnp.asarray(extent), jnp.asarray(nn_dist), metric
        )
        from hdbscan_tpu.core import compat

        # The reference only ever builds CFs for samples that received
        # points; our padded pipeline also carries zero-member bubbles, a
        # shape the Java walk would crash on (its covering loop runs off the
        # k-1 slot buffer). Compact to live bubbles — the same exclusion the
        # default path's `ok` mask applies — and walk those faithfully.
        nb_h = np.asarray(n_b, np.float64)[:m]
        ext_h = np.asarray(extent, np.float64)[:m]
        live = np.flatnonzero(nb_h > 0)
        dist_h = np.asarray(jax.device_get(dist), np.float64)[:m, :m]
        core_p = np.full(m_pad, np.inf)
        try:
            core_p[live] = compat.reference_bubble_core_distances(
                dist_h[np.ix_(live, live)], nb_h[live], ext_h[live], min_pts, dims
            )
        except IndexError as e:
            # The Java walk's AIOOBE surfaces here when the covering loop runs
            # off the k-1 slot buffer — duplicate-heavy subsets can collapse
            # live bubbles below min_pts - 1. Re-raise with the run context so
            # an opt-in compat run fails actionably instead of with a bare
            # IndexError (ADVICE r2).
            raise ValueError(
                "compat_cf_int_math: the reference's covering walk overran "
                f"its neighbor buffer ({m} bubbles, {len(live)} live, "
                f"min_pts={min_pts}) — the Java code throws "
                "ArrayIndexOutOfBoundsException on this shape. Lower "
                "min_pts, raise k/processing_units, or disable compat_cf"
            ) from e
        dist, u_d, v_d, mask_d, packed_d = _bubble_device_block_given_core(
            dist,
            jnp.asarray(core_p, rep.dtype),
            jnp.asarray(n_b, rep.dtype),
            jnp.int32(m),
        )
    else:
        dist, u_d, v_d, mask_d, packed_d = _bubble_device_block(
            rep,
            jnp.asarray(extent),
            jnp.asarray(nn_dist),
            jnp.asarray(n_b, rep.dtype),
            jnp.int32(m),
            min_pts,
            dims,
            metric,
        )
    # One single-leaf fetch for everything the host tree extraction needs.
    u_p, v_p, w_p, mask, core_p, n_b_h = _unpack_bubble_block(
        jax.device_get(packed_d), m_pad
    )
    u = u_p[mask]
    v = v_p[mask]
    w = w_p[mask]
    core_h = core_p[:m]
    weights = n_b_h[:m]

    tree, labels = tree_mod.extract_clusters(
        m, u, v, w, min_cluster_size, point_weights=weights, self_levels=core_h
    )

    labels_p = np.zeros(m_pad, np.int32)
    labels_p[:m] = labels
    out = jax.device_get(
        _bubble_reassign_block(
            dist, jnp.asarray(labels_p), u_d, v_d, mask_d, jnp.int32(m)
        )
    )
    labels = np.asarray(out[:m_pad].round(), np.int64)[:m]
    cross = (out[m_pad:] != 0)[mask]
    return BubbleModel(
        labels=labels,
        tree=tree,
        core=core_h,
        mst=(u, v, w),
        inter_edges=(u[cross], v[cross], w[cross]),
        weights=weights,
    )
